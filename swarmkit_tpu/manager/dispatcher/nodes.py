"""Registered-node store with heartbeat-TTL liveness.

Reference: manager/dispatcher/nodes.go (nodeStore, :44) and
manager/dispatcher/heartbeat/heartbeat.go.  Each registered node carries a
session ID and a heartbeat deadline; missing the deadline fires the expire
callback (which marks the node DOWN in the cluster store).  The per-node
``time.AfterFunc`` timer becomes a per-node asyncio task sleeping on the
injectable Clock, so tests drive expiry deterministically with FakeClock.

Rate limiting of re-registrations mirrors nodes.go:73-90 (RateLimitPeriod
8 s, CheckRateLimit counts rapid re-registrations).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from swarmkit_tpu.utils.clock import Clock
from swarmkit_tpu.utils.identity import new_id

# reference: dispatcher.go:31-36
DEFAULT_HEARTBEAT_PERIOD = 5.0
DEFAULT_HEARTBEAT_EPSILON = 0.5
DEFAULT_GRACE_PERIOD_MULTIPLIER = 3
DEFAULT_RATE_LIMIT_PERIOD = 8.0


class ErrNodeNotRegistered(Exception):
    """Reference: dispatcher/errors: node not registered."""


class ErrSessionInvalid(Exception):
    """Session ID does not match the registered session."""


class _Heartbeat:
    """One node's liveness timer (reference: heartbeat/heartbeat.go)."""

    def __init__(self, clock: Clock, timeout: float,
                 timeout_func: Callable[[], None]) -> None:
        self._clock = clock
        self._deadline = clock.now() + timeout
        self._timeout_func = timeout_func
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def update(self, timeout: float) -> None:
        self._deadline = self._clock.now() + timeout

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        try:
            while not self._stopped:
                remaining = self._deadline - self._clock.now()
                if remaining <= 0:
                    self._timeout_func()
                    return
                await self._clock.sleep(remaining)
        except asyncio.CancelledError:
            pass


@dataclass
class RegisteredNode:
    session_id: str
    node_id: str
    description: object = None
    addr: str = ""
    heartbeat: Optional[_Heartbeat] = None
    registrations: list[float] = field(default_factory=list)
    # disconnect notification: closed when the session is superseded/expired
    disconnect: asyncio.Event = field(default_factory=asyncio.Event)

    def check_session(self, session_id: str) -> None:
        if session_id != self.session_id:
            raise ErrSessionInvalid(
                f"session {session_id!r} invalid for node {self.node_id}")


class NodeStore:
    """Reference: manager/dispatcher/nodes.go nodeStore."""

    def __init__(self, clock: Clock,
                 period: float = DEFAULT_HEARTBEAT_PERIOD,
                 epsilon: float = DEFAULT_HEARTBEAT_EPSILON,
                 grace_multiplier: int = DEFAULT_GRACE_PERIOD_MULTIPLIER,
                 rate_limit_period: float = DEFAULT_RATE_LIMIT_PERIOD,
                 rng: Optional[random.Random] = None) -> None:
        self.clock = clock
        self.period = period
        self.epsilon = epsilon
        self.grace_multiplier = grace_multiplier
        self.rate_limit_period = rate_limit_period
        self.nodes: dict[str, RegisteredNode] = {}
        self._rng = rng or random.Random()

    # period ± epsilon (reference: period.go periodChooser)
    def choose_period(self) -> float:
        return self.period + self._rng.uniform(-self.epsilon, self.epsilon)

    def check_rate_limit(self, node_id: str) -> bool:
        """True if the node re-registers too fast (nodes.go:73-90)."""
        rn = self.nodes.get(node_id)
        if rn is None or self.rate_limit_period <= 0:
            return False
        now = self.clock.now()
        rn.registrations = [t for t in rn.registrations
                            if now - t < self.rate_limit_period]
        return len(rn.registrations) >= 3

    def add(self, node_id: str, description, addr: str,
            expire_func: Callable[[str], None]) -> RegisteredNode:
        """Register (or re-register) a node; supersedes any prior session."""
        old = self.nodes.get(node_id)
        history: list[float] = []
        if old is not None:
            history = old.registrations
            if old.heartbeat is not None:
                old.heartbeat.stop()
            old.disconnect.set()
        history.append(self.clock.now())
        rn = RegisteredNode(session_id=new_id(), node_id=node_id,
                            description=description, addr=addr,
                            registrations=history)
        timeout = self.choose_period() * self.grace_multiplier
        rn.heartbeat = _Heartbeat(
            self.clock, timeout,
            lambda nid=node_id: self._expire(nid, expire_func))
        rn.heartbeat.start()
        self.nodes[node_id] = rn
        return rn

    def _expire(self, node_id: str, expire_func: Callable[[str], None]) -> None:
        rn = self.nodes.pop(node_id, None)
        if rn is not None:
            rn.disconnect.set()
            expire_func(node_id)

    def get(self, node_id: str) -> RegisteredNode:
        rn = self.nodes.get(node_id)
        if rn is None:
            raise ErrNodeNotRegistered(node_id)
        return rn

    def get_with_session(self, node_id: str, session_id: str) -> RegisteredNode:
        rn = self.get(node_id)
        rn.check_session(session_id)
        return rn

    def heartbeat(self, node_id: str, session_id: str) -> float:
        """Reset the TTL; returns the next period (dispatcher.go:1177)."""
        rn = self.get_with_session(node_id, session_id)
        period = self.choose_period()
        if rn.heartbeat is not None:
            rn.heartbeat.update(period * self.grace_multiplier)
        return period

    def delete(self, node_id: str) -> None:
        rn = self.nodes.pop(node_id, None)
        if rn is not None:
            if rn.heartbeat is not None:
                rn.heartbeat.stop()
            rn.disconnect.set()

    def delete_all(self) -> None:
        for node_id in list(self.nodes):
            self.delete(node_id)

    def __len__(self) -> int:
        return len(self.nodes)

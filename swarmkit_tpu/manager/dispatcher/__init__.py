from swarmkit_tpu.manager.dispatcher.dispatcher import (
    Dispatcher, DispatcherConfigDefaults, ErrNodeNotRegistered,
    ErrSessionInvalid, ErrNodeNotFound,
)

__all__ = [
    "Dispatcher", "DispatcherConfigDefaults", "ErrNodeNotRegistered",
    "ErrSessionInvalid", "ErrNodeNotFound",
]

"""The manager↔agent boundary: registration, sessions, heartbeats,
assignment fan-out and batched status write-back.

Reference: manager/dispatcher/dispatcher.go (1948 LoC).  Behaviors kept:
- ``register`` requires the node object to already exist (node records are
  created at CA join / by the control plane), rate-limits re-registrations,
  marks the node READY with its observed address (register :542,
  markNodeReady), and arms a heartbeat-TTL that marks the node DOWN on
  expiry (markNodeNotReady).
- ``session`` streams SessionMessages (node, weighted manager list, network
  bootstrap keys, root CA) and re-sends when any of those change
  (Session :1219).
- ``heartbeat`` resets the TTL and returns the next period, 5 s ± 0.5 s with
  ×3 grace (Heartbeat :1177, constants :31-34).
- ``assignments`` sends one COMPLETE snapshot then INCREMENTAL diffs,
  batched 100 ms after the most recent change or 100 modifications,
  whichever first (Assignments :917, batchingWaitTime/modificationBatchLimit
  :45-48).
- ``update_task_status`` validates ownership, dedups by task id and batch
  writes via the store (UpdateTaskStatus :596, processUpdates :670,
  maxBatchItems :38); state regressions are dropped.
- leader start marks every READY node UNKNOWN until it re-registers
  (markNodesUnknown :410); nodes DOWN for 24 h get their tasks ORPHANED
  (defaultNodeDownPeriod :50-53, moveTasksToOrphaned :1065).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import AsyncIterator, Callable, Optional

from swarmkit_tpu.api import (
    Node, NodeState, TaskState, TaskStatus, WeightedPeer,
)
from swarmkit_tpu.api.dispatcher_msgs import (
    AssignmentsMessage, AssignmentsType, HeartbeatResponse, SessionMessage,
)
from swarmkit_tpu.manager.dispatcher.assignments import AssignmentSet
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.metrics import trace as obs_trace
from swarmkit_tpu.manager.dispatcher.nodes import (
    ErrNodeNotRegistered, ErrSessionInvalid, NodeStore,
)
from swarmkit_tpu.store.by import ByNode
from swarmkit_tpu.store.memory import MemoryStore, match
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.dispatcher")

# reference: dispatcher.go:36-53
MAX_BATCH_ITEMS = 10000
BATCHING_WAIT_TIME = 0.100
MODIFICATION_BATCH_LIMIT = 100
DEFAULT_NODE_DOWN_PERIOD = 24 * 3600.0


class ErrNodeNotFound(Exception):
    """The node has no record in the cluster store."""


class DispatcherConfigDefaults:
    heartbeat_period = 5.0
    heartbeat_epsilon = 0.5
    grace_period_multiplier = 3
    rate_limit_period = 8.0


class Dispatcher:
    def __init__(self, store: MemoryStore,
                 managers_fn: Optional[Callable[[], list[WeightedPeer]]] = None,
                 clock: Optional[Clock] = None,
                 peers_queue=None,
                 rng: Optional[random.Random] = None,
                 drivers=None,
                 obs: Optional[obs_registry.MetricsRegistry] = None) -> None:
        self.store = store
        self.drivers = drivers
        self.clock = clock or SystemClock()
        self.obs = obs or obs_registry.DEFAULT
        self._m_sessions = obs_catalog.get(
            self.obs, "swarm_dispatcher_sessions_total")
        self._m_heartbeats = obs_catalog.get(
            self.obs, "swarm_dispatcher_heartbeats_total")
        self._m_hb_rtt = obs_catalog.get(
            self.obs, "swarm_dispatcher_heartbeat_rtt_seconds")
        self._m_task_updates = obs_catalog.get(
            self.obs, "swarm_dispatcher_task_updates_total")
        self.managers_fn = managers_fn or (lambda: [])
        # raft membership broadcast (membership.Cluster.broadcast /
        # PeersBroadcast cluster.go:38): wakes session streams so agents
        # learn manager-list changes that write no store object
        self.peers_queue = peers_queue
        self.nodes = NodeStore(self.clock, rng=rng)
        # node_id -> timer task orphaning its tasks after 24 h down
        self._down_nodes: dict[str, asyncio.Task] = {}
        self._task_updates: dict[str, TaskStatus] = {}
        self._updates_ready = asyncio.Event()
        self._running = False
        self._process_task: Optional[asyncio.Task] = None
        self._bg: list[asyncio.Task] = []

    # ------------------------------------------------------------------
    async def start(self, mark_unknown: bool = True) -> None:
        self._running = True
        if mark_unknown:
            await self._mark_nodes_unknown()
        # watch-BEFORE-read so no committed update can fall between the
        # initial config read and the subscription (an update seen by
        # both is harmless: _apply_cluster_config is idempotent); kept on
        # self so stop() can close it even if the task never scheduled
        self._cfg_watcher = self.store.watch(
            match(kind="cluster", action="update"))
        self._apply_cluster_config()
        self._process_task = asyncio.get_running_loop().create_task(
            self._process_updates_loop())
        self._bg.append(asyncio.get_running_loop().create_task(
            self._watch_cluster_config(self._cfg_watcher)))

    def _apply_cluster_config(self) -> None:
        """Adopt DispatcherConfig from the replicated cluster spec
        (reference: dispatcher.go:242-244 initial read)."""
        clusters = self.store.find("cluster")
        if not clusters:
            return
        period = clusters[0].spec.dispatcher.heartbeat_period
        if period > 0 and period != self.nodes.period:
            log.info("dispatcher heartbeat period -> %.2fs", period)
            self.nodes.period = period

    async def _watch_cluster_config(self, watcher) -> None:
        """Re-read DispatcherConfig on cluster updates (reference:
        dispatcher.go:310-315 — heartbeat period changes apply to every
        subsequent heartbeat RPC's returned period)."""
        try:
            async for _ in watcher:
                self._apply_cluster_config()
        except asyncio.CancelledError:
            pass
        finally:
            watcher.close()

    async def stop(self) -> None:
        self._running = False
        self.nodes.delete_all()
        for t in list(self._down_nodes.values()) + self._bg:
            t.cancel()
        self._down_nodes.clear()
        self._bg.clear()
        if getattr(self, "_cfg_watcher", None) is not None:
            self._cfg_watcher.close()
            self._cfg_watcher = None
        if self._process_task is not None:
            self._updates_ready.set()
            self._process_task.cancel()
            try:
                await self._process_task
            except (asyncio.CancelledError, Exception):
                pass
            self._process_task = None

    def _check_running(self) -> None:
        if not self._running:
            raise RuntimeError("dispatcher is stopped")

    # ------------------------------------------------------------------
    async def _mark_nodes_unknown(self) -> None:
        """Reference: markNodesUnknown dispatcher.go:410."""
        nodes = self.store.find("node")
        batch = self.store.batch()
        for n in nodes:
            def cb(tx, nid=n.id):
                node = tx.get("node", nid)
                if node is None:
                    return
                if node.status.state == NodeState.DOWN:
                    self._arm_down_node(nid)
                    return
                node = node.copy()
                node.status.state = NodeState.UNKNOWN
                node.status.message = ("Node moved to \"unknown\" state due to"
                                       " leadership change in cluster")
                tx.update(node)
                self.nodes.add(nid, None, "", self._heartbeat_expired)
            await batch.update(cb)
        await batch.commit()

    def _heartbeat_expired(self, node_id: str) -> None:
        log.info("heartbeat expiration for node %s", node_id)
        t = asyncio.get_running_loop().create_task(
            self._mark_node_not_ready(node_id, NodeState.DOWN,
                                      "heartbeat failure"))
        self._bg.append(t)
        self._bg[:] = [b for b in self._bg if not b.done()]

    async def _mark_node_not_ready(self, node_id: str, state: NodeState,
                                   message: str) -> None:
        """Reference: markNodeNotReady — store write + down-node tracking."""
        self.nodes.delete(node_id)

        def cb(tx):
            node = tx.get("node", node_id)
            if node is None:
                return
            node = node.copy()
            node.status.state = state
            node.status.message = message
            tx.update(node)

        try:
            await self.store.update(cb)
        except Exception:
            log.exception("failed to mark node %s not ready", node_id)
            return
        if state == NodeState.DOWN:
            self._arm_down_node(node_id)

    def _arm_down_node(self, node_id: str) -> None:
        """Orphan the node's tasks after 24 h down (dispatcher.go:50-53)."""
        if node_id in self._down_nodes:
            return

        async def orphan_later():
            try:
                await self.clock.sleep(DEFAULT_NODE_DOWN_PERIOD)
                await self.move_tasks_to_orphaned(node_id)
            except asyncio.CancelledError:
                pass
            finally:
                self._down_nodes.pop(node_id, None)

        self._down_nodes[node_id] = asyncio.get_running_loop().create_task(
            orphan_later())

    async def move_tasks_to_orphaned(self, node_id: str) -> None:
        """Reference: moveTasksToOrphaned dispatcher.go:1065."""
        tasks = self.store.find("task", ByNode(node_id))
        batch = self.store.batch()
        for t in tasks:
            if not (TaskState.ASSIGNED <= t.status.state <= TaskState.RUNNING):
                continue

            def cb(tx, tid=t.id):
                task = tx.get("task", tid)
                if task is None:
                    return
                task = task.copy()
                task.status.state = TaskState.ORPHANED
                tx.update(task)
            await batch.update(cb)
        await batch.commit()

    # ------------------------------------------------------------------
    async def register(self, node_id: str, description=None, addr: str = ""
                       ) -> str:
        """Reference: register dispatcher.go:542. Returns the session ID."""
        self._check_running()
        if self.nodes.check_rate_limit(node_id):
            raise RuntimeError(f"node {node_id} exceeded rate limit")
        node = self.store.get("node", node_id)
        if node is None:
            raise ErrNodeNotFound(node_id)
        await self._mark_node_ready(node_id, description, addr)
        rn = self.nodes.add(node_id, description, addr,
                            self._heartbeat_expired)
        self._m_sessions.inc()
        return rn.session_id

    async def _mark_node_ready(self, node_id: str, description, addr: str
                               ) -> None:
        # cancel any pending orphaning
        t = self._down_nodes.pop(node_id, None)
        if t is not None:
            t.cancel()

        def cb(tx):
            node = tx.get("node", node_id)
            if node is None:
                raise ErrNodeNotFound(node_id)
            node = node.copy()
            node.status.state = NodeState.READY
            node.status.message = ""
            node.status.addr = addr
            if description is not None:
                node.description = description
            tx.update(node)

        await self.store.update(cb)

    # ------------------------------------------------------------------
    async def heartbeat(self, node_id: str, session_id: str
                        ) -> HeartbeatResponse:
        self._check_running()
        with self._m_hb_rtt.time():
            try:
                period = self.nodes.heartbeat(node_id, session_id)
            except Exception:
                self._m_heartbeats.labels(result="invalid").inc()
                raise
        self._m_heartbeats.labels(result="ok").inc()
        return HeartbeatResponse(period=period)

    async def update_task_status(self, node_id: str, session_id: str,
                                 updates: list[tuple[str, TaskStatus]]
                                 ) -> None:
        """Reference: UpdateTaskStatus dispatcher.go:596."""
        self._check_running()
        self.nodes.get_with_session(node_id, session_id)
        # validate the whole batch before enqueuing anything, so a bad
        # entry can't strand earlier valid updates unflushed
        # (reference: validTaskUpdates collected first, dispatcher.go:624)
        valid = []
        for task_id, status in updates:
            t = self.store.get("task", task_id)
            if t is None:
                continue  # task may have been deleted
            if t.node_id != node_id:
                raise PermissionError(
                    "cannot update a task not assigned this node")
            valid.append((task_id, status))
        if valid:
            self._m_task_updates.inc(len(valid))
        for task_id, status in valid:
            self._task_updates[task_id] = status
        if self._task_updates:
            self._updates_ready.set()

    async def _process_updates_loop(self) -> None:
        try:
            while self._running:
                await self._updates_ready.wait()
                self._updates_ready.clear()
                await self._process_updates()
        except asyncio.CancelledError:
            pass

    async def _process_updates(self) -> None:
        """Reference: processUpdates dispatcher.go:670."""
        if not self._task_updates:
            return
        updates, self._task_updates = self._task_updates, {}
        batch = self.store.batch()
        for task_id, status in updates.items():
            def cb(tx, tid=task_id, st=status):
                task = tx.get("task", tid)
                if task is None:
                    return
                if task.status.state > st.state:
                    return  # invalid (backward) transition — drop
                if task.status.to_dict() == st.to_dict():
                    return
                task = task.copy()
                task.status = st.copy()
                tx.update(task)
            try:
                await batch.update(cb)
            except Exception:
                log.exception("dispatcher task update transaction failed")
        await batch.commit()

    # ------------------------------------------------------------------
    def _session_message(self, node_id: str, session_id: str
                         ) -> Optional[SessionMessage]:
        node = self.store.get("node", node_id)
        if node is None:
            return None
        clusters = self.store.find("cluster")
        keys, root_ca = [], b""
        if clusters:
            keys = list(clusters[0].network_bootstrap_keys)
            root_ca = clusters[0].root_ca.ca_cert
        return SessionMessage(session_id=session_id, node=node,
                              managers=self.managers_fn(),
                              network_bootstrap_keys=keys, root_ca=root_ca)

    async def session(self, node_id: str, description=None,
                      session_id: str = "", addr: str = "",
                      parent_span: str = ""
                      ) -> AsyncIterator[SessionMessage]:
        """Reference: Session dispatcher.go:1219.  Registers (unless resuming
        an existing session) and streams SessionMessages until the session is
        superseded or expires.

        `parent_span` carries the caller's span id across the gRPC wire
        (rpc.py packs it) so the trace reparents instead of rooting a
        fresh tree in the serving process.
        """
        self._check_running()
        with obs_trace.DEFAULT.span("dispatcher.session", node=node_id,
                                    parent_id=parent_span or None,
                                    resumed=bool(session_id)) as sp:
            if not session_id:
                session_id = await self.register(node_id, description, addr)
            rn = self.nodes.get_with_session(node_id, session_id)
            sp.set(session=session_id)

        watcher = self.store.watch(match(kind="node"), match(kind="cluster"))
        peers_w = (self.peers_queue.watch()
                   if self.peers_queue is not None else None)
        # persistent waiters: only a consumed future is re-created, so an
        # event completing in a round won by another waiter is never lost
        get_ev = asyncio.ensure_future(watcher.get())
        disc = asyncio.ensure_future(rn.disconnect.wait())
        peers_ev = (asyncio.ensure_future(peers_w.get())
                    if peers_w is not None else None)

        def reap():
            _cancel_quietly(get_ev, disc,
                            *((peers_ev,) if peers_ev is not None else ()))
        try:
            msg = self._session_message(node_id, session_id)
            if msg is not None:
                yield msg
            last = msg
            while self._running and not rn.disconnect.is_set():
                waiters = {get_ev, disc}
                if peers_ev is not None:
                    waiters.add(peers_ev)
                try:
                    done, _ = await asyncio.wait(
                        waiters, return_when=asyncio.FIRST_COMPLETED)
                except BaseException:
                    # generator closed/cancelled mid-wait: reap the waiters
                    reap()
                    raise
                if disc in done:
                    break
                relevant = False
                if get_ev in done:
                    ev = get_ev.result()
                    get_ev = asyncio.ensure_future(watcher.get())
                    if not (ev.kind == "node" and ev.object.id != node_id):
                        relevant = True
                if peers_ev is not None and peers_ev in done:
                    peers_ev = asyncio.ensure_future(peers_w.get())
                    relevant = True
                if not relevant:
                    continue
                msg = self._session_message(node_id, session_id)
                if msg is None:  # node deleted
                    break
                if last is None or msg.to_dict() != last.to_dict():
                    yield msg
                    last = msg
        finally:
            reap()
            watcher.close()
            if peers_w is not None:
                peers_w.close()

    # ------------------------------------------------------------------
    async def assignments(self, node_id: str, session_id: str
                          ) -> AsyncIterator[AssignmentsMessage]:
        """Reference: Assignments dispatcher.go:917."""
        self._check_running()
        rn = self.nodes.get_with_session(node_id, session_id)
        aset = AssignmentSet(node_id, drivers=self.drivers)

        def init(read_tx):
            for t in read_tx.find("task", ByNode(node_id)):
                aset.add_or_update_task(read_tx, t)

        _, watcher = self.store.view_and_watch(init, match(kind="task"))
        try:
            yield aset.message(AssignmentsType.COMPLETE)
            read_tx = self.store.read_tx()
            while self._running and not rn.disconnect.is_set():
                self.nodes.get_with_session(node_id, session_id)
                modifications = 0
                deadline: Optional[float] = None
                while modifications < MODIFICATION_BATCH_LIMIT:
                    timeout = None
                    if deadline is not None:
                        timeout = max(0.0, deadline - self.clock.now())
                    ev = await self._next_event(watcher, rn, timeout)
                    if ev is _DISCONNECTED:
                        return
                    if ev is _TIMEOUT:
                        break
                    t = ev.object
                    if t.node_id != node_id and (
                            ev.old_object is None
                            or ev.old_object.node_id != node_id):
                        continue
                    if ev.action == "remove":
                        changed = aset.remove_task(t)
                    elif t.node_id != node_id:
                        changed = aset.remove_task(ev.old_object)
                    else:
                        changed = aset.add_or_update_task(read_tx, t)
                    if changed:
                        modifications += 1
                        deadline = self.clock.now() + BATCHING_WAIT_TIME
                if modifications > 0:
                    yield aset.message(AssignmentsType.INCREMENTAL)
        finally:
            watcher.close()

    async def _next_event(self, watcher, rn, timeout: Optional[float]):
        """Wait for the next watcher event, a session disconnect, or (when
        ``timeout`` is not None) the batching deadline."""
        get_ev = asyncio.ensure_future(watcher.get())
        disc = asyncio.ensure_future(rn.disconnect.wait())
        waiters = {get_ev: "ev", disc: "disc"}
        if timeout is not None:
            timer = asyncio.ensure_future(self.clock.sleep(timeout))
            waiters[timer] = "timeout"
        try:
            done, pending = await asyncio.wait(
                set(waiters), return_when=asyncio.FIRST_COMPLETED)
        except BaseException:
            _cancel_quietly(*waiters)
            raise
        _cancel_quietly(*pending)
        if get_ev in done:
            _cancel_quietly(*(done - {get_ev}))
            return get_ev.result()
        _cancel_quietly(*(done - {disc}))
        if disc in done:
            return _DISCONNECTED
        return _TIMEOUT


_DISCONNECTED = object()
_TIMEOUT = object()


def _cancel_quietly(*futs) -> None:
    """Cancel pending waiters, swallowing late exceptions (a watcher closed
    during teardown completes its pending get() with WatcherClosed after the
    cancel — retrieve it so asyncio doesn't log 'never retrieved')."""
    for f in futs:
        f.cancel()
        f.add_done_callback(
            lambda fut: fut.exception() if not fut.cancelled() else None)

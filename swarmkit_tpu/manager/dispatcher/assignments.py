"""Per-session assignment diff tracker.

Reference: manager/dispatcher/assignments.go (assignmentSet :19).  Tracks the
set of tasks assigned to one node plus the secrets/configs those tasks
reference; ``add_or_update_task``/``remove_task`` accumulate pending changes,
``message()`` drains them into one AssignmentsMessage.  Dependencies are
reference-counted so a secret is only REMOVEd once the last task using it
goes away (assignments.go tasksUsingDependency), and are released when a
task reaches a terminal state (addOrUpdateTask :229).
"""

from __future__ import annotations

from swarmkit_tpu.api import Config, Secret, Task, TaskState
from swarmkit_tpu.api.dispatcher_msgs import (
    Assignment, AssignmentAction, AssignmentChange, AssignmentsMessage,
    AssignmentsType,
)


def _task_dependencies(t) -> list[tuple[str, str]]:
    deps: list[tuple[str, str]] = []
    c = getattr(t.spec, "container", None)
    if c is not None:
        deps += [("secret", r.secret_id) for r in c.secrets]
        deps += [("config", r.config_id) for r in c.configs]
    return deps


def tasks_equal_stable(a, b) -> bool:
    """Equality ignoring status/meta (reference: api/equality
    TasksEqualStable)."""
    da, db = a.to_dict(), b.to_dict()
    for d in (da, db):
        d.pop("status", None)
        d.pop("meta", None)
    return da == db


class AssignmentSet:
    def __init__(self, node_id: str, drivers=None) -> None:
        self.node_id = node_id
        self.drivers = drivers  # DriverProvider for external secrets
        self.tasks: dict[str, Task] = {}
        # (kind, id) -> set of task ids using it
        self.tasks_using_dependency: dict[tuple[str, str], set[str]] = {}
        self.changes: dict[tuple[str, str], AssignmentChange] = {}

    # ------------------------------------------------------------------
    def _add_task_dependencies(self, read_tx, t) -> None:
        from swarmkit_tpu.manager.drivers import resolve_secret

        for kind, dep_id in _task_dependencies(t):
            key = (kind, dep_id)
            users = self.tasks_using_dependency.setdefault(key, set())
            if not users:
                if kind == "secret":
                    # External secrets resolve through their driver at
                    # assignment time, once per node per secret with the
                    # FIRST task's context — exactly the reference's dedup
                    # (assignments.go addTaskDependencies:
                    # len(tasksUsingDependency)==0 gate + assignSecret).
                    # Any driver failure withholds the secret, never the
                    # whole assignment stream.
                    try:
                        obj = resolve_secret(self.drivers, read_tx, t,
                                             dep_id)
                    except Exception as e:
                        import logging

                        logging.getLogger(
                            "swarmkit_tpu.dispatcher").warning(
                            "secret %s for task %s unavailable: %s",
                            dep_id, t.id, e)
                        obj = None
                else:
                    obj = read_tx.get(kind, dep_id)
                if obj is not None:
                    self.changes[key] = AssignmentChange(
                        assignment=Assignment(**{kind: obj}),
                        action=AssignmentAction.UPDATE)
            users.add(t.id)

    def _release_task_dependencies(self, t) -> bool:
        modified = False
        for kind, dep_id in _task_dependencies(t):
            key = (kind, dep_id)
            users = self.tasks_using_dependency.get(key)
            if users is None:
                continue
            users.discard(t.id)
            if not users:
                del self.tasks_using_dependency[key]
                stub = (Secret if kind == "secret" else Config)(id=dep_id)
                self.changes[key] = AssignmentChange(
                    assignment=Assignment(**{kind: stub}),
                    action=AssignmentAction.REMOVE)
                modified = True
        return modified

    # ------------------------------------------------------------------
    def add_or_update_task(self, read_tx, t) -> bool:
        """Reference: assignments.go addOrUpdateTask :214."""
        if t.status.state < TaskState.ASSIGNED:
            return False
        old = self.tasks.get(t.id)
        if old is not None:
            # States <= ASSIGNED are set by the orchestrator/scheduler, not
            # the agent, so those must always be re-sent; otherwise a
            # spec-stable update is agent-reported status echo — swallow it.
            if tasks_equal_stable(old, t) and t.status.state > TaskState.ASSIGNED:
                self.tasks[t.id] = t
                if t.status.state > TaskState.RUNNING:
                    return self._release_task_dependencies(t)
                return False
        elif t.status.state <= TaskState.RUNNING:
            self._add_task_dependencies(read_tx, t)
        self.tasks[t.id] = t
        self.changes[("task", t.id)] = AssignmentChange(
            assignment=Assignment(task=t),
            action=AssignmentAction.UPDATE)
        return True

    def remove_task(self, t) -> bool:
        """Reference: assignments.go removeTask :256."""
        if t.id not in self.tasks:
            return False
        self.changes[("task", t.id)] = AssignmentChange(
            assignment=Assignment(task=Task(id=t.id)),
            action=AssignmentAction.REMOVE)
        del self.tasks[t.id]
        self._release_task_dependencies(t)
        return True

    # ------------------------------------------------------------------
    def message(self, type: AssignmentsType = AssignmentsType.INCREMENTAL
                ) -> AssignmentsMessage:
        """Drain pending changes (assignments.go message :279)."""
        msg = AssignmentsMessage(type=type, changes=list(self.changes.values()))
        self.changes = {}
        return msg

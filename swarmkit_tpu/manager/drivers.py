"""External secret-driver provider seam.

Reference: manager/drivers/provider.go + secrets.go — a DriverProvider
resolves the Driver named in a SecretSpec to a plugin and fetches the
secret VALUE from it at assignment time (the store only holds the driver
name; the payload never rests in raft). The reference discovers plugins via
docker's plugingetter over HTTP; here drivers are objects registered with
the provider (in-process plugins), keeping the same seam shape:
``provider.new_secret_driver(spec.driver).get(spec, task)``.
"""

from __future__ import annotations

from typing import Protocol

MAX_SECRET_SIZE = 500 * 1024  # reference: validation.MaxSecretSize


class DriverError(Exception):
    pass


class SecretDriver(Protocol):
    """reference: drivers.SecretDriver — Get(spec, task) -> payload."""

    def get(self, spec, task) -> bytes: ...


class DriverProvider:
    """reference: drivers.DriverProvider provider.go."""

    def __init__(self) -> None:
        self._secret_drivers: dict[str, SecretDriver] = {}

    def register_secret_driver(self, name: str, driver: SecretDriver) -> None:
        self._secret_drivers[name] = driver

    def new_secret_driver(self, driver_spec) -> SecretDriver:
        """reference: NewSecretDriver provider.go:21."""
        if driver_spec is None or not driver_spec.name:
            raise DriverError("driver specification is nil")
        d = self._secret_drivers.get(driver_spec.name)
        if d is None:
            raise DriverError(f"secret driver {driver_spec.name!r} "
                              "not registered")
        return d


def resolve_secret(provider, read_tx, task, secret_id):
    """Populate a secret's value — from the store for ordinary secrets,
    from its driver for external ones (reference: assignmentSet.secret
    dispatcher/assignments.go:294-316). Returns a COPY with data filled,
    or raises DriverError."""
    secret = read_tx.get("secret", secret_id)
    if secret is None:
        raise DriverError(f"secret {secret_id} not found")
    if secret.spec.driver is None or not secret.spec.driver.name:
        return secret
    if provider is None:
        raise DriverError(
            f"secret {secret_id} needs driver "
            f"{secret.spec.driver.name!r} but no provider is configured")
    driver = provider.new_secret_driver(secret.spec.driver)
    value = driver.get(secret.spec, task)
    if not isinstance(value, (bytes, bytearray)) \
            or len(value) > MAX_SECRET_SIZE:
        raise DriverError(
            f"driver {secret.spec.driver.name!r} returned an invalid "
            "payload (reference: ValidateSecretPayload)")
    out = secret.copy()
    out.spec.data = bytes(value)
    return out

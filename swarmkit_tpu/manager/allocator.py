"""Network allocator: assigns network resources before tasks can schedule.

Reference: manager/allocator/ (allocator.go actor loop; network.go
doNetworkInit :70 / doNetworkAlloc :164 / doNodeAlloc :307 / doTaskAlloc;
cnmallocator/networkallocator.go IPAM; portallocator.go).  Tasks enter the
cluster in NEW and only become PENDING (schedulable) once every allocator has
acted — here that means: their service's endpoint (VIPs, published ports) and
their network attachments exist.

TPU-era simplification: a flat in-process IPAM — sequential /24 subnets from
10.<n>.0.0, sequential host addresses, and a dynamic published-port range
from 30000 (reference dynamicPortStart portallocator.go) — no external
drivers.  The allocation *protocol* (watch → allocate → PENDING, idempotent
re-allocation on restore) mirrors the reference.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.api.types import (
    Endpoint, EndpointVIP, IPAMConfig, IPAMOptions, NetworkAttachment,
    PortConfig,
)
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.allocator")

DYNAMIC_PORT_START = 30000   # reference: portallocator.go dynamicPortStart
DYNAMIC_PORT_END = 32767
INGRESS_NETWORK_NAME = "ingress"


def _gateway(subnet: str) -> str:
    """NETWORK base address + 1 — the host bits of the spec address are
    masked off first, so 10.5.0.7/24 -> 10.5.0.1 and non-octet-aligned
    subnets work too (192.168.7.128/25 -> 192.168.7.129)."""
    addr, prefix = subnet.split("/")
    parts = [int(x) for x in addr.split(".")]
    raw = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
    v = (raw & ~((1 << (32 - int(prefix))) - 1)) + 1
    return f"{(v >> 24) & 255}.{(v >> 16) & 255}.{(v >> 8) & 255}.{v & 255}"


class PortConflict(Exception):
    """An explicitly requested published port is already taken
    (reference: portallocator.go allocation error)."""


class SubnetExhausted(Exception):
    """A network's subnets have no free host addresses left."""


class _Subnet:
    """One CIDR pool with a sequential cursor (.1 reserved as gateway)."""

    def __init__(self, cidr: str) -> None:
        self.cidr = cidr
        addr, prefix = cidr.split("/")
        self.prefix = int(prefix)
        parts = [int(x) for x in addr.split(".")]
        raw = (parts[0] << 24) | (parts[1] << 16) \
            | (parts[2] << 8) | parts[3]
        self.size = 1 << (32 - self.prefix)
        # normalize to the network base: a spec subnet like 10.5.0.7/24
        # means the 10.5.0.0/24 network (reference IPAM parses CIDRs with
        # net.ParseCIDR, which masks the host bits the same way)
        self.base = raw & ~(self.size - 1)
        self.next_host = 2           # .0 network, .1 gateway
        self.used: set[int] = set()

    def _fmt(self, off: int) -> str:
        v = self.base + off
        return (f"{(v >> 24) & 255}.{(v >> 16) & 255}."
                f"{(v >> 8) & 255}.{v & 255}/{self.prefix}")

    def allocate(self) -> Optional[str]:
        while self.next_host < self.size - 1:   # last addr = broadcast
            off = self.next_host
            self.next_host += 1
            if off not in self.used:
                self.used.add(off)
                return self._fmt(off)
        return None

    def contains(self, addr: str) -> bool:
        parts = [int(x) for x in addr.split("/")[0].split(".")]
        v = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
        return self.base <= v < self.base + self.size

    def note(self, addr: str) -> None:
        parts = [int(x) for x in addr.split("/")[0].split(".")]
        v = (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]
        self.used.add(v - self.base)


class IPAM:
    """Multi-pool IPAM: user-configured subnets (NetworkSpec.ipam configs,
    reference cnmallocator IPAM options) or auto-assigned 10.<n>.0.0/24
    pools; a network GROWS an extra auto pool when its subnets fill
    (reference networks carry multiple IPAMConfig entries)."""

    def __init__(self) -> None:
        self._next_auto = 1
        self._pools: dict[str, list[_Subnet]] = {}

    def subnets(self, network_id: str) -> list[str]:
        return [sn.cidr for sn in self._pools.get(network_id, [])]

    def _overlaps(self, sn: "_Subnet") -> bool:
        for pools in self._pools.values():
            for other in pools:
                if (sn.base < other.base + other.size
                        and other.base < sn.base + sn.size):
                    return True
        return False

    def _auto_cidr(self) -> str:
        cidr = f"10.{self._next_auto}.0.0/24"
        self._next_auto += 1
        return cidr

    def allocate_subnet(self, network_id: str,
                        requested: str = "") -> str:
        return self.allocate_subnets(network_id,
                                     [requested] if requested else [])[0]

    def allocate_subnets(self, network_id: str,
                         requested: list[str]) -> list[str]:
        """Allocate ALL of `requested` (or one auto pool if empty)
        atomically: every subnet is validated against existing pools AND
        each other before any is registered, so a rejection leaks
        nothing."""
        new: list[_Subnet] = []

        def clashes(sn: _Subnet) -> bool:
            return self._overlaps(sn) or any(
                sn.base < o.base + o.size and o.base < sn.base + sn.size
                for o in new)

        for cidr in requested:
            sn = _Subnet(cidr)
            if clashes(sn):
                raise ValueError(
                    f"subnet {cidr} overlaps an allocated pool")
            new.append(sn)
        if not new:
            # auto pools skip over anything a user subnet already covers
            sn = _Subnet(self._auto_cidr())
            while clashes(sn):
                sn = _Subnet(self._auto_cidr())
            new.append(sn)
        self._pools.setdefault(network_id, []).extend(new)
        return [sn.cidr for sn in new]

    def release_network(self, network_id: str) -> None:
        """Drop every pool the network held (network removal) so its
        subnets become allocatable again."""
        self._pools.pop(network_id, None)

    def grow(self, network_id: str) -> str:
        """Append a fresh auto pool once the existing subnets fill."""
        return self.allocate_subnet(network_id)

    def restore_subnet(self, network_id: str, subnet: str) -> None:
        self._pools.setdefault(network_id, []).append(_Subnet(subnet))
        try:
            parts = subnet.split("/")[0].split(".")
            if parts[0] == "10":
                self._next_auto = max(self._next_auto, int(parts[1]) + 1)
        except (ValueError, IndexError):
            pass

    def allocate_address(self, network_id: str) -> str:
        if network_id not in self._pools:
            self.allocate_subnet(network_id)
        for sn in self._pools[network_id]:
            addr = sn.allocate()
            if addr is not None:
                return addr
        raise SubnetExhausted(
            f"network {network_id}: all subnets exhausted")

    def restore_address(self, network_id: str, addr: str) -> None:
        for sn in self._pools.get(network_id, []):
            if sn.contains(addr):
                sn.note(addr)
                return


class _PortSpace:
    """One protocol's port space (reference portallocator.go portSpace):
    a master set holding every allocation 1-65535 plus a dynamic cursor
    over [30000, 32767] that wraps, so churned dynamic ports are reusable
    after release."""

    def __init__(self) -> None:
        self.master: set[int] = set()
        self.cursor = DYNAMIC_PORT_START

    def allocate(self, port: int = 0) -> int:
        if port:
            if port in self.master:
                raise PortConflict(f"port {port} is already published")
            self.master.add(port)
            return port
        span = DYNAMIC_PORT_END - DYNAMIC_PORT_START + 1
        for _ in range(span):
            cand = self.cursor
            self.cursor += 1
            if self.cursor > DYNAMIC_PORT_END:
                self.cursor = DYNAMIC_PORT_START
            if cand not in self.master:
                self.master.add(cand)
                return cand
        raise PortConflict("dynamic port space exhausted")

    def release(self, port: int) -> None:
        self.master.discard(port)


class PortAllocator:
    """Published-port bookkeeping, one space PER PROTOCOL
    (reference: portallocator.go portSpaces map keyed tcp/udp/sctp)."""

    def __init__(self) -> None:
        self._spaces: dict[str, _PortSpace] = {}

    def _space(self, proto: str) -> _PortSpace:
        return self._spaces.setdefault(proto or "tcp", _PortSpace())

    def allocate(self, proto: str, port: int = 0) -> int:
        try:
            return self._space(proto).allocate(port)
        except PortConflict as e:
            raise PortConflict(f"{proto} {e}") from None

    def restore(self, proto: str, port: int) -> None:
        self._space(proto).master.add(port)

    def release(self, proto: str, port: int) -> None:
        self._space(proto).release(port)


class Allocator:
    """reference: allocator.Allocator allocator.go:16 (network actor only —
    the sole actor in the reference too)."""

    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.ipam = IPAM()
        self.ports = PortAllocator()
        self._pending_tasks: set[str] = set()
        self._pending_services: set[str] = set()
        self._pending_networks: set[str] = set()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="task"), match(kind="service"),
                                   match(kind="network"), match_commit)
        # restore state from the store (reference: doNetworkInit network.go:70)
        for net in self.store.find("network"):
            if net.ipam is not None and net.ipam.configs:
                for c in net.ipam.configs:
                    self.ipam.restore_subnet(net.id, c.subnet)
            else:
                self._pending_networks.add(net.id)
        for svc in self.store.find("service"):
            ep = svc.endpoint
            if ep is not None:
                for vip in ep.virtual_ips:
                    self.ipam.restore_address(vip.network_id, vip.addr)
                for p in ep.ports:
                    if p.published_port and p.publish_mode == "ingress":
                        self.ports.restore(p.protocol, p.published_port)
            if not self._service_allocated(svc):
                self._pending_services.add(svc.id)
        for t in self.store.find("task"):
            if t.status.state == TaskState.NEW:
                self._pending_tasks.add(t.id)
            for att in t.networks:
                for addr in att.addresses:
                    self.ipam.restore_address(att.network_id, addr)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            if self._pending_networks or self._pending_services \
                    or self._pending_tasks:
                await self.tick()
            while self._running:
                ev = await watcher.get()
                if isinstance(ev, Event):
                    self._handle(ev)
                elif isinstance(ev, EventCommit) and (
                        self._pending_tasks or self._pending_services
                        or self._pending_networks):
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("allocator crashed")

    def _handle(self, ev: Event) -> None:
        if ev.action == "remove":
            if ev.kind == "service" and ev.object.endpoint is not None:
                for p in ev.object.endpoint.ports:
                    if p.published_port and p.publish_mode == "ingress":
                        self.ports.release(p.protocol, p.published_port)
            elif ev.kind == "network":
                # free the network's subnets so an overlapping (or
                # identical) subnet can be allocated again
                self.ipam.release_network(ev.object.id)
            return
        if ev.kind == "network":
            self._pending_networks.add(ev.object.id)
        elif ev.kind == "service":
            if not self._service_allocated(ev.object):
                self._pending_services.add(ev.object.id)
        elif ev.kind == "task":
            if ev.object.status.state == TaskState.NEW:
                self._pending_tasks.add(ev.object.id)

    # ------------------------------------------------------------------
    def _service_allocated(self, svc) -> bool:
        spec_ep = svc.spec.endpoint
        if spec_ep is None or not spec_ep.ports:
            return True
        if svc.endpoint is None or svc.endpoint.spec is None:
            return False
        if svc.endpoint.spec.to_dict() != spec_ep.to_dict():
            return False  # spec changed since last allocation
        # only ingress-mode ports receive dynamic published ports; host-mode
        # ports without an explicit published_port stay 0 by design
        have = {(p.protocol, p.target_port) for p in svc.endpoint.ports
                if p.published_port}
        want = {(p.protocol, p.target_port) for p in spec_ep.ports
                if p.publish_mode == "ingress" or p.published_port}
        return want <= have

    async def tick(self) -> None:
        nets, self._pending_networks = self._pending_networks, set()
        for nid in nets:
            await self._alloc_network(nid)
        svcs, self._pending_services = self._pending_services, set()
        for sid in svcs:
            await self._alloc_service(sid)
        tasks, self._pending_tasks = self._pending_tasks, set()
        if tasks:
            await self._alloc_tasks(tasks)

    def _address_with_growth(self, tx, network_id: str) -> Optional[str]:
        """Allocate an address, GROWING the network by a fresh auto subnet
        when its pools fill (persisted to the network record so restore
        sees every pool).  None only when growth itself is impossible."""
        try:
            return self.ipam.allocate_address(network_id)
        except SubnetExhausted:
            pass
        subnet = self.ipam.grow(network_id)
        net = tx.get("network", network_id)
        if net is not None:
            if net.ipam is None:
                net.ipam = IPAMOptions(driver="default", configs=[])
            net.ipam.configs.append(IPAMConfig(
                subnet=subnet, gateway=_gateway(subnet)))
            tx.update(net)
        try:
            return self.ipam.allocate_address(network_id)
        except SubnetExhausted:
            return None

    async def _alloc_network(self, network_id: str) -> None:
        """reference: doNetworkAlloc network.go:164 — user-configured
        subnets (spec.ipam, cnmallocator IPAM options) are honored;
        otherwise an auto 10.<n>.0.0/24 pool is assigned."""
        def txn(tx):
            net = tx.get("network", network_id)
            if net is None:
                return
            if net.ipam is not None and net.ipam.configs:
                return  # already allocated
            requested = []
            if net.spec.ipam is not None:
                requested = [c.subnet for c in net.spec.ipam.configs
                             if c.subnet]
            try:
                subnets = self.ipam.allocate_subnets(network_id, requested)
            except ValueError as e:
                # a bad/overlapping user subnet is THIS network's failure,
                # not the allocator loop's: leave the network unallocated
                # and keep serving everyone else (reference: doNetworkAlloc
                # logs and continues, allocator.go actor loop survives)
                log.warning("network %s allocation rejected: %s",
                            network_id, e)
                return
            net.ipam = IPAMOptions(driver="default", configs=[
                IPAMConfig(subnet=sn, gateway=_gateway(sn))
                for sn in subnets])
            tx.update(net)
        await self.store.update(txn)

    async def _alloc_service(self, service_id: str) -> None:
        """Allocate endpoint: published ports + VIPs
        (reference: allocateService networkallocator)."""
        def txn(tx):
            svc = tx.get("service", service_id)
            if svc is None or self._service_allocated(svc):
                return
            spec_ep = svc.spec.endpoint
            ep = svc.endpoint or Endpoint()
            ep.spec = spec_ep.copy()
            existing = {(p.protocol, p.target_port): p for p in ep.ports}
            # decide which current allocations survive the new spec: same
            # mode and either dynamic or the same explicit published port
            reused: set[tuple[str, int]] = set()
            plan: list[tuple] = []  # (spec port, reuse cur | None)
            for p in spec_ep.ports:
                cur = existing.get((p.protocol, p.target_port))
                if (cur is not None and cur.published_port
                        and cur.publish_mode == p.publish_mode
                        and p.published_port in (0, cur.published_port)):
                    plan.append((p, cur))
                    # only ingress ports live in the allocator's books; a
                    # reused host-mode port must not shield a dropped
                    # ingress port with the same number from release
                    if cur.publish_mode == "ingress":
                        reused.add((cur.protocol, cur.published_port))
                else:
                    plan.append((p, None))
            # release ports the new spec dropped or changed BEFORE
            # allocating, so swapping a port within one update works
            # (reference: portallocator serviceDeallocatePorts on update).
            # Only ingress ports live in the allocator's books — host-mode
            # ports are per-node and never tracked.
            released = [(c.protocol, c.published_port)
                        for c in existing.values()
                        if c.published_port and c.publish_mode == "ingress"
                        and (c.protocol, c.published_port) not in reused]
            for proto, port in released:
                self.ports.release(proto, port)
            ports = []
            fresh: list[tuple[str, int]] = []
            for p, cur in plan:
                if cur is not None:
                    ports.append(cur)
                    continue
                try:
                    published = self.ports.allocate(
                        p.protocol, p.published_port) \
                        if p.publish_mode == "ingress" else p.published_port
                except PortConflict as e:
                    # leave the service unallocated; roll back this pass so
                    # the allocator's books match the (unchanged) store
                    # (reference: allocator records the error and retries)
                    for proto, port in fresh:
                        self.ports.release(proto, port)
                    for proto, port in released:
                        self.ports.restore(proto, port)
                    log.warning("service %s: %s", service_id, e)
                    return
                if published and p.publish_mode == "ingress":
                    fresh.append((p.protocol, published))
                ports.append(PortConfig(
                    name=p.name, protocol=p.protocol,
                    target_port=p.target_port, published_port=published,
                    publish_mode=p.publish_mode))
            ep.ports = ports
            # one VIP per attached network (+ ingress implicit for ports)
            want_nets = list(svc.spec.networks) or list(svc.spec.task.networks)
            have_vips = {v.network_id for v in ep.virtual_ips}
            for nid in want_nets:
                if nid not in have_vips:
                    addr = self._address_with_growth(tx, nid)
                    if addr is None:
                        log.warning("service %s VIP: network %s exhausted",
                                    service_id, nid)
                        continue
                    ep.virtual_ips.append(EndpointVIP(network_id=nid,
                                                      addr=addr))
            svc.endpoint = ep
            tx.update(svc)
        await self.store.update(txn)

    async def _alloc_tasks(self, task_ids: set[str]) -> None:
        """reference: doTaskAlloc + taskBallot allocator.go:45 — move NEW
        tasks to PENDING once their resources exist."""
        batch = self.store.batch()
        for tid in task_ids:
            def txn(tx, tid=tid):
                t = tx.get("task", tid)
                if t is None or t.status.state != TaskState.NEW:
                    return
                svc = tx.get("service", t.service_id) if t.service_id else None
                if svc is not None and not self._service_allocated(svc):
                    self._pending_tasks.add(tid)  # retry after service alloc
                    return
                # attach task to its networks
                want = list(t.spec.networks)
                if svc is not None:
                    want = want or list(svc.spec.networks)
                have = {a.network_id for a in t.networks}
                for nid in want:
                    if nid in have:
                        continue
                    net = tx.get("network", nid)
                    if net is None:
                        continue
                    addr = self._address_with_growth(tx, nid)
                    if addr is None:
                        log.warning("task %s: network %s exhausted",
                                    tid, nid)
                        continue
                    drv = ""
                    if net.spec.driver_config is not None:
                        drv = net.spec.driver_config.name
                    t.networks.append(NetworkAttachment(
                        network_id=nid, addresses=[addr], driver=drv))
                if svc is not None and svc.endpoint is not None:
                    t.endpoint = svc.endpoint.copy()
                t.status.state = TaskState.PENDING
                t.status.message = "pending task scheduling"
                t.status.timestamp = self.clock.now()
                tx.update(t)
            await batch.update(txn)
        await batch.commit()

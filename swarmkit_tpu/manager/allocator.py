"""Network allocator: assigns network resources before tasks can schedule.

Reference: manager/allocator/ (allocator.go actor loop; network.go
doNetworkInit :70 / doNetworkAlloc :164 / doNodeAlloc :307 / doTaskAlloc;
cnmallocator/networkallocator.go IPAM; portallocator.go).  Tasks enter the
cluster in NEW and only become PENDING (schedulable) once every allocator has
acted — here that means: their service's endpoint (VIPs, published ports) and
their network attachments exist.

TPU-era simplification: a flat in-process IPAM — sequential /24 subnets from
10.<n>.0.0, sequential host addresses, and a dynamic published-port range
from 30000 (reference dynamicPortStart portallocator.go) — no external
drivers.  The allocation *protocol* (watch → allocate → PENDING, idempotent
re-allocation on restore) mirrors the reference.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.api.types import (
    Endpoint, EndpointVIP, IPAMConfig, IPAMOptions, NetworkAttachment,
    PortConfig,
)
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.allocator")

DYNAMIC_PORT_START = 30000   # reference: portallocator.go dynamicPortStart
DYNAMIC_PORT_END = 32767
INGRESS_NETWORK_NAME = "ingress"


class PortConflict(Exception):
    """An explicitly requested published port is already taken
    (reference: portallocator.go allocation error)."""


class SubnetExhausted(Exception):
    """A network's /24 has no free host addresses left."""


class IPAM:
    """Flat sequential IPAM (cnmallocator analog)."""

    def __init__(self) -> None:
        self._next_subnet = 1
        self._next_host: dict[str, int] = {}   # network id -> next host octet
        self._subnets: dict[str, str] = {}     # network id -> subnet prefix

    def allocate_subnet(self, network_id: str) -> str:
        subnet = f"10.{self._next_subnet}.0.0/24"
        self._next_subnet += 1
        self._subnets[network_id] = subnet
        self._next_host[network_id] = 2  # .1 = gateway
        return subnet

    def restore_subnet(self, network_id: str, subnet: str) -> None:
        self._subnets[network_id] = subnet
        try:
            octet = int(subnet.split(".")[1])
            self._next_subnet = max(self._next_subnet, octet + 1)
        except (ValueError, IndexError):
            pass
        self._next_host.setdefault(network_id, 2)

    def allocate_address(self, network_id: str) -> str:
        if network_id not in self._subnets:
            self.allocate_subnet(network_id)
        base = self._subnets[network_id].rsplit(".", 2)[0]
        host = self._next_host[network_id]
        if host > 254:  # .255 is broadcast; stay inside the /24
            raise SubnetExhausted(
                f"network {network_id}: /24 address space exhausted")
        self._next_host[network_id] = host + 1
        return f"{base}.0.{host}/24"

    def restore_address(self, network_id: str, addr: str) -> None:
        try:
            host_part = addr.split("/")[0].split(".")
            host = int(host_part[2]) * 256 + int(host_part[3])
            self._next_host[network_id] = max(
                self._next_host.get(network_id, 2), host + 1)
        except (ValueError, IndexError):
            pass


class PortAllocator:
    """Published-port bookkeeping (reference: portallocator.go)."""

    def __init__(self) -> None:
        self._allocated: set[tuple[str, int]] = set()
        self._next_dynamic = DYNAMIC_PORT_START

    def allocate(self, proto: str, port: int = 0) -> int:
        if port:
            if (proto, port) in self._allocated:
                raise PortConflict(f"{proto} port {port} is already published")
            self._allocated.add((proto, port))
            return port
        while (proto, self._next_dynamic) in self._allocated:
            self._next_dynamic += 1
            if self._next_dynamic > DYNAMIC_PORT_END:
                raise RuntimeError("dynamic port space exhausted")
        self._allocated.add((proto, self._next_dynamic))
        return self._next_dynamic

    def restore(self, proto: str, port: int) -> None:
        self._allocated.add((proto, port))

    def release(self, proto: str, port: int) -> None:
        self._allocated.discard((proto, port))


class Allocator:
    """reference: allocator.Allocator allocator.go:16 (network actor only —
    the sole actor in the reference too)."""

    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.ipam = IPAM()
        self.ports = PortAllocator()
        self._pending_tasks: set[str] = set()
        self._pending_services: set[str] = set()
        self._pending_networks: set[str] = set()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="task"), match(kind="service"),
                                   match(kind="network"), match_commit)
        # restore state from the store (reference: doNetworkInit network.go:70)
        for net in self.store.find("network"):
            if net.ipam is not None and net.ipam.configs:
                self.ipam.restore_subnet(net.id, net.ipam.configs[0].subnet)
            else:
                self._pending_networks.add(net.id)
        for svc in self.store.find("service"):
            ep = svc.endpoint
            if ep is not None:
                for vip in ep.virtual_ips:
                    self.ipam.restore_address(vip.network_id, vip.addr)
                for p in ep.ports:
                    if p.published_port and p.publish_mode == "ingress":
                        self.ports.restore(p.protocol, p.published_port)
            if not self._service_allocated(svc):
                self._pending_services.add(svc.id)
        for t in self.store.find("task"):
            if t.status.state == TaskState.NEW:
                self._pending_tasks.add(t.id)
            for att in t.networks:
                for addr in att.addresses:
                    self.ipam.restore_address(att.network_id, addr)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            if self._pending_networks or self._pending_services \
                    or self._pending_tasks:
                await self.tick()
            while self._running:
                ev = await watcher.get()
                if isinstance(ev, Event):
                    self._handle(ev)
                elif isinstance(ev, EventCommit) and (
                        self._pending_tasks or self._pending_services
                        or self._pending_networks):
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("allocator crashed")

    def _handle(self, ev: Event) -> None:
        if ev.action == "remove":
            if ev.kind == "service" and ev.object.endpoint is not None:
                for p in ev.object.endpoint.ports:
                    if p.published_port and p.publish_mode == "ingress":
                        self.ports.release(p.protocol, p.published_port)
            return
        if ev.kind == "network":
            self._pending_networks.add(ev.object.id)
        elif ev.kind == "service":
            if not self._service_allocated(ev.object):
                self._pending_services.add(ev.object.id)
        elif ev.kind == "task":
            if ev.object.status.state == TaskState.NEW:
                self._pending_tasks.add(ev.object.id)

    # ------------------------------------------------------------------
    def _service_allocated(self, svc) -> bool:
        spec_ep = svc.spec.endpoint
        if spec_ep is None or not spec_ep.ports:
            return True
        if svc.endpoint is None or svc.endpoint.spec is None:
            return False
        if svc.endpoint.spec.to_dict() != spec_ep.to_dict():
            return False  # spec changed since last allocation
        # only ingress-mode ports receive dynamic published ports; host-mode
        # ports without an explicit published_port stay 0 by design
        have = {(p.protocol, p.target_port) for p in svc.endpoint.ports
                if p.published_port}
        want = {(p.protocol, p.target_port) for p in spec_ep.ports
                if p.publish_mode == "ingress" or p.published_port}
        return want <= have

    async def tick(self) -> None:
        nets, self._pending_networks = self._pending_networks, set()
        for nid in nets:
            await self._alloc_network(nid)
        svcs, self._pending_services = self._pending_services, set()
        for sid in svcs:
            await self._alloc_service(sid)
        tasks, self._pending_tasks = self._pending_tasks, set()
        if tasks:
            await self._alloc_tasks(tasks)

    async def _alloc_network(self, network_id: str) -> None:
        """reference: doNetworkAlloc network.go:164."""
        def txn(tx):
            net = tx.get("network", network_id)
            if net is None:
                return
            if net.ipam is not None and net.ipam.configs:
                return  # already allocated
            subnet = self.ipam.allocate_subnet(network_id)
            net.ipam = IPAMOptions(driver="default", configs=[
                IPAMConfig(subnet=subnet,
                           gateway=subnet.rsplit(".", 2)[0] + ".0.1")])
            tx.update(net)
        await self.store.update(txn)

    async def _alloc_service(self, service_id: str) -> None:
        """Allocate endpoint: published ports + VIPs
        (reference: allocateService networkallocator)."""
        def txn(tx):
            svc = tx.get("service", service_id)
            if svc is None or self._service_allocated(svc):
                return
            spec_ep = svc.spec.endpoint
            ep = svc.endpoint or Endpoint()
            ep.spec = spec_ep.copy()
            existing = {(p.protocol, p.target_port): p for p in ep.ports}
            # decide which current allocations survive the new spec: same
            # mode and either dynamic or the same explicit published port
            reused: set[tuple[str, int]] = set()
            plan: list[tuple] = []  # (spec port, reuse cur | None)
            for p in spec_ep.ports:
                cur = existing.get((p.protocol, p.target_port))
                if (cur is not None and cur.published_port
                        and cur.publish_mode == p.publish_mode
                        and p.published_port in (0, cur.published_port)):
                    plan.append((p, cur))
                    # only ingress ports live in the allocator's books; a
                    # reused host-mode port must not shield a dropped
                    # ingress port with the same number from release
                    if cur.publish_mode == "ingress":
                        reused.add((cur.protocol, cur.published_port))
                else:
                    plan.append((p, None))
            # release ports the new spec dropped or changed BEFORE
            # allocating, so swapping a port within one update works
            # (reference: portallocator serviceDeallocatePorts on update).
            # Only ingress ports live in the allocator's books — host-mode
            # ports are per-node and never tracked.
            released = [(c.protocol, c.published_port)
                        for c in existing.values()
                        if c.published_port and c.publish_mode == "ingress"
                        and (c.protocol, c.published_port) not in reused]
            for proto, port in released:
                self.ports.release(proto, port)
            ports = []
            fresh: list[tuple[str, int]] = []
            for p, cur in plan:
                if cur is not None:
                    ports.append(cur)
                    continue
                try:
                    published = self.ports.allocate(
                        p.protocol, p.published_port) \
                        if p.publish_mode == "ingress" else p.published_port
                except PortConflict as e:
                    # leave the service unallocated; roll back this pass so
                    # the allocator's books match the (unchanged) store
                    # (reference: allocator records the error and retries)
                    for proto, port in fresh:
                        self.ports.release(proto, port)
                    for proto, port in released:
                        self.ports.restore(proto, port)
                    log.warning("service %s: %s", service_id, e)
                    return
                if published and p.publish_mode == "ingress":
                    fresh.append((p.protocol, published))
                ports.append(PortConfig(
                    name=p.name, protocol=p.protocol,
                    target_port=p.target_port, published_port=published,
                    publish_mode=p.publish_mode))
            ep.ports = ports
            # one VIP per attached network (+ ingress implicit for ports)
            want_nets = list(svc.spec.networks) or list(svc.spec.task.networks)
            have_vips = {v.network_id for v in ep.virtual_ips}
            for nid in want_nets:
                if nid not in have_vips:
                    try:
                        addr = self.ipam.allocate_address(nid)
                    except SubnetExhausted as e:
                        log.warning("service %s VIP: %s", service_id, e)
                        continue
                    ep.virtual_ips.append(EndpointVIP(network_id=nid,
                                                      addr=addr))
            svc.endpoint = ep
            tx.update(svc)
        await self.store.update(txn)

    async def _alloc_tasks(self, task_ids: set[str]) -> None:
        """reference: doTaskAlloc + taskBallot allocator.go:45 — move NEW
        tasks to PENDING once their resources exist."""
        batch = self.store.batch()
        for tid in task_ids:
            def txn(tx, tid=tid):
                t = tx.get("task", tid)
                if t is None or t.status.state != TaskState.NEW:
                    return
                svc = tx.get("service", t.service_id) if t.service_id else None
                if svc is not None and not self._service_allocated(svc):
                    self._pending_tasks.add(tid)  # retry after service alloc
                    return
                # attach task to its networks
                want = list(t.spec.networks)
                if svc is not None:
                    want = want or list(svc.spec.networks)
                have = {a.network_id for a in t.networks}
                for nid in want:
                    if nid in have:
                        continue
                    net = tx.get("network", nid)
                    if net is None:
                        continue
                    try:
                        addr = self.ipam.allocate_address(nid)
                    except SubnetExhausted as e:
                        log.warning("task %s: %s", tid, e)
                        continue
                    t.networks.append(NetworkAttachment(
                        network_id=nid, addresses=[addr]))
                if svc is not None and svc.endpoint is not None:
                    t.endpoint = svc.endpoint.copy()
                t.status.state = TaskState.PENDING
                t.status.message = "pending task scheduling"
                t.status.timestamp = self.clock.now()
                tx.update(t)
            await batch.update(txn)
        await batch.commit()

"""Cluster-object metrics collector.

Reference: manager/metrics/collector.go (Collector :42, Run :61) — watches
store events and maintains object-count gauges (nodes by state, tasks by
state, services/networks/secrets/configs totals) for scraping; plus the
``swarm_manager_leader`` gauge set by the manager on leadership flips.

Accounting is INCREMENTAL off the event stream like the reference's
(collector.go handleEvent): a full-store recount per commit deep-copies
every object through the serde layer and was measured at >90% of
control-plane proposal latency once a few hundred objects exist.  A full
recount runs only at start and after a bulk store restore (snapshot
catch-up publishes no per-object events — detected via
``store.restore_generation``).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import NodeState, TaskState
from swarmkit_tpu.store.memory import Event, MemoryStore

log = logging.getLogger("swarmkit_tpu.metrics")

_TOTAL_KINDS = ("service", "network", "secret", "config")


def _node_key(obj) -> str:
    return f"swarm_node_{NodeState(obj.status.state).name.lower()}"


def _task_key(obj) -> str:
    return f"swarm_task_{TaskState(obj.status.state).name.lower()}"


class Collector:
    def __init__(self, store: MemoryStore) -> None:
        self.store = store
        self.gauges: dict[str, float] = {"swarm_manager_leader": 0.0}
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._restore_gen = -1
        self._watcher = None

    def set_leader(self, leader: bool) -> None:
        self.gauges["swarm_manager_leader"] = 1.0 if leader else 0.0

    def snapshot(self) -> dict[str, float]:
        # a bulk restore publishes no per-object events, so on a quiet
        # store nothing would ever wake _run to notice the generation
        # bump — a freshly promoted follower would serve pre-restore
        # counts until the next unrelated commit.  Recount at scrape time
        # instead of waiting for an event.
        if self._watcher is not None \
                and self.store.restore_generation != self._restore_gen:
            self._resync(self._watcher)
        return dict(self.gauges)

    async def start(self) -> None:
        watcher = self._watcher = self.store.watch(
            lambda e: isinstance(e, Event)
            and e.kind in ("node", "task") + _TOTAL_KINDS)
        self._recount()
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            async for ev in watcher:
                if not self._running:
                    return
                if self.store.restore_generation != self._restore_gen:
                    self._resync(watcher)   # bulk restore: from scratch
                elif not self._apply(ev):
                    self._resync(watcher)   # unknown prior state
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("metrics collector crashed")

    def _resync(self, watcher) -> None:
        """Full recount that DISCARDS everything the watcher has buffered:
        the store applies all of a commit's table mutations before
        publishing its events, so any event buffered when the recount runs
        is already reflected in the tables — applying it afterwards would
        double-count (and nothing can commit between poll and recount:
        both are synchronous)."""
        watcher.poll()
        self._recount()

    def _apply(self, ev: Event) -> bool:
        """O(1) gauge adjustment per object event (reference handleEvent).
        Returns False when the event cannot be applied incrementally (an
        update without its previous state) and a resync is required."""
        g = self.gauges
        if ev.kind == "node":
            keyfn = _node_key
        elif ev.kind == "task":
            keyfn = _task_key
        else:
            g[f"swarm_{ev.kind}_total"] = g.get(
                f"swarm_{ev.kind}_total", 0) + (
                1 if ev.action == "create"
                else -1 if ev.action == "remove" else 0)
            return True
        if ev.action == "update" and ev.old_object is None:
            return False   # unknown previous state
        if ev.action in ("update", "remove"):
            old = ev.old_object if ev.action == "update" else ev.object
            k = keyfn(old)
            g[k] = g.get(k, 0) - 1
        if ev.action in ("create", "update"):
            k = keyfn(ev.object)
            g[k] = g.get(k, 0) + 1
        return True

    def _recount(self) -> None:
        self._restore_gen = self.store.restore_generation
        g = self.gauges
        for state in NodeState:
            g[f"swarm_node_{state.name.lower()}"] = 0
        for n in self.store.find("node"):
            g[_node_key(n)] += 1
        for state in TaskState:
            g[f"swarm_task_{state.name.lower()}"] = 0
        for t in self.store.find("task"):
            g[_task_key(t)] += 1
        for kind in _TOTAL_KINDS:
            g[f"swarm_{kind}_total"] = len(self.store.find(kind))

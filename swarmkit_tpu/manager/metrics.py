"""Cluster-object metrics collector.

Reference: manager/metrics/collector.go (Collector :42, Run :61) — watches
store events and maintains object-count gauges (nodes by state, tasks by
state, services/networks/secrets/configs totals) for scraping; plus the
``swarm_manager_leader`` gauge set by the manager on leadership flips.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import NodeState, TaskState
from swarmkit_tpu.store.memory import EventCommit, MemoryStore

log = logging.getLogger("swarmkit_tpu.metrics")


class Collector:
    def __init__(self, store: MemoryStore) -> None:
        self.store = store
        self.gauges: dict[str, float] = {"swarm_manager_leader": 0.0}
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def set_leader(self, leader: bool) -> None:
        self.gauges["swarm_manager_leader"] = 1.0 if leader else 0.0

    def snapshot(self) -> dict[str, float]:
        return dict(self.gauges)

    async def start(self) -> None:
        # one recount per committed transaction, not per object event
        watcher = self.store.watch(lambda e: isinstance(e, EventCommit))
        self._recount()
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            async for ev in watcher:
                if not self._running:
                    return
                # incremental gauges would mirror the reference; a recount
                # per commit is simpler and the store is in-memory
                self._recount()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("metrics collector crashed")

    def _recount(self) -> None:
        g = self.gauges
        for state in NodeState:
            g[f"swarm_node_{state.name.lower()}"] = 0
        for n in self.store.find("node"):
            g[f"swarm_node_{NodeState(n.status.state).name.lower()}"] += 1
        for state in TaskState:
            g[f"swarm_task_{state.name.lower()}"] = 0
        for t in self.store.find("task"):
            g[f"swarm_task_{TaskState(t.status.state).name.lower()}"] += 1
        for kind in ("service", "network", "secret", "config"):
            g[f"swarm_{kind}_total"] = len(self.store.find(kind))

"""Watch API: filtered store event streams with resume-from-version.

Reference: manager/watchapi/server.go (:17) + watch.go — clients subscribe
to (kind, id-prefix/name) selectors; events arrive with the old object when
requested; ``resume_from`` replays history between the requested version
and now via the raft log (store.WatchFrom memory.go:871) before going live.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore

_KIND_ALL = ""


@dataclass
class WatchSelector:
    kind: str = _KIND_ALL
    id_prefix: str = ""
    name: str = ""
    actions: tuple[str, ...] = ()     # subset of create/update/remove


@dataclass
class WatchMessage:
    action: str
    kind: str
    object: object
    old_object: object = None
    version: int = 0


class WatchServer:
    def __init__(self, store: MemoryStore, proposer=None) -> None:
        self.store = store
        self.proposer = proposer   # for changes_between on resume

    def _matches(self, selectors: list[WatchSelector], ev: Event) -> bool:
        if not selectors:
            return True
        for s in selectors:
            if s.kind and ev.kind != s.kind:
                continue
            if s.actions and ev.action not in s.actions:
                continue
            if s.id_prefix and not ev.object.id.startswith(s.id_prefix):
                continue
            if s.name:
                ann = getattr(ev.object, "annotations", None)
                if ann is None or ann.name != s.name:
                    continue
            return True
        return False

    async def watch(self, selectors: Optional[list[WatchSelector]] = None,
                    resume_from: Optional[int] = None,
                    include_old_object: bool = False
                    ) -> AsyncIterator[WatchMessage]:
        """One subscription (reference: watchapi/watch.go Watch RPC)."""
        selectors = selectors or []
        watcher = self.store.watch(
            lambda e: isinstance(e, (Event, EventCommit)))
        version = self.store.version
        try:
            if resume_from is not None and self.proposer is not None:
                for idx, actions in self.proposer.changes_between(
                        resume_from, version):
                    for a in actions:
                        ev = Event(_ACTIONS[a.action], a.kind, a.object())
                        if self._matches(selectors, ev):
                            yield WatchMessage(
                                action=ev.action, kind=ev.kind,
                                object=ev.object, version=idx)
            pending: list[Event] = []
            async for ev in watcher:
                if isinstance(ev, Event):
                    if self._matches(selectors, ev):
                        pending.append(ev)
                    continue
                for p in pending:  # flush on commit with its version
                    yield WatchMessage(
                        action=p.action, kind=p.kind, object=p.object,
                        old_object=(p.old_object if include_old_object
                                    else None),
                        version=ev.version)
                pending = []
        finally:
            watcher.close()


def _action_name(kind_val) -> str:
    from swarmkit_tpu.api.raft_msgs import StoreActionKind

    return {StoreActionKind.CREATE: "create", StoreActionKind.UPDATE: "update",
            StoreActionKind.REMOVE: "remove"}[kind_val]


class _Actions:
    def __getitem__(self, kind_val) -> str:
        return _action_name(kind_val)


_ACTIONS = _Actions()

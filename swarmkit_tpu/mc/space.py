"""The counted branch space: action alphabet, codecs, scope presets.

One model-checking step is "pick action a from a fixed alphabet, apply it
for one tick".  An action is a full per-tick fault assignment drawn from
the `FaultSchedule` vocabulary — crash one row, drop one directed edge,
cut one bipartition, force one row's election timer (term_inflation), or
do nothing — so a branch of depth H is an integer in [0, A^H) read as H
base-A digits, and the entire schedule space at a scope is COUNTED:
exhaustion is a loop bound, not a sampling budget.

The single-fault-per-tick alphabet is the scope's documented coverage
choice (compound faults arise as sequences across ticks: a 3-tick
partition is the same cut chosen 3 times; crash-then-restart is crash_i
followed by any non-crash_i action).  What it deliberately excludes is
SIMULTANEOUS distinct faults within one tick — the standard small-scope
trade (the mCRL2/LNT models' schedules are one-event-per-transition for
the same reason), stated in README "Exhaustive model checking".
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from swarmkit_tpu.dst.schedule import FaultSchedule
from swarmkit_tpu.raft.sim.state import SimConfig


@dataclass(frozen=True)
class Alphabet:
    """The per-tick action tables: action k applies row k of each table.

    names    (A,) action labels ("noop", "crash_1", "drop_0to2",
             "part_0v12", "inflate_2") — also the LTS edge labels.
    alive    [A, n] bool  row liveness under the action
    drop     [A, n, n] bool  directed-edge drops under the action
    inflate  [A, n] bool or None  forced-campaign mask (None when the
             scope excludes term_inflation, keeping the compiled tick
             bit-identical to the pre-extension program)
    """

    n: int
    names: tuple
    alive: np.ndarray
    drop: np.ndarray
    inflate: Optional[np.ndarray]

    @property
    def size(self) -> int:
        return len(self.names)

    def tables(self):
        """Device copies for the compiled expand pass."""
        inflate = None if self.inflate is None else jnp.asarray(self.inflate)
        return jnp.asarray(self.alive), jnp.asarray(self.drop), inflate


def build_alphabet(n: int, *, crashes: bool = True, drops: bool = True,
                   partitions: bool = True,
                   term_inflation: bool = False) -> Alphabet:
    """The full single-fault alphabet for an n-row cluster.

    noop + n crashes + n(n-1) directed drops + (2^(n-1) - 1) bipartitions
    (+ n term inflations): 13 actions at n=3, 24 at n=4, 41 at n=5.
    """
    names = ["noop"]
    alive = [np.ones(n, bool)]
    drop = [np.zeros((n, n), bool)]
    inflate = [np.zeros(n, bool)]
    if crashes:
        for i in range(n):
            a = np.ones(n, bool)
            a[i] = False
            names.append(f"crash_{i}")
            alive.append(a)
            drop.append(np.zeros((n, n), bool))
            inflate.append(np.zeros(n, bool))
    if drops:
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                d = np.zeros((n, n), bool)
                d[i, j] = True
                names.append(f"drop_{i}to{j}")
                alive.append(np.ones(n, bool))
                drop.append(d)
                inflate.append(np.zeros(n, bool))
    if partitions:
        # every bipartition once: enumerate the side NOT containing row 0
        for mask in range(1, 1 << n):
            if mask & 1:
                continue
            side_b = [i for i in range(n) if mask >> i & 1]
            side_a = [i for i in range(n) if not mask >> i & 1]
            d = np.zeros((n, n), bool)
            for i in side_a:
                for j in side_b:
                    d[i, j] = d[j, i] = True
            names.append(f"part_{''.join(map(str, side_a))}"
                         f"v{''.join(map(str, side_b))}")
            alive.append(np.ones(n, bool))
            drop.append(d)
            inflate.append(np.zeros(n, bool))
    if term_inflation:
        for i in range(n):
            m = np.zeros(n, bool)
            m[i] = True
            names.append(f"inflate_{i}")
            alive.append(np.ones(n, bool))
            drop.append(np.zeros((n, n), bool))
            inflate.append(m)
    return Alphabet(
        n=n, names=tuple(names),
        alive=np.stack(alive), drop=np.stack(drop),
        inflate=np.stack(inflate) if term_inflation else None)


def branch_to_path(branch: int, size: int, depth: int) -> list:
    """Base-`size` digits of `branch`, tick 0 first (little-endian)."""
    if not 0 <= branch < size ** depth:
        raise ValueError(f"branch {branch} outside [0, {size}^{depth})")
    path = []
    for _ in range(depth):
        path.append(branch % size)
        branch //= size
    return path


def path_to_branch(path, size: int) -> int:
    """Inverse of `branch_to_path` (python int — A^H overflows i64 fast)."""
    branch = 0
    for a in reversed(list(path)):
        if not 0 <= a < size:
            raise ValueError(f"action {a} outside alphabet of {size}")
        branch = branch * size + a
    return branch


def path_to_schedule(alphabet: Alphabet, path) -> FaultSchedule:
    """Lower an action path to a replayable single FaultSchedule [T, ...].

    The lowered schedule drives `dst.repro.replay` through the exact
    `_tick_one` program the scan's expand pass compiled, so a violating
    branch reproduces bit-identically — and flows through the standard
    shrink / flight-capture / artifact pipeline unchanged.
    """
    path = list(path)
    ticks = len(path)
    drop = np.stack([alphabet.drop[a] for a in path]) if ticks else \
        np.zeros((0, alphabet.n, alphabet.n), bool)
    alive = np.stack([alphabet.alive[a] for a in path]) if ticks else \
        np.ones((0, alphabet.n), bool)
    ti = None
    if alphabet.inflate is not None:
        ti = jnp.asarray(np.stack([alphabet.inflate[a] for a in path])
                         if ticks else np.zeros((0, alphabet.n), bool))
    return FaultSchedule(
        drop=jnp.asarray(drop), alive=jnp.asarray(alive),
        target_leader=jnp.zeros((ticks,), bool),
        crash_campaign=jnp.zeros((ticks,), bool),
        term_inflate=ti)


# ---------------------------------------------------------------------------
# documented scope presets (PERF.md carries the measured branches/s and
# frontier-memory table per scope)


@dataclass(frozen=True)
class Scope:
    """One documented model-checking scope.

    `budget` is the default frontier cap (None = exhaustive); scopes whose
    raw frontier outgrows one host are shipped budget-bounded and their
    summaries say so (`exhaustive: false`, truncation counts per level).
    """

    name: str
    n: int
    horizon: int
    term_inflation: bool = False
    budget: Optional[int] = None
    prop_count: int = 1

    def alphabet(self) -> Alphabet:
        return build_alphabet(self.n, term_inflation=self.term_inflation)

    def cfg(self) -> SimConfig:
        # Small-scope tick config: election_tick=2 keeps randomized
        # timeouts in [2, 4), so elections, commits and re-elections all
        # fit inside an 8-tick horizon; the read path is armed
        # (read_batch=1) so LINEARIZABLE_READ is checked and the
        # stale_lease_read mutation self-test has a surface.  The log
        # ring is the smallest legal shape for 1 proposal/tick
        # (log_len > keep + 2*max_props + window).
        return SimConfig(n=self.n, log_len=32, window=4, apply_batch=4,
                         max_props=4, keep=2, election_tick=2,
                         read_batch=1)

    def space_size(self) -> int:
        return self.alphabet().size ** self.horizon


SCOPES = {
    # tier-1 smoke: seconds on one CPU core; also the .aut export scope
    "smoke": Scope(name="smoke", n=3, horizon=4),
    # the headline exhaustive claim: full crash/partition/drop alphabet,
    # 13^8 =~ 8.2e8 schedules collapsing to ~3.5M explored branches over
    # ~1.3M distinct reachable states; ~2 min on one CPU core
    "n3h8": Scope(name="n3h8", n=3, horizon=8),
    # widened branch alphabet (+ term_inflation, A=16); same horizon
    "n3h8t": Scope(name="n3h8t", n=3, horizon=8, term_inflation=True),
    # deeper horizon, budget-bounded (the level-9+ frontier outgrows the
    # exhaustive claim; truncation is logged per level)
    "n3h12": Scope(name="n3h12", n=3, horizon=12, budget=1 << 20),
    # wider cluster, budget-bounded (A=24)
    "n4h8": Scope(name="n4h8", n=4, horizon=8, budget=1 << 20),
}

"""swarm_mc_* metric names — the device vocabulary's scrape-side schema.

``tools/metrics_lint.py`` check #7 pins these constants to the catalog in
both directions (every constant has a spec with exactly these labels,
every swarm_mc_* spec has a constant), the same lockstep discipline the
flight recorder (check #5) and telemetry plane (check #6) get.
"""

METRIC_BRANCHES = "swarm_mc_branches_total"
METRIC_STATES = "swarm_mc_states_total"
METRIC_VIOLATIONS = "swarm_mc_violations_total"
METRIC_BRANCH_RATE = "swarm_mc_branches_per_second"
METRIC_FRONTIER_PEAK = "swarm_mc_frontier_peak_states"
METRIC_TRUNCATIONS = "swarm_mc_truncations_total"

# name -> required label names, exactly as the catalog must declare them
METRIC_NAMES = {
    METRIC_BRANCHES: ("result",),          # clean | violation
    METRIC_STATES: ("kind",),              # unique | duplicate
    METRIC_VIOLATIONS: ("invariant",),     # dst BIT_NAMES values
    METRIC_BRANCH_RATE: ("scope",),
    METRIC_FRONTIER_PEAK: ("scope",),
    METRIC_TRUNCATIONS: ("scope",),
}

# one valid value per label, for the lint's publishability probe
SAMPLE_LABELS = {
    "result": "clean",
    "kind": "unique",
    "invariant": "election_safety",
    "scope": "n3h8",
}

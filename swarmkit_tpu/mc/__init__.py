"""Exhaustive on-device model checking for tiny clusters.

Where :mod:`swarmkit_tpu.dst` SAMPLES fault schedules (256 counter-seeded
adversaries x 100 ticks), this package ENUMERATES them: every per-tick
fault action from a counted alphabet (crash / directed drop / partition /
optional term_inflation, the FaultSchedule vocabulary), every sequence up
to a small horizon — the explicit-state discipline of the mCRL2/LNT Raft
models (PAPERS.md arXiv:2403.18916, arXiv:2004.13284) run against the
REAL tick kernel instead of a hand-written abstraction, by vmapping
``raft/sim/kernel.step`` over a [B, N, ...] frontier of reachable states.

Layout:

- :mod:`space`       — the action alphabet (integer -> per-tick fault
  arrays), branch/path codecs, the lowering of a violating branch to a
  replayable `FaultSchedule`, and the documented scope presets.
- :mod:`fingerprint` — Zobrist-style SimState hashing (order-salted
  hash32 fold, 64-bit), node-relabeling and the optional symmetry-
  canonical fingerprint.
- :mod:`frontier`    — `exhaustive_scan()`: the batched BFS driver
  (frontier expand -> invariant bitmask -> fingerprint dedup -> next
  level), `--budget` truncation, LTS edge collection, and the violation
  -> shrink -> artifact -> flight-recorder pipeline reusing dst/repro.
- :mod:`metrics`     — the swarm_mc_* metric-name constants pinned to
  the catalog by ``tools/metrics_lint.py`` check #7.

Soundness notes: the tick kernel is PURE in (state, action) — the PRNG is
counter-based and ``tick`` is part of SimState — so two states with equal
fingerprints have identical futures and exact-fingerprint dedup preserves
the full reachable set (fingerprints are 64-bit Zobrist hashes; collision
odds at the documented scopes are ~1e-6, and any collision only MERGES
states, i.e. could hide but never fabricate a violation).  The symmetry
(node-relabeling) reduction is NOT exact — ``rand_timeout`` keys on the
row index, so relabeled states draw different timeouts — and is therefore
an opt-in heuristic, off for every headline claim.
"""

from swarmkit_tpu.mc.space import (
    SCOPES, Alphabet, Scope, branch_to_path, build_alphabet, path_to_branch,
    path_to_schedule,
)
from swarmkit_tpu.mc.fingerprint import (
    canonical_fingerprint, fingerprint, relabel_state,
)
from swarmkit_tpu.mc.frontier import (
    ScanResult, exhaustive_scan, violation_artifact,
)
from swarmkit_tpu.mc.metrics import METRIC_NAMES

__all__ = [
    "SCOPES", "Alphabet", "Scope", "branch_to_path", "build_alphabet",
    "path_to_branch", "path_to_schedule",
    "canonical_fingerprint", "fingerprint", "relabel_state",
    "ScanResult", "exhaustive_scan", "violation_artifact",
    "METRIC_NAMES",
]

"""SimState fingerprints: order-salted hash32 folds (Zobrist hashing).

A fingerprint must be (a) computable on device inside the vmapped expand
pass, (b) position-sensitive (swapping two rows' terms must change it),
and (c) stable across processes — it feeds the dedup sets, the LTS node
ids, and the cross-process stability test.  The construction is the model
checker's classic Zobrist form: every uint32 word of the flattened state
is XOR'd in as ``hash32(word ^ hash32(position))``, so each (position,
value) pair contributes an independent pseudo-random mask and the fold is
one vectorized hash + XOR-reduce, no sequential chain.  Two such folds
with different salt constants give 64 bits: at the documented scopes
(~1e6 states) the birthday bound is ~1e-7, and a collision can only MERGE
two states (under-approximation — may hide, never fabricate, a
violation).

Everything here keys off `hash32` (raft/sim/state.py) — integer math
only, independent of PYTHONHASHSEED and process identity.

Fingerprints are comparable only between states of the SAME SimConfig:
the flattened word stream is the register_dataclass leaf order, and which
Optional field groups exist (reads, telemetry, mailboxes) is a cfg
choice.
"""

from __future__ import annotations

import dataclasses
import itertools

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.state import SimState, hash32

U32 = jnp.uint32

_SALT1 = 0x9E3779B9   # golden-ratio constants, distinct per fold
_SALT2 = 0x6A09E667


def _words(state: SimState) -> jax.Array:
    """[W] uint32: every leaf raveled and bit-widened, field order."""
    ws = [leaf.astype(U32).ravel()
          for leaf in jax.tree_util.tree_leaves(state)]
    return jnp.concatenate(ws)


def fingerprint(state: SimState) -> jax.Array:
    """[2] uint32 (hi, lo) fingerprint of ONE state; vmap for a frontier."""
    w = _words(state)
    pos = jnp.arange(w.size, dtype=U32)
    h1 = hash32(w ^ hash32(pos + U32(_SALT1)))
    h2 = hash32((w + U32(_SALT2)) ^ hash32(pos ^ U32(_SALT2)))
    f1 = jax.lax.reduce(h1, U32(0), jax.lax.bitwise_xor, (0,))
    f2 = jax.lax.reduce(h2, U32(0), jax.lax.bitwise_xor, (0,))
    return jnp.stack([f1, f2])


# ---------------------------------------------------------------------------
# node relabeling (the optional symmetry reduction)

# [N, N(, K)] leaves permute BOTH leading axes; these carry node indices
# as VALUES and remap them through the inverse permutation (NONE = -1
# passes through).  Every other non-global field is a plain [N, ...] row
# permute.
_PAIR_FIELDS = frozenset((
    "match", "next_", "granted", "rejected", "recent_active", "member",
    "vreq_at", "vreq_term", "vreq_pre", "vresp_at", "vresp_term",
    "vresp_grant", "vresp_pre", "app_at", "app_prev", "app_term",
    "snp_at", "snp_term", "probing", "aresp_at", "aresp_term",
    "aresp_match", "aresp_ok", "hb_at", "hb_term", "hb_commit",
    "hbr_at", "hbr_term",
))
_INDEX_VALUED = frozenset(("vote", "lead", "transferee", "tn_from"))
_GLOBAL_FIELDS = frozenset((
    "tick", "stats", "tel_commit_hist", "tel_elect_hist", "tel_read_hist",
    "tel_series",
))


def relabel_state(state: SimState, perm) -> SimState:
    """Relabel nodes: new row k is old row perm[k], index values follow.

    NOT behavior-preserving in general: ``rand_timeout(cfg, node, term)``
    keys on the ROW INDEX, so a relabeled state draws different future
    election timeouts than the original (its `timeout` field keeps the
    permuted historical draws).  That is exactly why the symmetry-
    canonical dedup below is an opt-in heuristic rather than part of the
    exhaustive claim.
    """
    n = state.vote.shape[-1]
    perm = jnp.asarray(perm, jnp.int32)
    inv = jnp.zeros((n,), jnp.int32).at[perm].set(
        jnp.arange(n, dtype=jnp.int32))

    def remap(a):
        return jnp.where(a >= 0, inv[jnp.clip(a, 0, n - 1)], a)

    out = {}
    for f in dataclasses.fields(state):
        v = getattr(state, f.name)
        if v is None or f.name in _GLOBAL_FIELDS:
            out[f.name] = v
        elif f.name in _PAIR_FIELDS:
            out[f.name] = jnp.take(jnp.take(v, perm, axis=0), perm, axis=1)
        elif f.name in _INDEX_VALUED:
            out[f.name] = jnp.take(remap(v), perm, axis=0)
        else:
            out[f.name] = jnp.take(v, perm, axis=0)
    return SimState(**out)


def canonical_fingerprint(state: SimState, n: int) -> jax.Array:
    """[2] uint32: lexicographic minimum of `fingerprint` over all n!
    node relabelings — symmetric states collapse to one value.  Opt-in
    (``exhaustive_scan(symmetry=True)``): see `relabel_state` for why
    this reduction is a heuristic against the real kernel."""
    best = None
    for perm in itertools.permutations(range(n)):
        fp = fingerprint(relabel_state(state, perm))
        if best is None:
            best = fp
        else:
            less = (fp[0] < best[0]) | ((fp[0] == best[0])
                                        & (fp[1] < best[1]))
            best = jnp.where(less, fp, best)
    return best

"""The batched frontier driver: exhaustive BFS over the fault-branch space.

One level of the search expands every frontier state under every alphabet
action in wide compiled device passes — the SAME `_tick_one` program the
DST explorer scans (kernel step + fused propose + mutation hook + the
invariant bitmask), vmapped over a [B, N, ...] frontier instead of a
[S, N, ...] schedule batch, with the fingerprint fold fused into the pass
so the host only ever sees [B] bitmasks and [B, 2] fingerprints, never
the states.  Children deduplicate by exact fingerprint: the kernel is
pure in (state, action) and `tick` is part of the state, so equal
fingerprints mean equal futures and per-level dedup preserves the full
reachable set (states of different depths can never collide — their tick
words differ).

A violating child is never expanded further; its action path is lowered
back to a `FaultSchedule` (space.path_to_schedule) and handed to the
standard dst/repro pipeline — replay, shrink, flight-recorder capture,
seed-pinned JSON artifact — so a model-checker counterexample is the same
one-command regression a DST counterexample is.

`budget` caps the per-level frontier: once a level holds that many unique
states, further fresh children are DROPPED and counted — the summary then
says ``exhaustive: false`` with per-level truncation counts, never
silently narrowing a claim.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from swarmkit_tpu import parallel
from swarmkit_tpu.dst.explore import _tick_one, broadcast_state
from swarmkit_tpu.dst.invariants import ALL_BITS, BIT_NAMES, bits_to_names, \
    check_state
from swarmkit_tpu.dst.schedule import FaultSchedule
from swarmkit_tpu.mc import metrics as mc_metrics
from swarmkit_tpu.mc.fingerprint import canonical_fingerprint, fingerprint
from swarmkit_tpu.mc.space import Alphabet, path_to_branch, path_to_schedule
from swarmkit_tpu.raft.sim.state import SimConfig, init_state


@partial(jax.jit, static_argnames=("cfg", "prop_count", "mutation",
                                   "symmetry"))
def _expand(states, aids, alive_tab, drop_tab, inflate_tab,
            cfg: SimConfig, prop_count: int, mutation: Optional[str],
            symmetry: bool):
    """One device pass: step every (state, action) pair one tick.

    Returns (child states, violation bits [W], fingerprints [W, 2])."""

    def one(st, aid):
        sched_t = FaultSchedule(
            drop=drop_tab[aid], alive=alive_tab[aid],
            target_leader=jnp.zeros((), bool),
            crash_campaign=jnp.zeros((), bool),
            term_inflate=None if inflate_tab is None else inflate_tab[aid])
        new, bits = _tick_one(st, cfg, sched_t, prop_count, mutation)
        fp = canonical_fingerprint(new, cfg.n) if symmetry \
            else fingerprint(new)
        return new, bits, fp

    return jax.vmap(one)(states, aids)


@dataclass
class ScanResult:
    """Everything `exhaustive_scan` learned, JSON-able via `summary()`."""

    scope: str
    n: int
    horizon: int
    alphabet_size: int
    action_names: tuple
    prop_count: int
    mutation: Optional[str]
    symmetry: bool
    budget: Optional[int]
    schedule_space: int          # A^horizon (python int — can be huge)
    branches_explored: int = 0   # real (state, action) expansions
    passes: int = 0              # compiled device invocations
    max_branches_per_pass: int = 0
    states_discovered: int = 1   # unique reachable states incl. the root
    frontier_peak: int = 1
    duplicates: int = 0          # children merged into an existing state
    truncated: bool = False      # any level hit the budget cap
    stopped_early: bool = False  # stop_on_violation fired
    levels: list = field(default_factory=list)
    violations: list = field(default_factory=list)
    elapsed: float = 0.0
    branches_per_sec: float = 0.0
    edges: Optional[list] = None  # (src_id, action_idx, dst_id)
    num_states: int = 0           # LTS node count (edge mode only)

    @property
    def exhaustive(self) -> bool:
        """True iff every branch in the A^H space was covered (up to
        state merging): no budget truncation, no early stop."""
        return not self.truncated and not self.stopped_early

    def summary(self) -> dict:
        return {
            "scope": self.scope, "n": self.n, "horizon": self.horizon,
            "alphabet": list(self.action_names),
            "alphabet_size": self.alphabet_size,
            "prop_count": self.prop_count, "mutation": self.mutation,
            "symmetry": self.symmetry, "budget": self.budget,
            "schedule_space": self.schedule_space,
            "branches_explored": self.branches_explored,
            "passes": self.passes,
            "max_branches_per_pass": self.max_branches_per_pass,
            "states_discovered": self.states_discovered,
            "frontier_peak": self.frontier_peak,
            "duplicates": self.duplicates,
            "exhaustive": self.exhaustive,
            "truncated": self.truncated,
            "stopped_early": self.stopped_early,
            "levels": self.levels,
            "violations": [
                {k: v for k, v in viol.items() if k != "path"}
                | {"path": [int(a) for a in viol["path"]]}
                for viol in self.violations],
            "elapsed_sec": round(self.elapsed, 3),
            "branches_per_sec": round(self.branches_per_sec, 1),
        }


def _fp64(fp2: np.ndarray) -> np.ndarray:
    """[W, 2] uint32 device fingerprints -> [W] uint64 host keys."""
    return (fp2[:, 0].astype(np.uint64) << np.uint64(32)) \
        | fp2[:, 1].astype(np.uint64)


def exhaustive_scan(cfg: SimConfig, alphabet: Alphabet, horizon: int, *,
                    prop_count: int = 1, mutation: Optional[str] = None,
                    budget: Optional[int] = None,
                    pass_small: int = 4096, pass_large: int = 1 << 20,
                    collect_edges: bool = False, symmetry: bool = False,
                    stop_on_violation: bool = True,
                    max_violations: int = 8, shard: bool = True,
                    scope: str = "custom", obs=None,
                    log=None) -> ScanResult:
    """BFS the reachable states of (cfg, alphabet) to `horizon` ticks.

    Small levels run in `pass_small`-wide device passes, big levels in
    `pass_large`-wide ones (two compiled programs total per config) —
    size `pass_large` so the big levels put >= 1M real branches in one
    pass.  Violating children are recorded (capped at `max_violations`)
    and pruned; with `stop_on_violation` the scan finishes the current
    level and stops.  `collect_edges` additionally numbers every reached
    state and records (src, action, dst) transitions — the LTS the
    ``tools/mc_export.py`` Aldebaran writer emits; meant for smoke-sized
    scopes (the edge list is host memory and python-loop time).
    """
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.metrics import registry as obs_registry

    A = alphabet.size
    alive_tab, drop_tab, inflate_tab = alphabet.tables()
    t0 = time.monotonic()

    result = ScanResult(
        scope=scope, n=cfg.n, horizon=horizon, alphabet_size=A,
        action_names=alphabet.names, prop_count=prop_count,
        mutation=mutation, symmetry=symmetry, budget=budget,
        schedule_space=A ** horizon)

    root = init_state(cfg)
    root_bits = int(np.asarray(check_state(root, cfg)))
    if root_bits:
        result.violations.append({
            "level": 0, "path": [], "branch": 0, "bits": root_bits,
            "invariants": bits_to_names(root_bits)})
        result.stopped_early = True

    frontier = broadcast_state(root, 1)
    paths = np.zeros((1, 0), np.int16)
    fp_to_id: dict = {}
    ids = None
    if collect_edges:
        result.edges = []
        root_fp = int(_fp64(np.asarray(fingerprint(root))[None, :])[0])
        fp_to_id[root_fp] = 0
        ids = np.zeros((1,), np.int64)

    ndev = len(jax.devices())
    meshes: dict = {}

    for level in range(1, horizon + 1):
        if result.stopped_early:
            break
        F = paths.shape[0]
        C = F * A
        W = pass_small if C <= pass_small else pass_large
        last_level = level == horizon

        seen = np.empty((0,), np.uint64)   # this level's unique keys
        blocks, block_paths, block_ids = [], [], []
        lvl_unique = lvl_dups = lvl_viol = lvl_trunc = 0

        for g0 in range(0, C, W):
            real = min(W, C - g0)
            g = np.arange(g0, g0 + real, dtype=np.int64)
            pidx = np.zeros((W,), np.int32)
            aid = np.zeros((W,), np.int32)
            pidx[:real] = g // A
            aid[:real] = g % A

            chunk = jax.tree_util.tree_map(
                lambda a: jnp.take(a, jnp.asarray(pidx), axis=0), frontier)
            aids = jnp.asarray(aid)
            if shard and ndev > 1 and W % ndev == 0:
                mesh = meshes.get(W)
                if mesh is None:
                    mesh = meshes[W] = parallel.schedule_mesh(W)
                chunk, aids = parallel.shard_rows(
                    (chunk, aids), mesh, axis=parallel.SCHEDULE_AXIS)
            new, bits, fps = _expand(chunk, aids, alive_tab, drop_tab,
                                     inflate_tab, cfg, prop_count,
                                     mutation, symmetry)
            result.passes += 1
            result.branches_explored += real
            result.max_branches_per_pass = max(
                result.max_branches_per_pass, real)

            bits_h = np.asarray(jax.device_get(bits))[:real]
            keys = _fp64(np.asarray(jax.device_get(fps)))[:real]

            viol_pos = np.nonzero(bits_h)[0]
            lvl_viol += int(viol_pos.size)
            for k in viol_pos[:max(0, max_violations
                                   - len(result.violations))]:
                path = [int(a) for a in paths[pidx[k]]] + [int(aid[k])]
                result.violations.append({
                    "level": level, "path": path,
                    "branch": path_to_branch(path, A),
                    "bits": int(bits_h[k]),
                    "invariants": bits_to_names(int(bits_h[k]))})

            clean_pos = np.nonzero(bits_h == 0)[0]
            vals = keys[clean_pos]
            uniq_vals, uniq_first = np.unique(vals, return_index=True)
            if seen.size:
                pos = np.searchsorted(seen, uniq_vals)
                known = (pos < seen.size) \
                    & (seen[np.minimum(pos, seen.size - 1)] == uniq_vals)
            else:
                known = np.zeros(uniq_vals.shape, bool)
            fresh_pos = clean_pos[uniq_first[~known]]
            order = np.argsort(fresh_pos)
            fresh_pos = fresh_pos[order]
            lvl_dups += int(clean_pos.size - fresh_pos.size)

            if budget is not None and lvl_unique + fresh_pos.size > budget:
                room = max(0, budget - lvl_unique)
                lvl_trunc += int(fresh_pos.size - room)
                fresh_pos = fresh_pos[:room]
                result.truncated = True
            lvl_unique += int(fresh_pos.size)
            seen = np.union1d(seen, keys[fresh_pos])

            if collect_edges:
                # python loop: edge mode is for smoke-sized scopes
                kept = set(int(x) for x in fresh_pos)
                child_ids = np.empty((real,), np.int64)
                for k in range(real):
                    key_k = int(keys[k])
                    cid = fp_to_id.get(key_k)
                    if cid is None:
                        cid = len(fp_to_id)
                        fp_to_id[key_k] = cid
                    child_ids[k] = cid
                    result.edges.append(
                        (int(ids[pidx[k]]), int(aid[k]), cid))
                block_ids.append(child_ids[fresh_pos])

            if fresh_pos.size and not last_level:
                ui = jnp.asarray(fresh_pos.astype(np.int32))
                blocks.append(jax.tree_util.tree_map(
                    lambda a: jnp.take(a, ui, axis=0), new))
                block_paths.append(np.concatenate(
                    [paths[pidx[fresh_pos]],
                     aid[fresh_pos, None].astype(np.int16)], axis=1))

        result.states_discovered += lvl_unique
        result.frontier_peak = max(result.frontier_peak, lvl_unique)
        result.levels.append({
            "level": level, "frontier": F, "children": C,
            "unique": lvl_unique, "duplicates": lvl_dups,
            "violations": lvl_viol, "truncated": lvl_trunc})
        if log is not None:
            log(f"mc[{scope}] level {level}/{horizon}: children={C:,} "
                f"unique={lvl_unique:,} violations={lvl_viol} "
                + (f"TRUNCATED {lvl_trunc:,} (budget {budget:,})"
                   if lvl_trunc else ""))

        if lvl_viol and stop_on_violation:
            result.stopped_early = True
        if last_level or result.stopped_early or not blocks:
            break
        frontier = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *blocks)
        paths = np.concatenate(block_paths, axis=0)
        if collect_edges:
            ids = np.concatenate(block_ids, axis=0)

    result.elapsed = time.monotonic() - t0
    result.branches_per_sec = result.branches_explored / result.elapsed \
        if result.elapsed > 0 else float("inf")
    if collect_edges:
        result.num_states = len(fp_to_id)

    obs = obs or obs_registry.DEFAULT
    viol_children = sum(lv["violations"] for lv in result.levels)
    m = catalog.get(obs, mc_metrics.METRIC_BRANCHES)
    if result.branches_explored - viol_children:
        m.labels(result="clean").inc(result.branches_explored
                                     - viol_children)
    if viol_children:
        m.labels(result="violation").inc(viol_children)
    m = catalog.get(obs, mc_metrics.METRIC_STATES)
    m.labels(kind="unique").inc(result.states_discovered)
    result.duplicates = sum(lv["duplicates"] for lv in result.levels)
    if result.duplicates:
        m.labels(kind="duplicate").inc(result.duplicates)
    m = catalog.get(obs, mc_metrics.METRIC_VIOLATIONS)
    seen_bits = 0
    for viol in result.violations:
        seen_bits |= viol["bits"]
    for bit in ALL_BITS:
        if seen_bits & bit:
            m.labels(invariant=BIT_NAMES[bit]).inc()
    catalog.get(obs, mc_metrics.METRIC_BRANCH_RATE).labels(
        scope=scope).set(result.branches_per_sec)
    catalog.get(obs, mc_metrics.METRIC_FRONTIER_PEAK).labels(
        scope=scope).set(result.frontier_peak)
    trunc = sum(lv["truncated"] for lv in result.levels)
    if trunc:
        catalog.get(obs, mc_metrics.METRIC_TRUNCATIONS).labels(
            scope=scope).inc(trunc)
    return result


def violation_artifact(cfg: SimConfig, alphabet: Alphabet, violation: dict,
                       *, prop_count: int = 1,
                       mutation: Optional[str] = None,
                       scope: str = "custom", do_shrink: bool = True,
                       flight: bool = True, obs=None) -> dict:
    """Lower one scan violation to a standard seed-pinned repro artifact.

    The branch path becomes a FaultSchedule, replays through the same
    compiled tick program (bits and first tick must land exactly where
    the scan found them), shrinks greedily, and is captured with the
    flight recorder — the identical pipeline DST counterexamples ride,
    so ``tools/dst_sweep.py --replay`` re-runs model-checker repros too.
    """
    from swarmkit_tpu.dst import repro

    sched = path_to_schedule(alphabet, violation["path"])
    bits, first = repro.replay(cfg, sched, prop_count, mutation)
    evals = 0
    if do_shrink and bits:
        sched, evals = repro.shrink(cfg, sched, bits, prop_count,
                                    mutation, obs=obs)
        bits, first = repro.replay(cfg, sched, prop_count, mutation)
    fl = None
    if flight:
        fl = repro.capture_flight(cfg, sched, prop_count, mutation,
                                  first_tick=first,
                                  trigger="mc_violation", obs=obs)
    art = repro.to_artifact(
        cfg, sched, seed=0, profile=f"mc:{scope}",
        index=violation["branch"], prop_count=prop_count,
        mutation=mutation, viol=bits, first_tick=first, flight=fl)
    art["mc"] = {
        "scope": scope, "level": violation["level"],
        "path": [int(a) for a in violation["path"]],
        "actions": [alphabet.names[a] for a in violation["path"]],
        "scan_bits": violation["bits"],
        "shrink_evals": evals,
    }
    return art

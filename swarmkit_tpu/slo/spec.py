"""Declarative SLO catalog for the fleet health plane.

An `SloSpec` names one service-level objective over the serving plane and
the burn-rate alerting policy that guards it.  The semantics follow the
multi-window burn-rate recipe (Google SRE workbook ch. 5): every scrape
contributes a (bad, total) event pair per group; the burn rate over a
window is

    burn = (sum(bad) / sum(total)) / budget

i.e. 1.0 means the group is consuming its error budget exactly at the
allowed rate, ``page_burn`` means it is burning that many times faster.
Alerting requires BOTH a fast window (catches sudden cliffs quickly) and
a slow window (suppresses one-scrape blips) to exceed the threshold — see
slo/engine.py for the ok -> warn -> page state machine and its
hysteresis.

Two reading styles map onto the same (bad, total) shape:

- **ratio SLOs** count real events: read_block_ratio's scrape reading is
  (reads blocked, reads attempted); commit_p99's is (commit observations
  above the latency threshold, commit observations).
- **threshold SLOs** grade the scrape itself: fsync_lag reads (1, 1) when
  the group's durability lag exceeds the bound and (0, 1) otherwise, so
  the budget is the tolerated fraction of bad SCRAPES.  leader_churn
  counts changes against a per-scrape allowance the same way.

Budgets and windows here are tuned for the simulation's scrape cadence
(one scrape per a-few-hundred-ticks chunk), not wall-clock minutes; the
catalog is data, so a deployment with a different cadence builds its own
tuple and hands it to `SloEngine`.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SloSpec:
    """One objective + its multi-window burn-rate alerting policy.

    budget: allowed bad/total fraction (the error budget per unit of
    traffic — or per scrape, for threshold-style SLOs).
    fast_window / slow_window: evaluation windows in SCRAPES; both must
    exceed the burn threshold to change state (fast_window <= slow_window).
    warn_burn / page_burn: burn-rate thresholds for the two alert levels.
    clear_scrapes: consecutive calm scrapes (both windows below
    warn_burn) required to step DOWN one level — the hysteresis that
    stops a flapping group from paging repeatedly.
    """

    name: str
    description: str
    budget: float
    fast_window: int = 3
    slow_window: int = 12
    warn_burn: float = 2.0
    page_burn: float = 6.0
    clear_scrapes: int = 3

    def __post_init__(self) -> None:
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"{self.name}: budget must be in (0, 1], "
                             f"got {self.budget}")
        if not 0 < self.fast_window <= self.slow_window:
            raise ValueError(f"{self.name}: need 0 < fast_window <= "
                             f"slow_window, got {self.fast_window} / "
                             f"{self.slow_window}")
        if not 0.0 < self.warn_burn <= self.page_burn:
            raise ValueError(f"{self.name}: need 0 < warn_burn <= "
                             f"page_burn, got {self.warn_burn} / "
                             f"{self.page_burn}")
        if self.clear_scrapes < 1:
            raise ValueError(f"{self.name}: clear_scrapes must be >= 1, "
                             f"got {self.clear_scrapes}")


# The default fleet objectives.  Sources for each reading live in
# slo/source.py (FleetSource); an SLO whose inputs are off (telemetry,
# read path, storage model, router) simply produces no readings and the
# engine leaves it untouched.
SLO_CATALOG = (
    SloSpec(
        "commit_p99",
        "Propose-to-commit latency: the fraction of commit observations "
        "above the p99 latency bound stays within budget.",
        budget=0.05),
    SloSpec(
        "read_block_ratio",
        "Linearizable read availability: reads refused (deposal / lease "
        "expiry) as a fraction of reads attempted stays within budget.",
        budget=0.05),
    # threshold-style budgets must leave page_burn reachable: one
    # (bad, total) pair per scrape caps the burn at 1/budget, so 0.10
    # pages (burn 10 > 6) when most scrapes are bad — 0.25 would cap
    # the burn at 4 and make `page` unreachable
    SloSpec(
        "fsync_lag",
        "Durability lag: scrapes where a group's appended-but-unsynced "
        "window exceeds the configured bound stay within budget.",
        budget=0.10),
    SloSpec(
        "leader_churn",
        "Leadership stability: leader changes per scrape stay within "
        "the churn allowance.",
        budget=0.10),
    SloSpec(
        "spill_ratio",
        "Router capacity: keys spilled past a flush as a fraction of "
        "keys offered stays within budget.",
        budget=0.10),
)

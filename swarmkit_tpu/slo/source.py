"""FleetSource: grouped state + router -> per-SLO (bad, total) readings.

The seam between the device plane and the burn-rate evaluator: one
``scrape(gstate, router=...)`` call pulls the handful of per-group
aggregates off device, deltas the cumulative ones against the previous
scrape (re-baselining on decrease, the metrics/scrape.py reset rule), and
returns ``{slo_name: [G, 2] (bad, total)}`` arrays in exactly the shape
`SloEngine.observe` consumes.

Each SLO's reading rides a subsystem that may be off; a dark input means
the SLO is simply ABSENT from the scrape (the engine freezes its state)
rather than read-as-zero — a fleet without the storage model should not
accrue a spotless fsync_lag record:

- commit_p99      <- per-group telemetry histograms (collect_telemetry)
- read_block_ratio <- read_srv / read_block leaves (read_batch > 0)
- fsync_lag       <- sync_mark durability watermark (storage model on)
- leader_churn    <- group_leaders diff (always available)
- spill_ratio     <- Router per-group flow counters (router passed)
"""

from __future__ import annotations

import jax
import numpy as np

from swarmkit_tpu.multiraft.group import group_leaders
from swarmkit_tpu.raft.sim.state import SimConfig, SimState
from swarmkit_tpu.telemetry import series as tseries

# Fallback thresholds when the config does not pin its own device-side
# SLO bounds (cfg.slo_p99_commit_ticks / cfg.slo_fsync_lag == 0 = off).
DEFAULT_COMMIT_P99_TICKS = 8
DEFAULT_FSYNC_LAG_TICKS = 16


def _delta(prev: np.ndarray | None, cur: np.ndarray) -> np.ndarray:
    """Cumulative -> per-scrape delta; first scrape is the baseline,
    decreases re-baseline to the full reading (fresh state)."""
    if prev is None:
        return np.zeros_like(cur)
    d = cur - prev
    return np.where(d >= 0, d, cur)


class FleetSource:
    """Stateful per-scrape reading producer for one grouped fleet.

    Thresholds default from the config's own device-side SLO bounds
    (``slo_p99_commit_ticks`` / ``slo_fsync_lag``) when those are set,
    else to the module defaults — so a config that already declares its
    latency objective is graded against the SAME number host-side.
    """

    def __init__(self, cfg: SimConfig,
                 commit_p99_ticks: int | None = None,
                 fsync_lag_ticks: int | None = None) -> None:
        self.cfg = cfg
        self.commit_p99_ticks = (
            commit_p99_ticks if commit_p99_ticks is not None
            else (cfg.slo_p99_commit_ticks or DEFAULT_COMMIT_P99_TICKS))
        self.fsync_lag_ticks = (
            fsync_lag_ticks if fsync_lag_ticks is not None
            else (cfg.slo_fsync_lag or DEFAULT_FSYNC_LAG_TICKS))
        # first histogram bucket whose upper edge exceeds the bound:
        # observations landing there or above are "bad"
        edges = tseries.LATENCY_BUCKET_EDGES
        self._bad_bucket = next(
            (i for i, e in enumerate(edges) if e > self.commit_p99_ticks),
            len(edges) - 1)
        self._prev_hist: np.ndarray | None = None
        self._prev_blocked: np.ndarray | None = None
        self._prev_served: np.ndarray | None = None
        self._prev_leaders: np.ndarray | None = None
        self._prev_routed: np.ndarray | None = None
        self._prev_spilled: np.ndarray | None = None

    def scrape(self, gstate: SimState, router=None) -> dict:
        """One scrape: {slo_name: [G, 2] float64 (bad, total)}."""
        out = {}

        if gstate.tel_commit_hist is not None:
            hist = np.asarray(jax.device_get(gstate.tel_commit_hist),
                              np.float64)
            d = _delta(self._prev_hist, hist)
            self._prev_hist = hist
            out["commit_p99"] = np.stack(
                [d[:, self._bad_bucket:].sum(axis=1), d.sum(axis=1)],
                axis=1)

        if gstate.read_srv is not None and gstate.read_block is not None:
            served = np.asarray(jax.device_get(
                gstate.read_srv.sum(axis=-1)), np.float64)
            blocked = np.asarray(jax.device_get(
                gstate.read_block.sum(axis=-1)), np.float64)
            bad = _delta(self._prev_blocked, blocked)
            ok = _delta(self._prev_served, served)
            self._prev_blocked, self._prev_served = blocked, served
            out["read_block_ratio"] = np.stack([bad, bad + ok], axis=1)

        if gstate.sync_mark is not None:
            lag = np.asarray(jax.device_get(
                (gstate.last - gstate.sync_mark).max(axis=-1)), np.float64)
            bad = (lag > self.fsync_lag_ticks).astype(np.float64)
            out["fsync_lag"] = np.stack(
                [bad, np.ones_like(bad)], axis=1)

        leaders = np.asarray(jax.device_get(group_leaders(gstate)))
        if self._prev_leaders is not None:
            changed = ((leaders >= 0) & (leaders != self._prev_leaders)
                       ).astype(np.float64)
            out["leader_churn"] = np.stack(
                [changed, np.ones_like(changed)], axis=1)
        self._prev_leaders = leaders

        if router is not None:
            routed = np.asarray(router.routed_by_group, np.float64)
            spilled = np.asarray(router.spilled_by_group, np.float64)
            bad = _delta(self._prev_spilled, spilled)
            offered = _delta(self._prev_routed, routed)
            self._prev_routed, self._prev_spilled = routed, spilled
            out["spill_ratio"] = np.stack(
                [bad, np.maximum(offered, bad)], axis=1)

        return out

"""Multi-window burn-rate evaluator: readings -> alert state + metrics.

`SloEngine` is the host-side state machine of the fleet health plane.
Each scrape, the caller hands it per-SLO ``[G, 2]`` (bad, total) reading
arrays (slo/source.py produces them from a grouped state + router); the
engine folds them into per-(SLO, group) ring buffers, computes fast- and
slow-window burn rates, and walks the alert state machine:

    ok --(both windows >= warn_burn)--> warn
    ok/warn --(both windows >= page_burn)--> page
    any --(clear_scrapes consecutive calm scrapes)--> one level down

Escalation is immediate (a cliff can jump ok -> page in one scrape once
both windows agree); de-escalation is deliberately slow and one level at
a time — the hysteresis that keeps a flapping group from re-paging on
every oscillation.  Windows may be PARTIALLY filled: a brand-new fleet
can page on its very first scrapes if the readings are bad enough, which
is the behavior you want for a group born into an outage.

``METRIC_NAMES`` is the scrape-side schema; tools/metrics_lint.py check
#13 pins it to the catalog in both directions, the same lockstep the
multiraft plane (#11) gets.  Every transition also appends a flightrec-
style host alert record to ``self.alerts`` (bounded deque) so DST
artifacts and the swarm_top alerts panel can show WHAT fired and WHEN
without scraping the registry.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from swarmkit_tpu.slo.spec import SLO_CATALOG, SloSpec

METRIC_STATE = "swarm_slo_state"
METRIC_BURN = "swarm_slo_burn_rate"
METRIC_TRANSITIONS = "swarm_slo_transitions_total"

# name -> required label names, exactly as the catalog must declare them
METRIC_NAMES = {
    METRIC_STATE: ("slo", "group"),
    METRIC_BURN: ("slo", "group", "window"),      # fast | slow
    METRIC_TRANSITIONS: ("slo", "group", "state"),  # state ENTERED
}

# one valid value per label, for the lint's publishability probe
SAMPLE_LABELS = {
    "slo": "commit_p99",
    "group": "0",
    "window": "fast",
    "state": "page",
}

STATE_NAMES = ("ok", "warn", "page")
OK, WARN, PAGE = 0, 1, 2

# Per-group SLO families label by (slo, group[, window]); with the full
# default catalog the burn family holds len(catalog) * G * 2 label sets
# against the registry's MAX_LABEL_SETS cap, so per-group metric
# publishing is gated on G.  Evaluation and alert records never gate.
GROUP_LABEL_CAP = 16


class _SloState:
    """Ring of (bad, total) readings + alert state for one SLO."""

    def __init__(self, spec: SloSpec, groups: int) -> None:
        self.spec = spec
        self.ring = np.zeros((groups, spec.slow_window, 2), np.float64)
        self.filled = 0                    # scrapes folded, saturating
        self.pos = 0
        self.state = np.zeros((groups,), np.int64)
        self.calm = np.zeros((groups,), np.int64)

    def burn(self, window: int) -> np.ndarray:
        """[G] burn rate over the last `window` folded scrapes."""
        take = min(self.filled, window)
        if take == 0:
            return np.zeros((self.ring.shape[0],), np.float64)
        idx = [(self.pos - 1 - i) % self.spec.slow_window
               for i in range(take)]
        win = self.ring[:, idx, :]
        bad, total = win[:, :, 0].sum(axis=1), win[:, :, 1].sum(axis=1)
        frac = np.divide(bad, total, out=np.zeros_like(bad),
                         where=total > 0)
        return frac / self.spec.budget

    def push(self, readings: np.ndarray) -> None:
        self.ring[:, self.pos, :] = readings
        self.pos = (self.pos + 1) % self.spec.slow_window
        self.filled = min(self.filled + 1, self.spec.slow_window)


class SloEngine:
    """Evaluates an SLO catalog over per-scrape (bad, total) readings.

    >>> eng = SloEngine(registry=reg)
    >>> fired = eng.observe({"leader_churn": readings})   # [G, 2] array
    >>> eng.active()       # [{"slo": ..., "group": 3, "state": "page"}]

    `observe` returns the alert records newly fired by this scrape (state
    transitions only — a group that stays paged returns nothing new).
    Per-SLO group counts are sized from the first reading for that SLO;
    a reshaped fleet resets that SLO's windows and state.
    """

    def __init__(self, catalog=SLO_CATALOG, registry=None,
                 max_alerts: int = 256) -> None:
        from swarmkit_tpu.metrics import catalog as obs_catalog
        from swarmkit_tpu.metrics import registry as obs_registry

        self.catalog = {spec.name: spec for spec in catalog}
        self.obs = registry or obs_registry.DEFAULT
        self._m = {name: obs_catalog.get(self.obs, name)
                   for name in METRIC_NAMES}
        self._slos: dict[str, _SloState] = {}
        self.alerts: deque = deque(maxlen=max_alerts)
        self.scrapes = 0

    def _slo(self, name: str, groups: int) -> _SloState:
        st = self._slos.get(name)
        if st is None or st.ring.shape[0] != groups:
            st = _SloState(self.catalog[name], groups)
            self._slos[name] = st
        return st

    def observe(self, readings: dict) -> list:
        """Fold one scrape of {slo_name: [G, 2] (bad, total)} readings.

        Unknown SLO names raise (a typo'd source would otherwise silently
        never alert); catalog SLOs absent from `readings` keep their
        state frozen.  Returns the alert records fired by this scrape.
        """
        self.scrapes += 1
        fired = []
        for name, arr in readings.items():
            spec = self.catalog.get(name)
            if spec is None:
                raise KeyError(f"reading for unknown SLO {name!r}; "
                               f"catalog has {sorted(self.catalog)}")
            arr = np.asarray(arr, np.float64)
            if arr.ndim != 2 or arr.shape[1] != 2:
                raise ValueError(f"{name}: readings must be [G, 2] "
                                 f"(bad, total), got shape {arr.shape}")
            st = self._slo(name, arr.shape[0])
            st.push(arr)
            fast = st.burn(spec.fast_window)
            slow = st.burn(spec.slow_window)
            fired.extend(self._advance(st, fast, slow))
            self._publish(st, fast, slow)
        return fired

    def _advance(self, st: _SloState, fast, slow) -> list:
        spec, fired = st.spec, []
        for g in range(st.state.shape[0]):
            cur = int(st.state[g])
            if fast[g] >= spec.page_burn and slow[g] >= spec.page_burn:
                new, st.calm[g] = PAGE, 0
            elif fast[g] >= spec.warn_burn and slow[g] >= spec.warn_burn:
                new, st.calm[g] = max(cur, WARN), 0
            elif fast[g] < spec.warn_burn and slow[g] < spec.warn_burn:
                st.calm[g] += 1
                new = cur
                if cur > OK and st.calm[g] >= spec.clear_scrapes:
                    new, st.calm[g] = cur - 1, 0
            else:                      # windows disagree: hold, not calm
                new, st.calm[g] = cur, 0
            if new != cur:
                st.state[g] = new
                rec = {"scrape": self.scrapes, "slo": spec.name,
                       "group": g, "from": STATE_NAMES[cur],
                       "to": STATE_NAMES[new],
                       "fast_burn": round(float(fast[g]), 3),
                       "slow_burn": round(float(slow[g]), 3)}
                self.alerts.append(rec)
                fired.append(rec)
        return fired

    def _publish(self, st: _SloState, fast, slow) -> None:
        groups = st.state.shape[0]
        if groups > GROUP_LABEL_CAP:
            return
        name = st.spec.name
        for g in range(groups):
            gl = str(g)
            self._m[METRIC_STATE].labels(slo=name, group=gl).set(
                int(st.state[g]))
            burn = self._m[METRIC_BURN]
            burn.labels(slo=name, group=gl, window="fast").set(
                round(float(fast[g]), 6))
            burn.labels(slo=name, group=gl, window="slow").set(
                round(float(slow[g]), 6))
        # transitions publish from the alert records of this scrape
        for rec in list(self.alerts):
            if rec["scrape"] == self.scrapes and rec["slo"] == name:
                self._m[METRIC_TRANSITIONS].labels(
                    slo=name, group=str(rec["group"]),
                    state=rec["to"]).inc()

    def state_of(self, slo: str, group: int) -> str:
        """Current alert state name for one (SLO, group)."""
        st = self._slos.get(slo)
        if st is None:
            return STATE_NAMES[OK]
        return STATE_NAMES[int(st.state[group])]

    def active(self) -> list:
        """Every (SLO, group) currently above ok, pages first."""
        out = []
        for name, st in sorted(self._slos.items()):
            for g in np.nonzero(st.state > OK)[0]:
                out.append({"slo": name, "group": int(g),
                            "state": STATE_NAMES[int(st.state[g])]})
        out.sort(key=lambda r: (r["state"] != "page", r["slo"],
                                r["group"]))
        return out

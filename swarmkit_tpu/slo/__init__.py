"""Fleet health plane: declarative SLOs + multi-window burn-rate alerting.

The host-side alerting layer over the multi-raft serving plane (ISSUE
20): slo/spec.py declares WHAT is promised (the `SLO_CATALOG` of five
objectives — commit latency, read availability, durability lag, leader
stability, router capacity), slo/source.py reads the per-group evidence
off a grouped state + router each scrape, and slo/engine.py grades it
with fast/slow burn-rate windows and an ok -> warn -> page state machine
with hysteresis, publishing ``swarm_slo_*`` and appending host alert
records.  tools/swarm_top.py renders the active alerts as a panel.
"""

from swarmkit_tpu.slo.engine import (
    METRIC_NAMES, SAMPLE_LABELS, STATE_NAMES, SloEngine,
)
from swarmkit_tpu.slo.source import FleetSource
from swarmkit_tpu.slo.spec import SLO_CATALOG, SloSpec

__all__ = [
    "METRIC_NAMES", "SAMPLE_LABELS", "SLO_CATALOG", "STATE_NAMES",
    "FleetSource", "SloEngine", "SloSpec",
]

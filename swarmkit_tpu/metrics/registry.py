"""Dependency-free metrics registry: Counter / Gauge / Histogram with
labels and Prometheus text exposition.

Reference: the prometheus client the Go codebase hangs its collectors on
(manager/metrics/collector.go, manager/state/raft/raft.go:69-71) — this is
the stdlib-only re-expression for the asyncio build.  It subsumes the two
pre-existing partial surfaces:

- ``swarmkit_tpu.utils.metrics`` (reservoir latency timers) renders into
  the same exposition via :func:`swarmkit_tpu.metrics.exposition.render_all`
  as Prometheus summaries, keeping its reference-compatible metric names;
- ``swarmkit_tpu.manager.metrics.Collector`` (store-event object gauges)
  renders as untyped gauges next to the typed families here.

Every metric family has mandatory help text (enforced — the lint in
tools/metrics_lint.py walks registries and the catalog), and label
cardinality is bounded per family so an instrumentation bug (e.g. a
session id used as a label) fails loudly instead of leaking memory.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Iterable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram bucket upper bounds (seconds): the prometheus client
# defaults, which bracket everything from sub-ms store commits to multi-
# second XLA compiles.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Per-family bound on distinct label-value sets.  Generous for real usage
# (peers in a quorum, transport wires, kernel phases) but small enough that
# an unbounded label (task ids, timestamps) trips it within one test run.
MAX_LABEL_SETS = 256

# Label-value every over-cap series collapses into (non-strict registries).
OVERFLOW_LABEL_VALUE = "~overflow~"


class MetricError(Exception):
    """Registration or usage error (duplicate/conflicting family, bad
    name, missing help text)."""


class LabelCardinalityError(MetricError):
    """A family exceeded MAX_LABEL_SETS distinct label-value sets."""


def escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def escape_help(v: str) -> str:
    """HELP-line escaping per the 0.0.4 text format: backslash and
    newline only (quotes stay literal in HELP, unlike label values)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    if v != v:                      # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_suffix(label_names: Sequence[str], label_values: Sequence[str]
                   ) -> str:
    if not label_names:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(str(v))}"'
                     for k, v in zip(label_names, label_values))
    return "{" + inner + "}"


class _Child:
    """One (family, label-values) time series."""

    __slots__ = ("_value",)

    def __init__(self) -> None:
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters only go up")
        self._value += amount


class GaugeChild(_Child):
    __slots__ = ("_value", "_fn")

    def __init__(self) -> None:
        super().__init__()
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._fn = None
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._fn = None
        self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Lazily computed gauge: `fn` is called at collection time.  A
        raising callback reads as the last set value — scrapes must never
        take a component down."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                self._value = float(self._fn())
            except Exception:
                pass
        return self._value


class HistogramChild:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]) -> None:
        self.buckets = buckets          # sorted upper bounds, no +Inf
        self.counts = [0] * (len(buckets) + 1)   # last = overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, edge in enumerate(self.buckets):
            if value <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def observe_bucket(self, index: int, count: int = 1,
                       value: Optional[float] = None) -> None:
        """Bulk-merge `count` pre-bucketed observations into bucket
        `index` (len(buckets) = overflow).  For publishers whose source
        is already a bucketed device histogram (telemetry plane): the
        per-observation values are gone, so `sum` is approximated by the
        bucket's upper edge unless the caller supplies a better `value`
        per observation."""
        if not 0 <= index < len(self.counts):
            raise MetricError(
                f"bucket index {index} out of range 0..{len(self.counts) - 1}")
        if count < 0:
            raise MetricError("histogram bucket counts only go up")
        self.counts[index] += count
        self.count += count
        if value is None:
            value = self.buckets[min(index, len(self.buckets) - 1)]
        self.sum += count * float(value)

    def cumulative(self) -> list[int]:
        out, running = [], 0
        for c in self.counts:
            running += c
            out.append(running)
        return out

    def time(self) -> "_HistogramTimer":
        """Context manager: observe the wall-clock duration of a block."""
        return _HistogramTimer(self)


class MetricFamily:
    """One named metric with a fixed label schema and N children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = (),
                 max_label_sets: int = MAX_LABEL_SETS,
                 strict: bool = False) -> None:
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name {name!r}")
        if not help or not help.strip():
            raise MetricError(f"metric {name!r} needs non-empty help text")
        for ln in label_names:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise MetricError(f"invalid label name {ln!r} on {name!r}")
        self.name = name
        self.help = help.strip()
        self.label_names = tuple(label_names)
        self.max_label_sets = max_label_sets
        self.strict = strict
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labels):
        if set(labels) != set(self.label_names):
            raise MetricError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"schema is {sorted(self.label_names)}")
        key = tuple(str(labels[ln]) for ln in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if len(self._children) >= self.max_label_sets:
                        if self.strict:
                            raise LabelCardinalityError(
                                f"{self.name}: more than "
                                f"{self.max_label_sets} label sets — "
                                f"unbounded label value?")
                        # Non-strict (production default): a cardinality
                        # bug degrades the data, never the instrumented
                        # component — excess series collapse into one
                        # reserved overflow series.
                        key = (OVERFLOW_LABEL_VALUE,) * len(self.label_names)
                        child = self._children.get(key)
                        if child is None:
                            child = self._children[key] = self._new_child()
                        return child
                    child = self._children[key] = self._new_child()
        return child

    def _default(self):
        """The label-less series (only valid when the schema is empty)."""
        if self.label_names:
            raise MetricError(f"{self.name} has labels "
                              f"{self.label_names}; use .labels()")
        return self.labels()

    def clear(self) -> None:
        with self._lock:
            self._children.clear()

    # -- exposition --------------------------------------------------------
    def header(self) -> list[str]:
        return [f"# HELP {self.name} {escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}"]

    def sample_lines(self) -> list[str]:
        out = []
        for key in sorted(self._children):
            child = self._children[key]
            out.append(f"{self.name}{_labels_suffix(self.label_names, key)} "
                       f"{format_value(child.value)}")
        return out

    def render(self) -> list[str]:
        return self.header() + self.sample_lines()

    def snapshot(self):
        if not self.label_names:
            c = self._children.get(())
            return c.value if c is not None else 0.0
        return {",".join(f"{k}={v}" for k, v in zip(self.label_names, key)):
                child.value for key, child in sorted(self._children.items())}


class Counter(MetricFamily):
    kind = "counter"

    def _new_child(self) -> CounterChild:
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(MetricFamily):
    kind = "gauge"

    def _new_child(self) -> GaugeChild:
        return GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(MetricFamily):
    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 max_label_sets: int = MAX_LABEL_SETS,
                 strict: bool = False) -> None:
        super().__init__(name, help, label_names,
                         max_label_sets=max_label_sets, strict=strict)
        b = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not b:
            raise MetricError(f"{name}: histogram needs at least one bucket")
        if any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise MetricError(f"{name}: bucket edges must strictly increase")
        self.buckets = b

    def _new_child(self) -> HistogramChild:
        return HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def observe_bucket(self, index: int, count: int = 1,
                       value: Optional[float] = None) -> None:
        self._default().observe_bucket(index, count, value)

    def time(self):
        """Context manager: observe the wall-clock duration of a block on
        the label-less series."""
        return _HistogramTimer(self._default())

    def sample_lines(self) -> list[str]:
        out = []
        for key in sorted(self._children):
            child = self._children[key]
            cum = child.cumulative()
            for edge, c in zip(self.buckets, cum):
                lk = _labels_suffix(self.label_names + ("le",),
                                    key + (format_value(edge),))
                out.append(f"{self.name}_bucket{lk} {c}")
            lk = _labels_suffix(self.label_names + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{lk} {cum[-1]}")
            ls = _labels_suffix(self.label_names, key)
            out.append(f"{self.name}_sum{ls} {format_value(child.sum)}")
            out.append(f"{self.name}_count{ls} {child.count}")
        return out

    def snapshot(self):
        def one(child):
            return {"count": child.count, "sum": round(child.sum, 6)}
        if not self.label_names:
            c = self._children.get(())
            return one(c) if c is not None else {"count": 0, "sum": 0.0}
        return {",".join(f"{k}={v}" for k, v in zip(self.label_names, key)):
                one(child) for key, child in sorted(self._children.items())}


class _HistogramTimer:
    __slots__ = ("_child", "_start")

    def __init__(self, child: HistogramChild) -> None:
        self._child = child
        self._start = 0.0

    def __enter__(self) -> "_HistogramTimer":
        import time
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        import time
        self._child.observe(time.perf_counter() - self._start)


class MetricsRegistry:
    """Process- or component-scoped collection of metric families.

    ``counter/gauge/histogram`` are get-or-create: re-registering the same
    (name, kind, labels) schema returns the existing family so independent
    components can share series; a conflicting schema raises MetricError.
    """

    def __init__(self, strict: bool = False) -> None:
        # strict: label-cardinality overflow raises instead of collapsing
        # into the overflow series (tests and the lint opt in).
        self.strict = strict
        self._families: dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str,
                       label_names: Sequence[str], **kw) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if (type(fam) is not cls
                        or fam.label_names != tuple(label_names)):
                    raise MetricError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, requested "
                        f"{cls.kind}{tuple(label_names)}")
                return fam
            fam = cls(name, help, label_names, strict=self.strict, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str, labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not Histogram \
                        or fam.label_names != tuple(labels):
                    raise MetricError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}")
                return fam
            fam = Histogram(name, help, labels, buckets=buckets,
                            strict=self.strict)
            self._families[name] = fam
            return fam

    # -- views -------------------------------------------------------------
    def families(self) -> Iterable[MetricFamily]:
        return [self._families[k] for k in sorted(self._families)]

    def get(self, name: str) -> Optional[MetricFamily]:
        return self._families.get(name)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for fam in self.families():
            lines.extend(fam.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-able {name: value | {labelset: value}} view (the
        BENCH_*.json-compatible dump)."""
        return {fam.name: fam.snapshot() for fam in self.families()}

    def reset(self) -> None:
        with self._lock:
            self._families.clear()


# The process-global default: kernel/bench/tool metrics land here; per-node
# components take a registry argument and fall back to this.
DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return DEFAULT

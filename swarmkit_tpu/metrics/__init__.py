"""Framework-wide observability: metrics registry, catalog, tracing,
and Prometheus exposition.

Quick tour::

    from swarmkit_tpu import metrics as obs

    reg = obs.MetricsRegistry()              # or obs.default_registry()
    c = obs.catalog_get(reg, "swarm_raft_elections_won_total")
    c.labels(node="m1").inc()
    text = reg.render()                      # Prometheus text format
    data = reg.snapshot()                    # JSON-able dict

    with obs.default_tracer().span("raft.propose", node="m1") as sp:
        ...                                  # sp.span_id propagates via
                                             # contextvars to nested spans

Components accept an optional registry/tracer and fall back to the
process-global defaults, so tests can hand each cluster a fresh registry
while production shares one scrape surface per process.
"""

from .catalog import CATALOG, MetricSpec
from .catalog import get as catalog_get
from .exposition import render_all, snapshot_all
from .registry import (DEFAULT_BUCKETS, MAX_LABEL_SETS, Counter, Gauge,
                       Histogram, LabelCardinalityError, MetricError,
                       MetricsRegistry, default_registry)
from .trace import (Span, Tracer, current_span, current_span_id,
                    default_tracer, iter_ancestry)

__all__ = [
    "CATALOG", "MetricSpec", "catalog_get",
    "render_all", "snapshot_all",
    "DEFAULT_BUCKETS", "MAX_LABEL_SETS",
    "Counter", "Gauge", "Histogram",
    "LabelCardinalityError", "MetricError", "MetricsRegistry",
    "default_registry",
    "Span", "Tracer", "current_span", "current_span_id", "default_tracer",
    "iter_ancestry",
]

"""Cumulative-to-delta conversion for device-counter publishers.

The kernel keeps *cumulative* counters on device (SimState.stats, the
read tallies, the telemetry histograms).  Registry counters are also
cumulative — so a publisher that calls ``fam.inc(cumulative)`` on every
scrape double-counts the entire history each time.  KernelObs originally
guarded this with per-instance ``_last`` lists, which breaks as soon as
two publisher instances feed the same registry (bench.py builds a fresh
KernelObs per measure() call): each instance re-baselines at zero and
re-adds the other's history.

The fix lives here, once, shared by KernelObs and TelemetryObs: one
:class:`CounterDeltas` table *per registry* (weakly keyed, so throwaway
test registries are collectible), keyed by series identity, converting a
cumulative reading into the increment since the previous scrape of that
registry — regardless of which publisher instance does the scraping.

Reset semantics: a cumulative reading *below* the previous one means a
new run (fresh SimState, counters restart at zero).  We re-baseline and
return the full reading, so the first scrape of a new run is counted
rather than silently dropped.  Within one run device counters are
monotone, so this never misfires mid-run.
"""

from __future__ import annotations

import threading
import weakref

from .registry import MetricsRegistry


class CounterDeltas:
    """Per-registry last-seen table for cumulative device counters."""

    def __init__(self) -> None:
        self._last: dict[tuple, int] = {}
        self._lock = threading.Lock()

    def advance(self, key: tuple, cumulative: int) -> int:
        """Record `cumulative` for series `key`; return the delta since
        the previous reading (or the full reading after a reset)."""
        cumulative = int(cumulative)
        with self._lock:
            prev = self._last.get(key, 0)
            self._last[key] = cumulative
        return cumulative - prev if cumulative >= prev else cumulative


_PER_REGISTRY: "weakref.WeakKeyDictionary[MetricsRegistry, CounterDeltas]" \
    = weakref.WeakKeyDictionary()
_GUARD = threading.Lock()


def deltas_for(registry: MetricsRegistry) -> CounterDeltas:
    """The (single) delta table attached to `registry`."""
    with _GUARD:
        table = _PER_REGISTRY.get(registry)
        if table is None:
            table = _PER_REGISTRY[registry] = CounterDeltas()
        return table

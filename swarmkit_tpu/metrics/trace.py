"""Lightweight structured trace/event layer.

Spans are cheap structured events — not a distributed tracer.  A span has a
hex id, an optional parent, a name, attributes, and wall-clock bounds; the
current span propagates through ``contextvars`` so async call chains (raft
proposal -> transport send, dispatcher session -> heartbeat) pick up their
parent automatically, and span ids can be carried across process hops as
plain strings in message payloads.

Finished spans land in a bounded ring per :class:`Tracer`; tests and
``Manager.metrics_snapshot()`` read them back with :meth:`Tracer.finished`.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterator, Optional

_SPAN_COUNTER = itertools.count(1)
_CURRENT_SPAN: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("swarmkit_current_span", default=None)

MAX_FINISHED_SPANS = 512


def _new_span_id() -> str:
    # Counter-based, not random: ids only need uniqueness within a process
    # lifetime, and determinism keeps seed-pinned test output stable.
    return f"{next(_SPAN_COUNTER):012x}"


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration": self.duration,
            "attrs": dict(self.attrs),
        }


def current_span() -> Optional[Span]:
    return _CURRENT_SPAN.get()


def current_span_id() -> Optional[str]:
    s = _CURRENT_SPAN.get()
    return s.span_id if s is not None else None


class Tracer:
    """Collects finished spans into a bounded ring."""

    def __init__(self, maxlen: int = MAX_FINISHED_SPANS) -> None:
        self._finished: deque[Span] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def start(self, name: str, parent_id: Optional[str] = None,
              **attrs) -> Span:
        """Start a span.  Parent resolution order: explicit ``parent_id``
        (e.g. one carried in from a remote message), else the contextvar."""
        if parent_id is None:
            parent_id = current_span_id()
        return Span(name=name, span_id=_new_span_id(), parent_id=parent_id,
                    start=time.time(), attrs=dict(attrs))

    def finish(self, span: Span) -> Span:
        if span.end is None:
            span.end = time.time()
        with self._lock:
            self._finished.append(span)
        return span

    def span(self, name: str, parent_id: Optional[str] = None, **attrs
             ) -> "_SpanCtx":
        """Context manager: start a span, make it current for the duration
        of the block, finish it on exit (recording exceptions)."""
        return _SpanCtx(self, name, parent_id, attrs)

    def finished(self, name: Optional[str] = None) -> list[Span]:
        with self._lock:
            spans = list(self._finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans

    def snapshot(self) -> list[dict]:
        return [s.to_dict() for s in self.finished()]

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_parent_id", "_attrs", "_span",
                 "_token")

    def __init__(self, tracer: Tracer, name: str, parent_id: Optional[str],
                 attrs: dict) -> None:
        self._tracer = tracer
        self._name = name
        self._parent_id = parent_id
        self._attrs = attrs

    def __enter__(self) -> Span:
        self._span = self._tracer.start(self._name, self._parent_id,
                                        **self._attrs)
        self._token = _CURRENT_SPAN.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        _CURRENT_SPAN.reset(self._token)
        if exc_type is not None:
            self._span.attrs.setdefault("error", exc_type.__name__)
        self._tracer.finish(self._span)


def span_trace_tag(span) -> int:
    """Device-register form of a span id: a nonzero positive int32.

    The trace-tag rings (SimConfig.trace_tags) carry one i32 lane, so the
    12-hex span id is folded to 31 bits with the sign bit cleared (the
    kernel treats 0 as "untagged") and floored at 1.  Accepts a Span or a
    bare span-id string (the cross-process form).  The export layer
    (flightrec/export.py) applies the same fold to host span ids when
    matching flow events, so collisions only blur which of two
    simultaneous in-flight batches an arrow points at — never safety.
    """
    sid = span.span_id if isinstance(span, Span) else span
    return max(int(sid, 16) & 0x7FFFFFFF, 1)


# Process-global tracer, mirroring registry.DEFAULT.
DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return DEFAULT


def iter_ancestry(spans: list[Span], leaf: Span) -> Iterator[Span]:
    """Walk parent links through a finished-span list (test helper)."""
    by_id = {s.span_id: s for s in spans}
    cur: Optional[Span] = leaf
    while cur is not None:
        yield cur
        cur = by_id.get(cur.parent_id) if cur.parent_id else None

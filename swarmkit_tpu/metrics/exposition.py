"""Merged Prometheus text exposition across the three metric surfaces.

A manager scrape must show one coherent page built from:

1. the typed registry (:mod:`swarmkit_tpu.metrics.registry`) — counters,
   gauges, histograms declared through the catalog;
2. the legacy latency timers (:mod:`swarmkit_tpu.utils.metrics`) — rendered
   as Prometheus *summaries* (quantile series from the reservoir, plus
   exact ``_sum``/``_count``), keeping their reference-compatible names;
3. the store-object gauges (``manager.metrics.Collector.snapshot()``) —
   rendered as plain gauges.

:func:`render_all` is what ``Manager.metrics_text()`` and the gRPC
``swarmkit.Metrics/Scrape`` service serve; :func:`snapshot_all` is the
JSON-able equivalent consumed by tools/ and tests.
"""

from __future__ import annotations

from typing import Optional

from .registry import MetricsRegistry, format_value

_QUANTILES = ((50, "0.5"), (90, "0.9"), (99, "0.99"))


def render_timers(legacy_registry) -> str:
    """Render a utils.metrics.Registry's timers as summary families."""
    lines: list[str] = []
    snap = getattr(legacy_registry, "_timers", {})
    for name in sorted(snap):
        t = snap[name]
        lines.append(f"# HELP {name} Latency timer "
                     f"(reservoir quantiles over recent observations).")
        lines.append(f"# TYPE {name} summary")
        for p, q in _QUANTILES:
            lines.append(f'{name}{{quantile="{q}"}} '
                         f"{format_value(t.percentile(p))}")
        lines.append(f"{name}_sum {format_value(t.sum)}")
        lines.append(f"{name}_count {t.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_plain_gauges(gauges: dict, help_prefix: str = "Cluster object "
                        "gauge maintained by the store-event collector."
                        ) -> str:
    lines: list[str] = []
    for name in sorted(gauges):
        lines.append(f"# HELP {name} {help_prefix}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {format_value(gauges[name])}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_all(registry: Optional[MetricsRegistry] = None,
               legacy_registry=None,
               collector_gauges: Optional[dict] = None) -> str:
    parts = []
    if registry is not None:
        parts.append(registry.render())
    if legacy_registry is not None:
        parts.append(render_timers(legacy_registry))
    if collector_gauges:
        parts.append(render_plain_gauges(collector_gauges))
    return "".join(p for p in parts if p)


def snapshot_all(registry: Optional[MetricsRegistry] = None,
                 legacy_registry=None,
                 collector_gauges: Optional[dict] = None,
                 tracer=None) -> dict:
    out: dict = {}
    if registry is not None:
        out["metrics"] = registry.snapshot()
    if legacy_registry is not None:
        out["timers"] = legacy_registry.snapshot()
    if collector_gauges is not None:
        out["objects"] = dict(collector_gauges)
    if tracer is not None:
        out["spans"] = tracer.snapshot()
    return out

"""Merged Prometheus text exposition across the three metric surfaces.

A manager scrape must show one coherent page built from:

1. the typed registry (:mod:`swarmkit_tpu.metrics.registry`) — counters,
   gauges, histograms declared through the catalog;
2. the legacy latency timers (:mod:`swarmkit_tpu.utils.metrics`) — rendered
   as Prometheus *summaries* (quantile series from the reservoir, plus
   exact ``_sum``/``_count``), keeping their reference-compatible names;
3. the store-object gauges (``manager.metrics.Collector.snapshot()``) —
   rendered as plain gauges.

:func:`render_all` is what ``Manager.metrics_text()`` and the gRPC
``swarmkit.Metrics/Scrape`` service serve; :func:`snapshot_all` is the
JSON-able equivalent consumed by tools/ and tests.  When a tracer is
passed, the page ends with a recent-events comment section (finished
spans + any flight-recorder captures) — comments are format-legal, so
Prometheus scrapers ignore the section while humans hitting Scrape get
the last few interesting things that happened.
"""

from __future__ import annotations

from typing import Optional

from .registry import MetricsRegistry, escape_help, format_value

_QUANTILES = ((50, "0.5"), (90, "0.9"), (99, "0.99"))

RECENT_EVENT_LIMIT = 16


def render_timers(legacy_registry) -> str:
    """Render a utils.metrics.Registry's timers as summary families."""
    lines: list[str] = []
    snap = getattr(legacy_registry, "_timers", {})
    for name in sorted(snap):
        t = snap[name]
        lines.append(f"# HELP {name} Latency timer "
                     f"(reservoir quantiles over recent observations).")
        lines.append(f"# TYPE {name} summary")
        for p, q in _QUANTILES:
            lines.append(f'{name}{{quantile="{q}"}} '
                         f"{format_value(t.percentile(p))}")
        lines.append(f"{name}_sum {format_value(t.sum)}")
        lines.append(f"{name}_count {t.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_plain_gauges(gauges: dict, help_prefix: str = "Cluster object "
                        "gauge maintained by the store-event collector."
                        ) -> str:
    lines: list[str] = []
    for name in sorted(gauges):
        lines.append(f"# HELP {name} {escape_help(help_prefix)}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {format_value(gauges[name])}")
    return "\n".join(lines) + ("\n" if lines else "")


def recent_events(tracer=None, limit: int = RECENT_EVENT_LIMIT
                  ) -> list[dict]:
    """The scrape page's recent-events feed: the newest finished tracer
    spans merged with the newest flight-recorder capture summaries (both
    JSON-able dicts, newest last)."""
    out: list[dict] = []
    if tracer is not None:
        for s in tracer.finished()[-limit:]:
            d = s.to_dict()
            d["source"] = "span"
            out.append(d)
    try:
        from swarmkit_tpu.flightrec import record as flight_record
        out += flight_record.recent_capture_events(limit)
    except Exception:
        pass  # a flightrec problem must never break the scrape page
    return out[-limit:] if limit else out


def render_recent_events(tracer=None, limit: int = RECENT_EVENT_LIMIT
                         ) -> str:
    """Comment-only section ('# recent-event ...' lines): legal in the
    0.0.4 text format, invisible to scrapers, useful to humans."""
    events = recent_events(tracer, limit)
    if not events:
        return ""
    lines = ["# recent-events (newest last; spans + flightrec captures)"]
    for e in events:
        if e.get("source") == "span":
            dur = e.get("duration")
            dur_s = f"{dur * 1000:.3f}ms" if dur is not None else "open"
            desc = f"span {e['name']} {dur_s} attrs={e.get('attrs', {})}"
        else:
            desc = e.get("describe", str(e))
        lines.append("# recent-event " + escape_help(str(desc)))
    return "\n".join(lines) + "\n"


def render_all(registry: Optional[MetricsRegistry] = None,
               legacy_registry=None,
               collector_gauges: Optional[dict] = None,
               tracer=None) -> str:
    parts = []
    if registry is not None:
        parts.append(registry.render())
    if legacy_registry is not None:
        parts.append(render_timers(legacy_registry))
    if collector_gauges:
        parts.append(render_plain_gauges(collector_gauges))
    if tracer is not None:
        parts.append(render_recent_events(tracer))
    return "".join(p for p in parts if p)


def snapshot_all(registry: Optional[MetricsRegistry] = None,
                 legacy_registry=None,
                 collector_gauges: Optional[dict] = None,
                 tracer=None) -> dict:
    out: dict = {}
    if registry is not None:
        out["metrics"] = registry.snapshot()
    if legacy_registry is not None:
        out["timers"] = legacy_registry.snapshot()
    if collector_gauges is not None:
        out["objects"] = dict(collector_gauges)
    if tracer is not None:
        out["spans"] = tracer.snapshot()
        out["recent_events"] = recent_events(tracer)
    return out

"""Central catalog of every metric the framework emits.

One spec per metric name: kind, help text, label schema, and (for
histograms) bucket edges.  Components never hand-declare families — they
call :func:`get`, which instantiates the family in the target registry from
the spec.  This gives ``tools/metrics_lint.py`` a single ground truth: a
name emitted anywhere but absent here, a duplicate registration with a
different schema, or a spec with empty help text is a lint failure.

Naming follows the reference's prometheus namespace (``swarm_``) with a
subsystem segment per layer: raft / transport / kernel / scheduler /
dispatcher / store / bench.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from .registry import DEFAULT_BUCKETS, MetricsRegistry


@dataclass(frozen=True)
class MetricSpec:
    kind: str                       # "counter" | "gauge" | "histogram"
    help: str
    labels: tuple = ()
    buckets: Optional[tuple] = None


# Bucket ladders: RPC-ish latencies use the prometheus defaults; device
# ticks span 0.1 ms (tiny CPU shapes) to tens of seconds (XLA compile).
_TICK_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# Simulated-tick ladder for the on-device telemetry histograms.  Literal
# floats rather than an import of telemetry/series.py (that module's
# publisher imports this catalog); tools/metrics_lint.py check #6 pins
# these to series.LATENCY_BUCKET_EDGES so they cannot drift.
_TEL_TICK_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

CATALOG: dict[str, MetricSpec] = {
    # ---- raft node (L3) --------------------------------------------------
    "swarm_raft_elections_started_total": MetricSpec(
        "counter", "Campaigns this node started (entered candidate or "
        "pre-candidate state).", ("node",)),
    "swarm_raft_elections_won_total": MetricSpec(
        "counter", "Elections this node won (became leader).", ("node",)),
    "swarm_raft_leader_changes_total": MetricSpec(
        "counter", "Observed leadership changes, from any role.", ("node",)),
    "swarm_raft_term": MetricSpec(
        "gauge", "Current raft term.", ("node",)),
    "swarm_raft_commit_index": MetricSpec(
        "gauge", "Highest committed log index.", ("node",)),
    "swarm_raft_applied_index": MetricSpec(
        "gauge", "Highest applied log index.", ("node",)),
    "swarm_raft_is_leader": MetricSpec(
        "gauge", "1 while this node is the raft leader, else 0.", ("node",)),
    "swarm_raft_proposal_latency_seconds": MetricSpec(
        "histogram", "ProposeValue wall time: submit to quorum commit "
        "(the reference's proposeLatencyTimer span).", ("node",)),
    "swarm_raft_proposals_total": MetricSpec(
        "counter", "Proposals submitted, by outcome.", ("node", "result")),
    "swarm_raft_peer_sends_total": MetricSpec(
        "counter", "Raft messages handed to the transport, per peer.",
        ("node", "peer")),
    "swarm_raft_peer_send_failures_total": MetricSpec(
        "counter", "Per-peer delivery failures reported back to the node "
        "(feeds Node.status()['peer_failures']).", ("node", "peer")),

    # ---- transports (L2) -------------------------------------------------
    "swarm_transport_delivery_latency_seconds": MetricSpec(
        "histogram", "Queue-to-delivered wall time per raft message on the "
        "sending side.", ("wire",)),
    "swarm_transport_redials_total": MetricSpec(
        "counter", "Backoff redial sleeps taken by per-peer drain loops "
        "after delivery failures.", ("wire",)),
    "swarm_transport_send_failures_total": MetricSpec(
        "counter", "Message delivery failures across all peers.", ("wire",)),
    "swarm_transport_probe_transitions_total": MetricSpec(
        "counter", "gRPC health-prober state flips, by new state "
        "(healthy / unhealthy).", ("peer", "state")),
    "swarm_transport_probe_healthy": MetricSpec(
        "gauge", "Current prober verdict per peer: 1 healthy, 0 unhealthy.",
        ("peer",)),
    "swarm_transport_probes_total": MetricSpec(
        "counter", "Health probes sent, by result (ok / fail).",
        ("peer", "result")),
    "swarm_transport_mailbox_depth": MetricSpec(
        "gauge", "Device-mesh messages staged and awaiting the next "
        "all-to-all flush.", ()),
    "swarm_transport_device_flushes_total": MetricSpec(
        "counter", "Device-mesh all-to-all exchange invocations.", ()),
    "swarm_transport_device_messages_total": MetricSpec(
        "counter", "Raft messages moved through device-mesh exchanges.", ()),
    "swarm_transport_exchange_seconds": MetricSpec(
        "histogram", "Wall time of one device-mesh exchange flush "
        "(host-side, around the jitted all-to-all).", (),
        _TICK_BUCKETS),

    # ---- device tick kernel (L4) -----------------------------------------
    "swarm_kernel_tick_seconds": MetricSpec(
        "histogram", "Host-side wall time around jitted kernel calls, by "
        "driver (step / run_ticks chunk / run_until_leader).", ("call",),
        _TICK_BUCKETS),
    "swarm_kernel_phase_ms": MetricSpec(
        "gauge", "Isolated per-phase A-F cost in ms from the micro-kernel "
        "model (tools/perf_model.py), keyed by PERF.md's phase table.",
        ("phase",)),
    "swarm_kernel_bytes_touched": MetricSpec(
        "gauge", "Analytic per-tick kernel bytes read+written by phase and "
        "kernel variant: the C/E/F log-buffer hot phases (tools/"
        "perf_model.py --tiled; variant tiled / full), the read path "
        "(--reads; variant lease / readindex), the peer-axis quorum "
        "reductions phase=votes|commit (--peer-tiled; variant banded / "
        "dense), and the elementwise per-peer progress writes "
        "phase=progress (--active-rows; variant sparse / dense).",
        ("phase", "variant")),
    "swarm_kernel_elections_started_total": MetricSpec(
        "counter", "On-device cumulative campaigns across all rows "
        "(SimState.stats[0]).", ()),
    "swarm_kernel_elections_won_total": MetricSpec(
        "counter", "On-device cumulative election wins across all rows "
        "(SimState.stats[1]).", ()),
    "swarm_kernel_commit_advance_total": MetricSpec(
        "counter", "On-device cumulative commit-index advance summed over "
        "rows (SimState.stats[2]).", ()),
    "swarm_kernel_apply_advance_total": MetricSpec(
        "counter", "On-device cumulative applied-index advance summed over "
        "rows (SimState.stats[3]).", ()),
    "swarm_kernel_reads_served_total": MetricSpec(
        "counter", "On-device cumulative linearizable read ops served "
        "summed over rows (SimState.read_srv, cfg.read_batch > 0).", ()),
    "swarm_kernel_reads_blocked_total": MetricSpec(
        "counter", "On-device cumulative read ops refused (leadership lost "
        "or lease expired with the batch unstamped) summed over rows "
        "(SimState.read_block).", ()),
    "swarm_kernel_fsync_lag": MetricSpec(
        "gauge", "Widest unsynced log suffix max(last - sync_mark) across "
        "rows at last publish (cfg.fsync_lag_ticks >= 1; the quantity "
        "SLO_FSYNC_LAG budgets under disk_stall).", ()),
    "swarm_kernel_durable_commit_advance_total": MetricSpec(
        "counter", "On-device cumulative durable-commit advance summed "
        "over rows (SimState.dur_commit, the register RECOVERY_MONOTONIC "
        "pins; trails swarm_kernel_commit_advance_total by the fsync "
        "policy's lag).", ()),

    # ---- flight recorder (flightrec/) ------------------------------------
    "swarm_flightrec_events_total": MetricSpec(
        "counter", "Device flight-ring events decoded by capture(), by "
        "event code name (flightrec/codes.py).", ("code",)),
    "swarm_flightrec_dropped_total": MetricSpec(
        "counter", "Events overwritten in a row's ring before decoding "
        "(cursor ran past SimConfig.event_ring).", ()),
    "swarm_flightrec_captures_total": MetricSpec(
        "counter", "Flight-record captures, by trigger (manual / "
        "dst_violation / scenario_failure).", ("trigger",)),

    # ---- causal trace fusion (flightrec/clock.py, export.py) -------------
    "swarm_trace_clock_sync_points_total": MetricSpec(
        "counter", "Tick<->wall-clock sync points folded into captures "
        "(ClockSync.publish); each is one host observation of the device "
        "tick counter.", ()),
    "swarm_trace_clock_tick_us": MetricSpec(
        "gauge", "Fitted wall-clock microseconds per simulated tick "
        "(ClockFit slope, Theil-Sen over the sync points).", ()),
    "swarm_trace_clock_residual_us": MetricSpec(
        "gauge", "Worst |fit - sample| residual of the tick<->wall-clock "
        "fit in microseconds; large values mean the tick rate drifted "
        "within the capture window.", ()),
    "swarm_trace_flow_events_total": MetricSpec(
        "counter", "Chrome-trace flow events (ph s/t/f) emitted by the "
        "Perfetto export, linking host spans to tagged device instants "
        "(cfg.trace_tags).", ()),
    "swarm_trace_flow_orphans_total": MetricSpec(
        "counter", "Trace tags seen on only one side of the export: "
        "host_only (ring wrap ate the device instant) or device_only "
        "(span deque evicted the host span).", ("side",)),

    # ---- on-device telemetry plane (telemetry/) --------------------------
    "swarm_telemetry_commit_latency_ticks": MetricSpec(
        "histogram", "Propose-to-commit latency in simulated ticks, "
        "measured at the proposing leader for self-appended entries "
        "(SimState.tel_commit_hist, cfg.collect_telemetry).", (),
        _TEL_TICK_BUCKETS),
    "swarm_telemetry_election_ticks": MetricSpec(
        "histogram", "Election duration in simulated ticks, campaign "
        "start to leadership (SimState.tel_elect_hist).", (),
        _TEL_TICK_BUCKETS),
    "swarm_telemetry_read_latency_ticks": MetricSpec(
        "histogram", "Linearizable read-batch submit-to-settle latency "
        "in simulated ticks, served and blocked outcomes both counted "
        "(SimState.tel_read_hist, cfg.read_batch > 0).", (),
        _TEL_TICK_BUCKETS),
    "swarm_telemetry_series_value": MetricSpec(
        "gauge", "Latest sample of an on-device time-series ring row "
        "(SimState.tel_series), by series name "
        "(telemetry/series.py SERIES_NAMES).", ("series",)),

    # ---- scheduler / dispatcher / store (L5) -----------------------------
    "swarm_scheduler_latency_seconds": MetricSpec(
        "histogram", "One scheduler tick: snapshot, score, and commit of "
        "all pending assignments.", ()),
    "swarm_scheduler_decisions_total": MetricSpec(
        "counter", "Task placement decisions, by outcome "
        "(assigned / preassigned / unassigned).", ("result",)),
    "swarm_scheduler_pending_tasks": MetricSpec(
        "gauge", "Tasks currently awaiting placement.", ()),
    "swarm_dispatcher_sessions_total": MetricSpec(
        "counter", "Agent sessions opened against this dispatcher.", ()),
    "swarm_dispatcher_heartbeats_total": MetricSpec(
        "counter", "Heartbeats processed, by result (ok / invalid).",
        ("result",)),
    "swarm_dispatcher_heartbeat_rtt_seconds": MetricSpec(
        "histogram", "Server-side heartbeat handling time (store round "
        "trip included).", ()),
    "swarm_dispatcher_task_updates_total": MetricSpec(
        "counter", "Task status updates accepted from agents.", ()),
    "swarm_store_commits_total": MetricSpec(
        "counter", "Store transactions committed, by kind "
        "(read / write / batch).", ("kind",)),

    # ---- deterministic simulation testing (dst/) -------------------------
    "swarm_dst_schedules_total": MetricSpec(
        "counter", "Fault schedules fully explored, by result "
        "(clean / violation).", ("result",)),
    "swarm_dst_violations_total": MetricSpec(
        "counter", "Schedules that tripped a raft safety invariant, by "
        "invariant (dst/invariants.py bit names).", ("invariant",)),
    "swarm_dst_schedules_per_second": MetricSpec(
        "gauge", "Throughput of the last vmapped explore() call, by "
        "config (n<rows>x<ticks>t).", ("config",)),
    "swarm_dst_shrink_rounds_total": MetricSpec(
        "counter", "Counterexample-shrinker replay evaluations, by verdict "
        "on the candidate fault clearing (removed / required).", ("result",)),
    "swarm_dst_attack_ticks_total": MetricSpec(
        "counter", "Adversary verb gate firings lowered into explored "
        "schedules, by attack profile (dst/schedule.py ATTACK_PROFILES).",
        ("attack",)),

    # ---- exhaustive model checker (mc/) ----------------------------------
    # Names and label sets are pinned to swarmkit_tpu/mc/metrics.py by
    # tools/metrics_lint.py check #7.
    "swarm_mc_branches_total": MetricSpec(
        "counter", "Model-checker (state, action) expansions, by result "
        "(clean / violation).", ("result",)),
    "swarm_mc_states_total": MetricSpec(
        "counter", "Reached states, by dedup verdict (unique = entered "
        "the frontier, duplicate = merged into an existing fingerprint).",
        ("kind",)),
    "swarm_mc_violations_total": MetricSpec(
        "counter", "Invariants tripped by at least one enumerated branch, "
        "by invariant (dst/invariants.py bit names).", ("invariant",)),
    "swarm_mc_branches_per_second": MetricSpec(
        "gauge", "Expansion throughput of the last exhaustive_scan, by "
        "scope preset.", ("scope",)),
    "swarm_mc_frontier_peak_states": MetricSpec(
        "gauge", "Largest per-level unique frontier of the last "
        "exhaustive_scan, by scope preset.", ("scope",)),
    "swarm_mc_truncations_total": MetricSpec(
        "counter", "Fresh states dropped by the --budget frontier cap "
        "(scan no longer exhaustive), by scope preset.", ("scope",)),

    # ---- multi-raft serving plane (multiraft/) ---------------------------
    # Names and label sets are pinned to swarmkit_tpu/multiraft/obs.py by
    # tools/metrics_lint.py check #11.
    "swarm_multiraft_groups": MetricSpec(
        "gauge", "Raft groups in the serving plane (leading G axis of "
        "the grouped state).", ()),
    "swarm_multiraft_groups_with_leader": MetricSpec(
        "gauge", "Groups with an acting leader at last publish.", ()),
    "swarm_multiraft_router_keys_total": MetricSpec(
        "counter", "Keys handled by the key->group router, by outcome "
        "(routed = accepted into a per-group batch queue, spilled = "
        "deferred past one flush by the group's max_props capacity).",
        ("outcome",)),
    "swarm_multiraft_leader_changes_total": MetricSpec(
        "counter", "Per-group leader changes summed over groups: "
        "publishes where a group's acting leader row differs from the "
        "previous publish.", ()),
    "swarm_multiraft_committed_entries_total": MetricSpec(
        "counter", "Entries committed through consensus summed over "
        "groups (per group: max commit across rows).", ()),
    "swarm_multiraft_reads_served_total": MetricSpec(
        "counter", "Linearizable read ops served summed over groups and "
        "rows (cfg.read_batch > 0).", ()),
    "swarm_multiraft_group_commit_latency_ticks": MetricSpec(
        "gauge", "Per-group propose-to-commit latency in simulated ticks "
        "(bucket upper edge of the group's on-device telemetry "
        "histogram), by group index and quantile (p50 / p99).  Published "
        "only while the plane holds at most GROUP_LABEL_CAP groups.",
        ("group", "quantile")),
    "swarm_multiraft_group_leader_changes_total": MetricSpec(
        "counter", "Leader changes per group: publishes where this "
        "group's acting leader row differs from the previous publish "
        "(the churn-rate input for the SLO engine).", ("group",)),
    "swarm_multiraft_group_heat": MetricSpec(
        "gauge", "EWMA hot-group heat score, by group index: router "
        "spills (weighted SPILL_WEIGHT x) fused with per-group commit "
        "rate (multiraft/heat.py).  All groups up to GROUP_LABEL_CAP, "
        "top HEAT_TOP_K hottest beyond.", ("group",)),

    # ---- SLO burn-rate engine (slo/) -------------------------------------
    # Names and label sets are pinned to swarmkit_tpu/slo/engine.py by
    # tools/metrics_lint.py check #13.
    "swarm_slo_state": MetricSpec(
        "gauge", "Alert state of one SLO for one group: 0 = ok, 1 = "
        "warn, 2 = page (slo/engine.py state machine with hysteresis).",
        ("slo", "group")),
    "swarm_slo_burn_rate": MetricSpec(
        "gauge", "Burn rate of one SLO's error budget over the fast / "
        "slow evaluation window (1.0 = burning exactly the budget).",
        ("slo", "group", "window")),
    "swarm_slo_transitions_total": MetricSpec(
        "counter", "SLO state-machine transitions, by SLO, group, and "
        "the state ENTERED (warn escalations, page escalations, "
        "recoveries to ok).", ("slo", "group", "state")),

    # ---- coalescing proposal pipeline (store/pipeline.py) ----------------
    # Names and label sets are pinned to swarmkit_tpu/store/pipeline.py by
    # tools/metrics_lint.py check #12.
    "swarm_cpl_proposals_total": MetricSpec(
        "counter", "Packed raft proposals flushed by the coalescing "
        "pipeline, by outcome (committed / failed).", ("outcome",)),
    "swarm_cpl_txns_total": MetricSpec(
        "counter", "Store transactions routed through the coalescing "
        "pipeline, by outcome (committed / failed).", ("outcome",)),
    "swarm_cpl_batch_entries": MetricSpec(
        "histogram", "Transactions packed per raft proposal (the "
        "amortization factor of the batched pipeline).", (),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256)),
    "swarm_cpl_queue_depth": MetricSpec(
        "gauge", "Transactions queued behind the in-flight packed "
        "proposal.", ()),

    # ---- jitted scheduler kernel (manager/scheduler/kernel.py) -----------
    # Names and label sets are pinned to manager/scheduler/kernel.py by
    # tools/metrics_lint.py check #12.
    "swarm_sched_kernel_groups_total": MetricSpec(
        "counter", "Task groups scheduled, by path (kernel = jitted "
        "[tasks, nodes] kernel, host = host Pipeline fallback).",
        ("path",)),
    "swarm_sched_kernel_tasks_total": MetricSpec(
        "counter", "Tasks placed through the jitted kernel path.", ()),
    "swarm_sched_kernel_seconds": MetricSpec(
        "histogram", "Wall time of one kernel group-placement call "
        "(encode + device + decode).", (), buckets=_TICK_BUCKETS),

    # ---- bench / tools (L6) ----------------------------------------------
    "swarm_bench_entries_per_second": MetricSpec(
        "gauge", "Steady-state committed entries/sec, by bench config.",
        ("config",)),
    "swarm_bench_reads_per_second": MetricSpec(
        "gauge", "Steady-state linearizable reads served/sec, by bench "
        "config (read-mix configs only).", ("config",)),
    "swarm_bench_compile_seconds": MetricSpec(
        "gauge", "XLA compile+first-call wall time, by bench config.",
        ("config",)),
    "swarm_bench_election_seconds": MetricSpec(
        "gauge", "Election wall time on the cached program, by bench "
        "config.", ("config",)),
    "swarm_bench_commit_latency_ticks_p50": MetricSpec(
        "gauge", "Median propose-to-commit latency in simulated ticks "
        "from the bench telemetry probe (bucket upper edge), by bench "
        "config.", ("config",)),
    "swarm_bench_commit_latency_ticks_p99": MetricSpec(
        "gauge", "p99 propose-to-commit latency in simulated ticks from "
        "the bench telemetry probe (bucket upper edge), by bench "
        "config.", ("config",)),
    "swarm_bench_election_ticks": MetricSpec(
        "gauge", "Simulated ticks until first leader election, by bench "
        "config.", ("config",)),
    "swarm_bench_proposals_per_second": MetricSpec(
        "gauge", "Store proposals committed per second over the real "
        "control plane, by bench config.", ("config",)),
    "swarm_bench_assignments_per_second": MetricSpec(
        "gauge", "Task assignments delivered to simulated agents per "
        "second under control-plane load, by bench config.", ("config",)),
    "swarm_bench_agents_sustained": MetricSpec(
        "gauge", "Simulated agent sessions concurrently sustained by the "
        "load harness, by bench config.", ("config",)),
    "swarm_bench_heartbeat_rtt_p99_seconds": MetricSpec(
        "gauge", "Client-observed heartbeat round-trip p99 under "
        "control-plane load, by bench config.", ("config",)),
}


# Legacy exposition series rendered NEXT TO the typed families by
# exposition.render_all: the reservoir timers (utils.metrics, reference-
# compatible summary names) and the store-event Collector's object gauges
# (dynamic swarm_task_<state> / swarm_node_<state> names).  Allowlisted so
# tools/metrics_lint.py accepts them without a MetricSpec — they are not
# typed families and never instantiate through get().
LEGACY_SERIES = frozenset({
    "swarm_raft_propose_latency_seconds",
    "swarm_raft_snapshot_latency_seconds",
    "swarm_store_read_tx_latency_seconds",
    "swarm_store_write_tx_latency_seconds",
    "swarm_store_batch_latency_seconds",
    "swarm_manager_leader",
})
LEGACY_PREFIXES = ("swarm_task_", "swarm_node_")


def get(registry: MetricsRegistry, name: str):
    """Instantiate (or fetch) `name` in `registry` from its catalog spec."""
    spec = CATALOG.get(name)
    if spec is None:
        raise KeyError(f"metric {name!r} is not in the catalog; add a "
                       f"MetricSpec to swarmkit_tpu/metrics/catalog.py")
    if spec.kind == "counter":
        return registry.counter(name, spec.help, spec.labels)
    if spec.kind == "gauge":
        return registry.gauge(name, spec.help, spec.labels)
    if spec.kind == "histogram":
        return registry.histogram(name, spec.help, spec.labels,
                                  buckets=spec.buckets or DEFAULT_BUCKETS)
    raise ValueError(f"unknown metric kind {spec.kind!r} for {name!r}")

from swarmkit_tpu.encryption.encryption import (
    Decrypter, Encrypter, FernetCrypter, MaybeEncryptedRecord, MultiDecrypter,
    NopCrypter, SecretboxCrypter, defaults, generate_secret_key,
    human_readable_key, parse_human_readable_key,
)

__all__ = [
    "Decrypter", "Encrypter", "FernetCrypter", "MaybeEncryptedRecord",
    "MultiDecrypter", "NopCrypter", "SecretboxCrypter", "defaults",
    "generate_secret_key", "human_readable_key", "parse_human_readable_key",
]

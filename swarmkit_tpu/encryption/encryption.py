"""At-rest encryption for raft WAL/snapshots and TLS keys.

Behavioral reference: manager/encryption/encryption.go — the
``MaybeEncryptedRecord`` envelope (algorithm + data + nonce), a default
authenticated-secretbox algorithm, a FIPS-friendly fernet alternative, and a
``MultiDecrypter`` so key rotation can decrypt records written under either
the old or the new key.

TPU-era re-expression: instead of NaCl secretbox we use ChaCha20-Poly1305
(the same AEAD family) from the ``cryptography`` package, which is what this
environment ships.  Envelope wire format is msgpack.
"""

from __future__ import annotations

import base64
import enum
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

import hashlib
import hmac as _hmac

import msgpack

try:
    from cryptography.fernet import Fernet, InvalidToken
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - depends on the environment
    HAVE_CRYPTOGRAPHY = False

    class InvalidToken(Exception):
        pass

    class _HashlibAead:
        """Stand-in AEAD when the ``cryptography`` package is absent:
        SHA-256-CTR keystream + truncated HMAC-SHA256 tag, domain-separated
        per algorithm.  Same encrypt/decrypt surface as ChaCha20Poly1305.
        Records it writes are only readable by this fallback (and vice
        versa) — fine for a self-contained store, not for interop."""

        _TAG = 16

        def __init__(self, key: bytes, domain: bytes) -> None:
            self._key = key
            self._domain = domain

        def _stream(self, nonce: bytes, n: int) -> bytes:
            out = bytearray()
            ctr = 0
            while len(out) < n:
                out += hashlib.sha256(
                    self._domain + self._key + nonce
                    + ctr.to_bytes(8, "big")).digest()
                ctr += 1
            return bytes(out[:n])

        def _mac(self, nonce: bytes, ct: bytes) -> bytes:
            return _hmac.new(self._key, self._domain + nonce + ct,
                             hashlib.sha256).digest()[:self._TAG]

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
            ct = bytes(a ^ b for a, b in
                       zip(data, self._stream(nonce, len(data))))
            return ct + self._mac(nonce, ct)

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
            if len(data) < self._TAG:
                raise InvalidToken("ciphertext too short")
            ct, tag = data[:-self._TAG], data[-self._TAG:]
            if not _hmac.compare_digest(tag, self._mac(nonce, ct)):
                raise InvalidToken("tag mismatch")
            return bytes(a ^ b for a, b in
                         zip(ct, self._stream(nonce, len(ct))))

    def ChaCha20Poly1305(key: bytes):  # noqa: N802 - drop-in name
        return _HashlibAead(key, b"secretbox:")

    class Fernet:
        """Token-level stand-in for ``cryptography.fernet.Fernet`` backed
        by the same hashlib AEAD (nonce is prepended to the token)."""

        def __init__(self, b64_key: bytes) -> None:
            self._aead = _HashlibAead(base64.urlsafe_b64decode(b64_key),
                                      b"fernet:")

        def encrypt(self, data: bytes) -> bytes:
            nonce = os.urandom(16)
            return nonce + self._aead.encrypt(nonce, data, b"")

        def decrypt(self, token: bytes) -> bytes:
            if len(token) < 16:
                raise InvalidToken("token too short")
            return self._aead.decrypt(token[:16], token[16:], b"")


class Algorithm(enum.IntEnum):
    NONE = 0
    SECRETBOX = 1   # ChaCha20-Poly1305 AEAD (NaCl-secretbox analog)
    FERNET = 2      # AES128-CBC + HMAC (FIPS-friendly, like the reference)


@dataclass
class MaybeEncryptedRecord:
    """Envelope around possibly-encrypted bytes
    (reference: api/types.proto MaybeEncryptedRecord)."""

    algorithm: Algorithm = Algorithm.NONE
    data: bytes = b""
    nonce: bytes = b""

    def encode(self) -> bytes:
        return msgpack.packb((int(self.algorithm), self.data, self.nonce))

    @classmethod
    def decode(cls, raw: bytes) -> "MaybeEncryptedRecord":
        alg, data, nonce = msgpack.unpackb(raw)
        return cls(Algorithm(alg), data, nonce)


class DecryptError(Exception):
    pass


class Encrypter:
    def encrypt(self, data: bytes) -> MaybeEncryptedRecord:
        raise NotImplementedError


class Decrypter:
    algorithm: Algorithm = Algorithm.NONE

    def decrypt(self, rec: MaybeEncryptedRecord) -> bytes:
        raise NotImplementedError


class NopCrypter(Encrypter, Decrypter):
    """Passthrough (reference: NoopCrypter)."""

    algorithm = Algorithm.NONE

    def encrypt(self, data: bytes) -> MaybeEncryptedRecord:
        return MaybeEncryptedRecord(Algorithm.NONE, data, b"")

    def decrypt(self, rec: MaybeEncryptedRecord) -> bytes:
        if rec.algorithm != Algorithm.NONE:
            raise DecryptError("record is encrypted; nop decrypter")
        return rec.data


class SecretboxCrypter(Encrypter, Decrypter):
    """Default AEAD crypter keyed by a 32-byte secret
    (reference: NACLSecretbox, encryption.go)."""

    algorithm = Algorithm.SECRETBOX

    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("secretbox key must be 32 bytes")
        self._aead = ChaCha20Poly1305(key)

    def encrypt(self, data: bytes) -> MaybeEncryptedRecord:
        nonce = os.urandom(12)
        return MaybeEncryptedRecord(
            Algorithm.SECRETBOX, self._aead.encrypt(nonce, data, b""), nonce)

    def decrypt(self, rec: MaybeEncryptedRecord) -> bytes:
        if rec.algorithm != Algorithm.SECRETBOX:
            raise DecryptError(f"not a secretbox record: {rec.algorithm}")
        try:
            return self._aead.decrypt(rec.nonce, rec.data, b"")
        except Exception as e:  # InvalidTag
            raise DecryptError(str(e)) from e


class FernetCrypter(Encrypter, Decrypter):
    """FIPS-friendly alternative (reference: Fernet in encryption.go)."""

    algorithm = Algorithm.FERNET

    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("fernet key must be 32 bytes")
        self._f = Fernet(base64.urlsafe_b64encode(key))

    def encrypt(self, data: bytes) -> MaybeEncryptedRecord:
        return MaybeEncryptedRecord(Algorithm.FERNET, self._f.encrypt(data), b"")

    def decrypt(self, rec: MaybeEncryptedRecord) -> bytes:
        if rec.algorithm != Algorithm.FERNET:
            raise DecryptError(f"not a fernet record: {rec.algorithm}")
        try:
            return self._f.decrypt(rec.data)
        except InvalidToken as e:
            raise DecryptError("invalid fernet token") from e


class MultiDecrypter(Decrypter):
    """Tries each decrypter whose algorithm matches
    (reference: NewMultiDecrypter encryption.go:104)."""

    def __init__(self, *decrypters: Decrypter) -> None:
        # Flatten nested MultiDecrypters: a Multi has no `.algorithm` of
        # its own, so as a MEMBER it would never match any record and its
        # whole chain would be silently skipped (observed: DEK rotation
        # composing Multi(new, old_multi) losing the old generations).
        flat: list[Decrypter] = []
        for d in decrypters:
            if d is None:
                continue
            if isinstance(d, MultiDecrypter):
                flat.extend(d._decrypters)
            else:
                flat.append(d)
        self._decrypters = flat

    def decrypt(self, rec: MaybeEncryptedRecord) -> bytes:
        last: Optional[Exception] = None
        for d in self._decrypters:
            if d.algorithm == rec.algorithm:
                try:
                    return d.decrypt(rec)
                except DecryptError as e:
                    last = e
        raise DecryptError(
            f"no decrypter succeeded for algorithm {rec.algorithm}"
            + (f": {last}" if last else ""))


def defaults(key: Optional[bytes], fips: bool = False
             ) -> tuple[Encrypter, Decrypter]:
    """Default encrypter/decrypter pair for a key
    (reference: Defaults encryption.go:156)."""
    if key is None:
        nop = NopCrypter()
        return nop, nop
    if fips:
        f = FernetCrypter(key)
        return f, MultiDecrypter(f)
    s = SecretboxCrypter(key)
    return s, MultiDecrypter(s, FernetCrypter(key))


def generate_secret_key() -> bytes:
    return os.urandom(32)


def human_readable_key(key: bytes) -> str:
    return base64.b64encode(key).decode("ascii")


def parse_human_readable_key(s: str) -> bytes:
    key = base64.b64decode(s)
    if len(key) != 32:
        raise ValueError("key must decode to 32 bytes")
    return key

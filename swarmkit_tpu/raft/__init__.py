"""Raft consensus for swarmkit_tpu.

- messages/log/core/rawnode: host-side golden state machine (reference:
  vendor/github.com/coreos/etcd/raft), used by the Node shell and as the
  differential-test oracle.
- sim/: the batched JAX/XLA kernel where N simulated managers are rows of
  device arrays (the north-star backend).
"""

from swarmkit_tpu.raft.core import Config, ProposalDropped, Raft
from swarmkit_tpu.raft.log import RaftLog
from swarmkit_tpu.raft.messages import (
    ConfChange, ConfChangeType, Entry, EntryType, HardState, Message, MsgType,
    Snapshot, SnapshotMeta, SoftState,
)
from swarmkit_tpu.raft.rawnode import RawNode, Ready

# NOTE: the full consensus member lives in swarmkit_tpu.raft.node (Node,
# NodeOpts), transport seam in .transport (Network, Transport), persistence
# in .storage (EncryptedRaftLogger) — imported lazily by callers to keep this
# package import light for the sim kernel.

__all__ = [
    "Config", "ProposalDropped", "Raft", "RaftLog", "ConfChange",
    "ConfChangeType", "Entry", "EntryType", "HardState", "Message", "MsgType",
    "Snapshot", "SnapshotMeta", "SoftState", "RawNode", "Ready",
]

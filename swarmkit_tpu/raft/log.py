"""In-memory raft log with compaction watermark.

Behavioral reference: vendor/github.com/coreos/etcd/raft/log.go (raftLog:
maybeAppend/commitTo/isUpToDate) and storage.go (MemoryStorage compaction).
The stable/unstable split is collapsed: the host shell persists entries from
Ready before sending messages, which preserves the durability ordering the
reference gets from its two-level log.
"""

from __future__ import annotations

from typing import Optional, Sequence

from swarmkit_tpu.raft.messages import Entry, Snapshot, SnapshotMeta


class CompactedError(Exception):
    """Requested index was already compacted away."""


class UnavailableError(Exception):
    """Requested index is beyond the last index."""


class RaftLog:
    def __init__(self, snapshot: Optional[Snapshot] = None):
        snap = snapshot or Snapshot()
        # offset = index of the entry *before* entries[0] (the snapshot index).
        self.offset = snap.meta.index
        self.offset_term = snap.meta.term
        self.entries: list[Entry] = []
        self.committed = snap.meta.index
        self.applied = snap.meta.index
        # Highest index known persisted to stable storage (WAL). Entries above
        # this appear in Ready.entries for the shell to persist.
        self.stable = snap.meta.index
        self.pending_snapshot: Optional[Snapshot] = snap if not snap.empty else None

    # -- indexes -----------------------------------------------------------
    def first_index(self) -> int:
        return self.offset + 1

    def last_index(self) -> int:
        return self.offset + len(self.entries)

    def term(self, i: int) -> int:
        if i == self.offset:
            return self.offset_term
        if i < self.offset:
            raise CompactedError(i)
        if i > self.last_index():
            raise UnavailableError(i)
        return self.entries[i - self.offset - 1].term

    def zero_term(self, i: int) -> int:
        """term() but 0 on compacted/unavailable (zeroTermOnErrCompacted)."""
        try:
            return self.term(i)
        except (CompactedError, UnavailableError):
            return 0

    def last_term(self) -> int:
        return self.zero_term(self.last_index())

    def match_term(self, i: int, t: int) -> bool:
        try:
            return self.term(i) == t
        except (CompactedError, UnavailableError):
            return False

    def is_up_to_date(self, lasti: int, term: int) -> bool:
        return term > self.last_term() or (
            term == self.last_term() and lasti >= self.last_index())

    # -- slices ------------------------------------------------------------
    def slice(self, lo: int, hi: int, limit: Optional[int] = None) -> list[Entry]:
        """Entries in [lo, hi); raises on compacted lo."""
        if lo <= self.offset:
            raise CompactedError(lo)
        hi = min(hi, self.last_index() + 1)
        out = self.entries[lo - self.offset - 1: hi - self.offset - 1]
        if limit is not None:
            out = out[:limit]
        return list(out)

    def entries_from(self, i: int, limit: Optional[int] = None) -> list[Entry]:
        if i > self.last_index():
            return []
        return self.slice(i, self.last_index() + 1, limit)

    def unapplied_entries(self) -> list[Entry]:
        if self.committed <= self.applied:
            return []
        return self.slice(self.applied + 1, self.committed + 1)

    # -- mutation ----------------------------------------------------------
    def append(self, ents: Sequence[Entry]) -> int:
        if not ents:
            return self.last_index()
        after = ents[0].index - 1
        if after < self.committed:
            raise ValueError(f"append after {after} < committed {self.committed}")
        # Truncate any conflicting suffix, then extend.
        self.entries = self.entries[: after - self.offset]
        self.entries.extend(ents)
        self.stable = min(self.stable, after)
        return self.last_index()

    def find_conflict(self, ents: Sequence[Entry]) -> int:
        for e in ents:
            if not self.match_term(e.index, e.term):
                return e.index
        return 0

    def maybe_append(self, index: int, log_term: int, committed: int,
                     ents: Sequence[Entry]) -> Optional[int]:
        """Follower append path (raftLog.maybeAppend). Returns new last index
        on success, None on prev-entry mismatch."""
        if not self.match_term(index, log_term):
            return None
        lastnewi = index + len(ents)
        ci = self.find_conflict(ents)
        if ci != 0:
            if ci <= self.committed:
                raise ValueError(f"conflict {ci} <= committed {self.committed}")
            self.append([e for e in ents if e.index >= ci])
        self.commit_to(min(committed, lastnewi))
        return lastnewi

    def commit_to(self, tocommit: int) -> None:
        if tocommit > self.committed:
            if tocommit > self.last_index():
                raise ValueError(
                    f"commit {tocommit} out of range [last {self.last_index()}]")
            self.committed = tocommit

    def maybe_commit(self, max_index: int, term: int) -> bool:
        if max_index > self.committed and self.zero_term(max_index) == term:
            self.commit_to(max_index)
            return True
        return False

    def applied_to(self, i: int) -> None:
        if i == 0:
            return
        if i < self.applied or i > self.committed:
            raise ValueError(
                f"applied({i}) out of [{self.applied}, {self.committed}]")
        self.applied = i

    def compact(self, i: int) -> None:
        """Drop entries <= i (they live in a snapshot now)."""
        if i <= self.offset:
            return
        if i > self.applied:
            raise ValueError(f"compact {i} > applied {self.applied}")
        t = self.term(i)
        self.entries = self.entries[i - self.offset:]
        self.offset = i
        self.offset_term = t

    def restore(self, snap: Snapshot) -> None:
        self.entries = []
        self.offset = snap.meta.index
        self.offset_term = snap.meta.term
        self.committed = snap.meta.index
        self.applied = snap.meta.index
        self.stable = snap.meta.index
        self.pending_snapshot = snap

    def unstable_entries(self) -> list[Entry]:
        if self.stable >= self.last_index():
            return []
        return self.slice(max(self.stable + 1, self.first_index()),
                          self.last_index() + 1)

    def stabilized(self, to: int) -> None:
        self.stable = max(self.stable, min(to, self.last_index()))

    def snapshot_meta(self) -> SnapshotMeta:
        return SnapshotMeta(index=self.offset, term=self.offset_term)

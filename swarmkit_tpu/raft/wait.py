"""Proposal wait registry: correlates in-flight raft proposals with their
commit callbacks.  Reference: manager/state/raft/wait.go (register/trigger/
cancel/cancelAll over an id->channel map)."""

from __future__ import annotations

from typing import Any, Callable, Optional


class WaitItem:
    def __init__(self, on_commit: Optional[Callable[[Any], None]],
                 on_cancel: Optional[Callable[[], None]]) -> None:
        self.on_commit = on_commit
        self.on_cancel = on_cancel


class Wait:
    def __init__(self) -> None:
        self._items: dict[int, WaitItem] = {}

    def register(self, id: int, on_commit: Optional[Callable[[Any], None]],
                 on_cancel: Optional[Callable[[], None]] = None) -> None:
        if id in self._items:
            raise RuntimeError(f"duplicate wait id {id:x}")
        self._items[id] = WaitItem(on_commit, on_cancel)

    def trigger(self, id: int, value: Any) -> bool:
        item = self._items.pop(id, None)
        if item is None:
            return False
        if item.on_commit is not None:
            item.on_commit(value)
        return True

    def cancel(self, id: int) -> None:
        item = self._items.pop(id, None)
        if item is not None and item.on_cancel is not None:
            item.on_cancel()

    def forget(self, id: int) -> None:
        """Drop a wait without firing either callback (timeout path)."""
        self._items.pop(id, None)

    def cancel_all(self) -> None:
        for id in list(self._items):
            self.cancel(id)

    def __len__(self) -> int:
        return len(self._items)

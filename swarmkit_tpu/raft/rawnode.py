"""Ready/Advance driver around the Raft state machine.

Behavioral reference: vendor/github.com/coreos/etcd/raft/node.go (Ready
struct, node.go:115-168 Node interface) and rawnode.go — collapsed to a
synchronous, explicitly-driven API (no goroutines/channels): the shell calls
tick()/step()/propose(), then drains ready() and acknowledges with advance().

Durability contract preserved from the reference: the caller must persist
Ready.hard_state + Ready.entries (WAL) and Ready.snapshot before sending
Ready.messages, then apply Ready.committed_entries, then call advance().
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from swarmkit_tpu.raft.core import Config, Raft
from swarmkit_tpu.raft.log import RaftLog
from swarmkit_tpu.raft.messages import (
    NONE, ConfChange, ConfChangeType, Entry, EntryType, HardState, LOCAL_MSGS,
    Message, MsgType, Snapshot, SoftState,
)


@dataclass
class Ready:
    soft_state: Optional[SoftState] = None
    hard_state: Optional[HardState] = None
    entries: list = field(default_factory=list)            # to persist
    snapshot: Optional[Snapshot] = None                    # to persist+apply
    committed_entries: list = field(default_factory=list)  # to apply
    messages: list = field(default_factory=list)           # to send

    def contains_updates(self) -> bool:
        return bool(self.soft_state or self.hard_state or self.entries
                    or self.snapshot or self.committed_entries or self.messages)


class RawNode:
    def __init__(self, cfg: Config, log: Optional[RaftLog] = None,
                 hard_state: Optional[HardState] = None,
                 voters: Optional[Sequence[int]] = None):
        self.raft = Raft(cfg, log=log, hard_state=hard_state, voters=voters)
        self._prev_soft = self.raft.soft_state()
        self._prev_hard = self.raft.hard_state()

    # -- inputs ------------------------------------------------------------
    def tick(self) -> None:
        self.raft.tick()

    def campaign(self) -> None:
        self.raft.step(Message(type=MsgType.HUP, frm=self.raft.id))

    def propose(self, data: bytes) -> None:
        self.raft.step(Message(type=MsgType.PROP, frm=self.raft.id,
                               entries=(Entry(data=data),)))

    def propose_conf_change(self, cc: ConfChange) -> None:
        from swarmkit_tpu.raft.wire import encode_conf_change
        self.raft.step(Message(
            type=MsgType.PROP, frm=self.raft.id,
            entries=(Entry(type=EntryType.CONF_CHANGE,
                           data=encode_conf_change(cc)),)))

    def step(self, m: Message) -> None:
        if m.type in LOCAL_MSGS and m.frm != self.raft.id:
            raise ValueError(f"cannot step local message {m.type} from remote")
        if m.frm in self.raft.prs or m.type not in (MsgType.APP_RESP,
                                                    MsgType.HEARTBEAT_RESP,
                                                    MsgType.VOTE_RESP,
                                                    MsgType.PRE_VOTE_RESP):
            self.raft.step(m)

    def apply_conf_change(self, cc: ConfChange) -> tuple:
        if cc.type == ConfChangeType.ADD_NODE:
            self.raft.add_node(cc.node_id)
        elif cc.type == ConfChangeType.REMOVE_NODE:
            self.raft.remove_node(cc.node_id)
        elif cc.type == ConfChangeType.UPDATE_NODE:
            self.raft.pending_conf = False
        return self.raft.voter_ids()

    def report_unreachable(self, pid: int) -> None:
        self.raft.step(Message(type=MsgType.UNREACHABLE, frm=pid,
                               to=self.raft.id))

    def report_snapshot(self, pid: int, ok: bool) -> None:
        self.raft.step(Message(type=MsgType.SNAP_STATUS, frm=pid,
                               to=self.raft.id, reject=not ok))

    def transfer_leadership(self, to: int) -> None:
        self.raft.transfer_leadership(to)

    # -- outputs -----------------------------------------------------------
    def has_ready(self) -> bool:
        r = self.raft
        if r.soft_state() != self._prev_soft:
            return True
        if r.hard_state() != self._prev_hard:
            return True
        if r.log.pending_snapshot is not None:
            return True
        if r.msgs or r.log.unstable_entries() or r.log.unapplied_entries():
            return True
        return False

    def ready(self) -> Ready:
        r = self.raft
        rd = Ready()
        ss = r.soft_state()
        if ss != self._prev_soft:
            rd.soft_state = ss
        hs = r.hard_state()
        if hs != self._prev_hard:
            rd.hard_state = hs
        rd.entries = r.log.unstable_entries()
        rd.committed_entries = r.log.unapplied_entries()
        if r.log.pending_snapshot is not None:
            rd.snapshot = r.log.pending_snapshot
        rd.messages = r.msgs
        r.msgs = []
        return rd

    def advance(self, rd: Ready) -> None:
        r = self.raft
        if rd.soft_state is not None:
            self._prev_soft = rd.soft_state
        if rd.hard_state is not None:
            self._prev_hard = rd.hard_state
        if rd.entries:
            r.log.stabilized(rd.entries[-1].index)
        if rd.snapshot is not None:
            r.log.pending_snapshot = None
        if rd.committed_entries:
            r.log.applied_to(rd.committed_entries[-1].index)

    # -- views -------------------------------------------------------------
    @property
    def id(self) -> int:
        return self.raft.id

    def status(self) -> dict:
        r = self.raft
        return {
            "id": r.id, "term": r.term, "vote": r.vote, "state": r.state,
            "lead": r.lead, "commit": r.log.committed,
            "applied": r.log.applied, "last_index": r.log.last_index(),
            "voters": r.voter_ids(),
        }

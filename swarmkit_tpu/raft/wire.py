"""Binary wire codec for raft protocol messages.

The device-mesh transport moves raft messages through fixed-width uint32
mailbox arrays, and the gRPC transport moves them between processes; both
need a compact, versioned, code-free encoding (the reference wire format is
protobuf raftpb.Message — vendor/github.com/coreos/etcd/raft/raftpb).
msgpack of positional tuples: no pickle, no class names on the wire.
"""

from __future__ import annotations

import msgpack

from swarmkit_tpu.raft.messages import (
    Entry, EntryType, Message, MsgType, Snapshot, SnapshotMeta,
)

WIRE_VERSION = 1


def encode_message(m: Message) -> bytes:
    ents = [(e.index, e.term, int(e.type), e.data) for e in m.entries]
    snap = None
    if m.snapshot is not None:
        meta = m.snapshot.meta
        snap = (meta.index, meta.term, list(meta.voters), m.snapshot.data)
    return msgpack.packb((
        WIRE_VERSION, int(m.type), m.to, m.frm, m.term, m.log_term, m.index,
        ents, m.commit, m.reject, m.reject_hint, snap, m.context,
    ))


def decode_message(raw: bytes) -> Message:
    (ver, mtype, to, frm, term, log_term, index, ents, commit, reject,
     reject_hint, snap, context) = msgpack.unpackb(raw)
    if ver != WIRE_VERSION:
        raise ValueError(f"unsupported raft wire version {ver}")
    snapshot = None
    if snap is not None:
        sidx, sterm, voters, data = snap
        snapshot = Snapshot(meta=SnapshotMeta(index=sidx, term=sterm,
                                              voters=tuple(voters)),
                            data=data)
    return Message(
        type=MsgType(mtype), to=to, frm=frm, term=term, log_term=log_term,
        index=index,
        entries=tuple(Entry(index=i, term=t, type=EntryType(ty), data=d)
                      for i, t, ty, d in ents),
        commit=commit, reject=reject, reject_hint=reject_hint,
        snapshot=snapshot, context=context,
    )

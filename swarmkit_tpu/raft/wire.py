"""Binary wire codec for raft protocol messages and conf-change entries.

The device-mesh transport moves raft messages through fixed-width uint32
mailbox arrays, the gRPC transport moves them between processes, and the
encrypted WAL persists conf-change entry payloads; all need a compact,
versioned, CODE-FREE encoding — a log replay must never execute anything
(the reference wire/WAL format is protobuf raftpb —
vendor/github.com/coreos/etcd/raft/raftpb,
manager/state/raft/storage/walwrap.go). msgpack of positional tuples: no
pickle, no class names on the wire or on disk.
"""

from __future__ import annotations

import msgpack

from swarmkit_tpu.raft.messages import (
    ConfChange, ConfChangeType, Entry, EntryType, Message, MsgType, Snapshot,
    SnapshotMeta,
)

WIRE_VERSION = 1


def encode_conf_change(cc: ConfChange) -> bytes:
    return msgpack.packb((WIRE_VERSION, cc.id, int(cc.type), cc.node_id,
                          cc.context))


def decode_conf_change(raw: bytes) -> ConfChange:
    """Strict decode; anything else — including entries pickled by builds
    that predate this codec — fails loudly rather than deserializing
    arbitrary payloads from the log."""
    try:
        fields = msgpack.unpackb(raw)
        ver, cc_id, cc_type, node_id, context = fields
        if ver != WIRE_VERSION:
            raise ValueError(f"version {ver}")
    except Exception as e:
        raise ValueError(
            "undecodable ConfChange entry (legacy/pickled WAL formats are "
            f"not supported; re-bootstrap the member): {e}") from e
    return ConfChange(id=cc_id, type=ConfChangeType(cc_type),
                      node_id=node_id, context=context)


def encode_message(m: Message) -> bytes:
    ents = [(e.index, e.term, int(e.type), e.data) for e in m.entries]
    snap = None
    if m.snapshot is not None:
        meta = m.snapshot.meta
        snap = (meta.index, meta.term, list(meta.voters), m.snapshot.data)
    return msgpack.packb((
        WIRE_VERSION, int(m.type), m.to, m.frm, m.term, m.log_term, m.index,
        ents, m.commit, m.reject, m.reject_hint, snap, m.context,
    ))


def decode_message(raw: bytes) -> Message:
    (ver, mtype, to, frm, term, log_term, index, ents, commit, reject,
     reject_hint, snap, context) = msgpack.unpackb(raw)
    if ver != WIRE_VERSION:
        raise ValueError(f"unsupported raft wire version {ver}")
    snapshot = None
    if snap is not None:
        sidx, sterm, voters, data = snap
        snapshot = Snapshot(meta=SnapshotMeta(index=sidx, term=sterm,
                                              voters=tuple(voters)),
                            data=data)
    return Message(
        type=MsgType(mtype), to=to, frm=frm, term=term, log_term=log_term,
        index=index,
        entries=tuple(Entry(index=i, term=t, type=EntryType(ty), data=d)
                      for i, t, ty, d in ents),
        commit=commit, reject=reject, reject_hint=reject_hint,
        snapshot=snapshot, context=context,
    )

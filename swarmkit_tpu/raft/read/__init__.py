"""Linearizable read path: batched ReadIndex, tick-clock leader leases,
and follower reads served at the applied index.

Every operation the simulation modeled before this package was a log
write; the north-star workload is read-dominated.  The read
optimizations here are the ones the Paxos<->Raft parallels paper
(arXiv:1905.10786) catalogs as transferable across consensus variants:

* **Batched ReadIndex** — a pending read batch is *stamped* with the
  leader's commit index once the leader has confirmed it still leads.
  Confirmation reuses the [N, N] append/heartbeat ack collective the
  kernel already runs every tick (``q_ok`` = a quorum of member acks
  arrived this tick), so a ReadIndex round costs no extra messages.
* **Tick-clock leader leases** — each quorum-ack tick extends the
  leader's lease to ``now + lease_ticks`` where ``lease_ticks =
  election_tick - lease_margin - (latency + latency_jitter)``.  A
  lease-valid leader stamps read batches with zero additional
  collectives.  The margin term is the clock-skew guard: every ack in
  the quorum proves its sender refused votes until strictly after the
  lease expires (see ``lease.py``), so no rival can be elected — and
  commit new writes — while the lease is live.
* **Follower reads** — a follower forwards its batch to its known
  leader for stamping (resolved against the leader row's own gates)
  and serves locally once ``applied >= read_index``.

Serving itself never needs a quorum: the stamp is the linearization
point.  A batch stamped with read index R and submit-time goal G
(``max(commit)`` across rows at submit — the frontier of writes already
acknowledged to clients) satisfies R >= G by construction, so serving
at ``applied >= R`` can never miss an acknowledged write.  The DST
invariant ``LINEARIZABLE_READ`` (dst/invariants.py) checks exactly
that: ``read_srv_idx >= read_srv_goal`` on every row, every tick.

Layering mirrors ``flightrec/``: the kernel imports this package; this
package never imports the kernel.  All functions are pure array ops —
vmap/jit/scan-safe — and everything is Python-gated on
``cfg.read_batch > 0`` so a reads-off build stays bit-identical.
"""

from swarmkit_tpu.raft.read.lease import lease_span, renew, valid
from swarmkit_tpu.raft.read.serve import (ReadRegs, read_fields,
                                          regs_from_state, settle, stamp,
                                          submit)

__all__ = [
    "ReadRegs",
    "lease_span",
    "read_fields",
    "regs_from_state",
    "renew",
    "settle",
    "stamp",
    "submit",
    "valid",
]

"""Read-batch lifecycle: submit -> stamp -> serve (or refuse).

The per-row read registers are plain [N] i32 vectors — they ride the
scan carry like every other SimState scalar but never touch the [N, L]
log rings, so the read path stays outside the kernel's one-write-cond
budget and adds no per-read collective.

Lifecycle of one batch on row i:

1. ``submit`` (kernel phase R0) — an idle row takes a fresh batch of
   ``cfg.read_batch`` client reads.  The *goal* register captures
   ``max(commit)`` across rows at submit time: the frontier of writes
   already acknowledged to clients, i.e. the linearizability witness
   this batch must not miss.  The goal is oracle bookkeeping (like
   ``apply_chk``) — serving decisions never read it.
2. ``stamp`` (R1, after the commit phase) — the batch gets its read
   index.  A leader stamps with its own commit index once it has
   confirmed leadership (valid lease, or a quorum of acks this tick)
   *and* has committed an entry of its own term (the classic ReadIndex
   guard: a new leader's commit index may lag the true frontier until
   its own no-op commits).  A follower forwards to its known leader
   and stamps with the leader row's commit under the same gates,
   provided the round trip is clean this tick.
3. ``settle`` (R2, after the apply phase) — a stamped batch is served
   once ``applied >= read_index``; unstamped batches are refused when
   their row was deposed or its lease expired unrenewed (the client
   retries: the row's pend clears and R0 refills it with a fresh goal).

Safety: stamps only ever use a commit index proven >= the submit-time
goal (lease/quorum + own-term-commit gates), and commit/applied are
monotone — so every served batch has ``srv_idx >= srv_goal``.  The DST
invariant LINEARIZABLE_READ is exactly that reduction.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.read import lease
from swarmkit_tpu.raft.sim.state import LEADER, NONE, SimConfig

I32 = jnp.int32


class ReadRegs(NamedTuple):
    """The read subsystem's slice of SimState (all [N] i32)."""
    pend: jax.Array        # reads queued on this row (0 = idle)
    goal: jax.Array        # max(commit) anywhere at submit (oracle witness)
    idx: jax.Array         # ReadIndex stamp (NONE = not yet stamped)
    lease_until: jax.Array  # absolute expiry tick of the row's lease
    srv: jax.Array         # cumulative reads served
    block: jax.Array       # cumulative reads refused
    srv_idx: jax.Array     # applied index of the last served batch
    srv_goal: jax.Array    # submit goal of the last served batch


def regs_from_state(state) -> ReadRegs:
    return ReadRegs(pend=state.read_pend, goal=state.read_goal,
                    idx=state.read_idx, lease_until=state.lease_until,
                    srv=state.read_srv, block=state.read_block,
                    srv_idx=state.read_srv_idx,
                    srv_goal=state.read_srv_goal)


def read_fields(regs: ReadRegs) -> dict:
    """SimState field dict for dataclasses.replace at end of tick."""
    return dict(read_pend=regs.pend, read_goal=regs.goal,
                read_idx=regs.idx, lease_until=regs.lease_until,
                read_srv=regs.srv, read_block=regs.block,
                read_srv_idx=regs.srv_idx, read_srv_goal=regs.srv_goal)


def submit(cfg: SimConfig, regs: ReadRegs, alive: jax.Array,
           commit: jax.Array) -> ReadRegs:
    """R0: refill idle live rows with a fresh client batch, capturing
    the acked-write frontier as the batch's linearizability goal."""
    refill = alive & (regs.pend == 0)
    goal = jnp.max(commit)
    return regs._replace(
        pend=jnp.where(refill, cfg.read_batch, regs.pend),
        goal=jnp.where(refill, goal, regs.goal),
        idx=jnp.where(refill, NONE, regs.idx))


def stamp(cfg: SimConfig, regs: ReadRegs, *, alive: jax.Array,
          role: jax.Array, lead: jax.Array, term: jax.Array,
          commit: jax.Array, commit_term_ok: jax.Array, q_ok: jax.Array,
          transferee: jax.Array, now: jax.Array,
          drop: jax.Array) -> tuple[ReadRegs, jax.Array]:
    """R1: renew leases, then stamp pending batches with a read index.
    Returns (regs, confirm) where confirm[i] = row i could vouch for
    its leadership this tick (lease or quorum + own-term commit)."""
    n = regs.pend.shape[-1]
    is_leader = (role == LEADER) & alive
    lease_until = lease.renew(cfg, regs.lease_until, role, q_ok,
                              transferee, now)
    lease_ok = lease.valid(cfg, lease_until, is_leader, transferee, now)
    confirm = is_leader & commit_term_ok & (lease_ok | q_ok)
    unstamped = (regs.pend > 0) & (regs.idx == NONE)

    idx = jnp.where(unstamped & confirm, commit, regs.idx)

    # follower read: forward to the row's known leader, stamp with THAT
    # row's commit under the leader's own gates.  The round trip resolves
    # same-tick when both edge directions are clean (the mailbox wire's
    # latency budget is already inside lease_span, so same-tick resolution
    # never outruns the skew margin).
    node = jnp.arange(n, dtype=I32)
    li = jnp.clip(lead, 0, n - 1)
    has_lead = (lead != NONE) & (lead != node)
    rt_clean = ~drop[node, li] & ~drop[li, node]
    stamp_f = unstamped & alive & ~is_leader & has_lead \
        & (term == term[li]) & confirm[li] & rt_clean
    idx = jnp.where(stamp_f, commit[li], idx)
    return regs._replace(idx=idx, lease_until=lease_until), confirm


def settle(cfg: SimConfig, regs: ReadRegs, *, alive: jax.Array,
           applied: jax.Array, role: jax.Array, was_leader: jax.Array,
           now: jax.Array, prev_lease_until: jax.Array):
    """R2: serve stamped batches whose applied index caught the stamp;
    refuse unstamped batches whose serving basis is gone.

    Returns (regs, served, srv_cnt, blocked, blk_cnt, expired) — the
    masks feed the flight recorder (READ_SERVED / READ_BLOCKED /
    LEASE_EXPIRED).
    """
    is_leader = (role == LEADER) & alive
    served = alive & (regs.pend > 0) & (regs.idx != NONE) \
        & (applied >= regs.idx)
    srv_cnt = jnp.where(served, regs.pend, 0)
    regs = regs._replace(
        srv=regs.srv + srv_cnt,
        srv_idx=jnp.where(served, applied, regs.srv_idx),
        srv_goal=jnp.where(served, regs.goal, regs.srv_goal),
        pend=jnp.where(served, 0, regs.pend),
        idx=jnp.where(served, NONE, regs.idx))

    # a stamped batch is already linearizable and just waits for apply;
    # only UNSTAMPED batches get refused back to the client.
    unstamped = (regs.pend > 0) & (regs.idx == NONE)
    deposed = was_leader & (role != LEADER)
    if cfg.read_leases:
        # expiry edge: valid through tick now-1, invalid now, not renewed
        expired = is_leader & (prev_lease_until == now) \
            & (now >= regs.lease_until)
    else:
        expired = jnp.zeros_like(deposed)
    blocked = unstamped & (deposed | expired)
    blk_cnt = jnp.where(blocked, regs.pend, 0)
    regs = regs._replace(block=regs.block + blk_cnt,
                         pend=jnp.where(blocked, 0, regs.pend))
    return regs, served, srv_cnt, blocked, blk_cnt, expired

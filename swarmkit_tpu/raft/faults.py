"""Shared transport-seam fault surface + declarative fault plans.

Every raft wire in this repo (in-process asyncio ``Network``, the real-socket
``GrpcNetwork`` and the device-mesh mailbox ``DeviceMeshNet``) implements the
same injectable fault vocabulary, mirroring what the reference achieves with
real sockets in tests (WrappedListener drops, iptables partitions in BASELINE
configs):

- ``set_down(addr)``        — the node at `addr` is unreachable
- ``set_drop(frm, to, p)``  — probabilistic loss on a directed edge
- ``partition(*groups)``    — only nodes in the same group can talk
- ``set_delay(frm, to, s)`` — added latency on a directed edge
- ``crash_restart(addr)``   — sever wire-level state for a bounced process
                              (cached channels, staged mailbox slots)
- ``heal()``                — clear partitions, drops and delays

``FaultSurface`` holds the mutable fault state and decision helpers; wires
inherit it and consult ``_fault_blocked`` / ``lossy`` / ``delay_for`` on
their delivery paths (the in-process queue drain, the gRPC stub gate, the
device mailbox ``keep`` mask).  ``FaultPlan`` is the declarative form the
fault sweep (tools/fault_sweep.py) replays against each wire: a named list
of inject actions plus the repair actions that undo them.
"""

from __future__ import annotations

import random
from typing import Iterable


class FaultSurface:
    """Mutable fault state shared by every Network implementation."""

    def __init__(self, seed: int = 0) -> None:
        self._down: set[str] = set()
        self._drop: dict[tuple[str, str], float] = {}
        self._partitions: list[set[str]] = []
        self._delay: dict[tuple[str, str], float] = {}
        self._rng = random.Random(seed)
        self.delivered = 0
        self.dropped = 0

    # -- injection ---------------------------------------------------------
    def set_down(self, addr: str, down: bool = True) -> None:
        if down:
            self._down.add(addr)
        else:
            self._down.discard(addr)

    def set_drop(self, frm: str, to: str, p: float) -> None:
        if p <= 0:
            self._drop.pop((frm, to), None)
        else:
            self._drop[(frm, to)] = p

    def partition(self, *groups: Iterable[str]) -> None:
        self._partitions = [set(g) for g in groups]

    def set_delay(self, frm: str, to: str, seconds: float) -> None:
        if seconds <= 0:
            self._delay.pop((frm, to), None)
        else:
            self._delay[(frm, to)] = seconds

    def crash_restart(self, addr: str) -> None:
        """Sever wire-level state for a process bounce at `addr`.

        The base surface holds no per-connection state; wires that cache
        channels (GrpcNetwork) or stage undelivered payloads (DeviceMeshNet)
        override this to drop them, so a restarted process never receives
        traffic addressed to its previous incarnation."""

    def heal(self) -> None:
        self._partitions = []
        self._drop = {}
        self._delay = {}

    # -- decisions (consulted by delivery paths) ---------------------------
    def _fault_blocked(self, frm: str, to: str) -> bool:
        if to in self._down:
            return True
        for group in self._partitions:
            if (frm in group) != (to in group):
                return True
        return False

    def lossy(self, frm: str, to: str) -> bool:
        p = self._drop.get((frm, to), 0.0)
        return p > 0 and self._rng.random() < p

    def delay_for(self, frm: str, to: str) -> float:
        return self._delay.get((frm, to), 0.0)

    def faults_active(self) -> bool:
        return bool(self._down or self._drop or self._partitions
                    or self._delay)


class FaultPlan:
    """A named, replayable fault schedule: inject actions + repair actions.

    Actions are (method-name, args) pairs applied to any FaultSurface, so
    one plan definition runs identically against all three wires.  ``heal``
    runs the plan's repair actions (e.g. un-downing a node) and then the
    surface-wide ``heal()``.
    """

    def __init__(self, name: str, inject=(), repair=()) -> None:
        self.name = name
        self._inject = list(inject)
        self._repair = list(repair)

    def __repr__(self) -> str:
        return f"FaultPlan({self.name!r})"

    def inject(self, net: FaultSurface) -> None:
        for method, args in self._inject:
            getattr(net, method)(*args)

    def heal(self, net: FaultSurface) -> None:
        for method, args in self._repair:
            getattr(net, method)(*args)
        net.heal()

    # -- the five primitives ----------------------------------------------
    @classmethod
    def down(cls, addr: str) -> "FaultPlan":
        return cls(f"down({addr})",
                   inject=[("set_down", (addr, True))],
                   repair=[("set_down", (addr, False))])

    @classmethod
    def drop(cls, frm: str, to: str, p: float = 0.5,
             symmetric: bool = True) -> "FaultPlan":
        inject = [("set_drop", (frm, to, p))]
        if symmetric:
            inject.append(("set_drop", (to, frm, p)))
        return cls(f"drop({frm}<->{to},p={p})", inject=inject)

    @classmethod
    def split(cls, *groups: Iterable[str]) -> "FaultPlan":
        groups = tuple(tuple(g) for g in groups)
        return cls(f"partition({groups})",
                   inject=[("partition", groups)])

    @classmethod
    def delay(cls, frm: str, to: str, seconds: float,
              symmetric: bool = True) -> "FaultPlan":
        inject = [("set_delay", (frm, to, seconds))]
        if symmetric:
            inject.append(("set_delay", (to, frm, seconds)))
        return cls(f"delay({frm}<->{to},{seconds}s)", inject=inject)

    @classmethod
    def crash(cls, addr: str) -> "FaultPlan":
        return cls(f"crash_restart({addr})",
                   inject=[("crash_restart", (addr,))])


def plan_to_schedule(plan: FaultPlan, rows: dict[str, int], n: int,
                     ticks: int, inject_at: int = 0, heal_at=None,
                     seed: int = 0, tick_interval: float = 1.0) -> dict:
    """Lower a declarative FaultPlan into dense per-tick schedule arrays.

    The wire surfaces interpret faults at delivery time against live
    connection state; the DST kernel instead consumes the whole run as
    data — drop [T, N, N] and alive [T, N] — so each primitive lowers to a
    deterministic array pattern over the window [inject_at, heal_at):

    - ``set_down(addr)``      every edge INTO the row is dropped (the
                              surface blocks delivery TO down nodes)
    - ``set_drop(f, t, p)``   seeded Bernoulli per tick on the edge
    - ``partition(groups)``   cross-group edges dropped
    - ``set_delay(f, t, s)``  the synchronous wire retries every tick, so
                              a d-tick delay is the edge gated open only
                              every (d+1)-th tick (d = ceil(s / tick
                              interval)) — traffic lands d ticks late
    - ``crash_restart(addr)`` the row is not alive inside the window

    `rows` maps plan addresses to kernel row indices.  Returns numpy
    arrays (``dst.schedule.from_fault_plan`` wraps them on device).
    """
    import math

    import numpy as np

    heal_at = ticks if heal_at is None else heal_at
    if not 0 <= inject_at <= heal_at <= ticks:
        raise ValueError(f"bad fault window [{inject_at}, {heal_at}) "
                         f"for {ticks} ticks")
    drop = np.zeros((ticks, n, n), bool)
    alive = np.ones((ticks, n), bool)
    rng = np.random.default_rng(seed)
    win = slice(inject_at, heal_at)
    wlen = heal_at - inject_at

    for method, args in plan._inject:
        if method == "set_down":
            addr, down = (args + (True,))[:2]
            if down:
                drop[win, :, rows[addr]] = True
        elif method == "set_drop":
            frm, to, p = args
            drop[win, rows[frm], rows[to]] |= rng.random(wlen) < p
        elif method == "partition":
            groups = [set(rows[a] for a in g) for g in args]
            for i in range(n):
                for j in range(n):
                    if any((i in g) != (j in g) for g in groups):
                        drop[win, i, j] = True
        elif method == "set_delay":
            frm, to, seconds = args
            d = max(1, math.ceil(seconds / tick_interval))
            t = np.arange(inject_at, heal_at)
            drop[win, rows[frm], rows[to]] |= ((t - inject_at) % (d + 1)) != d
        elif method == "crash_restart":
            alive[win, rows[args[0]]] = False
        else:
            raise ValueError(f"cannot lower fault action {method!r}")
    return {"drop": drop, "alive": alive}

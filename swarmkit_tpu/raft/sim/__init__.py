"""Batched JAX/XLA raft simulation: N managers as rows of device arrays."""

from swarmkit_tpu.raft.sim.kernel import (
    propose, propose_conf, step, transfer_leadership,
)
from swarmkit_tpu.raft.sim.run import (
    committed_entries, has_leader, leader_mask, reads_blocked, reads_served,
    run_schedule, run_ticks, run_until_leader, submit_reads,
)
from swarmkit_tpu.raft.sim.state import (
    CANDIDATE, FOLLOWER, LEADER, NONE, SimConfig, SimState, drop_matrix,
    init_state, rand_timeout,
)

__all__ = [
    "propose", "propose_conf", "step", "transfer_leadership",
    "committed_entries", "has_leader", "leader_mask",
    "reads_blocked", "reads_served",
    "run_schedule", "run_ticks", "run_until_leader", "submit_reads",
    "CANDIDATE", "FOLLOWER", "LEADER",
    "NONE", "SimConfig", "SimState", "drop_matrix", "init_state",
    "rand_timeout",
]

"""The XLA-compiled raft tick kernel.

One call advances ALL N simulated managers by one logical tick, replacing the
reference's goroutine-per-node event loops (manager/state/raft/raft.go:540
Node.Run and vendor etcd raft Step/stepLeader/stepCandidate/stepFollower)
with branchless masked array ops:

- elections      = masked one-hot grant matrices + row reductions (poll)
- append fan-out = contiguous row-broadcast of the chosen sender's ring +
                   elementwise masked copies (see "slot alignment" below)
- commit         = per-leader quorum threshold located by a fixed-depth
                   binary search over the match row — decision-equivalent to
                   the sort-and-take rule of vendor raft.go:478-486
                   maybeCommit, but O(N log L) instead of an [N, N] sort
- network faults = per-edge boolean drop/partition masks; crashes = alive mask

TPU-first data movement: ring slot (idx-1) % L is index-determined and
identical on EVERY row, so "copy entries (p, p+W] from the sender" is a
row-gather (contiguous, bandwidth-bound) followed by elementwise masked
writes at the very same slot positions — the kernel contains no per-element
cross-row gathers and no sorts on its hot path. State-machine checksums are
order-independent sums of per-entry hashes, computed on the fly from
(index, payload); there is no checksum ring.

Two network models share one delivery-processing path:
- tick-synchronous (cfg.latency == 0): requests and their responses
  complete within one tick unless masked out — the bench fast path, with
  no mailbox state allocated.
- device-mailbox wire (cfg.latency/latency_jitter > 0; SURVEY §7's [N, N]
  in-flight slots): every message spends latency + hash-jitter ticks in a
  per-edge, per-class slot (appends: cfg.inflight slots — a pipelined
  window), so delivery is delayed and jitter REORDERS messages across
  edges.  Headers (term, prev) are captured at send; bodies are read from
  the sender's current ring at delivery, guarded by "sender role/term
  unchanged since send" (stale messages drop — always raft-safe, and the
  prefix (idx, term) content is immutable within a leader term).  At
  latency 0 the slots pass messages through same-tick, matching the
  synchronous wire bit-for-bit on fault-free runs; under faults the
  mailbox wire keeps its etcd flow-control semantics (gated by the
  differential suite's force_mailboxes cases).
Control flow divergence (leader vs candidate vs follower) is handled with
`jnp.where` over role masks — there is no data-dependent Python control
flow, so the whole step jits once and scans.

Implemented etcd behaviors beyond the basic protocol: vote rejections with
candidate step-down on a rejection quorum (vendor raft.go:988-1060);
CheckQuorum — both the periodic partitioned-leader step-down
(raft.go:536-560) and the leader lease that ignores vote requests from
rejoining nodes; PreVote (campaignPreElection: non-binding poll at term+1,
no term inflation from flapping nodes); leader transfer
(transfer_leadership() + the TIMEOUT_NOW wire, with CAMPAIGN_TRANSFER
lease bypass and proposal blocking while a transfer is in flight).
Windowed flow control (cfg.inflight = vendor MaxInflightMsgs) pipelines
appends on the mailbox wire with etcd's probe/replicate Progress states.
LOG-DRIVEN MEMBERSHIP: conf changes travel as committed CONF_TAG entries
(propose_conf) and activate at each row's own apply point (Phase E),
flipping that row's [N] slice of the `member` [N, N] view matrix — the
device analog of processConfChange (manager/state/raft/raft.go:1939,
membership/cluster.go:185).  Every quorum computation (votes, rejection
quorums, CheckQuorum, the commit bisection) counts over the deciding
row's view; campaign eligibility is the row's own self-membership (etcd
promotable); snapshots carry the sender's config.  etcd's one-in-flight
rule (pendingConf), the HUP gate on committed-but-unapplied conf entries
and the becomeLeader rescan are per-row registers (`pending_conf`,
`hup_conf`, `tail_conf`), the latter two carried from the previous tick's
Phase E scan (exact: nothing before their consumers mutates those log
ranges).  Win/lose poll decisions evaluate only on poll events (candidacy
start or response arrival), so a conf change shrinking a quorum between
arrivals cannot retro-promote a stale tally — mirroring core's _poll call
sites.
The mailbox wire carries a REAL HEARTBEAT CLASS (round 4, D1 closed):
MsgHeartbeat on the heartbeat_tick cadence with send-captured commit,
event-gated appends, and same-tick rejection re-sends; the synchronous
wire keeps appends-every-tick (at heartbeat_tick=1 that is etcd's
cadence with content folded in).
Deliberately simplified vs the host golden core (swarmkit_tpu.raft.core):
rejection hints are coarse (hint = follower last index).
Safety properties (election safety, log matching, leader completeness) are
preserved and asserted by tests/test_raft_sim.py invariant checks and the
per-tick differential gate (tests/test_raft_sim_differential.py against the
golden core).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.state import (
    CANDIDATE, CONF_REMOVE, CONF_TAG, CONF_TARGET_MASK, FOLLOWER, LEADER,
    NONE, SimConfig, SimState, hash32, latency_at, latency_matrix,
    rand_timeout,
)

I32 = jnp.int32
U32 = jnp.uint32


def _is_conf(data: jax.Array) -> jax.Array:
    """Conf-change entries are tagged in the payload (state.CONF_TAG)."""
    return (data & U32(CONF_TAG)) != 0


def _slot(cfg: SimConfig, idx):
    """Ring slot of 1-based log index (clamped so idx=0 is harmless)."""
    return (jnp.maximum(idx, 1) - 1) % cfg.log_len


def _idx_at_slots(cfg: SimConfig, last):
    """[*, L] log index stored at each ring slot, anchored at `last` [*]:
    the unique idx in (last - L, last] with (idx-1) % L == slot. Slots
    holding indexes <= snap_idx (or <= 0) are invalid — callers mask."""
    L = cfg.log_len
    s = jnp.arange(L, dtype=I32)[None, :]
    last = last[:, None]
    return last - ((last - (s + 1)) % L)


def _term_own(cfg, log_term, snap_idx, snap_term, last, idx):
    """Per-node own-log term lookup for [N] idx (single element per row)."""
    ring = jnp.take_along_axis(log_term, _slot(cfg, idx)[:, None],
                               axis=1)[:, 0]
    in_ring = (idx > snap_idx) & (idx <= last)
    return jnp.where(idx == snap_idx, snap_term, jnp.where(in_ring, ring, 0))


def _entry_chk(idx, data):
    """Order-independent state-machine checksum contribution of one entry."""
    return hash32(idx.astype(U32) * U32(0x01000193) ^ data.astype(U32))


# ---- log-axis tiling (cfg.tiled) ----------------------------------------
# Every [N, L] hot pass below (append copy, apply checksum, compaction
# subtraction, conf-gate scans, dense propose) only has live work inside
# the band of log indexes its cursors moved through this tick — at most
# window / apply_batch / max_props / keep entries, a compile-time bound.
# When cfg.tiled, the pass computes its band from [N] cursor extrema,
# visits cfg.band_chunks aligned ring chunks of cfg.log_chunk slots via
# lax.dynamic_slice, evaluates the SAME masked element-wise logic on each
# [N, log_chunk] chunk, and (for writes) dynamic_update_slice's it back —
# in place on the scan carry, so per-tick bytes scale with the work, not
# with log capacity.  A lax.cond falls back to the full pass when the
# cross-row straggler spread exceeds the band cap.  Bit-identity with the
# full pass holds because the masks are functions of absolute log indexes
# (false outside the live band), chunk visits are distinct
# (band_chunks < num_chunks, enforced by SimConfig validation), and the
# reductions are order-independent (bool any / int min / uint32 wrap-safe
# sums) — asserted by tests/test_raft_sim.py::TestTiledLog and the DST
# cross-check sweep.


def _band_origin(cfg: SimConfig, lo, hi):
    """Unwrapped chunk coordinates of the live band (lo, hi] of 1-based log
    indexes: (c0u, nchunks) where c0u is the chunk holding index lo+1 and
    nchunks counts chunks through index hi.  nchunks <= 0 on an empty band;
    callers compare nchunks against cfg.band_chunks for the fallback."""
    c0u = lo // cfg.log_chunk
    return c0u, (hi - 1) // cfg.log_chunk - c0u + 1


def _band_offsets(cfg: SimConfig, c0u):
    """Slot offsets of the cfg.band_chunks ring chunks a banded pass
    visits, starting at unwrapped chunk c0u.  Offsets are pairwise
    distinct (band_chunks < num_chunks), so per-chunk reductions never
    double count and per-chunk writes never overlap."""
    return [((c0u + k) % cfg.num_chunks) * cfg.log_chunk
            for k in range(cfg.band_chunks)]


def _idx_at_band(cfg: SimConfig, anchor, off):
    """[N, log_chunk] analog of _idx_at_slots for the single chunk at slot
    offset `off` (traced), anchored at `anchor` [N]."""
    s = (off + jnp.arange(cfg.log_chunk, dtype=I32))[None, :]
    a = anchor[:, None]
    return a - ((a - (s + 1)) % cfg.log_len)


_PALLAS_BAND = None


def _pallas_band_copy():
    """Opt-in fused Pallas kernel for the banded append copy (set
    SWARMKIT_PALLAS_BAND=1; parallel.pallas_ops.append_band_copy tiles the
    chunk through VMEM on TPU, interpret mode elsewhere).  Off by default
    so the portable hot path stays pure jnp — the op is value-identical to
    jnp.where either way (asserted by tests)."""
    global _PALLAS_BAND
    if _PALLAS_BAND is None:
        import os
        if os.environ.get("SWARMKIT_PALLAS_BAND", "0") not in ("", "0"):
            from swarmkit_tpu.parallel.pallas_ops import append_band_copy
            _PALLAS_BAND = append_band_copy
        else:
            _PALLAS_BAND = False
    return _PALLAS_BAND


def step(state: SimState, cfg: SimConfig,
         alive: Optional[jax.Array] = None,
         drop: Optional[jax.Array] = None,
         prop_count=None,
         payload_fn: Optional[Callable] = None,
         prop_tag=None) -> SimState:
    """Advance every simulated manager by one tick.

    alive: [N] bool — False rows are crashed (frozen, no send/receive).
    drop:  [N, N] bool — drop[i, j] drops all i->j traffic this tick.

    prop_tag: optional scalar i32 host trace tag for the fused propose
    batch (cfg.trace_tags; metrics/trace.py span_trace_tag) — stamped
    into the [N, PROP_RING] tag ring and carried to the COMMIT_ADVANCE
    event the proposing span is waiting on.  Ignored when trace_tags is
    off.

    prop_count/payload_fn: optional FUSED dense propose — bit-identical to
    ``step(propose_dense(state, cfg, payload_fn, prop_count, alive), ...)``
    but with the proposal ring stores folded into Phase C's single
    banded-write cond.  XLA keeps scan-carried [N, L] buffers in place only
    while the scan body holds at most ONE cond whose branches rewrite them;
    a separate propose_dense cond per tick costs full-capacity log copies
    (the exact decoupling this tiling exists to remove), so the scan
    drivers in run.py always propose through here.  The [N] cursor effects
    land at the top of the tick; the three pre-cond ring reads that could
    see a freshly proposed entry (last_term, local_p_term, have_term) are
    patched analytically: a proposing row's new entries all carry its own
    pre-tick term.
    """
    n = cfg.n
    node = jnp.arange(n, dtype=I32)
    eye = jnp.eye(n, dtype=bool)
    if alive is None:
        alive = jnp.ones((n,), bool)
    if drop is None:
        drop = jnp.zeros((n, n), bool)

    term, vote, role, lead = state.term, state.vote, state.role, state.lead
    elapsed, hb_elapsed = state.elapsed, state.hb_elapsed
    timeout = state.timeout
    last, commit, applied = state.last, state.commit, state.applied
    snap_idx, snap_term = state.snap_idx, state.snap_term
    snap_chk, apply_chk = state.snap_chk, state.apply_chk
    log_term, log_data = state.log_term, state.log_data
    match, next_, granted = state.match, state.next_, state.granted
    rejected, recent_active = state.rejected, state.recent_active
    pre = state.pre
    member = state.member
    pending_conf = state.pending_conf

    # Fused dense propose (see docstring): cursor effects now, ring stores
    # deferred to Phase C's write cond.  Rows are judged on the PRE-tick
    # state exactly as a standalone propose_dense call would.
    fused_prop = payload_fn is not None
    if fused_prop:
        prop_ok = _leader_ok(state, cfg, alive)
        prop_cnt = jnp.asarray(prop_count, I32)
        prop_last0 = last
        prop_anchor = prop_last0 + prop_cnt
        last = last + jnp.where(prop_ok, prop_cnt, 0).astype(I32)
        # (the match-diagonal bump rides the first progress segment below,
        # where match is held as an [A, N] slab under the sparse lowering)

    # Per-row membership views: every quorum decision counts over the
    # deciding row's APPLIED configuration (reference: each node's prs map
    # materializes conf changes at its own apply point, raft.go:1939).
    # Under cfg.static_members the config is the full row set forever:
    # views collapse to constants and every mask below traces away.
    static_m = cfg.static_members

    # ---- peer-axis tiling (cfg.peer_tiled): hierarchical quorum counts --
    # Every [N, N] tally in the tick (CheckQuorum heard-count, the vote /
    # pre-vote / rejection tallies, the commit bisection's per-round
    # compares, the heartbeat-ack quorum Phase R1 reuses) is a per-row
    # COUNT of peers satisfying a predicate.  When cfg.peer_tiled, _pcount
    # evaluates the predicate one [N, peer_chunk] column band at a time
    # (lax.dynamic_slice — device-local under parallel.shard_rows, which
    # shards rows and replicates columns), folds the deciding row's
    # membership view into the band (once per band — the dense bisect
    # instead materializes a full [N, N] match_eff once and re-compares it
    # every round), writes the group-local count into column g of an
    # [N, num_peer_chunks] partial buffer, and combines across groups with
    # one final sum — the two-level hierarchical reduction.  No full [N, N]
    # boolean/compare intermediate is ever materialized, so reduction
    # temporaries scale with n*peer_chunk instead of n².  Integer sums are
    # order-independent, hence bit-identical to the dense lowering
    # (TestTiledPeer + the DST cross-check assert this field-by-field);
    # composes with dst/explore.py's vmap (batched dynamic_slice) and the
    # row-sharded mesh (tests/test_sharded_sim.py).
    if cfg.peer_tiled:
        PC, PG = cfg.peer_chunk, cfg.num_peer_chunks

        def _pband(x, j0):
            """[R, peer_chunk] column band of an [R, N] matrix at j0 (R is
            n on the dense path, cfg.active_rows on a progress slab)."""
            return jax.lax.dynamic_slice(x, (0, j0), (x.shape[0], PC))

        def _peye_rows(rows_v, j0):
            """Analytic eye band for arbitrary row ids: no [N, N] identity
            materialized (rows_v is `node` dense, the slab ids sparse)."""
            return rows_v[:, None] == (j0 + jnp.arange(PC,
                                                       dtype=I32))[None, :]

        def _peye(j0):
            return _peye_rows(node, j0)

        def _pcount(pred, masked=True, mem=None, rows_n=None):
            """Per-row count of peers j with pred band true, hierarchical.
            `masked` folds the deciding row's membership view into each
            band (the _mview analog; no-op under static_members).
            mem/rows_n retarget the count at an [A, N] row slab for the
            role-sparse progress path: mem is the slab's membership view,
            rows_n its row count."""
            mem_ = member if mem is None else mem
            R0 = n if rows_n is None else rows_n

            def _grp(g, acc):
                j0 = g * PC
                p = pred(j0)
                if masked and not static_m:
                    p = p & _pband(mem_, j0)
                c = jnp.sum(p.astype(I32), axis=1)
                return jax.lax.dynamic_update_slice(acc, c[:, None], (0, g))
            parts = jax.lax.fori_loop(0, PG, _grp, jnp.zeros((R0, PG), I32))
            return jnp.sum(parts, axis=1)

    if static_m:
        self_mem = jnp.ones((n,), bool)
        quorum_row = n // 2 + 1                                  # scalar
    else:
        self_mem = jnp.diagonal(member)                          # [N]
        if cfg.peer_tiled:
            n_mem = _pcount(lambda j0: jnp.ones((n, PC), bool))  # [N]
        else:
            n_mem = jnp.sum(member.astype(I32), axis=1)          # [N]
        quorum_row = n_mem // 2 + 1                              # [N]

    def _mview(x):
        """Mask an [N, N] tally/flag matrix by the deciding row's view."""
        return x if static_m else (x & member)

    now = state.tick   # pre-increment tick: all wire timestamps key off it

    # ---- Phase R0: read-batch submit (cfg.read_batch; raft/read/) --------
    # Idle rows take a fresh client batch and capture the PRE-tick acked
    # frontier max(commit) as the batch's linearizability goal.  Python-
    # gated like the flight recorder: read_batch=0 traces none of this.
    reads_on = cfg.read_batch > 0
    if reads_on:
        from swarmkit_tpu.raft import read as _rd
        with jax.named_scope("phase_R0_submit"):
            read_regs = _rd.submit(cfg, _rd.regs_from_state(state), alive,
                                   commit)

    # ---- Phase A: timers + CheckQuorum + campaign start ------------------
    # Liveness splits from membership: crashed rows freeze entirely;
    # non-member rows still receive and respond (a joiner must be able to
    # catch up before its own view says it belongs) but never campaign
    # (etcd promotable()).
    with jax.named_scope("phase_A_timers"):
        is_leader = (role == LEADER) & alive
        elapsed = jnp.where(alive, elapsed + 1, elapsed)
        contact = jnp.where(alive, state.contact + 1, state.contact)
        hb_elapsed = jnp.where(is_leader, hb_elapsed + 1, hb_elapsed)
    # transfer-abuse cooldown (cfg.transfer_cooldown_ticks): count down one
    # tick here; the register re-arms in _progress_b on the row whose
    # TIMEOUT_NOW actually fired, and the request sites
    # (transfer_leadership, dst apply_transfer_abuse) refuse new targets
    # while it is nonzero.
    tx_cool = None
    if cfg.transfer_cooldown_ticks > 0 and state.tx_cool is not None:
        tx_cool = jnp.maximum(state.tx_cool - 1, 0)

    # ---- storage model (cfg.storage_on): the fsync round ----------------
    # The durable watermark chases the PRE-TICK last (state.last, before
    # the fused propose's cursor bump above): an entry appended this tick
    # is never durable the same tick.  Fsync completes only on cadence
    # ticks (tick % fsync_lag_ticks == fsync_lag_ticks - 1, so 1 = every
    # tick), syncs at most fsync_batch entries per round (0 = unlimited),
    # and is frozen on crashed rows and rows whose disk the disk_stall
    # verb is holding.  Vote-record writes are NOT on this policy: they
    # are write-through (etcd MustSync — the grant path fsyncs the vote
    # synchronously before responding), which is why a stalled disk
    # refuses grants below rather than lagging them.
    storage_on = cfg.storage_on and state.sync_mark is not None
    sync_mark = fsync_did = None
    if storage_on:
        with jax.named_scope("phase_A_fsync"):
            sync_mark = state.sync_mark
            fs_due = (now % cfg.fsync_lag_ticks) == cfg.fsync_lag_ticks - 1
            sync_inc = jnp.maximum(state.last - sync_mark, 0)
            if cfg.fsync_batch > 0:
                sync_inc = jnp.minimum(sync_inc, cfg.fsync_batch)
            sync_ok = alive & ~state.fsync_stall & fs_due
            sync_mark = sync_mark + jnp.where(sync_ok, sync_inc, 0)
            fsync_did = sync_ok

    # ---- role-sparse progress (cfg.active_rows_on): the active-row set --
    # Only rows whose node is a leader or candidate ever MUTATE their own
    # [N, N] progress view (match/next_/granted/rejected/recent_active, the
    # per-edge mailbox slots, and the ack folds that feed them) — follower
    # rows are dead weight on the row axis, though every row still acts as
    # a RECEIVER along the full column axis.  The two progress segments
    # below (_progress_a: Phase A matrix writes + Phase B + Phase C
    # send/deliver; _progress_b: ack folds + progress integration +
    # Phase D/R1 counts) therefore run on a compact [A, N] row slab
    # gathered here and scattered back, with a bit-identical dense
    # fallback cond on ticks where the active count exceeds A (election
    # storms) — mirroring the tiled-log fallback contract.
    #
    # The active predicate is a strict SUPERSET of "mutates its row this
    # tick", computed BEFORE any of this tick's role transitions:
    #   - role != FOLLOWER: standing leaders/candidates (a row that wins
    #     or campaigns mid-tick is covered by the terms below);
    #   - elapsed >= timeout (live, self-member): may campaign this tick;
    #   - tn_at > 0: a TIMEOUT_NOW delivery may force a campaign;
    #   - active_ttl > 0: the drain window — a row keeps its slab seat for
    #     2*(latency+latency_jitter)+2 ticks after leaving leadership, an
    #     upper bound on the round-trip lifetime of anything it still has
    #     in flight (in-flight acks must fold/clear on the slab).
    # Supersets are safe: every slab update is masked by the same role
    # conditions as the dense form, so an extra (or padding) row's slab
    # values scatter back unchanged.  Bit-identity of the two lowerings is
    # pinned by TestSparseProgress on all three wires plus the DST
    # cross-check sweep.
    sparse_on = cfg.active_rows_on
    if sparse_on:
        sp_act = (role != FOLLOWER) | (state.active_ttl > 0) \
            | (alive & self_mem & (elapsed >= timeout)) | (state.tn_at > 0)
        sp_fits = jnp.sum(sp_act.astype(I32)) <= cfg.active_rows
        # stable sort: active rows first, in ascending row order — so slab
        # argmax tie-breaks (lowest row wins) match the dense argmax
        sp_rows = jnp.argsort(~sp_act, stable=True)[:cfg.active_rows] \
            .astype(I32)

    def _slabify(rows, dense):
        """Row-slab toolkit for one progress segment instantiation.

        dense=True is the reference lowering: every helper is the
        identity, so the segment body IS the historical dense code op for
        op (no gathers, no scatters — the fallback branch and the
        active_rows=0 build stay bit-and-cost-identical to the pre-sparse
        kernel).  dense=False gathers row-indexed operands into [A, N]
        slabs and scatters merged rows back."""
        if dense:
            ident = lambda x: x                                # noqa: E731
            sc = lambda full, slab: slab                       # noqa: E731
            sfull = lambda vals, fill: vals                    # noqa: E731
            return (n, ident, sc, sfull, eye, drop, drop.T, member,
                    _mview)
        R = cfg.active_rows
        g = lambda x: x[rows]                                  # noqa: E731

        def sc(full, slab):
            """Merge a mutated [A, N] (or [A, N, K]) slab back."""
            return full.at[rows].set(slab, unique_indices=True)

        def sfull(vals, fill):
            """Scatter an [A] per-slab-row reduction to [N].  The fill
            lands on inactive rows, whose dense value differs only where
            downstream consumers are role-gated off anyway."""
            base = jnp.full((n,) + vals.shape[1:], fill, vals.dtype)
            return base.at[rows].set(vals, unique_indices=True)

        eye_r = rows[:, None] == node[None, :]
        member_r = member if static_m else member[rows]
        mview_r = (lambda x: x) if static_m else (lambda x: x & member_r)
        return (R, g, sc, sfull, eye_r, drop[rows], drop[:, rows].T,
                member_r, mview_r)

    # last/last_term are log-derived and Phase A/B never append, so both
    # are hoisted ABOVE the progress segments: no [N, L] read ever enters
    # the sparse/dense cond (the ring write cond stays the only cond that
    # consumes the log carries).
    last_term = _term_own(cfg, log_term, snap_idx, snap_term, last, last)
    if fused_prop:
        # ring stores are still pending in Phase C; a proposing row's new
        # last entry carries its own pre-tick term
        last_term = jnp.where(prop_ok & (prop_cnt > 0), state.term,
                              last_term)

    @jax.named_scope("phases_ABC_progress")
    def _progress_a(rows, dense, term=term, vote=vote, role=role, lead=lead,
                    elapsed=elapsed, contact=contact,
                    hb_elapsed=hb_elapsed, timeout=timeout, pre=pre,
                    last=last, commit=commit, pending_conf=pending_conf,
                    is_leader=is_leader, match=match, next_=next_,
                    granted=granted, rejected=rejected,
                    recent_active=recent_active):
        """Progress segment 1: Phase A's matrix tail (CheckQuorum count +
        campaign tally resets), all of Phase B, and Phase C's send/deliver
        half — every [N, N]/[N, N, K] op whose ROW index is a
        leader/candidate — instantiated either dense (rows=node: the
        historical lowering, op for op) or on the [A, N] active-row slab.
        [N]-vector logic is row/column mixed and cheap, so it stays
        verbatim at full width in both instantiations; only matrix
        operands go through the slab toolkit.  Column-axis reductions over
        the sender axis are exact on the slab because every true sender
        row is active and padding rows reduce at the identity (-1 max /
        big min / False any) via the same role masks the dense form uses;
        slab argmaxes map back through `rows` (ascending, so ties break
        identically) and are normalized with their any-gate (dense argmax
        of an all-False column is 0, so the normalized form is
        bit-identical there too)."""
        (R, g, sc, sfull, eye_r, drop_r, dropT_r, member_r,
         mview_r) = _slabify(rows, dense)
        match0, next0, granted0, rejected0, ra0 = (
            match, next_, granted, rejected, recent_active)
        match, next_, granted = g(match), g(next_), g(granted)
        rejected, recent_active = g(rejected), g(recent_active)
        if fused_prop:
            # the fused propose's match-diag store (deferred from the
            # pre-segment cursor block: prop_ok rows are leaders, hence
            # active, and nothing reads match before Phase B)
            match = jnp.where(g(prop_ok)[:, None] & eye_r,
                              g(last)[:, None], match)
        if cfg.has_vote_guard:
            # persisted-vote guard (the WAL-shadow defense for the
            # vote_equivocation adversary, subsumed by the full storage
            # model's durable register set): a durable (term, candidate)
            # record written alongside EVERY vote assignment and never
            # cleared by schedule verbs — so an adversarial wipe of
            # `vote` cannot make this row grant a SECOND candidate in
            # the same term.  Redundant (hence bit-identical) on stock
            # runs: vote == NONE at term t implies vg_term < t there.
            vg_vote, vg_term = state.vg_vote, state.vg_term

        # CheckQuorum (vendor raft.go:536-560 tickHeartbeat +
        # checkQuorumActive): every election_tick ticks a leader confirms
        # it heard from a quorum of members since the last round; a
        # partitioned stale leader steps down instead of lingering until a
        # higher term reaches it.  Python-gated by cfg.check_quorum
        # (True = the historical unconditional program; False exists only
        # for the disruptive_rejoin defense-off demo — the periodic timer
        # still drives the transfer abort below either way).
        check_due = is_leader & (elapsed >= cfg.election_tick)
        if cfg.check_quorum:
            if cfg.peer_tiled:
                n_heard = sfull(_pcount(
                    lambda j0: _pband(recent_active, j0)
                    | _peye_rows(rows, j0),
                    mem=member_r, rows_n=R), 0)
            else:
                n_heard = sfull(jnp.sum(mview_r(recent_active | eye_r)
                                        .astype(I32), axis=1), 0)
            cq_fail = check_due & (n_heard < quorum_row)
            role = jnp.where(cq_fail, FOLLOWER, role)
            lead = jnp.where(cq_fail, NONE, lead)
            # a quorum-confirmed leader re-arms its own lease (core
            # CHECK_QUORUM)
            contact = jnp.where(check_due & ~cq_fail, 0, contact)
            recent_active = jnp.where(g(check_due)[:, None], False,
                                      recent_active)
        elapsed = jnp.where(check_due, 0, elapsed)
        is_leader = (role == LEADER) & alive
        # a transfer that hasn't completed within an election timeout is
        # aborted so the leader can accept proposals again (vendor raft.go
        # tickHeartbeat abortLeaderTransfer)
        transferee = state.transferee
        transferee = jnp.where(check_due, NONE, transferee)
        transferee = jnp.where(role != LEADER, NONE, transferee)

        # TIMEOUT_NOW delivery (vendor stepFollower MsgTimeoutNow): the
        # transfer target campaigns immediately — a REAL campaign even
        # under PreVote, whose requests carry CAMPAIGN_TRANSFER and bypass
        # leases.
        tx_cand = state.tx_cand
        tn_at, tn_term, tn_from = state.tn_at, state.tn_term, state.tn_from
        tn_due = (tn_at > 0) & (state.tick + 1 >= tn_at)
        # only followers act on an equal-term TIMEOUT_NOW (stepCandidate
        # has no case for it); a higher-term one first demotes any
        # non-leader to follower via the Step catch-up, which then
        # campaigns.  The target must consider itself a member
        # (promotable(), vendor stepFollower MsgTimeoutNow) — but the HUP
        # conf gate does NOT apply (transfer campaigns bypass it by
        # calling campaign directly).
        tn_ok = tn_due & alive & self_mem & (role != LEADER) \
            & (tn_term >= term) & ((role == FOLLOWER) | (tn_term > term))
        # Step catch-up for a higher-term TIMEOUT_NOW: only the term
        # carries through — role/vote/lead are immediately overwritten by
        # the forced campaign below (vendor becomeFollower(m.Term) then
        # campaign)
        tn_newer = tn_ok & (tn_term > term)
        term = jnp.where(tn_newer, tn_term, term)
        tn_at = jnp.where(tn_due, 0, tn_at)

        # tickElection fires for any promotable non-leader whose timer
        # expired (resetting the timer either way); the HUP step then
        # refuses to campaign while a conf entry sits
        # committed-but-unapplied (vendor raft.go Step MsgHup
        # numOfPendingConf gate).
        want_campaign = (alive & self_mem & (role != LEADER)
                         & (elapsed >= timeout)) & ~tn_ok
        elapsed = jnp.where(want_campaign, 0, elapsed)
        campaign = want_campaign & ~state.hup_conf
        if cfg.pre_vote:
            # becomePreCandidate (vendor raft.go): a non-binding poll — no
            # term bump, no vote change, no timeout re-randomization, and
            # the known leader is KEPT (only the real campaign's reset
            # clears it); only the vote tallies and the candidacy marker
            # reset.
            pre = jnp.where(campaign, True, pre)
            role = jnp.where(campaign, CANDIDATE, role)
            granted = jnp.where(g(campaign)[:, None], eye_r, granted)
            rejected = jnp.where(g(campaign)[:, None], False, rejected)
        else:
            term = term + campaign.astype(I32)
            vote = jnp.where(campaign, node, vote)
            if cfg.has_vote_guard:
                vg_vote = jnp.where(campaign, node, vg_vote)
                vg_term = jnp.where(campaign, term, vg_term)
            role = jnp.where(campaign, CANDIDATE, role)
            lead = jnp.where(campaign, NONE, lead)
            timeout = jnp.where(campaign, rand_timeout(cfg, node, term),
                                timeout)
            granted = jnp.where(g(campaign)[:, None], eye_r, granted)
            rejected = jnp.where(g(campaign)[:, None], False, rejected)
        tx_cand = tx_cand & ~campaign  # a timeout candidacy is never forced
        # forced (transfer) campaign: always real, even under PreVote
        term = term + tn_ok.astype(I32)
        vote = jnp.where(tn_ok, node, vote)
        if cfg.has_vote_guard:
            vg_vote = jnp.where(tn_ok, node, vg_vote)
            vg_term = jnp.where(tn_ok, term, vg_term)
        role = jnp.where(tn_ok, CANDIDATE, role)
        pre = pre & ~tn_ok
        lead = jnp.where(tn_ok, NONE, lead)
        elapsed = jnp.where(tn_ok, 0, elapsed)
        timeout = jnp.where(tn_ok, rand_timeout(cfg, node, term), timeout)
        granted = jnp.where(g(tn_ok)[:, None], eye_r, granted)
        rejected = jnp.where(g(tn_ok)[:, None], False, rejected)
        tx_cand = jnp.where(tn_ok, True, tx_cand)

        # ---- Phase B: vote exchange --------------------------------------
        is_cand = (role == CANDIDATE) & alive
        # CheckQuorum leader lease (vendor raft.go Step, checkQuorum
        # branch): a receiver that heard from a live leader within the
        # last election_tick ignores vote requests entirely — no term
        # catch-up, no response — so a rejoining partitioned node cannot
        # depose a healthy leader.  Lease from LEADER CONTACT (not the
        # election timer, which re-arms on every campaign attempt —
        # core.py contact_elapsed rationale)
        if cfg.check_quorum:
            leased = (lead != NONE) & (contact < cfg.election_tick)  # [j]
        else:
            leased = jnp.zeros((n,), bool)   # defense off: no lease
        if cfg.mailboxes:
            # Device-mailbox wire (SURVEY §7): one in-flight message per
            # class per directed edge; *_at stores deliver-tick+1
            # (0 = empty).  The drop matrix acts at SEND (a dropped
            # message never enters the wire); receiver-side guards act at
            # DELIVERY.  On the slab, latency rows rebuild analytically
            # (latency_at) — no [N, N] latency matrix materializes.
            if dense:
                lat = latency_matrix(cfg, now)
                lat_T = lat.T
            else:
                lat = latency_at(cfg, now, rows[:, None], node[None, :])
                lat_T = latency_at(cfg, now, node[None, :], rows[:, None])
            vreq_at, vreq_term = g(state.vreq_at), g(state.vreq_term)
            vreq_pre = g(state.vreq_pre)
            vresp_at, vresp_term = g(state.vresp_at), g(state.vresp_term)
            vresp_grant, vresp_pre = (g(state.vresp_grant),
                                      g(state.vresp_pre))
            # sends: candidates (re-)request on any edge with no message
            # from the SAME candidacy (term, pre) still in flight (etcd
            # does not retry within a term — the re-send on a cleared slot
            # mirrors duplicate-tolerant voters)
            free = (vreq_at == 0) | (vreq_term != g(term)[:, None]) \
                | (vreq_pre != g(pre)[:, None])
            # requests go only to peers in the CANDIDATE's view (etcd
            # campaigns over its own prs map)
            send_vr = mview_r(g(is_cand)[:, None] & ~eye_r & ~drop_r
                              & free)
            vreq_at = jnp.where(send_vr, now + 1 + lat, vreq_at)
            vreq_term = jnp.where(send_vr, g(term)[:, None], vreq_term)
            vreq_pre = jnp.where(send_vr, g(pre)[:, None], vreq_pre)
            # deliveries: stale requests (sender no longer in the captured
            # candidacy) vanish — candidate log state (last/last_term) is
            # then safely readable at delivery, since candidates never
            # append
            due_vr = (vreq_at > 0) & (now + 1 >= vreq_at)
            deliv = due_vr & (g(role)[:, None] == CANDIDATE) \
                & (g(term)[:, None] == vreq_term) \
                & (g(pre)[:, None] == vreq_pre) \
                & alive[None, :] & (~leased[None, :] | g(tx_cand)[:, None])
            req = deliv & ~g(pre)[:, None]
            preq = deliv & g(pre)[:, None]
            vreq_at = jnp.where(due_vr, 0, vreq_at)
        else:
            base_req = mview_r(g(is_cand)[:, None] & alive[None, :]
                               & ~eye_r & ~drop_r
                               & (~leased[None, :] | g(tx_cand)[:, None]))
            req = base_req & ~g(pre)[:, None]
            preq = base_req & g(pre)[:, None]

        # -- PreVote exchange (vendor raft.go Step MsgPreVote): processed
        # BEFORE real votes each tick (defined delivery order), against
        # the receiver's pre-catch-up state; grants change NO receiver
        # state.  (last_term is hoisted above the segments — no log read
        # in here.)
        lt_i, lt_j = g(last_term)[:, None], last_term[None, :]
        log_ok = (lt_i > lt_j) \
            | ((lt_i == lt_j) & (g(last)[:, None] >= last[None, :]))
        if cfg.pre_vote:
            pv_term = jnp.where(preq, g(term)[:, None] + 1, -1)  # msg term
            # below the receiver's term: silently ignored (core stale
            # return)
            pv_cur = preq & (pv_term >= term[None, :])
            pv_can = (vote[None, :] == NONE) | (pv_term > term[None, :]) \
                | (vote[None, :] == rows[:, None])
            pv_grant = pv_cur & pv_can & log_ok
            # rejections count only when stamped with the candidacy's own
            # term (a reject from a receiver already past term+1 is
            # dropped in the wire; the lagging pre-candidate catches up
            # via appends — D2')
            pv_reject = pv_cur & ~pv_grant \
                & (term[None, :] == g(term)[:, None])
            pre_cand = is_cand & pre
            if cfg.mailboxes:
                send_pv = (pv_grant | pv_reject) & ~dropT_r
                vresp_at = jnp.where(send_pv, now + 1 + lat_T, vresp_at)
                vresp_term = jnp.where(send_pv, g(term)[:, None],
                                       vresp_term)
                vresp_pre = jnp.where(send_pv, True, vresp_pre)
                vresp_grant = jnp.where(send_pv, pv_grant, vresp_grant)
                due_pv = (vresp_at > 0) & (now + 1 >= vresp_at) & vresp_pre
                rv_pv = due_pv & g(pre_cand)[:, None] \
                    & (g(term)[:, None] == vresp_term)
                granted = granted | (rv_pv & vresp_grant)
                rejected = rejected | (rv_pv & ~vresp_grant)
                vresp_at = jnp.where(due_pv, 0, vresp_at)
                pv_polled = sfull(jnp.any(rv_pv, axis=1), False)
            else:
                granted = granted | (pv_grant & ~dropT_r
                                     & g(pre_cand)[:, None])
                rejected = rejected | (pv_reject & ~dropT_r
                                       & g(pre_cand)[:, None])
                pv_polled = sfull(jnp.any((pv_grant | pv_reject) & ~dropT_r
                                          & g(pre_cand)[:, None], axis=1),
                                  False)
            # Pre-quorum -> REAL campaign, evaluated BEFORE the real
            # exchange (vendor stepCandidate transitions the moment the
            # poll reaches quorum): bump term, vote self, reset tallies,
            # re-randomize the timeout.  Real vote requests go out next
            # send opportunity.  Evaluated only on POLL EVENTS (fresh
            # candidacy or a response arrival, core._poll call sites): a
            # conf change shrinking the quorum must not retro-promote a
            # stale tally between arrivals.
            if cfg.peer_tiled:
                votes_pv = sfull(_pcount(
                    lambda j0: _pband(granted, j0),
                    mem=member_r, rows_n=R), 0)
            else:
                votes_pv = sfull(jnp.sum(mview_r(granted).astype(I32),
                                         axis=1), 0)
            pre_win = pre_cand & (votes_pv >= quorum_row) \
                & (campaign | pv_polled)
            term = term + pre_win.astype(I32)
            vote = jnp.where(pre_win, node, vote)
            if cfg.has_vote_guard:
                vg_vote = jnp.where(pre_win, node, vg_vote)
                vg_term = jnp.where(pre_win, term, vg_term)
            pre = jnp.where(pre_win, False, pre)
            lead = jnp.where(pre_win, NONE, lead)  # becomeCandidate reset
            elapsed = jnp.where(pre_win, 0, elapsed)
            timeout = jnp.where(pre_win, rand_timeout(cfg, node, term),
                                timeout)
            granted = jnp.where(g(pre_win)[:, None], eye_r, granted)
            rejected = jnp.where(g(pre_win)[:, None], False, rejected)

        # -- real vote exchange.
        # Receiver-side term catch-up (Step m.Term > r.Term with MsgVote).
        req_term = jnp.where(req, g(term)[:, None], -1)
        mt = jnp.max(req_term, axis=0)                           # [j]
        newer = mt > term
        term = jnp.where(newer, mt, term)
        role = jnp.where(newer, FOLLOWER, role)
        vote = jnp.where(newer, NONE, vote)
        lead = jnp.where(newer, NONE, lead)
        # become_follower(m.term) runs _reset: timer zeroed, timeout
        # re-rolled at the new term (deterministic per (node, term))
        elapsed = jnp.where(newer, 0, elapsed)
        timeout = jnp.where(newer, rand_timeout(cfg, node, term), timeout)
        is_cand = (role == CANDIDATE) & alive  # stepped-down candidates
        #                                        drop out

        # (last_term / log_ok computed above the PreVote block; Phase B
        # never mutates log state, so they stay valid here.)
        can_vote = (vote[None, :] == NONE) | (vote[None, :] == rows[:, None])
        if cfg.has_vote_guard:
            # the durable record outlives an adversarial wipe of `vote`:
            # a row that already voted this term may only re-grant the
            # SAME candidate (a restarted voter re-sending a duplicate
            # grant is raft-legal; a conflicting grant is not)
            can_vote = can_vote & ((vg_term[None, :] < term[None, :])
                                   | (vg_vote[None, :] == rows[:, None]))
        if storage_on and cfg.ack_gating:
            # a stalled disk (disk_stall verb) cannot persist the vote
            # record before replying (etcd MustSync), so the grant is
            # refused outright; PreVote polls are non-binding and need
            # no persistence, hence stay un-gated
            can_vote = can_vote & ~state.fsync_stall[None, :]
        # Compare the SEND-TIME candidate term (req_term) with the
        # receiver's post-catch-up term: a candidate whose own term was
        # bumped this tick by a higher-term rival must not have its stale
        # request treated as current.
        cur = req & (req_term == term[None, :])  # requests at the rx term
        grantable = cur & can_vote & log_ok
        any_grant = jnp.any(grantable, axis=0)                   # [j]
        # first True; slab positions map back through `rows` (ascending,
        # so the lowest-row tie-break is preserved), gated on any_grant
        # (dense argmax of an all-False column is 0 — identical)
        chosen_cand = jnp.where(any_grant,
                                rows[jnp.argmax(grantable, axis=0)],
                                0).astype(I32)
        grant_mat = grantable & (rows[:, None] == chosen_cand[None, :])
        vote = jnp.where(any_grant, chosen_cand, vote)
        if cfg.has_vote_guard:
            vg_vote = jnp.where(any_grant, chosen_cand, vg_vote)
            vg_term = jnp.where(any_grant, term, vg_term)
        elapsed = jnp.where(any_grant, 0, elapsed)
        # Responses travel j -> i; may be dropped independently. Requests
        # that were processed at the receiver's term but not granted come
        # back as rejections (vendor raft.go:988-1060 stepCandidate poll).
        if cfg.mailboxes:
            # enqueue responses on the reverse edge; a response already in
            # flight on that edge is superseded (it addressed an older
            # term and would be guard-dropped at delivery anyway)
            send_vresp = cur & ~dropT_r
            vresp_at = jnp.where(send_vresp, now + 1 + lat_T, vresp_at)
            vresp_term = jnp.where(send_vresp, term[None, :], vresp_term)
            vresp_pre = jnp.where(send_vresp, False, vresp_pre)
            vresp_grant = jnp.where(send_vresp, grant_mat, vresp_grant)
            due_vs = (vresp_at > 0) & (now + 1 >= vresp_at)
            rvalid = due_vs & g(is_cand)[:, None] \
                & (g(term)[:, None] == vresp_term) \
                & (g(pre)[:, None] == vresp_pre)
            granted = granted | (rvalid & vresp_grant)
            rejected = rejected | (rvalid & ~vresp_grant)
            vresp_at = jnp.where(due_vs, 0, vresp_at)
            v_polled = sfull(jnp.any(rvalid & ~vresp_pre, axis=1), False)
        else:
            real_cand = is_cand & ~pre
            resp_arrive = grant_mat & ~dropT_r
            granted = granted | (resp_arrive & g(real_cand)[:, None])
            reject_arrive = cur & ~grant_mat & ~dropT_r
            rejected = rejected | (reject_arrive & g(real_cand)[:, None])
            v_polled = sfull(jnp.any((resp_arrive | reject_arrive)
                                     & g(real_cand)[:, None], axis=1),
                             False)

        # (pre-candidacies transitioned in the PreVote block above; a
        # fresh pre-winner has granted=eye here, so with a single active
        # voter it wins immediately — core's _campaign self-poll cascade.)
        # Votes (and rejections) count only from peers in the candidate's
        # OWN view — a grant from a node the candidacy's config no longer
        # contains is dead weight (modern etcd tallies over the tracker
        # config).  Win/lose evaluate only on POLL EVENTS (candidacy start
        # or response arrival — core's _poll call sites): a conf change
        # shrinking quorum between arrivals must not retro-promote a stale
        # tally.
        fresh_real = tn_ok | (pre_win if cfg.pre_vote else campaign)
        # pre-candidacies poll on PreVote response arrivals (pv_polled is
        # nonzero only on pre rows; the win line excludes them via ~pre)
        polled = v_polled | pv_polled if cfg.pre_vote else v_polled
        if cfg.peer_tiled:
            votes = sfull(_pcount(lambda j0: _pband(granted, j0),
                                  mem=member_r, rows_n=R), 0)
        else:
            votes = sfull(jnp.sum(mview_r(granted).astype(I32), axis=1), 0)
        win = is_cand & ~pre & (votes >= quorum_row) & (fresh_real | polled)
        # Rejection quorum: the candidate stands down (a REAL candidacy
        # keeps term and vote; a pre-candidacy keeps both untouched by
        # design) and waits out its timeout. A voter that granted earlier
        # in the term never counts as a rejection — etcd's votes map
        # records the FIRST response per voter (core._poll), and within
        # one candidacy a grant can only precede a rejection (log/vote
        # checks are monotone), so masking with ~granted reproduces
        # first-response-wins exactly.
        if cfg.peer_tiled:
            n_rej = sfull(_pcount(
                lambda j0: _pband(rejected, j0) & ~_pband(granted, j0),
                mem=member_r, rows_n=R), 0)
        else:
            n_rej = sfull(jnp.sum(mview_r(rejected & ~granted).astype(I32),
                                  axis=1), 0)
        lose = is_cand & ~win & (n_rej >= quorum_row) & (fresh_real | polled)
        role = jnp.where(lose, FOLLOWER, role)
        lead = jnp.where(lose, NONE, lead)  # become_follower(term, NONE)
        elapsed = jnp.where(lose, 0, elapsed)  # _reset zeroes the timer
        pre = pre & ~lose
        # becomeLeader: reset progress, append a no-op entry at the new
        # term.
        role = jnp.where(win, LEADER, role)
        lead = jnp.where(win, node, lead)
        hb_elapsed = jnp.where(win, 0, hb_elapsed)
        elapsed = jnp.where(win, 0, elapsed)
        contact = jnp.where(win, 0, contact)
        # becomeLeader re-derives the propose gate from the uncommitted
        # tail (vendor becomeLeader numOfPendingConf over (commit, last]);
        # tail_conf is the end-of-previous-tick scan, still exact here
        # because Phase A/B never append and propose() carries no conf
        # entries.
        pending_conf = jnp.where(win, state.tail_conf, pending_conf)
        next_ = jnp.where(g(win)[:, None], (g(last) + 1)[:, None], next_)
        match = jnp.where(g(win)[:, None], 0, match)
        recent_active = jnp.where(g(win)[:, None], eye_r, recent_active)
        if cfg.mailboxes:
            # becomeLeader resets every Progress to StateProbe (vendor
            # reset)
            probing = jnp.where(g(win)[:, None], True, g(state.probing))
        else:
            probing = None
        noop_term = term   # the winner's candidacy term, captured HERE:
        #                    later catch-ups must not leak into the noop
        #                    entry.  The untiled noop ring store runs just
        #                    after the segment (win/noop_term are outputs);
        #                    the tiled one rides Phase C's write cond.
        last = last + win.astype(I32)
        is_leader = (role == LEADER) & alive
        match = jnp.where(g(win)[:, None] & eye_r, g(last)[:, None], match)

        # ---- Phase C: append / heartbeat fan-out -------------------------
        if cfg.mailboxes:
            K = cfg.inflight
            app_at, app_prev = g(state.app_at), g(state.app_prev)
            app_term_box = g(state.app_term)
            snp_at, snp_term_box = g(state.snp_at), g(state.snp_term)
            term_e = g(term)[:, None]        # [i, 1] sender term per edge
            term_k = g(term)[:, None, None]  # [i, 1, 1] per slot
            # sends: up to K appends pipeline per edge (vendor
            # MaxInflightMsgs) with one NEW message per tick; next_
            # advances OPTIMISTICALLY by the entries known at send (etcd
            # Replicate-state pipelining) and backtracks on rejection.
            # Appends are EVENT-GATED (D1 closed, round 4): replicate
            # edges send only when there is content; probe edges establish
            # prev-match with one (possibly empty) append at a time; idle
            # edges carry HEARTBEATS instead (below).
            free_k = (app_at == 0) | (app_term_box != term_k)     # [i,j,K]
            any_free = jnp.any(free_k, axis=2)
            slot_sel = jnp.argmax(free_k, axis=2)                 # [i, j]
            kh_idx = jnp.arange(cfg.ack_depth, dtype=I32)[None, None]
            onehot = slot_sel[:, :, None] \
                == jnp.arange(K, dtype=I32)[None, None]
            inflight_same = jnp.any((app_at != 0)
                                    & (app_term_box == term_k), axis=2)
            snp_free = (snp_at == 0) | (snp_term_box != term_e)
            prev_send = next_ - 1
            can_ring_send = prev_send >= g(snap_idx)[:, None]
            has_new = next_ <= g(last)[:, None]
            send_base = mview_r(g(is_leader)[:, None] & ~eye_r & ~drop_r) \
                & snp_free
            # StateProbe: one append at a time, no pipelining;
            # StateReplicate: pipeline while a slot is free (vendor
            # progress.go)
            may = jnp.where(probing, ~inflight_same, has_new)
            s_app = send_base & can_ring_send & any_free & may
            s_snp = send_base & ~can_ring_send  # snp_free in send_base
            put = s_app[:, :, None] & onehot
            app_at = jnp.where(put, (now + 1 + lat)[:, :, None], app_at)
            app_prev = jnp.where(put, prev_send[:, :, None], app_prev)
            app_term_box = jnp.where(put, term_k, app_term_box)
            n_send = jnp.clip(g(last)[:, None] - prev_send, 0, cfg.window)
            # optimistic advance only in replicate state (optimisticUpdate)
            next_ = jnp.where(s_app & has_new & ~probing, next_ + n_send,
                              next_)
            snp_at = jnp.where(s_snp, now + 1 + lat, snp_at)
            snp_term_box = jnp.where(s_snp, term_e, snp_term_box)

            # -- heartbeat sends (etcd bcastHeartbeat, vendor
            # raft.go:456-462): every heartbeat_tick each leader
            # broadcasts MsgHeartbeat with the commit CAPTURED at send as
            # min(match, commit); ack_depth slots suffice (one send per
            # tick per edge, lifetime <= latency+jitter).
            hb_at_box, hb_term_box = g(state.hb_at), g(state.hb_term)
            hb_commit_box = g(state.hb_commit)
            hbr_at_box, hbr_term_box = g(state.hbr_at), g(state.hbr_term)
            hb_due_send = is_leader & (hb_elapsed >= cfg.heartbeat_tick)
            hb_elapsed = jnp.where(hb_due_send, 0, hb_elapsed)
            send_hb = mview_r(g(hb_due_send)[:, None] & ~eye_r & ~drop_r)
            hb_free = hb_at_box == 0
            hb_slot = jnp.argmax(hb_free, axis=2).astype(I32)
            put_hb = send_hb[:, :, None] & (hb_slot[:, :, None] == kh_idx)
            hb_at_box = jnp.where(put_hb, (now + 1 + lat)[:, :, None],
                                  hb_at_box)
            hb_term_box = jnp.where(put_hb, term_k, hb_term_box)
            hb_commit_box = jnp.where(
                put_hb,
                jnp.minimum(match, g(commit)[:, None])[:, :, None],
                hb_commit_box)

            # -- heartbeat deliveries: processed BEFORE append deliveries
            # (the oracle steps them first), so append validity below sees
            # any demotion a higher-term heartbeat causes.  All due
            # heartbeats integrate, aggregated; stale ones (sender no
            # longer the leader of the captured term) vanish.
            due_hb = (hb_at_box > 0) & (now + 1 >= hb_at_box)
            valid_hb = due_hb & (g(role)[:, None, None] == LEADER) \
                & (hb_term_box == term_k) & alive[None, :, None]
            hb_at_box = jnp.where(due_hb, 0, hb_at_box)
            mt_hb = jnp.max(jnp.where(valid_hb, hb_term_box, -1),
                            axis=(0, 2))
            newer_hb = mt_hb > term
            term = jnp.where(newer_hb, mt_hb, term)
            role = jnp.where(newer_hb, FOLLOWER, role)
            vote = jnp.where(newer_hb, NONE, vote)
            lead = jnp.where(newer_hb, NONE, lead)
            elapsed = jnp.where(newer_hb, 0, elapsed)
            timeout = jnp.where(newer_hb, rand_timeout(cfg, node, term),
                                timeout)
            cur_hb = valid_hb & (hb_term_box == term[None, :, None])
            got_hb = jnp.any(cur_hb, axis=(0, 2))                 # [j]
            # slab position -> node id through `rows`, gated on got_hb (a
            # dense all-False argmax is 0 — the gate keeps that identical)
            src_hb = jnp.where(
                got_hb,
                rows[jnp.argmax(jnp.any(cur_hb, axis=2), axis=0)],
                0).astype(I32)
            role = jnp.where(got_hb & (role == CANDIDATE), FOLLOWER, role)
            lead = jnp.where(got_hb, src_hb, lead)
            elapsed = jnp.where(got_hb, 0, elapsed)
            contact = jnp.where(got_hb, 0, contact)
            # commit_to(min(m.commit, last)) per message, as a max
            hbc = jnp.max(jnp.where(cur_hb, hb_commit_box, -1),
                          axis=(0, 2))
            commit = jnp.where(
                got_hb, jnp.maximum(commit, jnp.minimum(hbc, last)),
                commit)
            # one response per edge per tick (responses carry liveness)
            send_hbr = jnp.any(cur_hb, axis=2) & ~dropT_r
            hbr_free = hbr_at_box == 0
            hbr_slot = jnp.argmax(hbr_free, axis=2).astype(I32)
            put_hbr = send_hbr[:, :, None] \
                & (hbr_slot[:, :, None] == kh_idx)
            hbr_at_box = jnp.where(put_hbr, (now + 1 + lat_T)[:, :, None],
                                   hbr_at_box)
            hbr_term_box = jnp.where(put_hbr, term[None, :, None],
                                     hbr_term_box)
            term_k = g(term)[:, None, None]   # refresh: heartbeats may
            term_e = g(term)[:, None]         # have caught senders up
            # deliveries: the wire drains AT MOST ONE append per edge per
            # tick — the smallest-prev deliverable one; later-due messages
            # wait their turn.  Sender must still be the same-term leader,
            # so ring reads at delivery see an immutable prefix; an append
            # whose captured prev was compacted since send is
            # undeliverable and drops (the freed slot lets a snapshot go
            # out next tick).
            due_k = (app_at > 0) & (now + 1 >= app_at)
            lead_k = g(role)[:, None, None] == LEADER
            valid_k = due_k & lead_k & (app_term_box == term_k) \
                & alive[None, :, None] \
                & (app_prev >= g(snap_idx)[:, None, None])
            big = jnp.iinfo(jnp.int32).max
            key = jnp.where(valid_k, app_prev, big)
            sel_prev = jnp.min(key, axis=2)                       # [i, j]
            sel_slot = jnp.argmin(key, axis=2)
            send_app = jnp.any(valid_k, axis=2)
            taken = send_app[:, :, None] \
                & (sel_slot[:, :, None]
                   == jnp.arange(K, dtype=I32)[None, None])
            # clear the delivered slot and every due-but-invalid slot
            app_at = jnp.where(taken | (due_k & ~valid_k), 0, app_at)
            due_s = (snp_at > 0) & (now + 1 >= snp_at)
            send_snap = due_s & (g(role)[:, None] == LEADER) \
                & (term_e == snp_term_box) & alive[None, :]
            prev_mat = sel_prev
            snp_at = jnp.where(due_s, 0, snp_at)
        else:
            prev_mat = next_ - 1                                 # [i, j]
            can_ring = prev_mat >= g(snap_idx)[:, None]
            send_base = mview_r(g(is_leader)[:, None] & alive[None, :]
                                & ~eye_r & ~drop_r)
            send_app = send_base & can_ring
            send_snap = send_base & ~can_ring

        # Receiver-side term catch-up from append/snapshot senders.
        msg_term = jnp.where(send_app | send_snap, g(term)[:, None], -1)
        mt2 = jnp.max(msg_term, axis=0)
        newer2 = mt2 > term
        term = jnp.where(newer2, mt2, term)
        role = jnp.where(newer2, FOLLOWER, role)
        vote = jnp.where(newer2, NONE, vote)
        lead = jnp.where(newer2, NONE, lead)
        elapsed = jnp.where(newer2, 0, elapsed)
        timeout = jnp.where(newer2, rand_timeout(cfg, node, term), timeout)

        # Receiver picks its (unique) current-term leader, judged by the
        # SEND-TIME sender term (a leader deposed this tick sent at its
        # old term).  src_sel is the slab-LOCAL position (for indexing the
        # [R, N] send matrices); src maps it to a node id for the log-row
        # gathers in the dense middle section.
        eligible = (send_app | send_snap) & (msg_term == term[None, :])
        has_lmsg = jnp.any(eligible, axis=0)
        src_sel = jnp.argmax(eligible, axis=0)
        src = jnp.where(has_lmsg, rows[src_sel], 0).astype(I32)  # [j]
        role = jnp.where(has_lmsg & (role == CANDIDATE), FOLLOWER, role)
        lead = jnp.where(has_lmsg, src, lead)
        elapsed = jnp.where(has_lmsg, 0, elapsed)
        contact = jnp.where(has_lmsg, 0, contact)
        is_leader = (role == LEADER) & alive

        got_app = has_lmsg & send_app[src_sel, node]
        got_snap = has_lmsg & send_snap[src_sel, node]
        p = prev_mat[src_sel, node]                              # [j]

        out = dict(
            term=term, vote=vote, role=role, lead=lead, elapsed=elapsed,
            contact=contact, hb_elapsed=hb_elapsed, timeout=timeout,
            pre=pre, last=last, commit=commit, pending_conf=pending_conf,
            campaign=campaign, tn_ok=tn_ok, transferee=transferee,
            tn_at=tn_at, tn_term=tn_term, tn_from=tn_from, tx_cand=tx_cand,
            win=win, noop_term=noop_term, is_leader=is_leader,
            has_lmsg=has_lmsg, src=src, got_app=got_app, got_snap=got_snap,
            p=p,
            match=sc(match0, match), next_=sc(next0, next_),
            granted=sc(granted0, granted),
            rejected=sc(rejected0, rejected),
            recent_active=sc(ra0, recent_active))
        if cfg.has_vote_guard:
            out.update(vg_vote=vg_vote, vg_term=vg_term)
        if cfg.mailboxes:
            out.update(
                probing=sc(state.probing, probing),
                vreq_at=sc(state.vreq_at, vreq_at),
                vreq_term=sc(state.vreq_term, vreq_term),
                vreq_pre=sc(state.vreq_pre, vreq_pre),
                vresp_at=sc(state.vresp_at, vresp_at),
                vresp_term=sc(state.vresp_term, vresp_term),
                vresp_grant=sc(state.vresp_grant, vresp_grant),
                vresp_pre=sc(state.vresp_pre, vresp_pre),
                app_at=sc(state.app_at, app_at),
                app_prev=sc(state.app_prev, app_prev),
                app_term=sc(state.app_term, app_term_box),
                snp_at=sc(state.snp_at, snp_at),
                snp_term=sc(state.snp_term, snp_term_box),
                hb_at=sc(state.hb_at, hb_at_box),
                hb_term=sc(state.hb_term, hb_term_box),
                hb_commit=sc(state.hb_commit, hb_commit_box),
                hbr_at=sc(state.hbr_at, hbr_at_box),
                hbr_term=sc(state.hbr_term, hbr_term_box))
        return out

    # Dispatch segment 1.  Under the sparse lowering the tick pays the
    # [A, N] slab branch whenever the active predicate fits; the dense
    # branch is the bit-identical fallback (election storms).  Both
    # branches return full-[N]/[N, N] pytrees, so the cond output aliases
    # the state carries exactly like the historical dense code.
    if sparse_on:
        _oa = jax.lax.cond(sp_fits,
                           lambda: _progress_a(sp_rows, False),
                           lambda: _progress_a(node, True))
    else:
        _oa = _progress_a(node, True)
    term, vote, role = _oa["term"], _oa["vote"], _oa["role"]
    lead, elapsed, contact = _oa["lead"], _oa["elapsed"], _oa["contact"]
    hb_elapsed, timeout, pre = _oa["hb_elapsed"], _oa["timeout"], _oa["pre"]
    last, commit, pending_conf = (_oa["last"], _oa["commit"],
                                  _oa["pending_conf"])
    campaign, tn_ok, transferee = (_oa["campaign"], _oa["tn_ok"],
                                   _oa["transferee"])
    tn_at, tn_term, tn_from = _oa["tn_at"], _oa["tn_term"], _oa["tn_from"]
    tx_cand, win, noop_term = _oa["tx_cand"], _oa["win"], _oa["noop_term"]
    is_leader, has_lmsg, src = (_oa["is_leader"], _oa["has_lmsg"],
                                _oa["src"])
    got_app, got_snap, p = _oa["got_app"], _oa["got_snap"], _oa["p"]
    match, next_, granted = _oa["match"], _oa["next_"], _oa["granted"]
    rejected, recent_active = _oa["rejected"], _oa["recent_active"]
    vg_fields = {}
    if cfg.has_vote_guard:
        vg_fields = dict(vg_vote=_oa["vg_vote"], vg_term=_oa["vg_term"])
    probing = _oa["probing"] if cfg.mailboxes else None
    if cfg.mailboxes:
        vreq_at, vreq_term = _oa["vreq_at"], _oa["vreq_term"]
        vreq_pre = _oa["vreq_pre"]
        vresp_at, vresp_term = _oa["vresp_at"], _oa["vresp_term"]
        vresp_grant, vresp_pre = _oa["vresp_grant"], _oa["vresp_pre"]
        app_at, app_prev = _oa["app_at"], _oa["app_prev"]
        app_term_box = _oa["app_term"]
        snp_at, snp_term_box = _oa["snp_at"], _oa["snp_term"]
        hb_at_box, hb_term_box = _oa["hb_at"], _oa["hb_term"]
        hb_commit_box = _oa["hb_commit"]
        hbr_at_box, hbr_term_box = _oa["hbr_at"], _oa["hbr_term"]

    # The untiled noop ring store, deferred from Phase B (ring writes stay
    # outside the progress segments — a write under the cond would carry
    # the whole [N, L] log through both branches).  `last` is
    # post-increment here: win rows store at their new last (the noop
    # index); non-win rows read-modify-write their own slot unchanged —
    # bit-identical to the historical in-phase store at _slot(last + 1).
    if not cfg.tiled:
        noop_slot = _slot(cfg, jnp.where(win, last, last + 1))
        log_term = log_term.at[node, noop_slot].set(
            jnp.where(win, noop_term, log_term[node, noop_slot]))
        log_data = log_data.at[node, noop_slot].set(
            jnp.where(win, U32(0), log_data[node, noop_slot]))

    # -- append receive. All sender-side log reads use the POST-noop local
    # arrays so a just-elected leader replicates its no-op in the same tick.
    #
    # Slot alignment: slot(idx) = (idx-1) % L on every row, so entry idx
    # lives at the SAME slot on sender and receiver. The window copy is a
    # contiguous row-gather of the chosen sender's ring (log_*[src]) plus
    # elementwise masks over [N, L] — no per-element gathers.  Under
    # cfg.tiled the copy visits only the live chunk band (see the log-axis
    # tiling block above) with a full-pass fallback on straggler spread.
    last_src, snap_src = last[src], snap_idx[src]

    # (p — the chosen sender's prev per receiver — comes out of the first
    # progress segment; prev_mat itself never leaves the slab.)
    p_ring_term = log_term[src, _slot(cfg, p)]   # one element per row
    p_term_sent = jnp.where(
        p == snap_src, snap_term[src],
        jnp.where((p > snap_src) & (p <= last_src), p_ring_term, 0))
    # Window clamp for ring safety: accepting past snap_idx + L would wrap
    # the receiver's ring over entries it has not applied yet (a pipelining
    # leader can run its log far ahead of a catching-up follower's
    # compaction watermark).  The clamped remainder arrives after the
    # follower applies + compacts and headroom opens up.
    ring_cap = snap_idx + cfg.log_len - p                        # [j]
    n_avail = jnp.clip(jnp.minimum(last_src - p, ring_cap), 0, cfg.window)
    hi = p + n_avail                                             # lastnewi

    commit0 = commit  # pre-append commit (handleAppendEntries fast path)
    q_p = jnp.minimum(p, last)
    local_p_term = _term_own(cfg, log_term, snap_idx, snap_term, last, q_p)
    if fused_prop:
        # a stale co-leader's prev can reach into the receiver's OWN
        # freshly proposed range (still pending in the write cond)
        local_p_term = jnp.where(prop_ok & (q_p > prop_last0), state.term,
                                 local_p_term)
    if cfg.tiled:
        # likewise for a fresh winner's pending noop entry (idx == last)
        local_p_term = jnp.where(win & (q_p == last), noop_term,
                                 local_p_term)
    prev_ok = (p <= last) & (p >= snap_idx) & (local_p_term == p_term_sent)
    stale = p < commit0
    accept = got_app & prev_ok & ~stale
    big = jnp.iinfo(jnp.int32).max

    # -- snapshot-receive decision, hoisted ABOVE the ring write cond so the
    # banded branch can exclude restores from its predicate (the wipe is
    # full-width by nature and rides the full branch).  Safe to hoist: for
    # got_snap rows nothing the append pass updates (last/commit/ring row)
    # changes — append and snapshot receipt are edge-disjoint.  Semantics
    # at the original site, see "snapshot receive" below.
    snap_pt = jnp.minimum(snap_idx[src], last)
    have_term = _term_own(cfg, log_term, snap_idx, snap_term, last, snap_pt)
    if fused_prop:
        # deposed leader receiving a snapshot over its own pending proposals
        have_term = jnp.where(prop_ok & (snap_pt > prop_last0), state.term,
                              have_term)
    if cfg.tiled:
        have_term = jnp.where(win & (snap_pt == last), noop_term, have_term)
    already = (snap_idx[src] <= last) & (have_term == snap_term[src])
    advance = got_snap & (snap_idx[src] > commit)
    do_restore = advance & ~already
    snap_refuse = None
    if storage_on and cfg.ack_gating:
        # snap_corrupt defense (checksum verified BEFORE install): the
        # flagged arrival is refused outright — state kept, no ack-side
        # progress for the sender, so the unadvanced next_ re-sends the
        # snapshot next round and a clean copy installs then.  Without
        # gating the corrupt image installs below and poisons the
        # checksum chain (the CHECKSUM_AGREEMENT witness).
        snap_refuse = do_restore & state.snap_bad
        do_restore = do_restore & ~state.snap_bad

    if cfg.tiled:
        # Window extraction: every entry VALUE the append pass can copy this
        # tick lives in the sender's (p, p + window] range — gather it into
        # [N, window] side buffers BEFORE the write cond, then let both
        # branches read entry values ONLY from these.  This is what keeps
        # the scan-carried logs copy-free on CPU: if a branch's log_data
        # writes read log_term chunks (or row-gather lt[src]), XLA's fusion
        # duplicates those reads into the data-side update with the whole
        # term buffer as an operand, the live range of the pre-write value
        # then spans the in-place writes, and copy insertion materializes
        # full-capacity copies of the carry each tick.  With the values
        # pre-gathered, each branch chain touches only its own buffer plus
        # [N, window] operands, and the fallback becomes a pure elementwise
        # select — in-place eligible — so the cond output can alias the
        # carry.  The gathers see the PRE-cond ring; entries still pending
        # in the write cond (fused proposals, a fresh winner's noop) are
        # patched analytically, same trick as local_p_term above.
        wspan = jnp.arange(cfg.window, dtype=I32)[None, :]
        widx = p[:, None] + 1 + wspan                            # [N, W]
        wslot = _slot(cfg, widx)
        wsrc_t = log_term[src[:, None], wslot]   # sender window values
        wsrc_d = log_data[src[:, None], wslot]
        wown_t = jnp.take_along_axis(log_term, wslot, axis=1)
        if fused_prop:
            k_src = widx - prop_last0[src][:, None] - 1
            pend_s = prop_ok[src][:, None] & (k_src >= 0) \
                & (k_src < prop_cnt)
            wsrc_t = jnp.where(pend_s, state.term[src][:, None], wsrc_t)
            wsrc_d = jnp.where(
                pend_s,
                payload_fn(now, jnp.maximum(k_src, 0).astype(U32))
                & U32(0x7FFFFFFF), wsrc_d)
            k_own = widx - prop_last0[:, None] - 1
            pend_o = prop_ok[:, None] & (k_own >= 0) & (k_own < prop_cnt)
            wown_t = jnp.where(pend_o, state.term[:, None], wown_t)
        noop_s = win[src][:, None] & (widx == last[src][:, None])
        wsrc_t = jnp.where(noop_s, noop_term[src][:, None], wsrc_t)
        wsrc_d = jnp.where(noop_s, U32(0), wsrc_d)
        wown_t = jnp.where(win[:, None] & (widx == last[:, None]),
                           noop_term[:, None], wown_t)
        # find_conflict on the window axis (replaces the full-row scan):
        # first incoming entry missing locally or with a mismatched term.
        # widx > p by construction, so in_win needs only the upper bound.
        w_in = got_app[:, None] & (widx <= hi[:, None])
        w_exists = (widx <= last[:, None]) & (widx > snap_idx[:, None])
        w_mism = w_in & (~w_exists | (wown_t != wsrc_t))
        any_mism = jnp.any(w_mism, axis=1)
        ci_idx = jnp.min(jnp.where(w_mism, widx, big), axis=1)   # [j]

    def _prop_write_full(lt, ld):
        # propose_dense._write_full inlined: slot -> new index map anchored
        # one batch ahead of the pre-tick last
        new_idx = _idx_at_slots(cfg, prop_anchor)                # [N, L]
        k_of = new_idx - prop_last0[:, None] - 1
        valid = prop_ok[:, None] & (k_of >= 0) & (k_of < prop_cnt)
        pl = payload_fn(now, jnp.maximum(k_of, 0).astype(U32)) \
            & U32(0x7FFFFFFF)
        return (jnp.where(valid, state.term[:, None], lt),
                jnp.where(valid, pl, ld))

    def _append_full(lt, ld):
        # find_conflict: first incoming entry missing locally or with a
        # mismatched term, located by index (min over the masked index map).
        lead_term_row = lt[src]                                  # [N, L]
        lead_data_row = ld[src]
        lead_idx = _idx_at_slots(cfg, last_src)                  # [N, L]
        in_win = got_app[:, None] & (lead_idx > p[:, None]) \
            & (lead_idx <= hi[:, None])
        exists = (lead_idx <= last[:, None]) & (lead_idx > snap_idx[:, None])
        mism = in_win & (~exists | (lt != lead_term_row))
        am = jnp.any(mism, axis=1)
        ci_idx = jnp.min(jnp.where(mism, lead_idx, big), axis=1)  # [j]
        write = in_win & accept[:, None] & (lead_idx >= ci_idx[:, None])
        return (jnp.where(write, lead_term_row, lt),
                jnp.where(write, lead_data_row, ld), am)

    def _ring_full(lt, ld):
        # the tick's whole [N, L] mutation in original order: dense
        # propose, append receive, snapshot-restore wipe (the untiled
        # noop store is Phase B's scatter)
        if fused_prop:
            lt, ld = _prop_write_full(lt, ld)
        lt, ld, am = _append_full(lt, ld)
        lt = jnp.where(do_restore[:, None], 0, lt)
        ld = jnp.where(do_restore[:, None], U32(0), ld)
        return lt, ld, am

    if cfg.tiled:
        # Append band (min prev, max lastnewi] over receiving rows; the
        # fused propose stores get their own band over proposing rows.
        # ONE cond owns every [N, L] write of the tick (propose + noop +
        # append + restore wipe): more than one write cond per scan
        # iteration — or a scatter outside it — makes
        # XLA materialize full-capacity log copies, re-coupling tick cost
        # to L.  Entry values come from the pre-cond window buffers (no
        # sender-row reads of the carry inside either branch); the banded
        # predicate excludes restore ticks (full-width wipe), election
        # ticks, and either band overflowing cfg.band_chunks.
        lo_b = jnp.min(jnp.where(got_app, p, big))
        hi_b = jnp.max(jnp.where(got_app, hi, 0))
        c0u, nch = _band_origin(cfg, lo_b, hi_b)
        # election ticks (any win: pending noop store) and restore ticks
        # (full-width wipe) take the full branch — both are rare
        fits = (nch <= cfg.band_chunks) & ~jnp.any(do_restore) \
            & ~jnp.any(win)
        if fused_prop:
            lo_p = jnp.min(jnp.where(prop_ok, prop_last0, big))
            hi_p = jnp.max(jnp.where(prop_ok, prop_anchor, 0))
            c0p, nch_p = _band_origin(cfg, lo_p, hi_p)
            fits = fits & (nch_p <= cfg.band_chunks)

        def _write_at(lead_idx, lt_c, ld_c):
            """Masked append write for one chunk/full view: entry values
            come from the pre-gathered sender window, never from the other
            carried buffer (the decoupling the header comment explains)."""
            in_win = got_app[:, None] & (lead_idx > p[:, None]) \
                & (lead_idx <= hi[:, None])
            write = in_win & accept[:, None] & (lead_idx >= ci_idx[:, None])
            wk = jnp.clip(lead_idx - p[:, None] - 1, 0, cfg.window - 1)
            src_t = jnp.take_along_axis(wsrc_t, wk, axis=1)
            src_d = jnp.take_along_axis(wsrc_d, wk, axis=1)
            fused = _pallas_band_copy()
            if fused and lt_c.shape[1] == cfg.log_chunk:
                return fused(lt_c, src_t, write), fused(ld_c, src_d, write)
            return (jnp.where(write, src_t, lt_c),
                    jnp.where(write, src_d, ld_c))

        def _ring_banded(lt, ld):
            if fused_prop:
                for off in _band_offsets(cfg, c0p):
                    lt_c = jax.lax.dynamic_slice(lt, (0, off),
                                                 (n, cfg.log_chunk))
                    ld_c = jax.lax.dynamic_slice(ld, (0, off),
                                                 (n, cfg.log_chunk))
                    new_idx = _idx_at_band(cfg, prop_anchor, off)
                    k_of = new_idx - prop_last0[:, None] - 1
                    valid = prop_ok[:, None] & (k_of >= 0) \
                        & (k_of < prop_cnt)
                    pl = payload_fn(now, jnp.maximum(k_of, 0).astype(U32)) \
                        & U32(0x7FFFFFFF)
                    lt = jax.lax.dynamic_update_slice(
                        lt, jnp.where(valid, state.term[:, None], lt_c),
                        (0, off))
                    ld = jax.lax.dynamic_update_slice(
                        ld, jnp.where(valid, pl, ld_c), (0, off))
            # append write-back, one visit per chunk (the conflict scan ran
            # on the window buffers above, outside the cond)
            for off in _band_offsets(cfg, c0u):
                lt_c = jax.lax.dynamic_slice(lt, (0, off),
                                             (n, cfg.log_chunk))
                ld_c = jax.lax.dynamic_slice(ld, (0, off),
                                             (n, cfg.log_chunk))
                lt_w, ld_w = _write_at(_idx_at_band(cfg, last_src, off),
                                       lt_c, ld_c)
                lt = jax.lax.dynamic_update_slice(lt, lt_w, (0, off))
                ld = jax.lax.dynamic_update_slice(ld, ld_w, (0, off))
            return lt, ld

        def _ring_full_t(lt, ld):
            # tiled fallback: same mutations as _ring_full but elementwise
            # in the carry (values via the window buffers), so XLA can run
            # this branch in place too and the cond output aliases the
            # carry; includes the fallback-only noop store and restore wipe
            if fused_prop:
                lt, ld = _prop_write_full(lt, ld)
            own_idx = _idx_at_slots(cfg, last)
            noop_m = win[:, None] & (own_idx == last[:, None])
            lt = jnp.where(noop_m, noop_term[:, None], lt)
            ld = jnp.where(noop_m, U32(0), ld)
            lt, ld = _write_at(_idx_at_slots(cfg, last_src), lt, ld)
            lt = jnp.where(do_restore[:, None], 0, lt)
            ld = jnp.where(do_restore[:, None], U32(0), ld)
            return lt, ld

        log_term, log_data = jax.lax.cond(
            fits, _ring_banded, _ring_full_t, log_term, log_data)
    else:
        log_term, log_data, any_mism = _ring_full(log_term, log_data)
    lastnewi = hi
    last = jnp.where(accept,
                     jnp.where(any_mism, lastnewi, jnp.maximum(last, lastnewi)),
                     last)
    commit = jnp.where(accept,
                       jnp.maximum(commit,
                                   jnp.minimum(commit0[src], lastnewi)),
                       commit)

    # -- snapshot receive: jump to the sender's compaction watermark.
    # If our log already contains the snapshot point (same term), only
    # fast-forward commit — never wipe acked-but-uncommitted suffix entries
    # (core.py _restore / etcd raft.go restore semantics).  The decision
    # (do_restore) was hoisted above the write cond; the ring wipe already
    # happened inside it.  Only the cursor/meta effects land here.
    commit = jnp.where(advance & already, snap_idx[src], commit)
    r_src = src
    last = jnp.where(do_restore, snap_idx[r_src], last)
    commit = jnp.where(do_restore, snap_idx[r_src], commit)
    applied = jnp.where(do_restore, snap_idx[r_src], applied)
    apply_chk = jnp.where(do_restore, snap_chk[r_src], apply_chk)
    new_snap_term = jnp.where(do_restore, snap_term[r_src], snap_term)
    new_snap_chk = jnp.where(do_restore, snap_chk[r_src], snap_chk)
    new_snap_idx = jnp.where(do_restore, snap_idx[r_src], snap_idx)
    snap_term, snap_chk, snap_idx = new_snap_term, new_snap_chk, new_snap_idx
    if storage_on:
        if not cfg.ack_gating:
            # gating off: the corrupt image (snap_corrupt verb) installs
            # unverified — its decoded state differs from what the
            # checksum claims, modeled as a poisoned apply/snap checksum
            # chain.  CHECKSUM_AGREEMENT trips once the row's applied
            # frontier meets another row's.
            poison = do_restore & state.snap_bad
            apply_chk = jnp.where(poison, apply_chk ^ U32(0xBAD5EED5),
                                  apply_chk)
            snap_chk = jnp.where(poison, snap_chk ^ U32(0xBAD5EED5),
                                 snap_chk)
        # an installed snapshot is durable at install (the receiver
        # fsyncs it before acking — etcd applies snapshots through the
        # synchronous Ready path), so the watermark jumps with it
        sync_mark = jnp.where(do_restore,
                              jnp.maximum(sync_mark, snap_idx), sync_mark)
    # The snapshot carries the sender's configuration (SnapshotMeta.voters;
    # core._restore rebuilds prs from it): adopt the sender's view.  Conf
    # entries in (snap_idx, sender.applied] are re-applied later via the
    # append path — membership flips are idempotent sets, so the early
    # adoption is safe.  (Static members: every view is identical already.)
    if not static_m:
        member = jnp.where(do_restore[:, None], member[r_src], member)

    # -- responses back to senders (j -> i), may be dropped.
    # A duplicate snapshot (sender watermark <= our commit) still gets an
    # APP_RESP at our commit (core.py _handle_snapshot else-branch) so the
    # leader's progress un-wedges even if the original ack was dropped.
    resp_match = jnp.where(stale & got_app, commit0,
                           jnp.where(got_snap, commit, lastnewi))
    if storage_on and cfg.ack_gating:
        # ack-gating (the etcd/raft persistence contract — Ready/Advance:
        # fsync BEFORE MsgAppResp): a follower acks only the prefix its
        # durable watermark covers.  Snapshot acks are never clamped in
        # effect (sync_mark jumped to the installed watermark above).
        # The leader's max-fold makes a clamped ack pure under-report,
        # and the unsolicited durable-frontier ack in _progress_b below
        # re-acks the suffix once a later fsync round covers it.
        resp_match = jnp.minimum(resp_match, sync_mark)
        dur_match = jnp.minimum(last, sync_mark)                 # [j]
        fsync_ack = fsync_did & (lead != NONE) & (role == FOLLOWER)
    resp_ok = accept | got_snap | (stale & got_app)
    resp_reject = got_app & ~prev_ok & ~stale
    reject_hint = last                                           # [j]

    # Leader self-ack cap: under ack-gating a leader counts ITSELF in the
    # commit quorum only up to its own durable watermark (etcd: the
    # leader's Ready loop fsyncs before marking its own progress) — so a
    # committed entry is durable on a FULL quorum including the leader,
    # the property the DURABILITY invariant needs.  Without the storage
    # model this is `last` verbatim (bit-identical trace).
    if storage_on and cfg.ack_gating:
        self_ack_cap = jnp.minimum(last, sync_mark)
    else:
        self_ack_cap = last

    if cfg.mailboxes:
        _b_in = (app_at, app_prev, app_term_box, snp_at, snp_term_box,
                 hbr_at_box, hbr_term_box)

    @jax.named_scope("phase_D_progress")
    def _progress_b(rows, dense, match=match, next_=next_,
                    recent_active=recent_active, probing=probing,
                    tn_at=tn_at, tn_term=tn_term, tn_from=tn_from):
        """Progress segment 2: ack folds, progress integration, transfer
        completion, the Phase D bisect and the R1 ack counts — every
        remaining [N, N] elementwise consumer of per-peer progress.  Same
        contract as segment 1: `dense` instantiates the historical code
        op-for-op, the slab instantiation is bit-identical on the rows it
        scatters back (all matrix updates are gated on leadership, and
        the active set is a superset of every row those gates can fire
        on this tick)."""
        (R, g, sc, sfull, eye_r, drop_r, dropT_r, member_r,
         mview_r) = _slabify(rows, dense)
        match1, next1, ra1 = match, next_, recent_active
        match, next_, recent_active = g(match), g(next_), g(recent_active)
        if cfg.mailboxes:
            probing0 = probing
            probing = g(probing)
            if dense:
                lat = latency_matrix(cfg, now)
                lat_T = lat.T
            else:
                lat = latency_at(cfg, now, rows[:, None], node[None, :])
                lat_T = latency_at(cfg, now, node[None, :], rows[:, None])

        is_resp_tgt = rows[:, None] == src[None, :]              # [i, j]
        if cfg.mailboxes:
            (b_app_at, b_app_prev, b_app_term, b_snp_at, b_snp_term,
             b_hbr_at, b_hbr_term) = _b_in
            app_at, app_prev = g(b_app_at), g(b_app_prev)
            app_term_box = g(b_app_term)
            snp_at, snp_term_box = g(b_snp_at), g(b_snp_term)
            hbr_at_box, hbr_term_box = g(b_hbr_at), g(b_hbr_term)
            aresp_at, aresp_term = g(state.aresp_at), g(state.aresp_term)
            aresp_match, aresp_ok = (g(state.aresp_match),
                                     g(state.aresp_ok))
            big = jnp.iinfo(jnp.int32).max
            kr_idx = jnp.arange(cfg.ack_depth, dtype=I32)[None, None]
            # enqueue into the first free slot — cfg.ack_depth guarantees
            # one exists (acks arrive at most once per tick per edge and
            # live at most latency+jitter ticks), so no eviction policy
            # is needed
            send_ar = is_resp_tgt & has_lmsg[None, :] & ~dropT_r
            free_r = aresp_at == 0
            wslot = jnp.argmax(free_r, axis=2).astype(I32)
            put_r = send_ar[:, :, None] & (wslot[:, :, None] == kr_idx)
            aresp_at = jnp.where(put_r, (now + 1 + lat_T)[:, :, None],
                                 aresp_at)
            aresp_term = jnp.where(put_r, term[None, :, None], aresp_term)
            aresp_ok = jnp.where(put_r, resp_ok[None, :, None], aresp_ok)
            aresp_match = jnp.where(
                put_r,
                jnp.where(resp_reject, reject_hint,
                          resp_match)[None, :, None],
                aresp_match)
            if storage_on and cfg.ack_gating:
                # unsolicited durable-frontier ack (etcd emits MsgAppResp
                # from the Ready loop AFTER the fsync lands): every fsync
                # round a follower re-acks min(last, sync_mark) to its
                # known leader, so a suffix whose delivery ack was
                # clamped still commits once durable — without this the
                # event-gated append wire has no re-ack trigger and the
                # tail would never commit.  Best-effort enqueue (skipped
                # when the edge's ack slots are all busy — re-attempted
                # next fsync round, so no deadlock and no slot eviction).
                fa_tgt = jnp.clip(lead, 0, n - 1)
                send_fa = (rows[:, None] == fa_tgt[None, :]) \
                    & fsync_ack[None, :] & ~dropT_r & ~eye_r
                free_f = aresp_at == 0
                fa_slot = jnp.argmax(free_f, axis=2).astype(I32)
                put_f = send_fa[:, :, None] \
                    & (fa_slot[:, :, None] == kr_idx) \
                    & jnp.any(free_f, axis=2)[:, :, None]
                aresp_at = jnp.where(put_f, (now + 1 + lat_T)[:, :, None],
                                     aresp_at)
                aresp_term = jnp.where(put_f, term[None, :, None],
                                       aresp_term)
                aresp_ok = jnp.where(put_f, True, aresp_ok)
                aresp_match = jnp.where(put_f, dur_match[None, :, None],
                                        aresp_match)
            # deliveries: ALL due acks integrate this tick, aggregated
            # (ok: max match; reject: min hint — applied after the ok
            # advance, the conservative order)
            due_r = (aresp_at > 0) & (now + 1 >= aresp_at)
            val_r = due_r & g(is_leader)[:, None, None] \
                & (g(term)[:, None, None] == aresp_term)
            ok_k = val_r & aresp_ok
            rej_k = val_r & ~aresp_ok
            ok_mat = jnp.any(ok_k, axis=2)
            rej_mat = jnp.any(rej_k, axis=2)
            resp_match_del = jnp.max(jnp.where(ok_k, aresp_match, -1),
                                     axis=2)
            reject_hint_del = jnp.min(jnp.where(rej_k, aresp_match, big),
                                      axis=2)
            aresp_at = jnp.where(due_r, 0, aresp_at)
        else:
            arrive_back = ~dropT_r & is_resp_tgt & g(is_leader)[:, None] \
                & has_lmsg[None, :]
            ok_mat = arrive_back & resp_ok[None, :]
            rej_mat = arrive_back & resp_reject[None, :]
            resp_match_del = resp_match[None, :]
            reject_hint_del = reject_hint[None, :]
        # pre-view response arrivals also feed the active-TTL drain
        # tracking (a row draining in-flight acks must stay in the slab)
        got_resp_r = jnp.any(ok_mat | rej_mat, axis=1)
        # any response marks the peer recently-active for CheckQuorum
        # (even from a peer outside the current view: invisible there,
        # since the CheckQuorum count masks by member and a re-add forces
        # True anyway)
        recent_active = recent_active | ok_mat | rej_mat
        # ...but progress integration follows core's stepLeader exactly:
        # responses from peers the config no longer contains are dropped
        # (prs.get(m.frm) is None -> return).  The rejection path is
        # receiver-visible (backtrack + pipeline flush change future
        # deliveries), so this mask is required for core-exactness, not
        # just hygiene.
        ok_mat = mview_r(ok_mat)
        rej_mat = mview_r(rej_mat)
        if cfg.mailboxes:
            # vendor stepLeader MsgAppResp: maybeUpdate advances match
            # (and next to at least m+1); a match ADVANCE on a probing
            # edge enters replicate with next = match+1 EXACTLY
            # (becomeReplicate may lower an optimistic next)
            adv = ok_mat & (resp_match_del > match)
            to_repl = adv & probing
            match = jnp.where(ok_mat, jnp.maximum(match, resp_match_del),
                              match)
            next_ = jnp.where(
                to_repl, resp_match_del + 1,
                jnp.where(ok_mat, jnp.maximum(next_, resp_match_del + 1),
                          next_))
            probing = probing & ~to_repl
        else:
            match = jnp.where(ok_mat, jnp.maximum(match, resp_match_del),
                              match)
            next_ = jnp.where(ok_mat,
                              jnp.maximum(next_, resp_match_del + 1),
                              next_)
        # Probe decrement (maybeDecrTo, coarse): jump next back to hint.
        next_ = jnp.where(
            rej_mat,
            jnp.maximum(1, jnp.minimum(next_ - 1, reject_hint_del + 1)),
            next_)
        if cfg.mailboxes:
            probing = probing | rej_mat   # becomeProbe on rejection
            # probe reset flush: optimistically pipelined appends beyond
            # the conflict are now useless — clear the edge's same-term
            # in-flight slots so the backtracked window goes out instead
            # of waiting
            app_at = jnp.where(
                rej_mat[:, :, None]
                & (app_term_box == g(term)[:, None, None]),
                0, app_at)
            # etcd re-sends IMMEDIATELY after maybeDecrTo (stepLeader
            # APP_RESP reject -> send_append): enqueue the backtracked
            # probe this tick.  Ring-reachable case only — the snapshot
            # variant waits for the next send round on both sides.
            snp_busy = (snp_at != 0) & (snp_term_box == g(term)[:, None])
            prev_rs = next_ - 1
            rs = mview_r(rej_mat & g(is_leader)[:, None] & ~eye_r
                         & ~drop_r & ~snp_busy
                         & (prev_rs >= g(snap_idx)[:, None]))
            free_rs = (app_at == 0) \
                | (app_term_box != g(term)[:, None, None])
            rslot = jnp.argmax(free_rs, axis=2).astype(I32)
            put_rs = rs[:, :, None] \
                & (rslot[:, :, None]
                   == jnp.arange(cfg.inflight, dtype=I32)[None, None])
            app_at = jnp.where(put_rs, (now + 1 + lat)[:, :, None],
                               app_at)
            app_prev = jnp.where(put_rs, prev_rs[:, :, None], app_prev)
            app_term_box = jnp.where(put_rs, g(term)[:, None, None],
                                     app_term_box)
            # heartbeat responses: liveness only (the etcd match<last
            # resend trigger is unnecessary under send-time-drop wire
            # semantics — nothing in flight can be lost, so slot clearing
            # already guarantees probe retries)
            due_hbr = (hbr_at_box > 0) & (now + 1 >= hbr_at_box)
            val_hbr = due_hbr & g(is_leader)[:, None, None] \
                & (g(term)[:, None, None] == hbr_term_box)
            recent_active = recent_active | jnp.any(val_hbr, axis=2)
            hbr_at_box = jnp.where(due_hbr, 0, hbr_at_box)
            got_resp_r = got_resp_r | jnp.any(val_hbr, axis=(1, 2))

        # -- leader transfer completion: once the target's log caught up,
        # fire TIMEOUT_NOW on its wire slot (vendor stepLeader MsgAppResp
        # transferee branch).  Single slot per target; concurrent
        # transfers to one target are rare and last-writer-wins.
        tgt = jnp.clip(transferee, 0, n - 1)
        has_tx = is_leader & (transferee != NONE) & (tgt != node)
        if not static_m:
            tgt_mem = jnp.take_along_axis(member, tgt[:, None],
                                          axis=1)[:, 0]
            has_tx = has_tx & tgt_mem
        tgt_r = g(tgt)
        caught = g(has_tx) \
            & (jnp.take_along_axis(match, tgt_r[:, None], axis=1)[:, 0]
               == g(last))
        if cfg.mailboxes:
            tn_lat_r = jnp.take_along_axis(lat, tgt_r[:, None],
                                           axis=1)[:, 0]
        else:
            tn_lat_r = jnp.zeros((R,), I32)
        want_tn = caught & (tn_at[tgt_r] == 0) \
            & ~jnp.take_along_axis(drop_r, tgt_r[:, None], axis=1)[:, 0]
        send_tn = want_tn[:, None] & (tgt_r[:, None] == node[None, :])
        any_tn = jnp.any(send_tn, axis=0)                        # [j]
        tn_sel = jnp.argmax(send_tn, axis=0)   # lowest leader wins (rows
        #                                        ascend with node id)
        tn_src = jnp.where(any_tn, rows[tn_sel], 0).astype(I32)
        tn_at = jnp.where(any_tn, now + 1 + tn_lat_r[tn_sel], tn_at)
        tn_term = jnp.where(any_tn, term[tn_src], tn_term)
        tn_from = jnp.where(any_tn, tn_src, tn_from)
        if cfg.transfer_cooldown_ticks > 0:
            # transfer-abuse cooldown re-arm: the row that FIRED a
            # TIMEOUT_NOW refuses new transfer targets for the next
            # cfg.transfer_cooldown_ticks ticks (applied after the
            # segment — tx_cool is a plain [N] register)
            tn_fired = sfull(want_tn, False)

        # ---- Phase D: leader commit (quorum on the match row) ------------
        # maybeCommit (vendor raft.go:478-486) takes the quorum-th largest
        # match index. Equivalent decision, computed as the largest X in
        # (commit, last] acked by a quorum — a fixed-depth binary search
        # (range <= log_len, so ceil(log2(L))+1 rounds of compares)
        # instead of sorting the match plane every tick.
        match = jnp.where(g(is_leader)[:, None] & eye_r,
                          g(self_ack_cap)[:, None], match)
        q_row = quorum_row if static_m else g(quorum_row)
        if cfg.peer_tiled:
            # Banded bisect: the membership mask folds into each band
            # compare (once per band) instead of materializing a full
            # match_eff that every round re-compares.  Identity with the
            # dense form: (where(member, match, -1) >= mid) ==
            # member & (match >= mid) for every reachable mid
            # (mid = (lo+hi+1)>>1 with lo, hi, match >= 0, so
            # mid >= 0 > -1), and the integer band sums commute.
            def _bisect(_, lo_hi):
                lo, hi_b = lo_hi
                mid = (lo + hi_b + 1) >> 1
                cnt = _pcount(
                    lambda j0: _pband(match, j0) >= mid[:, None],
                    mem=member_r, rows_n=R)
                ok = (cnt >= q_row) & (hi_b >= mid) & (mid > lo)
                lo = jnp.where(ok, mid, lo)
                hi_b = jnp.where(ok, hi_b, mid - 1)
                return lo, hi_b
        else:
            match_eff = match if static_m else jnp.where(member_r, match,
                                                         -1)

            def _bisect(_, lo_hi):
                lo, hi_b = lo_hi
                mid = (lo + hi_b + 1) >> 1
                cnt = jnp.sum((match_eff >= mid[:, None]).astype(I32),
                              axis=1)
                ok = (cnt >= q_row) & (hi_b >= mid) & (mid > lo)
                lo = jnp.where(ok, mid, lo)
                hi_b = jnp.where(ok, hi_b, mid - 1)
                return lo, hi_b

        iters = max(1, (cfg.log_len).bit_length() + 1)
        mci_r, _ = jax.lax.fori_loop(0, iters, _bisect,
                                     (g(commit), g(last)))
        # inactive rows report mci = their own commit (a no-advance), so
        # the is_leader-gated commit fold below is branch-independent
        mci = mci_r if dense else commit.at[rows].set(
            mci_r, unique_indices=True)

        # ---- Phase R1 ack counts (raft/read/): the quorum confirmation
        # reuses THIS tick's ack collective — the same ok/reject mats (and
        # heartbeat responses on the mailbox wire) that just fed
        # recent_active/progress — so a ReadIndex round costs no extra
        # messages.  The lease/stamp decision itself runs after the
        # segment (it reads the log for the own-term-commit guard).
        rd_nack = None
        if reads_on:
            rd_ack = ok_mat | rej_mat
            if cfg.mailboxes:
                rd_ack = rd_ack | mview_r(jnp.any(val_hbr, axis=2))
            if cfg.peer_tiled:
                rd_nack_r = _pcount(
                    lambda j0: _pband(rd_ack, j0) | _peye_rows(rows, j0),
                    mem=member_r, rows_n=R)
            else:
                rd_nack_r = jnp.sum(mview_r(rd_ack | eye_r).astype(I32),
                                    axis=1)
            rd_nack = sfull(rd_nack_r, 0)

        out = dict(
            match=sc(match1, match), next_=sc(next1, next_),
            recent_active=sc(ra1, recent_active),
            tn_at=tn_at, tn_term=tn_term, tn_from=tn_from,
            mci=mci, got_resp=sfull(got_resp_r, False))
        if reads_on:
            out["rd_nack"] = rd_nack
        if cfg.transfer_cooldown_ticks > 0:
            out["tn_fired"] = tn_fired
        if cfg.mailboxes:
            out.update(
                probing=sc(probing0, probing),
                app_at=sc(b_app_at, app_at),
                app_prev=sc(b_app_prev, app_prev),
                app_term=sc(b_app_term, app_term_box),
                aresp_at=sc(state.aresp_at, aresp_at),
                aresp_term=sc(state.aresp_term, aresp_term),
                aresp_match=sc(state.aresp_match, aresp_match),
                aresp_ok=sc(state.aresp_ok, aresp_ok),
                hbr_at=sc(b_hbr_at, hbr_at_box))
        return out

    if sparse_on:
        _ob = jax.lax.cond(sp_fits,
                           lambda: _progress_b(sp_rows, False),
                           lambda: _progress_b(node, True))
    else:
        _ob = _progress_b(node, True)
    match, next_ = _ob["match"], _ob["next_"]
    recent_active = _ob["recent_active"]
    tn_at, tn_term, tn_from = _ob["tn_at"], _ob["tn_term"], _ob["tn_from"]
    mci, got_resp = _ob["mci"], _ob["got_resp"]
    if tx_cool is not None:
        tx_cool = jnp.where(_ob["tn_fired"],
                            I32(cfg.transfer_cooldown_ticks), tx_cool)
    if cfg.mailboxes:
        probing = _ob["probing"]
        app_at, app_prev = _ob["app_at"], _ob["app_prev"]
        app_term_box = _ob["app_term"]
        aresp_at, aresp_term = _ob["aresp_at"], _ob["aresp_term"]
        aresp_match, aresp_ok = _ob["aresp_match"], _ob["aresp_ok"]
        hbr_at_box = _ob["hbr_at"]

    # Commit fold, outside the segments (mci_term is a log read).
    with jax.named_scope("phase_D_commit_fold"):
        mci_term = _term_own(cfg, log_term, snap_idx, snap_term, last, mci)
        can_commit = is_leader & (mci > commit) & (mci_term == term)
        commit = jnp.where(can_commit, mci, commit)

    # ---- Phase R1: lease renewal + ReadIndex stamping (raft/read/) -------
    # A quorum of member acks in one tick both renews the tick-clock lease
    # and, with the own-term-commit guard (the classic ReadIndex subtlety:
    # a fresh leader's commit may lag the true frontier until its no-op
    # commits), authorizes stamping the pending batch with the
    # just-updated commit index.
    if reads_on:
        with jax.named_scope("phase_R1_stamp"):
            rd_nack = _ob["rd_nack"]
            rd_is_leader = (role == LEADER) & alive
            rd_q_ok = rd_is_leader & (rd_nack >= quorum_row)
            rd_cterm_ok = (commit > 0) \
                & (_term_own(cfg, log_term, snap_idx, snap_term, last,
                             commit) == term)
            read_regs, rd_confirm = _rd.stamp(
                cfg, read_regs, alive=alive, role=role, lead=lead,
                term=term, commit=commit, commit_term_ok=rd_cterm_ok,
                q_ok=rd_q_ok, transferee=transferee, now=now, drop=drop)

    # ---- Phase E: apply + checksum accumulation + conf activation --------
    # Entries (applied, new_applied] are summed in place via the slot->index
    # map of the OWN ring; _entry_chk is order-independent so no cumsum ring
    # is needed.  Conf-change entries activate HERE — at apply time, exactly
    # like the reference's processConfChange (raft.go:1939) — and the batch
    # is clamped AT the first conf entry so at most one membership flip
    # lands per row per tick (order within a batch is thereby trivial; the
    # propose-side one-in-flight gate makes >1 conf per window rare anyway).
    with jax.named_scope("phase_E_apply"):
        base_applied = jnp.minimum(commit, applied + cfg.apply_batch)
        base_applied = jnp.where(alive, base_applied, applied)  # crashed:
        #                                                         frozen
        if cfg.tiled:
            # Per-row gather window instead of a shared chunk band: each
            # row's apply window (applied, base_applied] is at most
            # apply_batch wide BY CONSTRUCTION, so a [N, apply_batch]
            # take_along_axis covers it exactly — no straggler fallback
            # cond needed, and keeping the buffer out of extra
            # conditionals lets the scan keep it in place (every lax.cond
            # consuming the log carry risks a defensive full-capacity
            # copy on the CPU backend).  The U32 checksum sum is
            # order-independent (modular add), so summing in index order
            # matches the full pass bit-for-bit.
            aspan = jnp.arange(cfg.apply_batch, dtype=I32)[None, :]
            aidx = applied[:, None] + 1 + aspan                 # [N, V]
            am_e = aidx <= base_applied[:, None]
        if static_m:
            # No conf entries can exist (propose masks the tag bit and
            # propose_conf is a trace-time error): apply the whole batch.
            new_applied = base_applied

            def _apply_full(ld):
                own_idx = _idx_at_slots(cfg, last)               # [N, L]
                app_mask = (own_idx > applied[:, None]) \
                    & (own_idx <= base_applied[:, None])
                return jnp.sum(jnp.where(app_mask, _entry_chk(own_idx, ld),
                                         U32(0)), axis=1, dtype=U32)

            if cfg.tiled:
                avals = jnp.take_along_axis(log_data, _slot(cfg, aidx),
                                            axis=1)
                chk_inc = jnp.sum(
                    jnp.where(am_e, _entry_chk(aidx, avals), U32(0)),
                    axis=1, dtype=U32)
            else:
                chk_inc = _apply_full(log_data)
        else:
            def _apply_full(ld):
                own_idx = _idx_at_slots(cfg, last)               # [N, L]
                win_mask = (own_idx > applied[:, None]) \
                    & (own_idx <= base_applied[:, None])
                conf_in_win = win_mask & _is_conf(ld)
                fc = jnp.min(jnp.where(conf_in_win, own_idx, big), axis=1)
                na = jnp.minimum(base_applied,
                                 jnp.where(fc < big, fc, big))
                app_mask = win_mask & (own_idx <= na[:, None])
                return (jnp.sum(jnp.where(app_mask,
                                          _entry_chk(own_idx, ld),
                                          U32(0)), axis=1, dtype=U32), fc)

            if cfg.tiled:
                avals = jnp.take_along_axis(log_data, _slot(cfg, aidx),
                                            axis=1)
                fc = jnp.min(jnp.where(am_e & _is_conf(avals), aidx, big),
                             axis=1)
                na = jnp.minimum(base_applied, jnp.where(fc < big, fc, big))
                chk_inc = jnp.sum(
                    jnp.where(am_e & (aidx <= na[:, None]),
                              _entry_chk(aidx, avals), U32(0)),
                    axis=1, dtype=U32)
                first_conf = fc
            else:
                chk_inc, first_conf = _apply_full(log_data)
            has_conf = first_conf < big
            new_applied = jnp.minimum(base_applied,
                                      jnp.where(has_conf, first_conf, big))
        apply_chk = apply_chk + chk_inc
        applied = new_applied

    if not static_m:
        # Decode + apply the (single) conf entry at new_applied.
        cslot = _slot(cfg, jnp.where(has_conf, first_conf, 1))
        cdata = jnp.take_along_axis(log_data, cslot[:, None], axis=1)[:, 0]
        ctgt = jnp.clip((cdata & U32(CONF_TARGET_MASK)).astype(I32), 0, n - 1)
        c_rm = (cdata & U32(CONF_REMOVE)) != 0
        tgt_onehot = node[None, :] == ctgt[:, None]              # [N, N]
        was_member = jnp.take_along_axis(member, ctgt[:, None], axis=1)[:, 0]
        newly_added = has_conf & ~c_rm & ~was_member
        member = jnp.where(has_conf[:, None] & tgt_onehot,
                           ~c_rm[:, None], member)
        # add_node initializes a fresh Progress(next=last+1, match=0,
        # recent_active=True) on every row (meaningful on leaders; core
        # add_node does the same unconditionally).  Re-adding an existing
        # member keeps its progress (core: early return).
        reset_pr = newly_added[:, None] & tgt_onehot
        match = jnp.where(reset_pr, 0, match)
        next_ = jnp.where(reset_pr, (last + 1)[:, None], next_)
        recent_active = jnp.where(reset_pr, True, recent_active)
        if cfg.mailboxes:
            probing = jnp.where(reset_pr, True, probing)
        # remove_node aborts an in-flight transfer to the removed peer
        # (core.remove_node) ...
        transferee = jnp.where(has_conf & c_rm & (transferee == ctgt),
                               NONE, transferee)
        # ... and clears the leader's propose gate (add/remove_node both do).
        pending_conf = pending_conf & ~has_conf

    # ---- Phase R2: serve / refuse read batches (raft/read/) --------------
    # Stamped batches serve once the fresh applied index covers the stamp
    # (leader same-tick in steady state, followers one apply round later);
    # unstamped batches on a deposed row or behind an unrenewed lease
    # expiry are refused back to the client (READ_BLOCKED accounting —
    # the stale-leader path the DST adversary exercises).
    if reads_on:
        with jax.named_scope("phase_R2_settle"):
            read_regs, rd_served, rd_srv_cnt, rd_blocked, rd_blk_cnt, \
                rd_expired = _rd.settle(
                    cfg, read_regs, alive=alive, applied=applied, role=role,
                    was_leader=(state.role == LEADER), now=now,
                    prev_lease_until=state.lease_until)

    # ---- Phase F: compaction (ring-pressure driven) ----------------------
    # Compact to applied-keep (mirroring LogEntriesForSlowFollowers=500)
    # when the ring is running out of writable headroom. The checksum at the
    # new watermark is apply_chk minus the contributions of the entries
    # still ahead of it (uint32 wrap-safe).
    with jax.named_scope("phase_F_compact"):
        pressure = (last - snap_idx) > (cfg.log_len - 2 * cfg.max_props - 1)
        new_snap = jnp.maximum(snap_idx, applied - cfg.keep)
        do_compact = pressure & (new_snap > snap_idx) & alive
        nst = _term_own(cfg, log_term, snap_idx, snap_term, last, new_snap)

        def _ahead_full(ld):
            own_idx = _idx_at_slots(cfg, last)                   # [N, L]
            ahead = (own_idx > new_snap[:, None]) \
                & (own_idx <= applied[:, None])
            return jnp.sum(jnp.where(ahead, _entry_chk(own_idx, ld),
                                     U32(0)), axis=1, dtype=U32)

        if cfg.tiled:
            # Per-row gather window, same trade as the apply pass: the
            # span (new_snap, applied] is at most `keep` wide by
            # construction (new_snap >= applied - keep on every row), so
            # [N, keep] indices cover it exactly with no fallback cond.
            fspan = jnp.arange(max(cfg.keep, 1), dtype=I32)[None, :]
            fidx = new_snap[:, None] + 1 + fspan                # [N, keep]
            fvals = jnp.take_along_axis(log_data, _slot(cfg, fidx), axis=1)
            ahead_sum = jnp.sum(
                jnp.where(fidx <= applied[:, None], _entry_chk(fidx, fvals),
                          U32(0)), axis=1, dtype=U32)
        else:
            ahead_sum = _ahead_full(log_data)
        nsc = apply_chk - ahead_sum
        snap_term = jnp.where(do_compact, nst, snap_term)
        snap_chk = jnp.where(do_compact, nsc, snap_chk)
        snap_idx = jnp.where(do_compact, new_snap, snap_idx)
    if storage_on:
        # a compacted-to snapshot is durable by construction (compaction
        # only discards APPLIED entries, and writing the snapshot is the
        # fsync); this also pins the global invariant sync_mark >=
        # snap_idx that the lost_tail truncation rule relies on
        sync_mark = jnp.maximum(sync_mark, snap_idx)

    # invariants: `pre`/`tx_cand` mark live candidacies only (any
    # transition away from CANDIDATE clears them), and `transferee` only
    # means anything on a standing leader
    pre = pre & (role == CANDIDATE)
    tx_cand = tx_cand & (role == CANDIDATE) & ~pre
    transferee = jnp.where(role == LEADER, transferee, NONE)

    # Active-row TTL (sparse progress lowering): leaders/candidates pin
    # their row hot; a row that just stepped down — or is still draining
    # responses — keeps a countdown long enough to cover every in-flight
    # message it could yet send, receive, or have answered
    # (2*(latency+jitter) bounds the worst request+response round trip,
    # +2 for the enqueue/deliver tick offsets).  Derived from end-of-tick
    # values only, so both cond branches produce the same ttl bit-for-bit.
    sp_fields = {}
    if sparse_on:
        ttl_w = 2 * (cfg.latency + cfg.latency_jitter) + 2
        keep_hot = (role == CANDIDATE) | (role == LEADER) | got_resp
        sp_fields = dict(active_ttl=jnp.where(
            keep_hot, I32(ttl_w),
            jnp.maximum(state.active_ttl - 1, 0)).astype(I32))

    # End-of-tick conf-gate scans, carried for the NEXT tick's Phase A/B
    # (exact there: nothing that runs before them mutates (applied, commit]
    # or adds conf entries to (commit, last] — propose() masks the tag bit
    # and propose_conf() updates pending_conf itself).
    if static_m:
        hup_conf, tail_conf = state.hup_conf, state.tail_conf  # all-False
    else:
        def _gates_full(ld):
            own_idx = _idx_at_slots(cfg, last)                   # [N, L]
            icr = _is_conf(ld)
            hup = jnp.any((own_idx > applied[:, None])
                          & (own_idx <= commit[:, None]) & icr, axis=1)
            tail = jnp.any((own_idx > commit[:, None])
                           & (own_idx <= last[:, None]) & icr, axis=1)
            return hup, tail

        if cfg.tiled:
            # applied <= commit <= last, so (applied, last] covers both
            # scans; a straggler's whole backlog can exceed the band cap,
            # falling back to the full scan.
            work_g = last > applied
            lo_g = jnp.min(jnp.where(work_g, applied, big))
            hi_g = jnp.max(jnp.where(work_g, last, 0))
            c0g, nch_g = _band_origin(cfg, lo_g, hi_g)

            def _gates_banded(ld):
                hup = jnp.zeros((n,), bool)
                tail = jnp.zeros((n,), bool)
                for off in _band_offsets(cfg, c0g):
                    ld_c = jax.lax.dynamic_slice(ld, (0, off),
                                                 (n, cfg.log_chunk))
                    oi = _idx_at_band(cfg, last, off)
                    icr = _is_conf(ld_c)
                    hup = hup | jnp.any(
                        (oi > applied[:, None]) & (oi <= commit[:, None])
                        & icr, axis=1)
                    tail = tail | jnp.any(
                        (oi > commit[:, None]) & (oi <= last[:, None])
                        & icr, axis=1)
                return hup, tail

            hup_conf, tail_conf = jax.lax.cond(
                nch_g <= cfg.band_chunks, _gates_banded, _gates_full,
                log_data)
        else:
            hup_conf, tail_conf = _gates_full(log_data)
    # Cumulative event counters (cfg.collect_stats): cheap reduces appended
    # to the program so host metrics can read kernel activity from a [4]
    # vector instead of diffing full states (see metrics/catalog.py
    # swarm_kernel_* families).
    # Storage end-of-tick folds: the durable commit record is the running
    # max of min(commit, sync_mark) (what this row has both learned
    # committed and covered durably — RECOVERY_MONOTONIC pins it);
    # ack_frontier is pure oracle bookkeeping (running max of commit, the
    # DURABILITY witness — no verb and no decision ever reads it).  The
    # transient verb flags (fsync_stall, snap_bad) are one-tick inputs,
    # consumed above and cleared here.
    storage_fields = {}
    if storage_on:
        storage_fields = dict(
            sync_mark=sync_mark,
            dur_commit=jnp.maximum(state.dur_commit,
                                   jnp.minimum(commit, sync_mark)),
            ack_frontier=jnp.maximum(state.ack_frontier, commit),
            fsync_stall=jnp.zeros((n,), bool),
            snap_bad=jnp.zeros((n,), bool))

    stats = state.stats
    if cfg.collect_stats and stats is not None:
        stats = stats + jnp.stack([
            jnp.sum((campaign | tn_ok).astype(I32)),
            jnp.sum(win.astype(I32)),
            jnp.sum(commit - state.commit),
            jnp.sum(applied - state.applied)])

    # Causal trace tags (cfg.trace_tags; ISSUE 17): derive the per-row
    # tags the tagged _emit calls below stamp into the event ring's 5th
    # lane.  The commit tag is read off the propose-batch tag ring — the
    # freshest still-live tagged batch whose index range intersects this
    # tick's commit advance (the same fold window the telemetry commit
    # histogram uses, including this tick's fused stamp so an instant-
    # wire same-tick commit still links) — and the read tag off the [N]
    # read_tag register, cleared on the kernel's own closed-loop refill
    # (device-generated batches have no host span to link to).  Python-
    # gated like both donor planes, so a tags-off program is structurally
    # identical to a build without the subsystem.
    tt_fields = {}
    commit_tag = read_tag_now = None
    if cfg.trace_tags and state.tel_prop_tag is not None:
        from swarmkit_tpu.telemetry import series as _ts
        ttag = state.tel_prop_tag
        tidx = state.tel_prop_idx
        tcnt = state.tel_prop_cnt
        ttick = state.tel_prop_tick
        t_ring = ttag.shape[1]        # cfg.telemetry_prop_ring or default
        if fused_prop:
            ptag = jnp.zeros((n,), I32) if prop_tag is None else \
                jnp.broadcast_to(jnp.asarray(prop_tag, I32), (n,))
            ts_ = now % t_ring
            ttag = _ts.col_set(ttag, ts_, jnp.where(prop_ok, ptag, 0))
            tidx = _ts.col_set(tidx, ts_,
                               jnp.where(prop_ok, prop_last0 + 1, NONE))
            tcnt = _ts.col_set(tcnt, ts_,
                               jnp.where(prop_ok, prop_cnt, 0).astype(I32))
            ttick = _ts.col_set(ttick, ts_,
                                jnp.where(prop_ok, now, NONE).astype(I32))
        tlo = jnp.maximum(tidx, state.commit[:, None] + 1)
        thi = jnp.minimum(tidx + tcnt - 1, commit[:, None])
        tsel = can_commit[:, None] & (tidx != NONE) & (ttick >= 0) \
            & (now - ttick < t_ring) & (thi >= tlo) & (ttag != 0)
        tbest = jnp.argmax(jnp.where(tsel, ttick, -1), axis=1)
        commit_tag = jnp.where(
            jnp.any(tsel, axis=1),
            jnp.take_along_axis(ttag, tbest[:, None], axis=1)[:, 0],
            0).astype(I32)
        # step-down wipe mirrors the telemetry batch ring's: a regained
        # leadership must not link another leader's entries to this tag
        ttag = jnp.where(is_leader[:, None], ttag, 0)
        tt_fields = dict(tel_prop_tag=ttag)
        if reads_on and state.read_tag is not None:
            tt_refill = alive & (state.read_pend == 0)
            read_tag_now = jnp.where(tt_refill, 0, state.read_tag)
            tt_fields["read_tag"] = read_tag_now

    # Flight recorder (cfg.record_events; flightrec/codes.py owns the event
    # vocabulary): append coded (tick, code, arg0, arg1) rows into the
    # per-row event ring from the masks this tick already computed.  Like
    # collect_stats, the whole block is Python-gated, so a recorder-off
    # program is structurally identical to a recorder-less build (the
    # bit-identity acceptance test).  The ring writes are plain scatters —
    # the one-write-cond discipline protects the [N, L] log carries, not
    # this [N, ring, 4] side buffer — and every operand is row-local, so
    # recording composes with dst/explore.py's vmap over schedules.
    ev_fields = {}
    if cfg.record_events and state.ev_buf is not None:
        from swarmkit_tpu.flightrec import codes as _fc
        ev_buf, ev_pos = state.ev_buf, state.ev_pos
        zero = jnp.zeros((n,), I32)

        def _emit(mask, code, a0, a1, tag=None):
            nonlocal ev_buf, ev_pos
            ev_buf, ev_pos = _fc.ring_append(ev_buf, ev_pos, mask, now,
                                             code, a0, a1, tag=tag)

        # fault edges: crash/heal transitions + partition-degree changes,
        # detected against the PREVIOUS tick's inputs carried in ev_*
        if cfg.peer_tiled:
            # fault-layer banding: the drop/partition mask's degree
            # reduction runs band-at-a-time too — out-degree via the
            # column-band count (unmasked: fault edges ignore membership),
            # in-degree by accumulating row-band column sums, so neither
            # direction widens a temporary past n*peer_chunk.
            def _colsum(g, acc):
                i0 = g * PC
                return acc + jnp.sum(jax.lax.dynamic_slice(
                    drop, (i0, 0), (PC, n)).astype(I32), axis=0)
            drop_deg = _pcount(lambda j0: _pband(drop, j0), masked=False) \
                + jax.lax.fori_loop(0, PG, _colsum, jnp.zeros((n,), I32))
        else:
            drop_deg = (jnp.sum(drop.astype(I32), axis=1)
                        + jnp.sum(drop.astype(I32), axis=0))
        _emit(state.ev_alive & ~alive, _fc.FAULT_EDGE,
              jnp.full((n,), _fc.EDGE_DOWN, I32), zero)
        _emit(~state.ev_alive & alive, _fc.FAULT_EDGE,
              jnp.full((n,), _fc.EDGE_UP, I32), zero)
        _emit(drop_deg != state.ev_drop, _fc.FAULT_EDGE,
              jnp.full((n,), _fc.EDGE_DROP, I32), drop_deg)
        # protocol events, from the end-of-tick values vs the pre-tick
        # state (TERM_BUMP covers every bump source — campaign, transfer,
        # pre-vote promotion, catch-up from any message class — uniformly)
        _emit(term != state.term, _fc.TERM_BUMP, term, state.term)
        _emit(win, _fc.ELECTION_WON, term, last)
        _emit(resp_reject, _fc.APPEND_REJECT, src, reject_hint)
        _emit(do_restore, _fc.SNAPSHOT_RESTORE, src, snap_idx)
        _emit(commit > state.commit, _fc.COMMIT_ADVANCE, commit,
              commit - state.commit, tag=commit_tag)
        if storage_on:
            _emit(sync_mark > state.sync_mark, _fc.FSYNC_ADVANCE,
                  sync_mark, sync_mark - state.sync_mark)
            if snap_refuse is not None:
                _emit(snap_refuse, _fc.RECOVER_REJECT_SNAP, src, snap_idx)
        if cfg.tiled:
            # cluster-wide event: one row (0) records the fallback so the
            # ring doesn't burn N slots on every full-pass tick
            _emit(~fits & (node == 0), _fc.FALLBACK_TICK,
                  jnp.broadcast_to(nch, (n,)),
                  jnp.full((n,), cfg.band_chunks, I32))
        if reads_on:
            # read lifecycle (masks from phases R1/R2): serves carry the
            # index actually observed, refusals their reason, expiries the
            # count of client reads they bounced
            _emit(rd_served, _fc.READ_SERVED, applied, rd_srv_cnt,
                  tag=read_tag_now)
            _emit(rd_blocked, _fc.READ_BLOCKED, rd_blk_cnt,
                  jnp.where(rd_expired, _fc.BLOCK_LEASE,
                            _fc.BLOCK_DEPOSED).astype(I32))
            _emit(rd_expired, _fc.LEASE_EXPIRED, read_regs.lease_until,
                  rd_blk_cnt)
        ev_fields = dict(ev_buf=ev_buf, ev_pos=ev_pos, ev_alive=alive,
                         ev_drop=drop_deg)

    # Telemetry plane (cfg.collect_telemetry; telemetry/series.py owns the
    # bucket ladder and series enum): stamp-and-fold latency histograms
    # plus the strided time-series ring, from masks this tick already
    # computed.  Python-gated exactly like the recorder block above, and
    # deliberately OFF the [N, L] log axis: stamps live in the compact
    # [N, PROP_RING] batch ring, so telemetry costs a few [N, 512] passes
    # per tick instead of re-introducing the full-ring scans the tiled
    # phases avoid.  Measurement semantics: propose->commit latency is
    # observed at the PROPOSING leader for its self-appended entries
    # (followers receive entries without client-arrival times, like a
    # real cluster); the fold window (state.commit, commit] is exactly
    # this tick's Phase D advance because roles settle in phases A/B,
    # before any Phase C append could land on a row that reaches D as
    # leader.
    tel_fields = {}
    if cfg.collect_telemetry and state.tel_commit_hist is not None:
        from swarmkit_tpu.telemetry import series as _ts
        bidx = state.tel_prop_idx
        bcnt = state.tel_prop_cnt
        btick = state.tel_prop_tick
        ring = bidx.shape[1]          # cfg.telemetry_prop_ring or default
        if fused_prop:
            # stamp this tick's fused appends as ONE batch record: every
            # entry of the batch shares the propose tick, so the stamp is
            # a single-column write, not a per-entry scatter
            bs = now % ring
            bidx = _ts.col_set(bidx, bs,
                               jnp.where(prop_ok, prop_last0 + 1, NONE))
            bcnt = _ts.col_set(
                bcnt, bs, jnp.where(prop_ok, prop_cnt, 0).astype(I32))
            btick = _ts.col_set(
                btick, bs, jnp.where(prop_ok, now, NONE).astype(I32))
        # election duration: campaign start -> win, in ticks.  A re-fired
        # campaign (timeout while still candidate) restarts the clock —
        # the histogram measures the successful attempt, matching how
        # etcd's election metrics count per-campaign.  Same-tick wins
        # (instant wire) stamp before folding and land in bucket 0.
        estart = jnp.where(campaign | tn_ok, now, state.tel_elect_start)
        tel_elect_hist = _ts.hist_fold(state.tel_elect_hist,
                                       win & (estart >= 0), now - estart)
        estart = jnp.where(win, NONE, estart)
        # propose->commit: each batch record folds the slice of its index
        # range covered by this tick's commit advance, weighted by the
        # slice width.  Freshness (< PROP_RING ticks) retires lap-old
        # records without explicit clearing; the step-down wipe below
        # guards against a regained leadership folding another leader's
        # entries at the same indexes.
        lo = jnp.maximum(bidx, state.commit[:, None] + 1)
        hi = jnp.minimum(bidx + bcnt - 1, commit[:, None])
        cw = jnp.maximum(hi - lo + 1, 0)
        cfold = can_commit[:, None] & (bidx != NONE) & (btick >= 0) \
            & (now - btick < ring) & (cw > 0)
        tel_commit_hist = _ts.hist_fold(state.tel_commit_hist, cfold,
                                        now - btick, weight=cw)
        # is_leader here is the settled post-A/B role this tick (the same
        # mask Phase D commits under)
        bidx = jnp.where(is_leader[:, None], bidx, NONE)
        # read submit->settle: the submit stamp mirrors Phase R0's refill
        # condition on the pre-tick registers (serve.py submit), so no
        # mid-kernel read-path change is needed; served and refused
        # batches both settle (a refusal is a completed client round
        # trip too).
        rsub = state.tel_read_submit
        if reads_on:
            tel_refill = alive & (state.read_pend == 0)
            rsub = jnp.where(tel_refill, now, rsub)
            rfold = (rd_served | rd_blocked) & (rsub >= 0)
            tel_read_hist = _ts.hist_fold(state.tel_read_hist, rfold,
                                          now - rsub)
        else:
            tel_read_hist = state.tel_read_hist
        tel_vals = jnp.stack([
            jnp.sum(commit - state.commit),              # commit_rate
            jnp.sum(win.astype(I32)),                    # leader_changes
            jnp.sum(last - snap_idx),                    # log_occupancy
            (jnp.sum(jnp.where(rd_blocked, rd_blk_cnt, 0))
             if reads_on else jnp.asarray(0, I32))])     # reads_blocked
        tel_series = _ts.ring_write(state.tel_series, cfg.telemetry_stride,
                                    now, tel_vals)
        tel_fields = dict(
            tel_prop_idx=bidx, tel_prop_cnt=bcnt, tel_prop_tick=btick,
            tel_elect_start=estart, tel_read_submit=rsub,
            tel_commit_hist=tel_commit_hist, tel_elect_hist=tel_elect_hist,
            tel_read_hist=tel_read_hist, tel_series=tel_series)

    rd_fields = {}
    if reads_on:
        rd_fields = _rd.read_fields(read_regs)
    boxes = {}
    if cfg.mailboxes:
        boxes = dict(
            vreq_at=vreq_at, vreq_term=vreq_term, vreq_pre=vreq_pre,
            vresp_at=vresp_at, vresp_term=vresp_term,
            vresp_grant=vresp_grant, vresp_pre=vresp_pre,
            app_at=app_at, app_prev=app_prev, app_term=app_term_box,
            snp_at=snp_at, snp_term=snp_term_box, probing=probing,
            aresp_at=aresp_at, aresp_term=aresp_term,
            aresp_match=aresp_match, aresp_ok=aresp_ok,
            hb_at=hb_at_box, hb_term=hb_term_box, hb_commit=hb_commit_box,
            hbr_at=hbr_at_box, hbr_term=hbr_term_box)
    return dataclasses.replace(
        state,
        term=term, vote=vote, role=role, lead=lead,
        elapsed=elapsed, contact=contact,
        hb_elapsed=hb_elapsed, timeout=timeout,
        last=last, commit=commit, applied=applied,
        snap_idx=snap_idx, snap_term=snap_term,
        snap_chk=snap_chk, apply_chk=apply_chk,
        log_term=log_term, log_data=log_data,
        match=match, next_=next_, granted=granted,
        rejected=rejected, recent_active=recent_active, pre=pre,
        transferee=transferee, tx_cand=tx_cand,
        tn_at=tn_at, tn_term=tn_term, tn_from=tn_from,
        member=member, pending_conf=pending_conf,
        hup_conf=hup_conf, tail_conf=tail_conf,
        tick=state.tick + 1,
        stats=stats,
        **vg_fields,
        **storage_fields,
        **({} if tx_cool is None else dict(tx_cool=tx_cool)),
        **sp_fields,
        **ev_fields,
        **tel_fields,
        **tt_fields,
        **rd_fields,
        **boxes,
    )


def _leader_ok(state: SimState, cfg: SimConfig, alive=None):
    """Rows that accept proposals: leaders still in their own applied
    config (core raises ProposalDropped for a removed proposer), with ring
    room and no transfer in flight.  `alive` optionally masks crashed
    claimants (clients cannot reach a crashed process)."""
    is_leader = (state.role == LEADER) & jnp.diagonal(state.member)
    room = (state.last + cfg.max_props - state.snap_idx) <= cfg.log_len
    ok = is_leader & room & (state.transferee == NONE)
    if cfg.prop_inflight_cap > 0:
        # append-flood defense: a leader refuses new proposals while its
        # uncommitted tail is at the cap, so a flooding client drains the
        # ring instead of driving it into compaction pressure
        ok = ok & ((state.last - state.commit) < cfg.prop_inflight_cap)
    if alive is not None:
        ok = ok & alive
    return ok


def propose(state: SimState, cfg: SimConfig, payloads: jax.Array,
            count, alive=None, tag=None) -> SimState:
    """Append up to `count` payload entries to every node currently acting
    as leader (clients talk to whoever claims leadership; only a real
    leader's entries can ever commit). payloads: [max_props] uint32
    (bit 31 is reserved for conf entries and masked off).  `tag` is an
    optional scalar host trace tag for this batch (cfg.trace_tags)."""
    n = cfg.n
    node = jnp.arange(n, dtype=I32)
    # a transferring leader rejects proposals (vendor stepLeader MsgProp:
    # ErrProposalDropped while leadTransferee is set)
    ok = _leader_ok(state, cfg, alive)
    k = jnp.arange(cfg.max_props, dtype=I32)
    valid = (k[None, :] < count) & ok[:, None]                   # [N, B]
    idx = state.last[:, None] + 1 + k[None, :]
    slot = _slot(cfg, idx)
    payloads = payloads & U32(0x7FFFFFFF)
    pl = jnp.broadcast_to(payloads[None, :], (n, cfg.max_props))
    log_term = state.log_term.at[node[:, None], slot].set(
        jnp.where(valid, state.term[:, None], state.log_term[node[:, None], slot]))
    log_data = state.log_data.at[node[:, None], slot].set(
        jnp.where(valid, pl, state.log_data[node[:, None], slot]))
    new_last = state.last + jnp.where(ok, count, 0).astype(I32)
    eye = jnp.eye(n, dtype=bool)
    match = jnp.where(ok[:, None] & eye, new_last[:, None], state.match)
    tel_fields = {}
    if cfg.collect_telemetry and state.tel_prop_idx is not None:
        # telemetry stamp: one batch record in the (row, tick) ring — the
        # whole append shares this client-arrival tick
        from swarmkit_tpu.telemetry import series as _ts
        bs = state.tick % state.tel_prop_idx.shape[1]
        cnt = jnp.asarray(count, I32)
        tel_fields = dict(
            tel_prop_idx=_ts.col_set(state.tel_prop_idx, bs,
                                     jnp.where(ok, state.last + 1, NONE)),
            tel_prop_cnt=_ts.col_set(state.tel_prop_cnt, bs,
                                     jnp.where(ok, cnt, 0).astype(I32)),
            tel_prop_tick=_ts.col_set(
                state.tel_prop_tick, bs,
                jnp.where(ok, state.tick, NONE).astype(I32)))
        if cfg.trace_tags and state.tel_prop_tag is not None:
            tg = jnp.zeros((n,), I32) if tag is None else \
                jnp.broadcast_to(jnp.asarray(tag, I32), (n,))
            tel_fields["tel_prop_tag"] = _ts.col_set(
                state.tel_prop_tag, bs, jnp.where(ok, tg, 0))
    return dataclasses.replace(state, log_term=log_term, log_data=log_data,
                               last=new_last, match=match, **tel_fields)


def propose_dense(state: SimState, cfg: SimConfig,
                  payload_fn: Callable[[jax.Array, jax.Array], jax.Array],
                  count, alive=None, tag=None) -> SimState:
    """Gather/scatter-free propose for the benchmark hot path: payloads are
    generated ON DEVICE as payload_fn(tick, k) (k = 0..count-1, uint32
    result), written via the slot->index map as elementwise [N, L] masked
    stores. Decision-equivalent to propose(state, cfg, payloads, count) with
    payloads[k] = payload_fn(tick, k) — asserted by tests/test_raft_sim.py.
    """
    n = cfg.n
    ok = _leader_ok(state, cfg, alive)
    count = jnp.asarray(count, I32)
    anchor = state.last + count

    def _write_full(lt, ld):
        # slot -> new index map anchored one batch ahead of last
        new_idx = _idx_at_slots(cfg, anchor)                     # [N, L]
        k_of = new_idx - state.last[:, None] - 1                 # [N, L]
        valid = ok[:, None] & (k_of >= 0) & (k_of < count)
        pl = payload_fn(state.tick, jnp.maximum(k_of, 0).astype(U32)) \
            & U32(0x7FFFFFFF)
        return (jnp.where(valid, state.term[:, None], lt),
                jnp.where(valid, pl, ld))

    if cfg.tiled:
        # Banded store over (min last, max last+count] of proposing rows —
        # same geometry as the kernel's append band (leaders at different
        # terms can sit far apart: the cond falls back to the full pass).
        big = jnp.iinfo(jnp.int32).max
        lo_p = jnp.min(jnp.where(ok, state.last, big))
        hi_p = jnp.max(jnp.where(ok, anchor, 0))
        c0p, nch_p = _band_origin(cfg, lo_p, hi_p)

        def _write_banded(lt, ld):
            for off in _band_offsets(cfg, c0p):
                lt_c = jax.lax.dynamic_slice(lt, (0, off),
                                             (n, cfg.log_chunk))
                ld_c = jax.lax.dynamic_slice(ld, (0, off),
                                             (n, cfg.log_chunk))
                new_idx = _idx_at_band(cfg, anchor, off)
                k_of = new_idx - state.last[:, None] - 1
                valid = ok[:, None] & (k_of >= 0) & (k_of < count)
                pl = payload_fn(state.tick,
                                jnp.maximum(k_of, 0).astype(U32)) \
                    & U32(0x7FFFFFFF)
                lt = jax.lax.dynamic_update_slice(
                    lt, jnp.where(valid, state.term[:, None], lt_c),
                    (0, off))
                ld = jax.lax.dynamic_update_slice(
                    ld, jnp.where(valid, pl, ld_c), (0, off))
            return lt, ld

        log_term, log_data = jax.lax.cond(
            nch_p <= cfg.band_chunks, _write_banded, _write_full,
            state.log_term, state.log_data)
    else:
        log_term, log_data = _write_full(state.log_term, state.log_data)
    new_last = state.last + jnp.where(ok, count, 0).astype(I32)
    eye = jnp.eye(n, dtype=bool)
    match = jnp.where(ok[:, None] & eye, new_last[:, None], state.match)
    tel_fields = {}
    if cfg.collect_telemetry and state.tel_prop_idx is not None:
        # telemetry stamp: identical batch record to propose()'s — the
        # dense path changes how payloads are materialised, not the
        # measurement semantics
        from swarmkit_tpu.telemetry import series as _ts
        bs = state.tick % state.tel_prop_idx.shape[1]
        tel_fields = dict(
            tel_prop_idx=_ts.col_set(state.tel_prop_idx, bs,
                                     jnp.where(ok, state.last + 1, NONE)),
            tel_prop_cnt=_ts.col_set(state.tel_prop_cnt, bs,
                                     jnp.where(ok, count, 0).astype(I32)),
            tel_prop_tick=_ts.col_set(
                state.tel_prop_tick, bs,
                jnp.where(ok, state.tick, NONE).astype(I32)))
        if cfg.trace_tags and state.tel_prop_tag is not None:
            tg = jnp.zeros((n,), I32) if tag is None else \
                jnp.broadcast_to(jnp.asarray(tag, I32), (n,))
            tel_fields["tel_prop_tag"] = _ts.col_set(
                state.tel_prop_tag, bs, jnp.where(ok, tg, 0))
    return dataclasses.replace(state, log_term=log_term, log_data=log_data,
                               last=new_last, match=match, **tel_fields)


def transfer_leadership(state: SimState, cfg: SimConfig, leader,
                        target) -> SimState:
    """Host-side transfer request (vendor stepLeader MsgTransferLeader):
    records the target on the leader row and resets its election timer; the
    kernel fires TIMEOUT_NOW once the target's log catches up.  A repeat
    request for the SAME in-flight target is a no-op; a different target
    aborts and replaces the previous transfer."""
    leader = jnp.asarray(leader, I32)
    target = jnp.asarray(target, I32)
    is_l = (state.role[leader] == LEADER) & (target != leader) \
        & state.member[leader, target]
    if cfg.transfer_cooldown_ticks > 0 and state.tx_cool is not None:
        # transfer-abuse defense: refuse new targets while the cooldown
        # from this leader's last fired TIMEOUT_NOW is still counting down
        is_l = is_l & (state.tx_cool[leader] == 0)
    changed = is_l & (state.transferee[leader] != target)
    transferee = state.transferee.at[leader].set(
        jnp.where(changed, target, state.transferee[leader]))
    elapsed = state.elapsed.at[leader].set(
        jnp.where(changed, 0, state.elapsed[leader]))
    return dataclasses.replace(state, transferee=transferee, elapsed=elapsed)


def propose_conf(state: SimState, cfg: SimConfig, target, remove,
                 alive=None) -> SimState:
    """Propose ONE membership change (add/remove `target`) to every node
    currently accepting proposals.  Mirrors core stepLeader MsgProp with a
    CONF_CHANGE entry (vendor raft.go:~700): while an earlier conf change
    is still in flight on that leader (pending_conf), the entry DEGRADES to
    an empty normal entry — the one-in-flight rule that keeps apply windows
    to at most one membership flip.  Activation happens at apply time in
    step() Phase E; reference flow manager/state/raft/raft.go:920-1087
    (Join/Leave) -> :1939 (processConfChange)."""
    if cfg.static_members:
        raise ValueError("propose_conf on a static_members config: "
                         "membership changes need static_members=False")
    n = cfg.n
    node = jnp.arange(n, dtype=I32)
    target = jnp.asarray(target, I32)
    # a target outside [0, n) degrades to an empty normal entry, exactly
    # like the pending-conf case (the host validates ids; this is the
    # last-line guard against retargeting row n-1 via the decode clip)
    valid_tgt = (target >= 0) & (target < n)
    remove = jnp.asarray(remove, bool)
    ok = _leader_ok(state, cfg, alive)
    payload = jnp.where(
        ok & ~state.pending_conf & valid_tgt,
        U32(CONF_TAG)
        | jnp.where(remove, U32(CONF_REMOVE), U32(0))
        | (target.astype(U32) & U32(CONF_TARGET_MASK)),
        U32(0))                                   # degraded: empty normal
    idx = state.last + 1
    slot = _slot(cfg, idx)
    log_term = state.log_term.at[node, slot].set(
        jnp.where(ok, state.term, state.log_term[node, slot]))
    log_data = state.log_data.at[node, slot].set(
        jnp.where(ok, payload, state.log_data[node, slot]))
    new_last = state.last + ok.astype(I32)
    eye = jnp.eye(n, dtype=bool)
    match = jnp.where(ok[:, None] & eye, new_last[:, None], state.match)
    appended_conf = ok & ~state.pending_conf & valid_tgt
    pending_conf = state.pending_conf | appended_conf
    tail_conf = state.tail_conf | appended_conf
    return dataclasses.replace(state, log_term=log_term, log_data=log_data,
                               last=new_last, match=match,
                               pending_conf=pending_conf,
                               tail_conf=tail_conf)

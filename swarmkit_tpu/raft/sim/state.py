"""Device-array state for the batched raft simulation.

N simulated managers are rows of device arrays (the north-star layout from
BASELINE.json): per-node scalars are [N], the leader's per-peer progress view
is [N, N], and each node's log is a fixed-width ring buffer [N, L] with an
explicit compaction watermark (snap_idx) replacing the reference's unbounded
Go slices + WAL (manager/state/raft/raft.go Node state, vendor etcd raft
struct raft.go:209-264).

Node indices are 0-based on device; `NONE` (no leader / no vote) is -1.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

# Roles
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

NONE = -1

# Conf-change entries ride the uint32 payload with a tag bit (the device
# analog of raftpb EntryConfChange + ConfChange{Add,Remove}Node; reference
# apply path manager/state/raft/raft.go:1939 processConfChange):
#   bit 31 = conf entry, bit 30 = remove (else add), low 16 bits = target row.
# Normal payloads must stay below bit 31 (propose() masks them).
CONF_TAG = 0x8000_0000
CONF_REMOVE = 0x4000_0000
CONF_TARGET_MASK = 0xFFFF


def conf_payload(target: int, remove: bool) -> int:
    """uint32 payload encoding one ConfChange (add/remove of `target`)."""
    return CONF_TAG | (CONF_REMOVE if remove else 0) | (target & CONF_TARGET_MASK)


@dataclass(frozen=True)
class SimConfig:
    """Static (compile-time) simulation parameters.

    Mirrors the reference defaults where meaningful: election_tick=10,
    heartbeat_tick=1 (raft.go:484-488); keep=500 entries retained for slow
    followers after compaction (raft.go:501).
    """

    n: int = 64                 # simulated managers
    log_len: int = 8192         # ring-buffer slots per manager (L)
    window: int = 1024          # max entries per append message (W)
    apply_batch: int = 2048     # entries applied per node per tick (A)
    max_props: int = 1024       # proposal batch width (B)
    election_tick: int = 10
    heartbeat_tick: int = 1
    keep: int = 500             # entries kept behind `applied` at compaction
    seed: int = 0
    # Per-edge message latency in ticks (SURVEY §7 device mailboxes).
    # latency=0 with jitter=0 is the tick-synchronous fast path: no mailbox
    # arrays are allocated and request+response complete within one tick.
    # Otherwise every message spends latency + (hash % (jitter+1)) ticks in
    # an [N, N] in-flight slot; one message per class per directed edge
    # (inflight window of 1), content read at delivery, stale messages
    # (sender term/role changed since send) dropped at delivery.
    latency: int = 0
    latency_jitter: int = 0
    # Append pipelining depth on the mailbox wire (vendor MaxInflightMsgs,
    # reference swarmkit uses 256): up to `inflight` appends ride each
    # directed edge concurrently, with optimistic next_ advance at send and
    # rejection backtracking (etcd Replicate-state pipeline).  Depth ~RTT
    # sustains full window throughput per tick.  Vote/snapshot classes stay
    # single-slot (etcd also serializes those).
    inflight: int = 1
    # testing knob: run the mailbox wire even at latency 0 (same-tick
    # delivery) — must be decision-identical to the synchronous path
    force_mailboxes: bool = False
    # Carry cumulative event counters in SimState.stats ([4] int32:
    # campaigns started, elections won, commit advance, apply advance) so
    # host-side metrics read live kernel activity without hauling the full
    # state back per tick.  Off by default: the extra reduces are traced
    # into the step program only when enabled.
    collect_stats: bool = False
    # PreVote (vendor raft.go campaignPreElection): a timed-out node runs a
    # non-binding poll at term+1 WITHOUT bumping its term first, so a
    # flapping/partitioned node cannot inflate cluster terms.  Mirrors
    # core.Config.pre_vote.
    pre_vote: bool = False
    # Compile-time specialization for FIXED membership (the bench configs
    # and any cluster that never reconfigures): quorum is the constant
    # n//2+1, every per-row [N, N] membership view collapses to "all rows",
    # and Phase E's conf-entry decode + the hup/tail conf scans are elided
    # from the compiled program entirely.  Decision-identical to the dynamic
    # path when no conf change is ever proposed (asserted by
    # tests/test_raft_sim.py::test_static_members_equivalence); the
    # reference analog is etcd allocating its progress tracker per config —
    # a config that never changes pays nothing for the machinery
    # (manager/state/raft/raft.go:482-508 documents its perf levers the
    # same way).  propose_conf() on a static-members config is a trace-time
    # error.
    static_members: bool = False
    # Flight recorder (flightrec/): carry a per-row event ring
    # [N, event_ring, 4] in SimState plus a monotonic write cursor, and
    # have the kernel append coded events (election won, term bump, commit
    # advance, snapshot restore, fault edges, append rejects, tiling
    # fallbacks) at the end of every tick.  Off by default: like
    # collect_stats, the recording scatters are traced into the step
    # program only when enabled, so the off path stays bit-identical to a
    # recorder-less build.  Decode host-side with flightrec.decode_rings.
    record_events: bool = False
    event_ring: int = 128       # slots per row (oldest events overwrite)
    # Log-axis tiling (kernel.py banded passes): chunk width in ring slots.
    # When 0 < log_chunk < log_len the [N, L] hot phases (append receive,
    # apply+checksum, conf scans, compaction, dense propose) slice only the
    # lane-aligned chunks covering the tick's active cursor band out of the
    # ring, so per-tick cost scales with window/apply_batch instead of L —
    # with a full-pass fallback branch when straggler spread exceeds the
    # band cap (bit-identical either way; see TestTiledLog).  log_chunk=0
    # disables tiling explicitly; a chunk >= log_len disables it trivially
    # (the default leaves every small-ring test config untiled).
    log_chunk: int = 1024
    # Peer-axis tiling (kernel.py hierarchical quorum reductions): column
    # band width in rows.  When 0 < peer_chunk < n every [N, N]
    # tally/reduction in the tick (CheckQuorum heard-count, vote/pre-vote/
    # rejection tallies, the commit bisection's per-round compares, the
    # heartbeat-ack quorum the read path reuses) runs as a two-level
    # hierarchical pass: a scan over [N, peer_chunk] column bands computes
    # group-local counts into an [N, n/peer_chunk] partial buffer, and a
    # cross-group combine produces the per-row total — so no full [N, N]
    # boolean/compare intermediate is ever materialized and per-band
    # membership masking happens once per band, not once per bisection
    # round.  Integer sums are order-independent, so the banded lowering
    # is bit-identical to the dense one (see TestTiledPeer).
    # peer_chunk=0 disables peer tiling explicitly; a chunk >= n disables
    # it trivially (the default leaves every small-cluster test config
    # dense).  Chunks must divide n and be sublane-aligned (multiple of
    # 8); 128-multiples are recommended on real TPUs for lane alignment.
    peer_chunk: int = 1024
    # Role-sparse per-peer progress (kernel.py sparse progress cond): slab
    # height in rows.  Only rows whose node is a leader or candidate (plus
    # rows still draining in-flight responses) ever mutate their [N, N]
    # progress view — follower rows are dead weight — so when
    # 0 < active_rows < n the kernel gathers those active rows into compact
    # [A, N] slabs each tick, runs every elementwise progress/fan-out
    # update (match/next_/granted/rejected bookkeeping and the ack folds
    # that feed them) on the slabs, and scatters back.  Ticks where the
    # active-row count exceeds A (election storms) take a bit-identical
    # masked dense fallback, mirroring the tiled-log fallback contract
    # (see TestSparseProgress).  active_rows=0 disables the sparse lowering
    # explicitly; a value >= n disables it trivially (the default engages
    # only on clusters larger than 16 rows).  Must be sublane-aligned
    # (multiple of 8); the drain window that keeps in-flight responses
    # active is 2*(latency + latency_jitter) + 2 ticks.
    active_rows: int = 16
    # Linearizable read path (raft/read/): read_batch > 0 threads the
    # read-serving phases (R0 submit / R1 stamp / R2 settle) through the
    # tick and allocates the [N] read registers.  Each idle row auto-
    # submits a batch of `read_batch` client reads per refill; batches
    # are stamped with a ReadIndex (leader lease or quorum-ack
    # confirmation) and served once applied >= read_index.  Off by
    # default: like the flight recorder, the phases are traced into the
    # step program only when enabled, so read_batch=0 stays bit-identical
    # to a build without the subsystem.
    read_batch: int = 0
    # False = ReadIndex-classic: every batch waits for a quorum-ack tick
    # before stamping.  True = tick-clock leader leases: a leader inside
    # its lease stamps immediately, with zero extra collectives (see
    # raft/read/lease.py for the clock-skew safety argument).
    read_leases: bool = True
    # Safety margin subtracted from the lease span, in ticks.  Must stay
    # >= 1: the voter no-vote window and the lease are measured by the
    # same tick clock, and the margin is what keeps lease expiry strictly
    # before the earliest rival election.
    lease_margin: int = 1
    # On-device telemetry plane (telemetry/): carry fixed-bucket latency
    # histograms (propose->commit, election duration, read submit->settle,
    # all in ticks) plus a strided [series, window] time-series ring in
    # SimState, folded at the end of every tick.  Off by default: like the
    # flight recorder, the telemetry scatters are traced into the step
    # program only when enabled, so the off path stays bit-identical to a
    # telemetry-less build.  Scrape host-side with telemetry.TelemetryObs.
    collect_telemetry: bool = False
    telemetry_window: int = 64   # ring columns (stride-wide buckets) kept
    telemetry_stride: int = 8    # ticks aggregated per ring column
    # Propose-batch ring depth (0 = telemetry.series.PROP_RING = 512).
    # The commit-latency fold scans the whole [N, ring] ring every tick,
    # so the ring is the telemetry plane's dominant cost at SMALL N — the
    # multi-raft fleet's tiny per-group shapes (kernel work is a few
    # [N, window] passes) see ~2x from the default depth where n=256
    # quorums see noise.  A ring of R measures latencies up to R/2 ticks
    # (coverage rule: ring >= 2x the largest histogram edge it must
    # resolve; batches older than R ticks age out unmeasured), so fleets
    # whose per-group commit latency is tick-scale can drop to 64 and
    # keep every bucket they can populate.  PERF.md "Fleet health"
    # documents the A/B.
    telemetry_prop_ring: int = 0
    # Causal trace tags (ISSUE 17): carry a host-assigned trace tag per
    # propose batch ([N, PROP_RING] alongside the telemetry batch ring)
    # and per read batch ([N]), widen the flight-recorder event rows to
    # (tick, code, arg0, arg1, tag), and stamp the tag into the
    # COMMIT_ADVANCE / READ_SERVED events the host span is waiting on —
    # the device half of the flow-linked Perfetto export
    # (flightrec/export.py).  Requires both donor planes: the telemetry
    # batch ring locates which committed indexes belong to which propose
    # batch, the event ring carries the stamped instants.  Off by
    # default and Python-gated like both donors, so a tags-off program
    # stays bit-identical to a build without the subsystem.
    trace_tags: bool = False
    # Optional steady-state latency SLO for the DST oracle: when > 0 (and
    # collect_telemetry is on), dst/invariants.py raises SLO_COMMIT_P99
    # if the device-computed p99 propose->commit latency bucket edge
    # exceeds this many ticks.  0 disables the oracle bit.
    slo_p99_commit_ticks: int = 0
    # ---- adversary-suite defense knobs (dst/schedule.py attack verbs) ----
    # Every default below reproduces the pre-suite compiled program
    # bit-for-bit: check_quorum=True keeps the lease + periodic step-down
    # that were previously unconditional, and the three new defenses are
    # Python-gated OFF so their registers/ops are never traced.
    #
    # CheckQuorum (raft dissertation §4.2.3, previously always-on): the
    # voter lease on (pre)vote requests plus the leader's periodic
    # heard-from-a-quorum step-down.  False exists ONLY so the
    # disruptive_rejoin adversary demo can show the undefended election
    # storm; production configs keep it True.
    check_quorum: bool = True
    # Persisted-vote guard (the vote_equivocation defense): carry a
    # durable WAL-analog (vg_vote, vg_term) that records every granted
    # vote and is consulted alongside the volatile `vote` register.  The
    # equivocation verb wipes `vote` (a crash-restart without fsync); with
    # the guard on, a second same-term grant is unrepresentable because
    # the WAL shadow still pins the first choice.  Decision-identical to
    # the stock kernel when no verb tampers with `vote`.
    vote_guard: bool = False
    # Leadership-transfer cooldown (the transfer_abuse defense): after a
    # row fires TIMEOUT_NOW for a transfer, it refuses further transfer
    # requests for this many ticks (transfer_leadership and the
    # transfer_abuse verb both consult the register).  0 disables the
    # register entirely.
    transfer_cooldown_ticks: int = 0
    # Per-row proposal inflight cap (the append_flood defense): a leader
    # whose uncommitted backlog (last - commit) has reached this many
    # entries refuses new proposals until the pipeline drains — bounding
    # the ring/Phase-F compaction pressure a targeted append flood can
    # build.  0 disables the cap.
    prop_inflight_cap: int = 0
    # Leadership-churn SLO for the DST oracle: when > 0 (and
    # collect_telemetry is on), dst/invariants.py raises SLO_LEADER_CHURN
    # if the cumulative election-win count (sum of tel_elect_hist) exceeds
    # this bound — the disruptive_rejoin / transfer_abuse witness.
    slo_leader_changes: int = 0
    # Log-occupancy SLO for the DST oracle: when > 0, dst/invariants.py
    # raises SLO_LOG_OCCUPANCY if any row's uncommitted tail
    # (last - commit, the quantity prop_inflight_cap gates acceptance
    # on) exceeds this bound — the append_flood witness.  With the cap
    # on, the tail never exceeds prop_inflight_cap - 1 + max_props.
    slo_log_occupancy: int = 0
    # ---- storage model (the durability boundary; dst storage verbs) ------
    # fsync cadence: the per-row durable watermark `sync_mark` advances
    # toward `last` only on ticks where tick % fsync_lag_ticks ==
    # fsync_lag_ticks - 1 (so 1 = fsync every tick, the tightest policy).
    # 0 disables the storage model entirely — no durable registers are
    # traced and the compiled program is bit-identical to the pre-storage
    # kernel.  This is the master knob: every other storage field below
    # requires fsync_lag_ticks > 0.
    fsync_lag_ticks: int = 0
    # Max log entries one fsync round may durable-ize (a batched-write
    # disk model).  0 = unlimited (the whole unsynced suffix syncs).
    fsync_batch: int = 0
    # Ack-gating (the lost_tail / torn_write defense): a follower only
    # acks append entries — and a row only grants votes / counts its own
    # leader self-match — up to its durable watermark.  This is the
    # etcd/raft persistence contract (Ready/Advance: fsync BEFORE
    # sending MsgAppResp); with it on, every committed entry is fsynced
    # on a quorum, so losing any crashed minority's unsynced tail can
    # never lose acked-as-committed data.  Off models the unsafe
    # ack-before-fsync fast path the DURABILITY invariant exists to
    # catch.
    ack_gating: bool = False
    # Fsync-lag SLO for the DST oracle: when > 0, dst/invariants.py
    # raises SLO_FSYNC_LAG if any row's unsynced suffix (last -
    # sync_mark, the quantity disk_stall inflates) exceeds this bound.
    # With ack_gating + prop_inflight_cap on, the suffix is bounded by
    # the cap (a leader stops accepting once its uncommitted — hence
    # unsynced-beyond — backlog fills).
    slo_fsync_lag: int = 0

    @property
    def lease_ticks(self) -> int:
        """Lease span: election_tick - lease_margin - (latency + jitter).
        The latency term discounts ack staleness on the mailbox wire —
        an ack delivered now proves follower contact only as of up to
        latency + jitter ticks ago."""
        return self.election_tick - self.lease_margin \
            - (self.latency + self.latency_jitter)

    @property
    def tiled(self) -> bool:
        """True when the kernel compiles the banded (chunked) log passes."""
        return 0 < self.log_chunk < self.log_len

    @property
    def num_chunks(self) -> int:
        """Chunks per ring (only meaningful when tiled)."""
        return self.log_len // self.log_chunk

    @property
    def band_chunks(self) -> int:
        """Compile-time cap on chunks one banded pass visits: the widest
        per-tick cursor advance (window / apply_batch / max_props / keep)
        plus two boundary chunks for band misalignment and cross-row
        spread.  A band wider than this falls back to the full pass."""
        widest = max(self.window, self.apply_batch, self.max_props,
                     self.keep)
        return widest // self.log_chunk + 2

    @property
    def peer_tiled(self) -> bool:
        """True when the kernel compiles the banded (hierarchical) peer
        reductions instead of dense [N, N] tallies."""
        return 0 < self.peer_chunk < self.n

    @property
    def num_peer_chunks(self) -> int:
        """Column bands per peer row (only meaningful when peer_tiled)."""
        return self.n // self.peer_chunk

    @property
    def active_rows_on(self) -> bool:
        """True when the kernel compiles the role-sparse [A, N] progress
        slabs instead of dense [N, N] elementwise progress writes."""
        return 0 < self.active_rows < self.n

    @property
    def ack_depth(self) -> int:
        """Ack-wire slots per edge: acks are generated at most once per
        tick per edge and live latency..latency+jitter ticks, so this
        depth can NEVER overflow — no eviction policy to keep in sync
        between kernel and oracle."""
        return self.latency + self.latency_jitter + 1

    @property
    def mailboxes(self) -> bool:
        return self.latency > 0 or self.latency_jitter > 0 \
            or self.force_mailboxes

    @property
    def storage_on(self) -> bool:
        """True when the kernel traces the durable-watermark registers
        (sync_mark et al.) and the fsync-advance / recovery machinery."""
        return self.fsync_lag_ticks > 0

    @property
    def event_width(self) -> int:
        """Flight-ring row width: the base (tick, code, arg0, arg1)
        vocabulary, plus the trace-tag lane when cfg.trace_tags."""
        from swarmkit_tpu.flightrec import codes as _fc
        return _fc.EVENT_WIDTH_TAGGED if self.trace_tags \
            else _fc.EVENT_WIDTH

    @property
    def has_vote_guard(self) -> bool:
        """True when the persisted-vote registers (vg_vote, vg_term) are
        carried: either the standalone PR-15 defense knob or the full
        storage model (which subsumes the WAL-shadow — vote durability is
        part of the durable register set)."""
        return self.vote_guard or self.storage_on

    def __post_init__(self):
        assert self.apply_batch >= self.max_props
        assert self.log_len > self.keep + 2 * self.max_props + self.window
        assert self.latency >= 0 and self.latency_jitter >= 0
        assert self.inflight >= 1
        assert self.inflight == 1 or self.mailboxes, \
            "append pipelining requires the mailbox wire"
        if self.mailboxes:
            # a full round trip must fit well inside the election timeout or
            # healthy leaders get deposed by their own followers
            assert 2 * (self.latency + self.latency_jitter) < self.election_tick
        if self.read_batch < 0:
            raise ValueError(f"read_batch must be >= 0, got {self.read_batch}")
        if self.read_batch and self.read_leases:
            if self.lease_margin < 1:
                raise ValueError(
                    f"lease_margin={self.lease_margin} must be >= 1: the "
                    f"margin is the clock-skew guard keeping lease expiry "
                    f"strictly before the earliest rival election")
            if self.lease_ticks <= 0:
                raise ValueError(
                    f"lease_ticks={self.lease_ticks} (election_tick="
                    f"{self.election_tick} - lease_margin="
                    f"{self.lease_margin} - latency+jitter="
                    f"{self.latency + self.latency_jitter}) must be > 0 — "
                    f"the wire is too slow for this election timeout to "
                    f"support leases; raise election_tick or set "
                    f"read_leases=False for ReadIndex-only serving")
        if self.record_events and self.event_ring < 8:
            raise ValueError(
                f"event_ring={self.event_ring} is too small to hold one "
                f"tick's worth of events; use >= 8 slots per row")
        # Tiling validation: clear trace-time errors instead of silent
        # mis-tiling (the banded passes assume aligned, ring-dividing
        # chunks and a band cap strictly under the chunk count).
        if self.log_chunk < 0:
            raise ValueError(f"log_chunk must be >= 0, got {self.log_chunk}")
        if self.tiled:
            if self.log_chunk % 128 != 0:
                raise ValueError(
                    f"log_chunk={self.log_chunk} must be a multiple of 128 "
                    f"(TPU lane alignment for the banded dynamic slices); "
                    f"set log_chunk=0 to disable tiling")
            if self.log_len % self.log_chunk != 0:
                raise ValueError(
                    f"log_chunk={self.log_chunk} must divide "
                    f"log_len={self.log_len} (the ring is sliced in whole "
                    f"chunks); set log_chunk=0 to disable tiling")
            if self.band_chunks >= self.num_chunks:
                raise ValueError(
                    f"window/apply_batch/max_props/keep "
                    f"({self.window}/{self.apply_batch}/{self.max_props}/"
                    f"{self.keep}) are inconsistent with the band cap: "
                    f"band_chunks={self.band_chunks} must stay below "
                    f"num_chunks={self.num_chunks} or the banded pass "
                    f"covers the whole ring — raise log_len, raise "
                    f"log_chunk, or set log_chunk=0 to disable tiling")
        if self.collect_telemetry:
            if self.telemetry_stride < 1:
                raise ValueError(
                    f"telemetry_stride={self.telemetry_stride} must be "
                    f">= 1 (ticks aggregated per ring column)")
            if self.telemetry_window < 8:
                raise ValueError(
                    f"telemetry_window={self.telemetry_window} is too "
                    f"small to hold a useful history; use >= 8 columns")
            if self.telemetry_prop_ring < 0 or \
                    0 < self.telemetry_prop_ring < 16:
                raise ValueError(
                    f"telemetry_prop_ring={self.telemetry_prop_ring} "
                    f"must be 0 (default depth) or >= 16 (a ring of R "
                    f"only measures latencies up to R/2 ticks)")
        if self.trace_tags and not (self.record_events
                                    and self.collect_telemetry):
            raise ValueError(
                "trace_tags needs both donor planes: set "
                "record_events=True (tagged event ring) and "
                "collect_telemetry=True (propose-batch ring)")
        if self.slo_p99_commit_ticks < 0:
            raise ValueError(f"slo_p99_commit_ticks must be >= 0, got "
                             f"{self.slo_p99_commit_ticks}")
        if self.slo_p99_commit_ticks > 0 and not self.collect_telemetry:
            raise ValueError(
                "slo_p99_commit_ticks needs the commit-latency histogram; "
                "set collect_telemetry=True")
        if self.transfer_cooldown_ticks < 0:
            raise ValueError(f"transfer_cooldown_ticks must be >= 0, got "
                             f"{self.transfer_cooldown_ticks}")
        if self.prop_inflight_cap < 0:
            raise ValueError(f"prop_inflight_cap must be >= 0, got "
                             f"{self.prop_inflight_cap}")
        if self.slo_leader_changes < 0:
            raise ValueError(f"slo_leader_changes must be >= 0, got "
                             f"{self.slo_leader_changes}")
        if self.slo_leader_changes > 0 and not self.collect_telemetry:
            raise ValueError(
                "slo_leader_changes needs the election histogram; "
                "set collect_telemetry=True")
        if self.slo_log_occupancy < 0:
            raise ValueError(f"slo_log_occupancy must be >= 0, got "
                             f"{self.slo_log_occupancy}")
        if self.fsync_lag_ticks < 0:
            raise ValueError(f"fsync_lag_ticks must be >= 0, got "
                             f"{self.fsync_lag_ticks}")
        if self.fsync_batch < 0:
            raise ValueError(f"fsync_batch must be >= 0, got "
                             f"{self.fsync_batch}")
        if self.slo_fsync_lag < 0:
            raise ValueError(f"slo_fsync_lag must be >= 0, got "
                             f"{self.slo_fsync_lag}")
        if not self.storage_on:
            for knob in ("fsync_batch", "ack_gating", "slo_fsync_lag"):
                if getattr(self, knob):
                    raise ValueError(
                        f"{knob} requires the storage model; set "
                        f"fsync_lag_ticks >= 1 (1 = fsync every tick)")
        if self.peer_chunk < 0:
            raise ValueError(f"peer_chunk must be >= 0, got {self.peer_chunk}")
        if self.peer_tiled:
            if self.peer_chunk % 8 != 0:
                raise ValueError(
                    f"peer_chunk={self.peer_chunk} must be a multiple of 8 "
                    f"(sublane alignment for the banded column slices; use "
                    f"128-multiples on real TPUs for lane alignment); set "
                    f"peer_chunk=0 to disable peer tiling")
            if self.n % self.peer_chunk != 0:
                raise ValueError(
                    f"peer_chunk={self.peer_chunk} must divide n={self.n} "
                    f"(the peer axis is sliced in whole column bands); set "
                    f"peer_chunk=0 to disable peer tiling")
        if self.active_rows < 0:
            raise ValueError(
                f"active_rows must be >= 0, got {self.active_rows}")
        if self.active_rows_on and self.active_rows % 8 != 0:
            raise ValueError(
                f"active_rows={self.active_rows} must be a multiple of 8 "
                f"(sublane alignment for the gathered [A, N] progress "
                f"slabs); set active_rows=0 to disable the sparse "
                f"progress lowering")


@jax.tree_util.register_dataclass
@dataclass
class SimState:
    # per-node scalars [N]
    term: jax.Array
    vote: jax.Array        # voted-for node index, NONE if none
    role: jax.Array        # FOLLOWER / CANDIDATE / LEADER
    lead: jax.Array        # known leader index, NONE if unknown
    elapsed: jax.Array     # election timer (resets on campaign/grant/
                           # leader contact — vendor electionElapsed)
    contact: jax.Array     # ticks since last CURRENT-TERM leader contact;
                           # the CheckQuorum lease measures THIS (raft
                           # dissertation §4.2.3), NOT elapsed — see
                           # core.py contact_elapsed for why etcd-3.1's
                           # conflation livelocks PreVote elections
    hb_elapsed: jax.Array  # leader heartbeat timer
    timeout: jax.Array     # randomized election timeout in ticks
    last: jax.Array        # last log index
    commit: jax.Array
    applied: jax.Array
    snap_idx: jax.Array    # compaction watermark (log holds (snap_idx, last])
    snap_term: jax.Array
    snap_chk: jax.Array    # state-machine checksum at snap_idx (uint32)
    apply_chk: jax.Array   # state-machine checksum at applied (uint32)
    # log ring buffers [N, L]; slot of index i (1-based) = (i-1) % L.
    # Slots are INDEX-DETERMINED and therefore identical across rows — the
    # kernel's append path exploits this to replace per-entry gathers with
    # elementwise masked copies (kernel.py Phase C). State-machine checksums
    # are derived on the fly from (index, data), so no checksum ring exists.
    log_term: jax.Array
    log_data: jax.Array    # uint32 payload ids
    # leader-view progress [N, N]: row i = node i's view as (potential) leader
    match: jax.Array
    next_: jax.Array
    granted: jax.Array     # bool: granted[i, j] = j voted for i this term
    rejected: jax.Array    # bool: rejected[i, j] = j refused i this term
                           # (a rejection quorum steps the candidate down,
                           # vendor raft.go stepCandidate poll)
    pre: jax.Array         # bool [N]: candidacy is a PreVote poll (role ==
                           # CANDIDATE, term NOT yet bumped; vendor
                           # becomePreCandidate)
    # leader transfer (vendor raft.go leadTransferee + MsgTimeoutNow):
    transferee: jax.Array  # i32 [N]: row i's pending transfer target while
                           # i leads (NONE = no transfer in progress)
    tx_cand: jax.Array     # bool [N]: candidacy was forced by TIMEOUT_NOW
                           # (its vote requests carry CAMPAIGN_TRANSFER and
                           # bypass the leader lease)
    tn_at: jax.Array       # i32 [N]: TIMEOUT_NOW wire, deliver tick+1
                           # (0 = empty; single slot per target)
    tn_term: jax.Array     # i32 [N]: sender leader's term at send
    tn_from: jax.Array     # i32 [N]: sender leader row
    recent_active: jax.Array  # bool: leader i heard from j since the last
                              # CheckQuorum round (Progress.RecentActive)
    # membership [N, N] bool: member[i, j] = row i's APPLIED configuration
    # contains j.  Conf changes travel as committed CONF_TAG log entries and
    # flip these at apply time (Phase E) — per-node views, exactly like each
    # etcd node's prs map materializing at its own apply point (reference
    # raft.go:1939 processConfChange, membership/cluster.go:185).  Every
    # quorum computation (votes, rejections, CheckQuorum, commit bisection)
    # counts over the deciding row's view.
    member: jax.Array
    # conf-change gates [N] bool (etcd pendingConf + the HUP gate):
    pending_conf: jax.Array  # leader propose gate: a conf entry this leader
                             # appended is not yet applied (a second conf
                             # proposal degrades to an empty normal entry,
                             # vendor raft.go stepLeader MsgProp)
    hup_conf: jax.Array      # campaign gate: a conf entry sits in
                             # (applied, commit] (vendor raft.go HUP case);
                             # computed end-of-tick for the next Phase A
    tail_conf: jax.Array     # becomeLeader scan: a conf entry sits in
                             # (commit, last] (vendor becomeLeader
                             # numOfPendingConf); computed end-of-tick
    # global tick counter (scalar) — also the PRNG stream position
    tick: jax.Array
    # cumulative event counters [4] int32 (cfg.collect_stats; see SimConfig):
    # [0] campaigns started  [1] elections won
    # [2] sum of commit-index advance  [3] sum of applied-index advance
    stats: Optional[jax.Array] = None
    # ---- role-sparse progress (cfg.active_rows_on; kernel.py) -----------
    # active_ttl [N] i32: drain countdown keeping a row in the sparse
    # active set while responses it solicited may still be in flight.
    # Refreshed to 2*(latency + jitter) + 2 whenever the row ends a tick
    # as leader/candidate or receives any response; decremented toward 0
    # otherwise.  Rows with ttl == 0 and a follower role provably have no
    # pending progress mutations, so the [A, N] slab can skip them.
    active_ttl: Optional[jax.Array] = None
    # ---- adversary-defense registers (Python-gated by SimConfig) --------
    # vg_vote/vg_term [N] i32 (cfg.vote_guard): the durably-persisted vote
    # record — (candidate granted, term it was granted at).  Written
    # alongside every `vote` assignment, NEVER cleared by schedule verbs
    # (the vote_equivocation attack wipes only the volatile `vote`
    # register, modeling a restart that lost the unsynced WAL tail), and
    # consulted by Phase B's can_vote so a second same-term grant is
    # unrepresentable.  NONE/NONE = never voted.
    vg_vote: Optional[jax.Array] = None
    vg_term: Optional[jax.Array] = None
    # tx_cool [N] i32 (cfg.transfer_cooldown_ticks > 0): ticks until this
    # row accepts another leadership-transfer request.  Armed to the
    # cooldown span when the row fires TIMEOUT_NOW for a completing
    # transfer; decremented toward 0 each tick.
    tx_cool: Optional[jax.Array] = None
    # ---- storage model (cfg.storage_on; the durability boundary) --------
    # sync_mark [N] i32: the fsynced log watermark — every entry at index
    # <= sync_mark survives any crash.  Advanced toward `last` by the
    # fsync_lag_ticks / fsync_batch policy at the top of each tick (before
    # this tick's appends, so a just-appended entry is never instantly
    # durable); pinned >= snap_idx (installed/compacted-to snapshots are
    # durable by definition); frozen while the row is crashed or
    # disk_stall holds its fsync.  The lost_tail verb truncates back to
    # it; torn_write truncates one entry below it (the last durable entry
    # failed its checksum at recovery).
    sync_mark: Optional[jax.Array] = None
    # dur_commit [N] i32: the durable commit record — the running max of
    # min(commit, sync_mark), i.e. the highest commit index this row has
    # both learned and covered durably.  Recovery never regresses it
    # (RECOVERY_MONOTONIC); the volatile `commit` may legally fall after
    # lost_tail/torn_write truncation, and the record survives even when
    # a torn tail costs the row the entry's own copy (cluster-wide
    # durability is the DURABILITY invariant's job, not this register's).
    dur_commit: Optional[jax.Array] = None
    # ack_frontier [N] i32: oracle bookkeeping, never read by any decision
    # and never touched by storage verbs — the running max of `commit`
    # each row has ever observed.  The DURABILITY invariant's witness:
    # an entry counted committed here must exist on SOME live log after
    # any crash schedule (max(ack_frontier) <= max(last) cluster-wide).
    ack_frontier: Optional[jax.Array] = None
    # fsync_stall [N] bool (transient, one tick): set by the disk_stall
    # verb before the step; the tick's fsync round skips flagged rows and
    # (under ack_gating) flagged rows refuse vote grants — a stalled disk
    # cannot persist the vote record.  Cleared at end of tick.
    fsync_stall: Optional[jax.Array] = None
    # snap_bad [N] bool (transient, one tick): set by the snap_corrupt
    # verb — a snapshot arriving at a flagged row this tick fails its
    # checksum at restore.  With ack_gating the row refuses the install
    # (keeps state; the sender's unadvanced progress re-sends); without
    # it the corrupt image installs and poisons the apply/snap checksum
    # chain (caught later by CHECKSUM_AGREEMENT).  Cleared at end of
    # tick.
    snap_bad: Optional[jax.Array] = None
    # ---- flight recorder (cfg.record_events; flightrec/) ----------------
    # ev_buf [N, event_ring, 4] i32 rows of (tick, code, arg0, arg1);
    # ev_pos [N] is the CUMULATIVE events-written cursor per row (slot of
    # event k = k % event_ring, so dropped-event count = max(0, pos - cap)
    # and the decoder can order survivors without a separate epoch field).
    # ev_alive / ev_drop carry the previous tick's fault inputs so the
    # kernel can emit FAULT_EDGE events on transitions only.
    ev_buf: Optional[jax.Array] = None
    ev_pos: Optional[jax.Array] = None
    ev_alive: Optional[jax.Array] = None   # bool [N]: last tick's alive
    ev_drop: Optional[jax.Array] = None    # i32 [N]: last tick's drop degree
    # ---- linearizable read path (cfg.read_batch > 0; raft/read/) --------
    # All [N] i32.  pend/goal/idx are the in-flight batch (goal = the
    # acked-write frontier max(commit) captured at submit — the oracle
    # witness the DST invariant checks against, never read by serving
    # decisions); lease_until is the leader-lease register; srv/block are
    # cumulative served/refused read counters; srv_idx/srv_goal snapshot
    # (applied, goal) of the last served batch — the LINEARIZABLE_READ
    # invariant is jnp.any(srv_idx < srv_goal).
    read_pend: Optional[jax.Array] = None
    read_goal: Optional[jax.Array] = None
    read_idx: Optional[jax.Array] = None      # NONE = not yet stamped
    lease_until: Optional[jax.Array] = None
    read_srv: Optional[jax.Array] = None
    read_block: Optional[jax.Array] = None
    read_srv_idx: Optional[jax.Array] = None
    read_srv_goal: Optional[jax.Array] = None
    # ---- telemetry plane (cfg.collect_telemetry; telemetry/) ------------
    # Propose-batch ring [N, PROP_RING]: every entry a leader appends in
    # one tick shares that tick's client-arrival stamp, so the stamps are
    # per (row, tick-batch) — slot t % PROP_RING holds (first idx, count,
    # tick) of the batch proposed at tick t, NONE/0 when the row was not
    # an accepting leader.  This keeps the commit fold off the [N, L] log
    # axis entirely (a full-ring pass per tick costs ~10x the tiled
    # kernel's banded phases at the bench shape).  Records invalidate on
    # step-down (a regained leadership may hold different entries at the
    # same indexes) and by age (>= PROP_RING ticks, beyond the histogram's
    # overflow edge).  tel_elect_start / tel_read_submit [N] mark campaign
    # start / read-batch submit ticks (NONE = idle).  Aggregates:
    # tel_*_hist [NUM_BUCKETS] i32 bucket counters (edges in
    # telemetry/series.py); tel_series [NUM_SERIES, telemetry_window] is
    # the strided time-series ring.
    tel_prop_idx: Optional[jax.Array] = None
    tel_prop_cnt: Optional[jax.Array] = None
    tel_prop_tick: Optional[jax.Array] = None
    # ---- causal trace tags (cfg.trace_tags; ISSUE 17) -------------------
    # tel_prop_tag [N, PROP_RING] rides the propose-batch ring: slot
    # t % PROP_RING holds the host trace tag of the batch proposed at
    # tick t (0 = untagged / device-generated).  read_tag [N] holds the
    # tag of the in-flight read batch (submit_reads(tag=...); cleared to
    # 0 on the kernel's own closed-loop refill).  Both feed the tagged
    # 5th lane of ev_buf.
    tel_prop_tag: Optional[jax.Array] = None
    read_tag: Optional[jax.Array] = None
    tel_elect_start: Optional[jax.Array] = None
    tel_read_submit: Optional[jax.Array] = None
    tel_commit_hist: Optional[jax.Array] = None
    tel_elect_hist: Optional[jax.Array] = None
    tel_read_hist: Optional[jax.Array] = None
    tel_series: Optional[jax.Array] = None
    # ---- in-flight mailboxes [N, N], only when cfg.mailboxes ------------
    # One slot per message class per directed edge; *_at holds deliver
    # tick + 1 (0 = empty).  Request classes index [sender, receiver];
    # response classes index [original sender, responder] so the leader's
    # progress row stays row-major.  Content beyond the captured header is
    # read from the sender's CURRENT state at delivery, guarded by "sender
    # term unchanged since send" (stale messages drop — always raft-safe).
    vreq_at: Optional[jax.Array] = None     # i -> j vote request
    vreq_term: Optional[jax.Array] = None   # SENDER's term at send (message
                                            # term = vreq_term + vreq_pre)
    vreq_pre: Optional[jax.Array] = None    # bool: request is a PreVote
    vresp_at: Optional[jax.Array] = None    # j -> i vote response
    vresp_term: Optional[jax.Array] = None
    vresp_grant: Optional[jax.Array] = None  # bool
    vresp_pre: Optional[jax.Array] = None    # bool: response to a PreVote
    app_at: Optional[jax.Array] = None      # i -> j append [N, N, K]
    app_prev: Optional[jax.Array] = None    # (K = cfg.inflight pipelining
    app_term: Optional[jax.Array] = None    #  depth; delivery drains one
                                            #  per edge per tick, smallest
                                            #  prev first)
    snp_at: Optional[jax.Array] = None      # i -> j snapshot install
    snp_term: Optional[jax.Array] = None
    probing: Optional[jax.Array] = None     # bool [N, N]: edge is in etcd
                                            # StateProbe (one append at a
                                            # time, no optimistic next);
                                            # an accepted ack flips it to
                                            # replicate, a rejection back
    aresp_at: Optional[jax.Array] = None    # j -> i append/snap response
    aresp_term: Optional[jax.Array] = None  # [N, N, ack_depth]: one ack is
    aresp_match: Optional[jax.Array] = None  # generated per delivery, and
    aresp_ok: Optional[jax.Array] = None    # deliveries aggregate (max
                                            # match / min reject hint)
    # heartbeat class (etcd MsgHeartbeat/MsgHeartbeatResp; vendor
    # bcastHeartbeat raft.go:456-462): [N, N, ack_depth] — sent once per
    # heartbeat_tick per edge, so the ack-style depth bound holds.  The
    # commit is CAPTURED at send as min(match, commit) (etcd semantics);
    # appends, by contrast, read commit at delivery.
    hb_at: Optional[jax.Array] = None       # i -> j heartbeat
    hb_term: Optional[jax.Array] = None
    hb_commit: Optional[jax.Array] = None
    hbr_at: Optional[jax.Array] = None      # j -> i response, indexed
    hbr_term: Optional[jax.Array] = None    # [leader, responder]


def init_state(cfg: SimConfig,
               voters: Optional[Sequence[int]] = None) -> SimState:
    """Fresh cluster state.  `voters` is the bootstrap configuration (row
    indices); default: all N rows.  Every row starts knowing the same
    bootstrap config (all nodes are launched with the same --join peer
    list); non-voter rows stay passive until a committed CONF entry adds
    them."""
    n, L = cfg.n, cfg.log_len
    i32 = jnp.int32
    z = lambda *s: jnp.zeros(s, i32)  # noqa: E731
    if cfg.static_members and voters is not None:
        raise ValueError("static_members requires the full bootstrap config "
                         "(voters=None); partial configs need conf changes")
    if voters is None:
        member_row = jnp.ones((n,), bool)
    else:
        member_row = jnp.zeros((n,), bool).at[jnp.asarray(list(voters),
                                                          i32)].set(True)
    member = jnp.broadcast_to(member_row, (n, n))
    boxes = {}
    if cfg.mailboxes:
        boxes = dict(
            vreq_at=z(n, n), vreq_term=z(n, n),
            vreq_pre=jnp.zeros((n, n), jnp.bool_),
            vresp_pre=jnp.zeros((n, n), jnp.bool_),
            vresp_at=z(n, n), vresp_term=z(n, n),
            vresp_grant=jnp.zeros((n, n), jnp.bool_),
            app_at=z(n, n, cfg.inflight), app_prev=z(n, n, cfg.inflight),
            app_term=z(n, n, cfg.inflight),
            snp_at=z(n, n), snp_term=z(n, n),
            probing=jnp.ones((n, n), jnp.bool_),
            aresp_at=z(n, n, cfg.ack_depth),
            aresp_term=z(n, n, cfg.ack_depth),
            aresp_match=z(n, n, cfg.ack_depth),
            aresp_ok=jnp.zeros((n, n, cfg.ack_depth), jnp.bool_),
            hb_at=z(n, n, cfg.ack_depth),
            hb_term=z(n, n, cfg.ack_depth),
            hb_commit=z(n, n, cfg.ack_depth),
            hbr_at=z(n, n, cfg.ack_depth),
            hbr_term=z(n, n, cfg.ack_depth))
    return SimState(
        **boxes,
        term=z(n),
        vote=jnp.full((n,), NONE, i32),
        role=z(n),
        lead=jnp.full((n,), NONE, i32),
        elapsed=z(n),
        contact=z(n),
        hb_elapsed=z(n),
        timeout=_initial_timeouts(cfg),
        last=z(n), commit=z(n), applied=z(n),
        snap_idx=z(n), snap_term=z(n),
        snap_chk=jnp.zeros((n,), jnp.uint32),
        apply_chk=jnp.zeros((n,), jnp.uint32),
        log_term=z(n, L),
        log_data=jnp.zeros((n, L), jnp.uint32),
        match=z(n, n),
        next_=jnp.ones((n, n), i32),
        granted=jnp.zeros((n, n), jnp.bool_),
        rejected=jnp.zeros((n, n), jnp.bool_),
        pre=jnp.zeros((n,), jnp.bool_),
        transferee=jnp.full((n,), NONE, i32),
        tx_cand=jnp.zeros((n,), jnp.bool_),
        tn_at=z(n), tn_term=z(n), tn_from=z(n),
        recent_active=jnp.zeros((n, n), jnp.bool_),
        member=member,
        pending_conf=jnp.zeros((n,), jnp.bool_),
        hup_conf=jnp.zeros((n,), jnp.bool_),
        tail_conf=jnp.zeros((n,), jnp.bool_),
        tick=jnp.zeros((), i32),
        stats=jnp.zeros((4,), i32) if cfg.collect_stats else None,
        active_ttl=z(n) if cfg.active_rows_on else None,
        **(dict(vg_vote=jnp.full((n,), NONE, i32),
                vg_term=jnp.full((n,), NONE, i32))
           if cfg.has_vote_guard else {}),
        **(dict(tx_cool=z(n)) if cfg.transfer_cooldown_ticks > 0 else {}),
        **(dict(sync_mark=z(n), dur_commit=z(n), ack_frontier=z(n),
                fsync_stall=jnp.zeros((n,), jnp.bool_),
                snap_bad=jnp.zeros((n,), jnp.bool_))
           if cfg.storage_on else {}),
        **(dict(ev_buf=z(n, cfg.event_ring, cfg.event_width), ev_pos=z(n),
                ev_alive=jnp.ones((n,), jnp.bool_), ev_drop=z(n))
           if cfg.record_events else {}),
        **(dict(read_pend=z(n), read_goal=z(n),
                read_idx=jnp.full((n,), NONE, i32),
                lease_until=z(n), read_srv=z(n), read_block=z(n),
                read_srv_idx=z(n), read_srv_goal=z(n))
           if cfg.read_batch > 0 else {}),
        **(_telemetry_init(cfg) if cfg.collect_telemetry else {}),
        **(_trace_tag_init(cfg) if cfg.trace_tags else {}),
    )


def _trace_tag_init(cfg: SimConfig) -> dict:
    from swarmkit_tpu.telemetry import series as tel
    n, i32 = cfg.n, jnp.int32
    ring = cfg.telemetry_prop_ring or tel.PROP_RING
    out = dict(tel_prop_tag=jnp.zeros((n, ring), i32))
    if cfg.read_batch > 0:
        out["read_tag"] = jnp.zeros((n,), i32)
    return out


def _telemetry_init(cfg: SimConfig) -> dict:
    from swarmkit_tpu.telemetry import series as tel
    n, i32 = cfg.n, jnp.int32
    ring = cfg.telemetry_prop_ring or tel.PROP_RING
    z = lambda *s: jnp.zeros(s, i32)  # noqa: E731
    return dict(
        tel_prop_idx=jnp.full((n, ring), NONE, i32),
        tel_prop_cnt=z(n, ring),
        tel_prop_tick=jnp.full((n, ring), NONE, i32),
        tel_elect_start=jnp.full((n,), NONE, i32),
        tel_read_submit=jnp.full((n,), NONE, i32),
        tel_commit_hist=z(tel.NUM_BUCKETS),
        tel_elect_hist=z(tel.NUM_BUCKETS),
        tel_read_hist=z(tel.NUM_BUCKETS),
        tel_series=z(tel.NUM_SERIES, cfg.telemetry_window))


def hash32(x: jax.Array) -> jax.Array:
    """splitmix32-style integer mix (uint32 -> uint32); the deterministic
    PRNG behind randomized election timeouts and drop matrices."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def rand_timeout(cfg: SimConfig, node: jax.Array, term: jax.Array) -> jax.Array:
    """Randomized election timeout in [election_tick, 2*election_tick),
    deterministic per (node, term, seed) — reference: vendor raft.go:255-258."""
    h = hash32(node.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
               ^ term.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
               ^ jnp.uint32(cfg.seed))
    return (cfg.election_tick + (h % jnp.uint32(cfg.election_tick))).astype(jnp.int32)


def _initial_timeouts(cfg: SimConfig) -> jax.Array:
    node = jnp.arange(cfg.n, dtype=jnp.int32)
    return rand_timeout(cfg, node, jnp.zeros((cfg.n,), jnp.int32))


def latency_at(cfg: SimConfig, tick: jax.Array, i: jax.Array,
               j: jax.Array) -> jax.Array:
    """Per-edge latency for arbitrary (broadcastable) sender/receiver index
    arrays — the same hash latency_matrix uses, evaluated only at the
    requested edges.  The role-sparse progress slabs (cfg.active_rows_on)
    use this to rebuild [A, N] latency rows without materializing the full
    [N, N] matrix; latency_matrix(cfg, t)[i, j] == latency_at(cfg, t, i, j)
    bit-for-bit."""
    shape = jnp.broadcast_shapes(jnp.shape(i), jnp.shape(j))
    base = jnp.full(shape, cfg.latency, jnp.int32)
    if cfg.latency_jitter == 0:
        return base
    h = hash32(i.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
               ^ j.astype(jnp.uint32) * jnp.uint32(0x01000193)
               ^ tick.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
               ^ jnp.uint32(cfg.seed ^ 0x7A77))
    return base + (h % jnp.uint32(cfg.latency_jitter + 1)).astype(jnp.int32)


def latency_matrix(cfg: SimConfig, tick: jax.Array) -> jax.Array:
    """[N, N] per-message latency in ticks for messages SENT this tick:
    cfg.latency + hash(i, j, tick, seed) % (jitter+1).  Deterministic, so
    the oracle replays the identical schedule."""
    i = jnp.arange(cfg.n, dtype=jnp.uint32)
    return latency_at(cfg, tick, i[:, None], i[None, :])


def drop_matrix(cfg: SimConfig, tick: jax.Array, rate: float) -> jax.Array:
    """Per-edge Bernoulli message-drop mask for this tick (BASELINE churn
    configs). drop[i, j] = True drops messages i -> j."""
    n = cfg.n
    i = jnp.arange(n, dtype=jnp.uint32)
    h = hash32(i[:, None] * jnp.uint32(0x01000193)
               ^ i[None, :] * jnp.uint32(0x9E3779B1)
               ^ tick.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
               ^ jnp.uint32(cfg.seed ^ 0xD1FF))
    return (h.astype(jnp.float32) / jnp.float32(2**32)) < rate

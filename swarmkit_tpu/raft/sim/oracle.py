"""Differential-test oracle: the host golden raft core (swarmkit_tpu.raft
.core, a semantically-exact re-expression of vendored etcd/raft — see
/root/reference/vendor/github.com/coreos/etcd/raft/raft.go:679-1060) driven
through a scheduler that reproduces the device kernel's tick-synchronous
phase order exactly, so kernel state and oracle state can be compared
per-tick, field by field.

The kernel (swarmkit_tpu.raft.sim.kernel.step) advances all N managers one
tick as: A) timers/campaign, B) vote request+response exchange, C) append/
snapshot fan-out + responses, D) leader quorum-commit, E) apply batch,
F) ring compaction — with requests and responses completing within one tick
unless masked by the drop matrix. This module replays those phases against
real `core.Raft` nodes.

INTENTIONAL DIVERGENCES between the kernel and stock etcd/raft semantics,
all masked here (this is the single list the differential gate maintains;
each knob names the kernel simplification it mirrors). Vote rejections
(candidate steps down on a rejection quorum) and CheckQuorum (leader lease
on vote requests + periodic step-down of partitioned leaders) are now
IMPLEMENTED by the kernel and replayed faithfully here — they are no longer
divergences.

 D1' CLOSED for the mailbox wire (round 4): a real heartbeat class now
    exists (hb_*/hbr_* boxes — MsgHeartbeat on the heartbeat_tick cadence
    with commit CAPTURED at send as min(match, commit), responses feeding
    CheckQuorum liveness), appends are EVENT-GATED (replicate edges send
    only content; probe edges one at a time; idle edges carry heartbeats),
    and a rejection re-sends the backtracked probe within the same tick
    (etcd stepLeader APP_RESP -> send_append).  Replayed here by
    _tick_mailbox's hbq/hbrq queues and the post-backtrack enqueue.
    Two deliberate residues, both argued strictly-fresher-than-etcd AND
    test-backed (round 5): tests/test_oracle_residues.py constructs each
    scenario and asserts trajectory convergence (same leader/term/commit,
    bounded extra delay) against an UNMASKED etcd-faithful core replay:
    (a) commit-advance-triggered EMPTY append broadcasts are subsumed —
    content appends read commit at DELIVERY (fresher than etcd's capture
    at send) and caught-up edges learn commit from next tick's heartbeat;
    (b) the heartbeat-response match<last append trigger is unnecessary
    because the wire drops at SEND only (nothing in flight can be lost;
    freed slots already guarantee probe retries).  The SYNCHRONOUS wire
    keeps appends-every-tick by definition — at heartbeat_tick=1 that IS
    etcd's heartbeat cadence with content folded in; the scheduler calls
    _bcast_append each tick there and never fires BEAT.
 D2' PreVote and leader transfer ARE implemented (cfg.pre_vote;
    kernel.transfer_leadership + the TIMEOUT_NOW wire) and replayed here.
    One wire simplification remains: a PreVote rejection stamped with a
    receiver term ABOVE the candidacy's own term is dropped in the wire
    instead of deposing the pre-candidate (it catches up via appends);
    equal-term rejections count toward the rejection quorum exactly as
    etcd's poll does. Mask: _prevote_exchange_sync/_tick_mailbox enqueue
    only countable rejections.  Test-backed (round 5):
    test_oracle_residues.py::test_d2_* shows the dropped-rejection
    pre-candidate and etcd's deposed follower converge to the identical
    (leader, term, commit) trajectory once a real election lands.
 D3' windowed flow control IS implemented on the mailbox wire
    (cfg.inflight = vendor MaxInflightMsgs): up to K appends pipeline per
    edge with optimistic next advance in StateReplicate, becomeReplicate's
    exact next=match+1 on the probe->replicate flip, and becomeProbe +
    pipeline flush on rejection — replayed here via per-edge queues and
    core's own Progress states.  The synchronous wire still re-sends the
    window from next_ every tick (its whole point is one-tick rounds).
    Mask: SyncRaft._send_append is a side-effect-free windowed send, and
    _tick_mailbox mirrors the kernel's send gating and aggregate-ack
    integration (all due acks per edge per tick: max match, then one
    min-hint rejection fallback).
 D4' CLOSED (round 4): kernel election timers now follow etcd's
    become_follower/_reset scope exactly — zeroed AND re-randomized (at
    the deterministic per-(node, term) value) on every term catch-up from
    vote requests or leader messages, zeroed on a rejection-quorum
    step-down, plus the original campaign/grant/leader-contact/CheckQuorum
    resets.  The scheduler's elapsed/timeout arrays replay the same rules
    (core CheckQuorum decisions still driven externally with
    Config(check_quorum=False) so core's internal lease stays off — a
    bookkeeping choice, not a semantic divergence).
 D5' CLOSED (round 4): propose()/propose_conf() take the alive mask
    (clients cannot reach a crashed claimant) and kernel phases E/F freeze
    apply + compaction on crashed rows; _phase_propose*/_phase_def consult
    `up` identically.

 D6 STORAGE: the oracle models a PERFECT DISK.  It has no sync_mark
    register, no fsync cadence, and no storage-fault verbs — core.Raft
    persists everything the moment it is written, exactly stock etcd
    with an ideal WAL.  The kernel's durability boundary
    (cfg.fsync_lag_ticks / ack_gating, the lost_tail/torn_write/
    snap_corrupt/disk_stall FaultSchedule leaves) is therefore mirrored
    on the KERNEL side only: dst.repro.oracle_trace stops a compared
    range before the first storage verb fires (replay_artifact's
    `until` bound), so the differential gate still certifies the clean
    prefix while the DURABILITY/RECOVERY_MONOTONIC invariant bits own
    the faulted suffix.  Ack-gating with a clean disk is transparent by
    construction (acks merely lag; no decision changes), which the
    storage-off bit-identity tests in tests/test_durability.py pin.

MEMBERSHIP REPLAY (log-driven conf changes): _phase_propose_conf mirrors
kernel propose_conf (one CONF entry per leader, degraded to an empty
normal entry while one is pending); the apply loop in _phase_def clamps
each batch at the first conf entry (kernel's one-flip-per-tick rule) and
calls core add_node/remove_node at the apply point, with remove_node's
quorum-lowering commit re-check deferred to the next Phase D
(recheck=False) and commit advancement HELD during the propose phases
(SyncRaft._maybe_commit) — both keep commit evaluation at the kernel's
once-per-tick Phase D position without changing any decision.  Vote
request/append send loops follow each sender's CURRENT prs view, and
responses from peers outside the config are dropped exactly as core's
stepLeader does.  The per-tick comparison includes the full [N, N]
member-view matrix.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from swarmkit_tpu.raft import core
from swarmkit_tpu.raft.log import CompactedError, RaftLog, UnavailableError
from swarmkit_tpu.raft.messages import (
    Entry, EntryType, Message, MsgType, Snapshot, SnapshotMeta,
)
from swarmkit_tpu.raft.sim.state import (
    CONF_REMOVE, CONF_TARGET_MASK, SimConfig, conf_payload,
)

M32 = 0xFFFFFFFF


def hash32_py(x: int) -> int:
    """Python mirror of state.hash32 (splitmix32-style uint32 mix)."""
    x &= M32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & M32
    x ^= x >> 15
    x = (x * 0x846CA68B) & M32
    x ^= x >> 16
    return x


def rand_timeout_py(cfg: SimConfig, node: int, term: int) -> int:
    """Python mirror of state.rand_timeout."""
    h = hash32_py(((node * 0x9E3779B1) & M32)
                  ^ ((term * 0x85EBCA77) & M32)
                  ^ (cfg.seed & M32))
    return cfg.election_tick + (h % cfg.election_tick)


def entry_chk_py(idx: int, data: int) -> int:
    """Python mirror of kernel._entry_chk."""
    return hash32_py(((idx * 0x01000193) & M32) ^ (data & M32))


def _data_u32(e: Entry) -> int:
    return int.from_bytes(e.data, "big") if e.data else 0


class SyncRaft(core.Raft):
    """core.Raft with the kernel's send discipline (divergences D1/D3):
    windowed side-effect-free appends, and a suppress flag that swallows
    sends triggered while responses are being stepped."""

    def __init__(self, cfg: core.Config, window: int, voters=None):
        super().__init__(cfg, voters=voters)
        self.window = window
        self.suppress = False
        self.hold_commit = False
        self.cluster = None   # backref set by OracleCluster (ring clamp)

    def _maybe_commit(self) -> bool:
        """Commit advancement is HELD during the propose phases: the kernel
        evaluates commit once per tick in Phase D (after sends), so a
        propose-time advance — possible when a quorum-lowering removal
        applied last tick left commit lagging — would leak a newer commit
        index into this tick's appends.  Held advances land in _phase_def
        (same decision, kernel timing)."""
        if self.hold_commit:
            return False
        return super()._maybe_commit()

    def _ring_limit(self, to: int, prev: int) -> int:
        """Receiver ring headroom (kernel's snap_idx + L - prev clamp):
        a window past it would wrap the fixed-width device ring over
        unapplied entries."""
        if self.cluster is None:
            return self.window
        rcv = self.cluster.nodes[to - 1]
        cap = rcv.log.offset + self.cluster.cfg.log_len - prev
        return max(0, min(self.window, cap))

    def _send_append(self, to: int) -> None:
        if self.suppress:
            return
        pr = self.prs[to]
        prev = pr.next - 1
        try:
            prev_term = self.log.term(prev)
            ents = self.log.slice(pr.next, self.log.last_index() + 1,
                                  self._ring_limit(to, prev))
        except (CompactedError, UnavailableError):
            meta = SnapshotMeta(index=self.log.offset,
                                term=self.log.offset_term,
                                voters=self.voter_ids())
            self._send(Message(type=MsgType.SNAP, to=to,
                               snapshot=Snapshot(meta=meta)))
            return
        self._send(Message(type=MsgType.APP, to=to, index=prev,
                           log_term=prev_term, entries=tuple(ents),
                           commit=self.log.committed))

    def _bcast_append(self) -> None:
        if self.suppress:
            return
        super()._bcast_append()

    def take_msgs(self) -> list[Message]:
        out, self.msgs = self.msgs, []
        return out


ROLE_INT = {core.FOLLOWER: 0, core.CANDIDATE: 1, core.PRE_CANDIDATE: 1,
            core.LEADER: 2}


@dataclass
class OracleView:
    """Per-tick comparable state, kernel conventions (0-based ids, -1=none)."""

    term: np.ndarray
    vote: np.ndarray
    role: np.ndarray
    lead: np.ndarray
    last: np.ndarray
    commit: np.ndarray
    applied: np.ndarray
    apply_chk: np.ndarray
    member: np.ndarray   # [N, N] per-node applied-config views

    FIELDS = ("term", "vote", "role", "lead", "last", "commit", "applied",
              "apply_chk", "member")


class OracleCluster:
    """N core.Raft nodes stepped with the kernel's phase schedule."""

    def __init__(self, cfg: SimConfig, voters=None):
        self.cfg = cfg
        n = cfg.n
        peers = tuple(range(1, n + 1))  # core ids are 1-based (NONE=0)
        # bootstrap configuration (kernel init_state(voters=...)): every
        # node knows the same initial member set; non-members stay passive
        boot = peers if voters is None else tuple(
            v + 1 for v in sorted(voters))
        self.nodes = [
            SyncRaft(core.Config(id=i + 1, peers=peers,
                                 election_tick=cfg.election_tick,
                                 heartbeat_tick=cfg.heartbeat_tick,
                                 max_size_per_msg=cfg.window,
                                 max_inflight_msgs=1 << 30,
                                 check_quorum=False,
                                 pre_vote=cfg.pre_vote,
                                 seed=cfg.seed),
                     window=cfg.window, voters=boot)
            for i in range(n)
        ]
        for nd in self.nodes:
            nd.cluster = self
        self.elapsed = [0] * n
        # lease clock: ticks since last current-term leader contact (the
        # kernel's `contact`; see core.contact_elapsed for the rationale)
        self.contact = [0] * n
        self.hb_elapsed = [0] * n
        self.timeout = [rand_timeout_py(cfg, i, 0) for i in range(n)]
        self.applied = [0] * n
        self.apply_chk = [0] * n
        # CheckQuorum bookkeeping (mirrors kernel recent_active [N, N])
        self.recent_active: list[set[int]] = [set() for _ in range(n)]
        # Canonical applied-log content (safety cross-check): idx ->
        # (term, data); chk_at[idx] = cumulative checksum through idx.
        self.canon: dict[int, tuple[int, int]] = {}
        self.chk_at: dict[int, int] = {0: 0}
        # Mailbox wire replay (kernel [N, N] in-flight slots; see
        # kernel.py "Device-mailbox wire").  Keyed (sender, receiver) for
        # request classes and (leader, responder) for response classes;
        # values carry (deliver_tick, captured header...).
        self.now = 0
        # leader transfer mirrors (kernel transferee/tx_cand/tn_* wires)
        self.tx_term: dict[int, int] = {}   # i -> term of tx-born candidacy
        self.tnq: dict[int, tuple[int, int, int]] = {}  # tgt -> (at, tm, frm)
        # vreq: (deliver_at, sender_term, is_pre) per edge
        self.vreq: dict[tuple[int, int], tuple[int, int, bool]] = {}
        # (deliver_at, candidacy_term, grant, is_pre)
        self.vresp: dict[tuple[int, int], tuple[int, int, bool, bool]] = {}
        # appq: per-edge pipelined list of (deliver_at, prev, term)
        self.appq: dict[tuple[int, int], list[tuple[int, int, int]]] = {}
        self.snpq: dict[tuple[int, int], tuple[int, int]] = {}
        # arespq: per-edge list of (deliver_at, term, resp) — capacity is
        # unbounded here; the kernel's ack_depth guarantees the same set
        self.arespq: dict[tuple[int, int], list[tuple[int, int, Message]]] = {}
        # heartbeat wire (kernel hb_*/hbr_* boxes): (deliver_at, term,
        # captured commit) per i->j edge; responses (deliver_at, term)
        # keyed [leader, responder]
        self.hbq: dict[tuple[int, int], list[tuple[int, int, int]]] = {}
        self.hbrq: dict[tuple[int, int], list[tuple[int, int]]] = {}

    def _lat(self, i: int, j: int, tick: int) -> int:
        """Python mirror of state.latency_matrix for one edge."""
        cfg = self.cfg
        if cfg.latency_jitter == 0:
            return cfg.latency
        h = hash32_py(((i * 0x9E3779B1) & M32)
                      ^ ((j * 0x01000193) & M32)
                      ^ ((tick * 0xC2B2AE35) & M32)
                      ^ ((cfg.seed ^ 0x7A77) & M32))
        return cfg.latency + (h % (cfg.latency_jitter + 1))

    # -- canonical applied-log bookkeeping --------------------------------
    def _canon_note(self, idx: int, term: int, data: int) -> None:
        prev = self.canon.get(idx)
        if prev is not None and prev != (term, data):
            raise AssertionError(
                f"state-machine divergence at index {idx}: "
                f"{prev} vs {(term, data)}")
        self.canon[idx] = (term, data)
        if idx - 1 in self.chk_at and idx not in self.chk_at:
            self.chk_at[idx] = (self.chk_at[idx - 1]
                                + entry_chk_py(idx, data)) & M32

    # -- shared phases -----------------------------------------------------
    def _phase_propose(self, up, payloads, prop_count: int) -> None:
        """Phase 0: propose (run_ticks calls propose() before step()).
        Clients cannot reach a crashed claimant, so `up` masks leaders the
        same way kernel propose(alive=...) does."""
        cfg = self.cfg
        if not prop_count:
            return
        ents = tuple(
            Entry(type=EntryType.NORMAL,
                  # bit 31 is reserved for conf entries; kernel propose()
                  # masks it off, so the oracle must store the same value
                  data=(int(payloads[k]) & 0x7FFFFFFF).to_bytes(4, "big"))
            for k in range(prop_count))
        for i, nd in enumerate(self.nodes):
            if not up[i] or nd.state != core.LEADER:
                continue
            room = (nd.log.last_index() + cfg.max_props
                    - nd.log.offset) <= cfg.log_len
            if not room:
                continue
            nd.suppress = nd.hold_commit = True
            try:
                nd.step(Message(type=MsgType.PROP, frm=nd.id, entries=ents))
            except core.ProposalDropped:
                pass
            nd.suppress = nd.hold_commit = False
            nd.take_msgs()

    def _phase_propose_conf(self, up, conf) -> None:
        """Phase 0b: one membership-change proposal (kernel propose_conf).
        conf = (target_row, remove).  Core's stepLeader degrades the entry
        to an empty normal one while an earlier conf change is pending —
        the one-in-flight rule."""
        if conf is None:
            return
        cfg = self.cfg
        tgt, rm = conf
        if not (0 <= int(tgt) < cfg.n):
            # kernel propose_conf degrades an out-of-range target to an
            # empty normal entry (same as the pending-conf case)
            ent = Entry(type=EntryType.NORMAL, data=b"")
        else:
            ent = Entry(type=EntryType.CONF_CHANGE,
                        data=conf_payload(int(tgt),
                                          bool(rm)).to_bytes(4, "big"))
        for i, nd in enumerate(self.nodes):
            if not up[i] or nd.state != core.LEADER:
                continue
            room = (nd.log.last_index() + cfg.max_props
                    - nd.log.offset) <= cfg.log_len
            if not room:
                continue
            nd.suppress = nd.hold_commit = True
            try:
                nd.step(Message(type=MsgType.PROP, frm=nd.id,
                                entries=(ent,)))
            except core.ProposalDropped:
                pass
            nd.suppress = nd.hold_commit = False
            nd.take_msgs()

    def _phase_a(self, up) -> None:
        """Phase A: timers + CheckQuorum + campaign."""
        cfg, n, nodes = self.cfg, self.cfg.n, self.nodes
        for i in range(n):
            if up[i]:
                self.elapsed[i] += 1
                self.contact[i] += 1
                if nodes[i].state == core.LEADER:
                    self.hb_elapsed[i] += 1
        for i, nd in enumerate(nodes):
            # CheckQuorum: every election_tick ticks a standing leader must
            # have heard from a quorum since its last round (kernel Phase A)
            if up[i] and nd.state == core.LEADER \
                    and self.elapsed[i] >= cfg.election_tick:
                if cfg.check_quorum:
                    members = {p - 1 for p in nd.prs}
                    heard = (self.recent_active[i] | {i}) & members
                    if len(heard) < nd.quorum():
                        nd.become_follower(nd.term, core.NONE)
                    else:
                        # transfer not completed within an election
                        # timeout: abort (kernel Phase A; vendor
                        # tickHeartbeat); a quorum-confirmed leader
                        # re-arms its own lease
                        nd._abort_leader_transfer()
                        self.contact[i] = 0
                    self.recent_active[i] = set()
                else:
                    # defense off (kernel gates only the step-down and
                    # lease re-arm; the periodic transfer abort and the
                    # timer reset run either way)
                    nd._abort_leader_transfer()
                self.elapsed[i] = 0
        # TIMEOUT_NOW deliveries land between CheckQuorum and the timeout
        # campaigns (kernel Phase A order)
        self._transfer_deliver(up)
        for i, nd in enumerate(nodes):
            if not up[i]:
                continue
            # tickElection: only promotable nodes fire (the timer resets
            # either way once it expires); core's HUP step then refuses to
            # campaign over a committed-but-unapplied conf entry
            if nd.state != core.LEADER and nd.promotable() \
                    and self.elapsed[i] >= self.timeout[i]:
                self.elapsed[i] = 0
                nd.step(Message(type=MsgType.HUP, frm=nd.id))
                nd.take_msgs()  # Phase B re-emits vote requests uniformly
                if nd.state != core.PRE_CANDIDATE:
                    # vendor becomePreCandidate never re-randomizes the
                    # timeout (only a REAL campaign's reset does); the
                    # kernel matches, so the oracle must too or the two
                    # sides fire later campaigns on different ticks
                    self.timeout[i] = rand_timeout_py(cfg, i, nd.term)

    def _phase_def(self, up) -> None:
        """Phases D (leader commit), E (apply + checksums), F (compaction)."""
        cfg, nodes = self.cfg, self.nodes
        for i, nd in enumerate(nodes):
            if up[i] and nd.state == core.LEADER:
                nd.suppress = True
                nd._maybe_commit()
                nd.suppress = False
                nd.take_msgs()
        for i, nd in enumerate(nodes):
            if not up[i]:
                continue   # crashed rows freeze (apply AND compaction)
            if nd.log.applied > self.applied[i]:  # snapshot restore jumped
                self.applied[i] = nd.log.applied
                base = self.chk_at.get(self.applied[i])
                if base is None:
                    raise AssertionError(
                        f"restore to unapplied index {self.applied[i]}")
                self.apply_chk[i] = base
            new_applied = min(nd.log.committed,
                              self.applied[i] + cfg.apply_batch)
            # at most ONE membership flip lands per node per tick: the
            # batch clamps at the first conf entry (kernel Phase E clamp)
            for idx in range(self.applied[i] + 1, new_applied + 1):
                e = nd.log.entries[idx - nd.log.offset - 1]
                if e.type == EntryType.CONF_CHANGE:
                    new_applied = idx
                    break
            for idx in range(self.applied[i] + 1, new_applied + 1):
                e = nd.log.entries[idx - nd.log.offset - 1]
                d = _data_u32(e)
                self._canon_note(idx, e.term, d)
                self.apply_chk[i] = (self.apply_chk[i]
                                     + entry_chk_py(idx, d)) & M32
                if e.type == EntryType.CONF_CHANGE:
                    # kernel Phase E clips the decoded target into range
                    tgt = min(d & CONF_TARGET_MASK, self.cfg.n - 1) + 1
                    if d & CONF_REMOVE:
                        # quorum-lowering commit re-check waits for the
                        # next Phase D (the oracle evaluates commit once
                        # per tick, same decision one tick later)
                        nd.remove_node(tgt, recheck=False)
                    else:
                        newly = tgt not in nd.prs
                        nd.add_node(tgt)
                        if newly:
                            # kernel: a fresh joiner starts recent_active
                            # (core add_node pr.recent_active analog)
                            self.recent_active[i].add(tgt - 1)
            self.applied[i] = new_applied
            nd.log.applied_to(new_applied)
        for i, nd in enumerate(nodes):
            if not up[i]:
                continue
            last, off = nd.log.last_index(), nd.log.offset
            pressure = (last - off) > (cfg.log_len - 2 * cfg.max_props - 1)
            new_snap = max(off, self.applied[i] - cfg.keep)
            if pressure and new_snap > off:
                nd.log.compact(new_snap)

    def transfer(self, leader: int, target: int) -> None:
        """Mirror of kernel.transfer_leadership: record the target on the
        leader's core node and reset its election timer (vendor stepLeader
        MsgTransferLeader; a repeat for the same in-flight target is a
        no-op)."""
        nd = self.nodes[leader]
        if nd.state != core.LEADER or target == leader:
            return
        if (target + 1) not in nd.prs:
            return   # kernel gate: member[leader, target] (core would
            # reject inside stepLeader, but AFTER the timer reset)
        if nd.lead_transferee == target + 1:
            return
        self.elapsed[leader] = 0
        nd.step(Message(type=MsgType.TRANSFER_LEADER, frm=target + 1,
                        to=nd.id))
        nd.take_msgs()   # TIMEOUT_NOW/append bursts ride the kernel's wire

    def _is_tx(self, i: int) -> bool:
        nd = self.nodes[i]
        return (nd.state == core.CANDIDATE
                and self.tx_term.get(i) == nd.term)

    def _transfer_fire(self, up, drop) -> None:
        """Kernel's per-tick TIMEOUT_NOW send rule: a transferring leader
        whose target is fully caught up fires once into the target's
        single wire slot (vendor stepLeader MsgAppResp transferee
        branch)."""
        cfg, n, nodes = self.cfg, self.cfg.n, self.nodes
        now = self.now
        for i in range(n):   # lowest leader index wins a contested slot
            nd = nodes[i]
            if not up[i] or nd.state != core.LEADER \
                    or nd.lead_transferee == core.NONE:
                continue
            t = nd.lead_transferee - 1
            if t == i or not (0 <= t < n) or t in self.tnq \
                    or nd.lead_transferee not in nd.prs:
                continue
            if nd.prs[nd.lead_transferee].match != nd.log.last_index():
                continue
            if drop[i][t]:
                continue
            lat = self._lat(i, t, now) if cfg.mailboxes else 0
            self.tnq[t] = (now + 1 + lat, nd.term, i)

    def _transfer_deliver(self, up) -> None:
        """TIMEOUT_NOW deliveries (kernel Phase A): the target runs a
        forced REAL campaign whose requests bypass the leader lease."""
        cfg, nodes = self.cfg, self.nodes
        now = self.now
        for t in sorted(k for k, v in self.tnq.items() if v[0] <= now + 1):
            _, tm, frm = self.tnq.pop(t)
            nd = nodes[t]
            if not up[t] or nd.state == core.LEADER or tm < nd.term:
                continue
            if tm == nd.term and nd.state != core.FOLLOWER:
                continue  # candidates ignore equal-term TIMEOUT_NOW
            nd.step(Message(type=MsgType.TIMEOUT_NOW, frm=frm + 1,
                            to=nd.id, term=tm))
            nd.take_msgs()
            if nd.state == core.CANDIDATE:
                self.elapsed[t] = 0
                self.timeout[t] = rand_timeout_py(cfg, t, nd.term)
                self.tx_term[t] = nd.term
            elif nd.state == core.LEADER:   # quorum-of-1 forced cascade
                self.elapsed[t] = 0
                self.contact[t] = 0
                self.hb_elapsed[t] = 0
                self.timeout[t] = rand_timeout_py(cfg, t, nd.term)
                self.recent_active[t] = set()

    def _prevote_exchange_sync(self, up, drop, leased) -> None:
        """PreVote round on the synchronous wire, processed BEFORE real
        votes (the kernel's defined delivery order).  Grants mutate no
        receiver state; rejections count only when stamped with the
        candidacy's own term (kernel D2' drop rule for higher-term
        rejects); pre-quorum transitions to a real candidacy with the
        kernel's elapsed/timeout resets."""
        cfg, n, nodes = self.cfg, self.cfg.n, self.nodes
        if not cfg.pre_vote:
            return
        pv_requests: list[tuple[int, int, Message]] = []
        for i in range(n):
            nd = nodes[i]
            if not up[i] or nd.state != core.PRE_CANDIDATE:
                continue
            for j in range(n):
                if j == i or not up[j] or drop[i][j] or leased[j] \
                        or (j + 1) not in nd.prs:
                    continue
                pv_requests.append((i, j, Message(
                    type=MsgType.PRE_VOTE, to=j + 1, frm=nd.id,
                    term=nd.term + 1, index=nd.log.last_index(),
                    log_term=nd.log.last_term())))
        pv_requests.sort(key=lambda r: (-r[2].term, r[0]))
        pv_grants: list[tuple[int, int, Message]] = []
        pv_rejects: list[tuple[int, int, Message]] = []
        for i, j, msg in pv_requests:
            nodes[j].step(msg)
            for resp in nodes[j].take_msgs():
                if resp.type != MsgType.PRE_VOTE_RESP:
                    continue
                if not resp.reject:
                    pv_grants.append((j, i, resp))
                elif resp.term == msg.term - 1:
                    pv_rejects.append((j, i, resp))
        for j, i, resp in pv_grants:
            if drop[j][i]:
                continue
            nd = nodes[i]
            if nd.state != core.PRE_CANDIDATE:
                continue
            nd.step(resp)
            nd.take_msgs()   # real-campaign bursts go via normal sends
            if nd.state in (core.CANDIDATE, core.LEADER):
                # pre-win: kernel bumps term, resets elapsed and
                # re-randomizes the timeout at the new term
                self.elapsed[i] = 0
                self.timeout[i] = rand_timeout_py(cfg, i, nd.term)
                if nd.state == core.LEADER:  # quorum-of-1 cascade
                    self.recent_active[i] = set()
        for j, i, resp in pv_rejects:
            if drop[j][i] or nodes[i].state != core.PRE_CANDIDATE:
                continue
            nodes[i].step(resp)
            nodes[i].take_msgs()
            if nodes[i].state == core.FOLLOWER:   # rejection-quorum lose
                self.elapsed[i] = 0

    # -- one kernel-schedule tick -----------------------------------------
    def tick(self, alive, drop, payloads=(), prop_count: int = 0,
             conf=None) -> None:
        if self.cfg.mailboxes:
            self._tick_mailbox(alive, drop, payloads, prop_count, conf)
        else:
            self._tick_sync(alive, drop, payloads, prop_count, conf)

    def _tick_sync(self, alive, drop, payloads=(), prop_count: int = 0,
                   conf=None) -> None:
        cfg, n = self.cfg, self.cfg.n
        nodes = self.nodes
        up = [bool(alive[i]) for i in range(n)]

        self._phase_propose(up, payloads, prop_count)
        self._phase_propose_conf(up, conf)
        self._phase_a(up)

        # Phase B: vote exchange. Candidates re-request every tick (the
        # kernel's req matrix); delivery order (term desc, candidate asc)
        # reproduces the kernel's max-term catch-up + lowest-index grant.
        # Lease flags snapshot BEFORE any vote is delivered (kernel computes
        # `leased` once from post-Phase-A state).
        leased = [cfg.check_quorum and nodes[j].lead != core.NONE
                  and self.contact[j] < cfg.election_tick
                  for j in range(n)]
        # capture candidacies BEFORE any exchange (kernel send sets are
        # fixed from post-Phase-A state: a pre-winner sends real requests
        # only from the NEXT tick)
        real_cands = [i for i in range(n)
                      if up[i] and nodes[i].state == core.CANDIDATE]
        self._prevote_exchange_sync(up, drop, leased)
        requests: list[tuple[int, int, Message]] = []  # (cand, to, msg)
        for i in real_cands:
            nd = nodes[i]
            if nd.state != core.CANDIDATE:
                continue
            for j in range(n):
                if j == i or not up[j] or drop[i][j] \
                        or (j + 1) not in nd.prs \
                        or (leased[j] and not self._is_tx(i)):
                    continue
                requests.append((i, j, Message(
                    type=MsgType.VOTE, to=j + 1, frm=nd.id, term=nd.term,
                    index=nd.log.last_index(),
                    log_term=nd.log.last_term())))
        requests.sort(key=lambda r: (-r[2].term, r[0]))
        grants: list[tuple[int, int, Message]] = []  # (voter, cand, resp)
        rejects: list[tuple[int, int, Message]] = []
        for i, j, msg in requests:
            if msg.term > nodes[j].term:   # become_follower _reset (D4')
                self.elapsed[j] = 0
                self.timeout[j] = rand_timeout_py(self.cfg, j, msg.term)
            nodes[j].step(msg)
            for resp in nodes[j].take_msgs():
                if resp.type == MsgType.VOTE_RESP and not resp.reject:
                    self.elapsed[j] = 0
                    grants.append((j, i, resp))
                elif resp.type == MsgType.VOTE_RESP and resp.reject \
                        and resp.term == msg.term:
                    # processed at the candidate's term: a real rejection
                    # (kernel counts only current-term refusals)
                    rejects.append((j, i, resp))
        new_leader_msgs: list[Message] = []
        for j, i, resp in grants:
            if drop[j][i]:
                continue
            was_leader = nodes[i].state == core.LEADER
            nodes[i].step(resp)
            msgs = nodes[i].take_msgs()
            if not was_leader and nodes[i].state == core.LEADER:
                self.elapsed[i] = 0
                self.contact[i] = 0
                self.hb_elapsed[i] = 0
                self.recent_active[i] = set()
                new_leader_msgs.extend(msgs)  # win-cascade appends (Phase C)
        # rejections step in AFTER all grants (kernel: win evaluated before
        # the rejection quorum); only still-candidates care
        for j, i, resp in rejects:
            if drop[j][i] or nodes[i].state != core.CANDIDATE:
                continue
            nodes[i].step(resp)
            nodes[i].take_msgs()
            if nodes[i].state == core.FOLLOWER:   # rejection-quorum lose
                self.elapsed[i] = 0

        # Phase C: append/snapshot fan-out from every standing leader.
        out: list[Message] = list(new_leader_msgs)
        already_sent = {m.frm for m in new_leader_msgs}
        for i, nd in enumerate(nodes):
            if up[i] and nd.state == core.LEADER and nd.id not in already_sent:
                nd._bcast_append()
                out.extend(nd.take_msgs())
        by_rcpt: dict[int, list[Message]] = {}
        for m in out:
            if m.type not in (MsgType.APP, MsgType.SNAP):
                continue
            i, j = m.frm - 1, m.to - 1
            if not up[j] or drop[i][j]:
                continue
            by_rcpt.setdefault(j, []).append(m)
        responses: list[tuple[int, int, Message]] = []
        for j, msgs in by_rcpt.items():
            msgs.sort(key=lambda m: (-m.term, m.frm))
            for m in msgs:
                if m.term > nodes[j].term:   # become_follower _reset (D4')
                    self.elapsed[j] = 0
                    self.timeout[j] = rand_timeout_py(self.cfg, j, m.term)
                nodes[j].step(m)
                for resp in nodes[j].take_msgs():
                    if resp.type == MsgType.APP_RESP:
                        responses.append((j, m.frm - 1, resp))
                if m.term == nodes[j].term:
                    self.elapsed[j] = 0
                    self.contact[j] = 0
        for j, i, resp in responses:
            if drop[j][i] or not up[i]:
                continue
            if nodes[i].state == core.LEADER:
                self.recent_active[i].add(j)  # kernel: any resp arrival
            nodes[i].suppress = True
            nodes[i].step(resp)
            nodes[i].suppress = False
            nodes[i].take_msgs()

        # Phases D/E/F (commit, apply, compaction) — shared with the
        # mailbox tick.
        self._transfer_fire(up, drop)
        self._phase_def(up)
        self.now += 1

    def _tick_mailbox(self, alive, drop, payloads=(), prop_count: int = 0,
                      conf=None) -> None:
        """Replay of the kernel's mailbox wire (kernel.py Phase B/C under
        cfg.mailboxes): sends fill empty per-edge slots capturing (term,
        prev); deliveries at deliver-tick construct messages from the
        sender's CURRENT core state, dropped when the sender's term/role
        changed since send; responses ride the reverse edge with the same
        latency schedule."""
        cfg, n = self.cfg, self.cfg.n
        nodes = self.nodes
        up = [bool(alive[i]) for i in range(n)]
        now = self.now

        self._phase_propose(up, payloads, prop_count)
        self._phase_propose_conf(up, conf)
        self._phase_a(up)

        # ---- Phase B: vote wire ----
        # sends: any candidacy (pre or real) refills edges carrying no
        # message from the SAME candidacy (term, pre)
        for i, nd in enumerate(nodes):
            if not up[i] or nd.state not in (core.CANDIDATE,
                                             core.PRE_CANDIDATE):
                continue
            is_pre = nd.state == core.PRE_CANDIDATE
            for j in range(n):
                if j == i or drop[i][j] or (j + 1) not in nd.prs:
                    continue
                slot = self.vreq.get((i, j))
                if slot is None or slot[1] != nd.term or slot[2] != is_pre:
                    self.vreq[(i, j)] = (now + self._lat(i, j, now),
                                         nd.term, is_pre)
        # request deliveries (lease snapshot BEFORE any vote is stepped);
        # prevote requests process before real ones (kernel phase order)
        leased = [cfg.check_quorum and nodes[j].lead != core.NONE
                  and self.contact[j] < cfg.election_tick
                  for j in range(n)]
        due = sorted(k for k, v in self.vreq.items() if v[0] <= now)
        pv_requests: list[tuple[int, int, Message]] = []
        requests: list[tuple[int, int, Message]] = []
        for (i, j) in due:
            _, tm, is_pre = self.vreq.pop((i, j))
            nd = nodes[i]
            # stale guard: sender crashed state is frozen, so an in-flight
            # request from a crashed candidate still delivers (kernel: the
            # validity mask reads the frozen role/term/pre row)
            want = core.PRE_CANDIDATE if is_pre else core.CANDIDATE
            if nd.state != want or nd.term != tm:
                continue
            if not up[j] or (leased[j] and not self._is_tx(i)):
                continue
            if is_pre:
                pv_requests.append((i, j, Message(
                    type=MsgType.PRE_VOTE, to=j + 1, frm=nd.id,
                    term=nd.term + 1, index=nd.log.last_index(),
                    log_term=nd.log.last_term())))
            else:
                requests.append((i, j, Message(
                    type=MsgType.VOTE, to=j + 1, frm=nd.id, term=nd.term,
                    index=nd.log.last_index(),
                    log_term=nd.log.last_term())))
        # prevote exchange: requests, then due prevote responses, then the
        # pre-win transition — all BEFORE any real vote is stepped
        pv_requests.sort(key=lambda r: (-r[2].term, r[0]))
        for i, j, msg in pv_requests:
            nodes[j].step(msg)
            for resp in nodes[j].take_msgs():
                if resp.type != MsgType.PRE_VOTE_RESP or drop[j][i]:
                    continue
                if not resp.reject:
                    self.vresp[(i, j)] = (now + self._lat(j, i, now),
                                          msg.term - 1, True, True)
                elif resp.term == msg.term - 1:
                    # countable only at the candidacy's own term (kernel
                    # D2' higher-term reject drop rule)
                    self.vresp[(i, j)] = (now + self._lat(j, i, now),
                                          msg.term - 1, False, True)
        pv_due = sorted(k for k, v in self.vresp.items()
                        if v[0] <= now and v[3])
        pv_arrivals = [(i, j, *self.vresp.pop((i, j))[1:])
                       for (i, j) in pv_due]
        for want_grant in (True, False):
            for (i, j, tm, grant, _pre) in pv_arrivals:
                if grant is not want_grant:
                    continue
                nd = nodes[i]
                if not up[i] or nd.state != core.PRE_CANDIDATE \
                        or nd.term != tm:
                    continue
                nd.step(Message(
                    type=MsgType.PRE_VOTE_RESP, to=nd.id, frm=j + 1,
                    term=tm + 1 if grant else tm, reject=not grant))
                nd.take_msgs()
                if nd.state in (core.CANDIDATE, core.LEADER):
                    self.elapsed[i] = 0
                    self.timeout[i] = rand_timeout_py(cfg, i, nd.term)
                    if nd.state == core.LEADER:  # quorum-of-1 cascade
                        self.contact[i] = 0
                        self.hb_elapsed[i] = 0
                        self.recent_active[i] = set()
                elif nd.state == core.FOLLOWER:  # rejection-quorum lose
                    self.elapsed[i] = 0
        # real vote exchange
        requests.sort(key=lambda r: (-r[2].term, r[0]))
        for i, j, msg in requests:
            if msg.term > nodes[j].term:   # become_follower _reset (D4')
                self.elapsed[j] = 0
                self.timeout[j] = rand_timeout_py(self.cfg, j, msg.term)
            nodes[j].step(msg)
            for resp in nodes[j].take_msgs():
                if resp.type != MsgType.VOTE_RESP:
                    continue
                if not resp.reject:
                    self.elapsed[j] = 0
                    if not drop[j][i]:
                        self.vresp[(i, j)] = (
                            now + self._lat(j, i, now), msg.term, True,
                            False)
                elif resp.term == msg.term:
                    # processed at the candidate's term: a real rejection
                    if not drop[j][i]:
                        self.vresp[(i, j)] = (
                            now + self._lat(j, i, now), msg.term, False,
                            False)
        # response deliveries: all due grants integrate before rejections
        # (kernel evaluates win before the rejection quorum)
        vdue = sorted(k for k, v in self.vresp.items()
                      if v[0] <= now and not v[3])
        arrivals = [(i, j, *self.vresp.pop((i, j))[1:]) for (i, j) in vdue]
        for want_grant in (True, False):
            for (i, j, tm, grant, _pre) in arrivals:
                if grant is not want_grant:
                    continue
                nd = nodes[i]
                if not up[i] or nd.state != core.CANDIDATE or nd.term != tm:
                    continue
                nd.step(Message(type=MsgType.VOTE_RESP, to=nd.id, frm=j + 1,
                                term=tm, reject=not grant))
                nd.take_msgs()  # win-cascade appends go via the mailbox wire
                if nd.state == core.LEADER:  # the guard above filtered
                    self.elapsed[i] = 0      # out already-leaders
                    self.contact[i] = 0
                    self.hb_elapsed[i] = 0
                    self.recent_active[i] = set()
                elif nd.state == core.FOLLOWER:  # rejection-quorum lose
                    self.elapsed[i] = 0

        # ---- Phase C: append/snapshot wire ----
        # sends: up to cfg.inflight appends pipeline per edge, one NEW one
        # per tick, with pr.next advanced optimistically by the entries
        # known at send (kernel n_send; etcd Replicate-state pipelining).
        # Entries from a stale candidacy never deliver on either side, so
        # they are pruned eagerly here.
        K = cfg.inflight
        for i, nd in enumerate(nodes):
            if not up[i] or nd.state != core.LEADER:
                continue
            for j in range(n):
                if j == i or drop[i][j] or (j + 1) not in nd.prs:
                    continue
                q = [e for e in self.appq.get((i, j), [])
                     if e[2] == nd.term]
                self.appq[(i, j)] = q
                s = self.snpq.get((i, j))
                if s is not None and s[1] == nd.term:
                    continue   # snapshot in flight blocks the edge
                pr = nd.prs[j + 1]
                prev = pr.next - 1
                last = nd.log.last_index()
                has_new = pr.next <= last
                probing = pr.state == core.PROBE
                if prev >= nd.log.offset:
                    # StateProbe: one append at a time, no optimism;
                    # StateReplicate: pipeline while a slot is free
                    if probing:
                        if q:
                            continue
                    elif len(q) >= K or not has_new:
                        continue
                    q.append((now + self._lat(i, j, now), prev, nd.term))
                    if has_new and not probing:  # optimisticUpdate
                        pr.next = prev + min(cfg.window, last - prev) + 1
                else:
                    self.snpq[(i, j)] = (now + self._lat(i, j, now), nd.term)
        # -- heartbeat sends (kernel hb wire; etcd bcastHeartbeat): commit
        # captured at send as min(pr.match, committed)
        for i, nd in enumerate(nodes):
            if not up[i] or nd.state != core.LEADER \
                    or self.hb_elapsed[i] < cfg.heartbeat_tick:
                continue
            self.hb_elapsed[i] = 0
            for j in range(n):
                if j == i or drop[i][j] or (j + 1) not in nd.prs:
                    continue
                self.hbq.setdefault((i, j), []).append(
                    (now + self._lat(i, j, now), nd.term,
                     min(nd.prs[j + 1].match, nd.log.committed)))
        # -- heartbeat deliveries: BEFORE append deliveries (the kernel
        # computes append validity after heartbeat effects), all due per
        # tick, stale (sender left the captured term/role) dropped,
        # stepped per receiver in term-desc order like appends
        hb_out: list[tuple[int, int, int, int]] = []
        for (i, j) in sorted(self.hbq):
            q = self.hbq[(i, j)]
            due = [e for e in q if e[0] <= now]
            if not due:
                continue
            self.hbq[(i, j)] = [e for e in q if e[0] > now]
            nd = nodes[i]
            for (_, tm, cm) in due:
                if nd.state != core.LEADER or nd.term != tm or not up[j]:
                    continue
                hb_out.append((i, j, tm, cm))
        by_hb: dict[int, list[tuple[int, int, int]]] = {}
        for i, j, tm, cm in hb_out:
            by_hb.setdefault(j, []).append((i, tm, cm))
        for j, msgs in sorted(by_hb.items()):
            msgs.sort(key=lambda x: (-x[1], x[0]))
            responded: set[int] = set()
            for i, tm, cm in msgs:
                m = Message(type=MsgType.HEARTBEAT, to=j + 1, frm=i + 1,
                            term=tm, commit=cm)
                if m.term > nodes[j].term:   # become_follower _reset (D4')
                    self.elapsed[j] = 0
                    self.timeout[j] = rand_timeout_py(self.cfg, j, m.term)
                nodes[j].step(m)
                for resp in nodes[j].take_msgs():
                    if resp.type == MsgType.HEARTBEAT_RESP \
                            and not drop[j][i] and i not in responded:
                        # one response per edge per tick (liveness only)
                        responded.add(i)
                        self.hbrq.setdefault((i, j), []).append(
                            (now + self._lat(j, i, now), nodes[j].term))
                if m.term == nodes[j].term:
                    self.elapsed[j] = 0
                    self.contact[j] = 0
        # deliveries: the wire drains AT MOST ONE append per edge per tick
        # — the smallest-prev deliverable one; construct messages from the
        # sender's CURRENT state
        out: list[tuple[int, int, Message]] = []
        for (i, j) in sorted(self.appq):
            q = self.appq[(i, j)]
            nd = nodes[i]
            due = [e for e in q if e[0] <= now]
            if not due:
                continue
            # stale/undeliverable due entries clear without delivering
            deliverable = []
            for e in due:
                if nd.state != core.LEADER or nd.term != e[2] \
                        or not up[j] or e[1] < nd.log.offset:
                    continue   # cleared
                deliverable.append(e)
            if deliverable:
                sel = min(deliverable, key=lambda e: e[1])
                deliverable.remove(sel)
                _, prev, tm = sel
                prev_term = nd.log.term(prev)
                ents = nd.log.slice(prev + 1, nd.log.last_index() + 1,
                                    nd._ring_limit(j + 1, prev))
                out.append((i, j, Message(
                    type=MsgType.APP, to=j + 1, frm=nd.id, term=nd.term,
                    index=prev, log_term=prev_term, entries=tuple(ents),
                    commit=nd.log.committed)))
            self.appq[(i, j)] = [e for e in q if e[0] > now] + deliverable
        for (i, j) in sorted(k for k, v in self.snpq.items() if v[0] <= now):
            _, tm = self.snpq.pop((i, j))
            nd = nodes[i]
            if nd.state != core.LEADER or nd.term != tm or not up[j]:
                continue
            meta = SnapshotMeta(index=nd.log.offset, term=nd.log.offset_term,
                                voters=nd.voter_ids())
            out.append((i, j, Message(
                type=MsgType.SNAP, to=j + 1, frm=nd.id, term=nd.term,
                snapshot=Snapshot(meta=meta))))
        by_rcpt: dict[int, list[tuple[int, Message]]] = {}
        for i, j, m in out:
            by_rcpt.setdefault(j, []).append((i, m))
        for j, msgs in sorted(by_rcpt.items()):
            msgs.sort(key=lambda im: (-im[1].term, im[1].frm))
            for i, m in msgs:
                if m.term > nodes[j].term:   # become_follower _reset (D4')
                    self.elapsed[j] = 0
                    self.timeout[j] = rand_timeout_py(self.cfg, j, m.term)
                nodes[j].step(m)
                for resp in nodes[j].take_msgs():
                    if resp.type == MsgType.APP_RESP and not drop[j][i]:
                        rq = self.arespq.setdefault((i, j), [])
                        rq.append((now + self._lat(j, i, now), m.term, resp))
                if m.term == nodes[j].term:
                    self.elapsed[j] = 0
                    self.contact[j] = 0
        # response deliveries: ALL due acks integrate, oks first (core's
        # match/next merges are monotone), then ONE aggregate rejection
        # fallback with the min hint (the kernel's conservative order)
        for (i, j) in sorted(self.arespq):
            rq = self.arespq[(i, j)]
            due = [e for e in rq if e[0] <= now]
            if not due:
                continue
            self.arespq[(i, j)] = [e for e in rq if e[0] > now]
            nd = nodes[i]
            oks = []
            rej_hints = []
            for _, tm, resp in due:
                if not up[i] or nd.state != core.LEADER or nd.term != tm:
                    continue
                self.recent_active[i].add(j)  # kernel: any resp arrival
                if resp.reject:
                    rej_hints.append(resp.reject_hint)
                else:
                    oks.append(resp)
            for resp in oks:
                nd.suppress = True
                nd.step(resp)
                nd.suppress = False
                nd.take_msgs()
            if rej_hints and nd.state == core.LEADER \
                    and (j + 1) in nd.prs:
                # kernel reject rule + becomeProbe (flush pipelined
                # same-term appends past the conflict).  Responses from a
                # peer the config no longer contains are dropped on BOTH
                # sides (core stepLeader: prs.get(m.frm) is None -> return;
                # kernel: ok_mat/rej_mat &= member before integration —
                # the rejection path is receiver-visible, so the mask is
                # required for exactness).
                pr = nd.prs[j + 1]
                pr.next = max(1, min(pr.next - 1, min(rej_hints) + 1))
                pr.state = core.PROBE
                pr.inflights = []
                pr.paused = False
                self.appq[(i, j)] = [e for e in self.appq.get((i, j), [])
                                     if e[2] != nd.term]
                # etcd re-sends immediately after maybeDecrTo (stepLeader
                # APP_RESP reject -> send_append): enqueue the backtracked
                # probe this tick (ring-reachable case only; the snapshot
                # variant waits for the next send round on both sides)
                s_ = self.snpq.get((i, j))
                prev = pr.next - 1
                if not drop[i][j] \
                        and not (s_ is not None and s_[1] == nd.term) \
                        and prev >= nd.log.offset:
                    self.appq[(i, j)].append(
                        (now + self._lat(i, j, now), prev, nd.term))

        # heartbeat responses: liveness bookkeeping only (kernel val_hbr;
        # the etcd match<last resend trigger is unnecessary under
        # send-time-drop wire semantics)
        for (i, j) in sorted(self.hbrq):
            q = self.hbrq[(i, j)]
            due = [e for e in q if e[0] <= now]
            if not due:
                continue
            self.hbrq[(i, j)] = [e for e in q if e[0] > now]
            nd = nodes[i]
            for (_, tm) in due:
                if up[i] and nd.state == core.LEADER and nd.term == tm:
                    self.recent_active[i].add(j)

        self._transfer_fire(up, drop)
        self._phase_def(up)
        self.now += 1

    # -- comparable view ---------------------------------------------------
    def view(self) -> OracleView:
        n = self.cfg.n
        nodes = self.nodes

        def arr(f, dtype=np.int32):
            return np.array([f(nodes[i], i) for i in range(n)], dtype=dtype)

        return OracleView(
            term=arr(lambda nd, i: nd.term),
            vote=arr(lambda nd, i: nd.vote - 1),     # core NONE=0 -> -1
            role=arr(lambda nd, i: ROLE_INT[nd.state]),
            lead=arr(lambda nd, i: nd.lead - 1),
            last=arr(lambda nd, i: nd.log.last_index()),
            commit=arr(lambda nd, i: nd.log.committed),
            applied=arr(lambda nd, i: self.applied[i]),
            apply_chk=arr(lambda nd, i: self.apply_chk[i], np.uint32),
            member=np.array([[(j + 1) in nodes[i].prs for j in range(n)]
                             for i in range(n)], dtype=bool),
        )

"""Scan-compiled simulation drivers for the BASELINE.json bench configs.

These wrap the tick kernel in `lax.scan`/`lax.while_loop` so an entire
benchmark run (election + steady-state replication + crash/churn schedules)
executes as ONE XLA program on device — the host only sees the final state
and per-tick summary rows. This is the swarm-bench analogue
(cmd/swarm-bench/benchmark.go:38) for simulated manager quorums.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.kernel import propose, propose_dense, step
from swarmkit_tpu.raft.sim.state import (
    LEADER, NONE, SimConfig, SimState, drop_matrix, hash32, init_state,
)

I32 = jnp.int32
U32 = jnp.uint32


def leader_mask(state: SimState) -> jax.Array:
    return (state.role == LEADER) & jnp.diagonal(state.member)


def has_leader(state: SimState) -> jax.Array:
    return jnp.any(leader_mask(state))


def _payload_at(tick, k) -> jax.Array:
    """Deterministic device-generated payload id for proposal k of `tick`:
    encodes the (tick, k) origin so the applied-checksum detects
    loss/reorder. k may be any uint32 array shape."""
    return tick.astype(U32) * U32(1 << 16) + k.astype(U32) + U32(1)


def _payloads(cfg: SimConfig, tick, count) -> jax.Array:
    """Batch form of _payload_at for the host propose() API."""
    k = jnp.arange(cfg.max_props, dtype=U32)
    return _payload_at(tick, k)


@partial(jax.jit, static_argnames=("cfg", "n_ticks", "prop_count",
                                   "drop_rate", "crash_every", "down_for"))
def run_ticks(state: SimState, cfg: SimConfig, n_ticks: int,
              prop_count: int = 0, drop_rate: float = 0.0,
              crash_every: int = 0, down_for: int = 5):
    """Advance n_ticks. Per tick: optionally propose `prop_count` entries to
    the current leader(s), optionally drop traffic per-edge at `drop_rate`,
    and optionally crash the sitting leader every `crash_every` ticks for
    `down_for` ticks (BASELINE configs 3-5).

    Returns (final_state, trace) where trace rows are per-tick
    [n_leaders, max_commit, max_term].
    """
    n = cfg.n

    def body(carry, _):
        st, downed, down_left = carry
        tick = st.tick
        alive = jnp.ones((n,), bool)
        if crash_every:
            crash_now = (tick % crash_every == 0) & (tick > 0)
            lm = leader_mask(st)
            new_downed = jnp.where(crash_now & jnp.any(lm),
                                   jnp.argmax(lm).astype(I32), downed)
            new_left = jnp.where(crash_now & jnp.any(lm), down_for,
                                 jnp.maximum(down_left - 1, 0))
            downed, down_left = new_downed, new_left
            alive = alive & ~((jnp.arange(n, dtype=I32) == downed)
                              & (down_left > 0))
        drop = drop_matrix(cfg, tick, drop_rate) if drop_rate else None
        if prop_count:
            # fused propose: bit-identical to a propose_dense call before
            # step, but all [N, L] stores share ONE cond inside the scan
            # body so XLA keeps the log buffers in place (kernel.step
            # docstring; a separate propose cond costs full-log copies)
            st = step(st, cfg, alive=alive, drop=drop,
                      prop_count=jnp.asarray(prop_count, I32),
                      payload_fn=_payload_at)
        else:
            st = step(st, cfg, alive=alive, drop=drop)
        row = jnp.stack([jnp.sum(leader_mask(st).astype(I32)),
                         jnp.max(st.commit), jnp.max(st.term)])
        return (st, downed, down_left), row

    init = (state, jnp.asarray(-1, I32), jnp.asarray(0, I32))
    (final, _, _), trace = jax.lax.scan(body, init, None, length=n_ticks)
    return final, trace


@partial(jax.jit, static_argnames=("cfg", "prop_count"))
def run_schedule(state: SimState, cfg: SimConfig, drop: jax.Array,
                 alive: jax.Array, prop_count: int = 0):
    """Advance len(drop) ticks under a PRECOMPILED fault schedule: drop is
    [T, N, N] per-tick edge drops, alive is [T, N] row liveness (the
    schedule-shaped form the DST layer generates — see dst/schedule.py and
    raft/faults.py plan_to_schedule; run_ticks, by contrast, derives its
    faults from scalar knobs inside the scan).

    Returns (final_state, trace) with the run_ticks trace rows
    [n_leaders, max_commit, max_term].
    """

    def body(st, xs):
        drop_t, alive_t = xs
        if prop_count:
            # fused propose, same rationale as run_ticks
            st = step(st, cfg, alive=alive_t, drop=drop_t,
                      prop_count=jnp.asarray(prop_count, I32),
                      payload_fn=_payload_at)
        else:
            st = step(st, cfg, alive=alive_t, drop=drop_t)
        row = jnp.stack([jnp.sum(leader_mask(st).astype(I32)),
                         jnp.max(st.commit), jnp.max(st.term)])
        return st, row

    return jax.lax.scan(body, state, (drop, alive))


@partial(jax.jit, static_argnames=("cfg", "max_ticks"))
def run_until_leader(state: SimState, cfg: SimConfig, max_ticks: int = 1000):
    """Tick until some node is leader (leader-election latency measurement).
    Returns (state, ticks_taken)."""

    def cond(carry):
        st, t = carry
        return (~has_leader(st)) & (t < max_ticks)

    def body(carry):
        st, t = carry
        return step(st, cfg), t + 1

    return jax.lax.while_loop(cond, body, (state, jnp.asarray(0, I32)))


class KernelObs:
    """Host-side observability for the device kernel.

    Two jobs (see metrics/catalog.py, swarm_kernel_* families):

    - ``timed(call)``: wall-time histogram around a jitted driver call
      (``swarm_kernel_tick_seconds{call=...}``), making PERF.md's cost
      table live data instead of a one-off measurement.
    - ``publish(state)``: fold the on-device cumulative event counters
      (``SimState.stats``, cfg.collect_stats) into the kernel counter
      families, incrementing by delta since the previous publish so
      repeated calls are idempotent over the same state.  The last-seen
      table lives on the REGISTRY (metrics/scrape.py), not this
      instance, so several KernelObs feeding one registry — bench.py
      builds a fresh one per measure() — cannot re-add each other's
      cumulative history.
    """

    _STAT_NAMES = ("swarm_kernel_elections_started_total",
                   "swarm_kernel_elections_won_total",
                   "swarm_kernel_commit_advance_total",
                   "swarm_kernel_apply_advance_total")
    _READ_NAMES = ("swarm_kernel_reads_served_total",
                   "swarm_kernel_reads_blocked_total")
    _DUR_NAME = "swarm_kernel_durable_commit_advance_total"
    _LAG_NAME = "swarm_kernel_fsync_lag"

    def __init__(self, obs=None, clock_sync=None) -> None:
        from swarmkit_tpu.metrics import catalog as obs_catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        from swarmkit_tpu.metrics import scrape as obs_scrape

        self.obs = obs or obs_registry.DEFAULT
        # Optional flightrec/clock.py ClockSync: publish() already pays a
        # blocking device_get, so each publish doubles as a tick<->wall
        # sync point for the causal trace export (flightrec/export.py).
        self.clock_sync = clock_sync
        self._m_tick = obs_catalog.get(self.obs, "swarm_kernel_tick_seconds")
        self._m_stats = [obs_catalog.get(self.obs, n)
                         for n in self._STAT_NAMES]
        self._m_reads = [obs_catalog.get(self.obs, n)
                         for n in self._READ_NAMES]
        self._m_dur = obs_catalog.get(self.obs, self._DUR_NAME)
        self._m_lag = obs_catalog.get(self.obs, self._LAG_NAME)
        self._deltas = obs_scrape.deltas_for(self.obs)

    def timed(self, call: str):
        return self._m_tick.labels(call=call).time()

    def publish(self, state: SimState) -> dict:
        """Returns the cumulative stats as a dict (empty when the state
        carries none, i.e. cfg.collect_stats was off and the read path
        is not compiled in)."""
        if self.clock_sync is not None:
            sync_point(self.clock_sync, state)
        out: dict[str, int] = {}
        if state.stats is not None:
            # a multiraft grouped state carries [G, 4] stats; the kernel
            # families are fleet aggregates, so fold the group axis first
            arr = jax.device_get(state.stats)
            cur = [int(v) for v in
                   (arr.sum(axis=0) if getattr(arr, "ndim", 1) > 1
                    else arr)]
            for name, fam, c in zip(self._STAT_NAMES, self._m_stats, cur):
                d = self._deltas.advance((name,), c)
                if d:
                    fam.inc(d)
            out.update(zip(("elections_started", "elections_won",
                            "commit_advance", "apply_advance"), cur))
        if state.read_srv is not None:
            cur_r = [int(jax.device_get(reads_served(state))),
                     int(jax.device_get(reads_blocked(state)))]
            for name, fam, c in zip(self._READ_NAMES, self._m_reads, cur_r):
                d = self._deltas.advance((name,), c)
                if d:
                    fam.inc(d)
            out.update(zip(("reads_served", "reads_blocked"), cur_r))
        if state.sync_mark is not None:
            # durable-commit advance is a cumulative sum like the stats
            # counters (dur_commit is per-row monotone, so the sum is
            # too); fsync lag is a point-in-time width, hence a gauge
            cur_d = int(jax.device_get(jnp.sum(state.dur_commit)))
            d = self._deltas.advance((self._DUR_NAME,), cur_d)
            if d:
                self._m_dur.inc(d)
            lag = int(jax.device_get(jnp.max(state.last - state.sync_mark)))
            self._m_lag.set(lag)
            out.update(durable_commit=cur_d, fsync_lag=lag)
        return out


def sync_point(clock, state: SimState) -> int:
    """Record one (tick, host_ns) clock-correlation sample on `clock`
    (flightrec/clock.py ClockSync) and return the observed tick.

    The device_get of state.tick is a genuine host<->device sync: when
    it returns, the device HAS reached that tick, so "now" bounds it
    from above.  Drivers call this at their natural exchange boundaries
    (after a run_ticks burst, around propose/read submission) — two or
    three points across a run are enough for the Theil-Sen fit to remap
    the flight-ring tracks onto the host span timeline."""
    import numpy as _np

    # grouped multiraft states carry a [G] tick vector that advances in
    # lock-step; any element is the correlation sample (max is robust)
    tick = int(_np.max(jax.device_get(state.tick)))
    clock.add(tick)
    return tick


def submit_reads(state: SimState, cfg: SimConfig, count: int,
                 rows=None, tag=None) -> SimState:
    """Enqueue a linearizable read batch of `count` ops on the selected
    rows (all rows when `rows` is None), step-compatible: the next
    `step()` stamps the batch with a ReadIndex (or serves it under a
    valid lease) and serves it once applied catches up.

    Mirrors the kernel's own closed-loop refill (read/serve.py `submit`):
    only rows whose previous batch fully drained accept a new one, and
    the submit-time linearizability goal — max(commit) anywhere — is
    recorded for the LINEARIZABLE_READ oracle.  Requires
    cfg.read_batch > 0 so the read registers are compiled in.

    `tag` is an optional scalar host trace tag for this batch
    (cfg.trace_tags; metrics/trace.py span_trace_tag): the READ_SERVED
    event that settles it carries the tag, linking the device instant
    back to the submitting host span in the Perfetto export.
    """
    if state.read_pend is None:
        raise ValueError("read path is off (SimConfig.read_batch == 0); "
                         "no read registers to submit into")
    sel = jnp.ones((cfg.n,), bool) if rows is None \
        else jnp.zeros((cfg.n,), bool).at[jnp.asarray(rows)].set(True)
    open_ = sel & (state.read_pend == 0)
    goal = jnp.max(state.commit)
    tag_fields = {}
    if cfg.trace_tags and state.read_tag is not None:
        tg = jnp.asarray(0 if tag is None else tag, I32)
        tag_fields = dict(
            read_tag=jnp.where(open_, tg, state.read_tag))
    return dataclasses.replace(
        state,
        read_pend=jnp.where(open_, jnp.asarray(count, I32), state.read_pend),
        read_goal=jnp.where(open_, goal, state.read_goal),
        read_idx=jnp.where(open_, jnp.asarray(NONE, I32), state.read_idx),
        **tag_fields)


def reads_served(state: SimState) -> jax.Array:
    """Total read ops served across rows (0 when the read path is off)."""
    if state.read_srv is None:
        return jnp.asarray(0, I32)
    return jnp.sum(state.read_srv)


def reads_blocked(state: SimState) -> jax.Array:
    """Total read ops refused (deposal / lease expiry) across rows."""
    if state.read_block is None:
        return jnp.asarray(0, I32)
    return jnp.sum(state.read_block)


def committed_entries(state: SimState) -> jax.Array:
    """Total entries committed through consensus (max commit across rows)."""
    return jnp.max(state.commit)


def quorum_applied_checksum(state: SimState):
    """(applied, checksum) pairs — equal applied MUST imply equal checksum
    (state-machine safety); checked by tests and the bench verifier."""
    return state.applied, state.apply_chk

"""Encrypted raft log persistence: write-ahead log + snapshots.

Behavioral reference: manager/state/raft/storage/ (EncryptedRaftLogger
storage.go:37, walwrap.go, snapwrap.go) — every record is wrapped in a
MaybeEncryptedRecord envelope so the log is encrypted at rest with a DEK, the
DEK can rotate without closing the WAL (old records decrypt via a
MultiDecrypter), and old WALs/snapshots are GC'd after a snapshot.

Design differences (deliberate): instead of etcd's wal/snap packages we use
self-contained WAL segments — `save_snapshot` writes the snapshot file AND
starts a fresh segment seeded with the entries beyond the snapshot index, so
boot = read newest valid snapshot + replay exactly one segment.  Records are
length+crc32 framed; a torn tail record is dropped (crash tolerance), and a
corrupt record mid-file raises.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import msgpack

from swarmkit_tpu.encryption import (
    Decrypter, Encrypter, MaybeEncryptedRecord, MultiDecrypter, NopCrypter,
)
from swarmkit_tpu.raft.messages import Entry, EntryType, HardState, Snapshot, SnapshotMeta

# record types
_REC_HARDSTATE = 1
_REC_ENTRY = 2

# frame layout lives in swarmkit_tpu/native (wal_codec.cpp): u32 len,
# u32 crc32, body
from swarmkit_tpu.native import prebuild_in_background as _prebuild

_prebuild()


class DataCorrupt(Exception):
    pass


@dataclass
class BootstrapResult:
    hard_state: Optional[HardState]
    entries: list
    snapshot: Optional[Snapshot]


def _pack_entry(e: Entry) -> bytes:
    return msgpack.packb((e.index, e.term, int(e.type), e.data))


def _unpack_entry(raw: bytes) -> Entry:
    index, term, typ, data = msgpack.unpackb(raw)
    return Entry(index=index, term=term, type=EntryType(typ), data=data)


def _pack_hardstate(hs: HardState) -> bytes:
    return msgpack.packb((hs.term, hs.vote, hs.commit))


def _unpack_hardstate(raw: bytes) -> HardState:
    term, vote, commit = msgpack.unpackb(raw)
    return HardState(term=term, vote=vote, commit=commit)


def _pack_snapshot(s: Snapshot) -> bytes:
    return msgpack.packb(
        (s.meta.index, s.meta.term, list(s.meta.voters), s.data))


def _unpack_snapshot(raw: bytes) -> Snapshot:
    index, term, voters, data = msgpack.unpackb(raw)
    return Snapshot(meta=SnapshotMeta(index=index, term=term,
                                      voters=tuple(voters)), data=data)


class _Segment:
    """One append-only WAL file of framed, enveloped records."""

    def __init__(self, path: str, encrypter: Encrypter) -> None:
        self.path = path
        self.encrypter = encrypter
        self._f = open(path, "ab")

    def append(self, rec_type: int, payload: bytes) -> None:
        self.append_many([(rec_type, payload)])

    def append_many(self, records: list[tuple[int, bytes]]) -> None:
        """Batch-frame records in one native call (native/wal_codec.cpp —
        the analog of etcd/wal's compiled encoder)."""
        from swarmkit_tpu.native import wal_codec

        bodies = [self.encrypter.encrypt(
            msgpack.packb((rt, pl))).encode() for rt, pl in records]
        self._f.write(wal_codec().frame(bodies))

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.sync()
        except (OSError, ValueError):
            pass
        self._f.close()


def _read_segment(path: str, decrypter: Decrypter) -> list[tuple[int, bytes]]:
    """Validated scan via the native codec (torn tails dropped, mid-WAL
    corruption fatal — matching etcd/wal semantics)."""
    from swarmkit_tpu.native import STATUS_CORRUPT, wal_codec

    with open(path, "rb") as f:
        blob = f.read()
    bodies, status = wal_codec().scan(blob)
    if status == STATUS_CORRUPT:
        raise DataCorrupt(f"crc mismatch mid-WAL in {path}")
    records = []
    for body in bodies:
        raw = decrypter.decrypt(MaybeEncryptedRecord.decode(body))
        rec_type, payload = msgpack.unpackb(raw)
        records.append((rec_type, payload))
    return records


class EncryptedRaftLogger:
    """reference: storage.EncryptedRaftLogger storage.go:37."""

    def __init__(self, state_dir: str,
                 encrypter: Optional[Encrypter] = None,
                 decrypter: Optional[Decrypter] = None) -> None:
        self.state_dir = state_dir
        self.raft_dir = os.path.join(state_dir, "raft")
        nop = NopCrypter()
        self.encrypter: Encrypter = encrypter or nop
        # always able to read plaintext records too (pre-autolock logs)
        self.decrypter: Decrypter = MultiDecrypter(decrypter or nop, nop)
        self._segment: Optional[_Segment] = None

    # -- paths -------------------------------------------------------------
    def _wal_path(self, index: int) -> str:
        return os.path.join(self.raft_dir, f"wal-{index:016x}.log")

    def _snap_path(self, index: int) -> str:
        return os.path.join(self.raft_dir, f"snap-{index:016x}.bin")

    def _list(self, prefix: str) -> list[tuple[int, str]]:
        if not os.path.isdir(self.raft_dir):
            return []
        out = []
        for name in os.listdir(self.raft_dir):
            if name.startswith(prefix):
                hex_part = name[len(prefix):].split(".")[0]
                try:
                    out.append((int(hex_part, 16),
                                os.path.join(self.raft_dir, name)))
                except ValueError:
                    continue
        return sorted(out)

    def has_existing_state(self) -> bool:
        return bool(self._list("wal-") or self._list("snap-"))

    # -- bootstrap ---------------------------------------------------------
    def bootstrap_new(self) -> None:
        """reference: BootstrapNew storage.go:144."""
        os.makedirs(self.raft_dir, exist_ok=True)
        self._segment = _Segment(self._wal_path(0), self.encrypter)

    def bootstrap_from_disk(self) -> BootstrapResult:
        """reference: BootstrapFromDisk storage.go:52 — newest readable
        snapshot + its segment replayed."""
        snapshot = None
        snap_index = 0
        for index, path in reversed(self._list("snap-")):
            try:
                with open(path, "rb") as f:
                    raw = self.decrypter.decrypt(
                        MaybeEncryptedRecord.decode(f.read()))
                snapshot = _unpack_snapshot(raw)
                snap_index = index
                break
            except Exception:
                continue  # fall back to an older snapshot
        # choose the newest segment at-or-below the snapshot index (each
        # segment is self-contained from its snapshot)
        segs = self._list("wal-")
        chosen = None
        for index, path in segs:
            if index <= snap_index or chosen is None:
                chosen = (index, path)
            # also prefer exactly the snapshot's own segment if present
        for index, path in segs:
            if index == snap_index:
                chosen = (index, path)
        hard_state: Optional[HardState] = None
        entries: list[Entry] = []
        if chosen is not None:
            for rec_type, payload in _read_segment(chosen[1], self.decrypter):
                if rec_type == _REC_HARDSTATE:
                    hard_state = _unpack_hardstate(payload)
                elif rec_type == _REC_ENTRY:
                    e = _unpack_entry(payload)
                    # later appends at same index override (term conflicts)
                    while entries and entries[-1].index >= e.index:
                        entries.pop()
                    entries.append(e)
        if snapshot is not None:
            entries = [e for e in entries if e.index > snap_index]
        os.makedirs(self.raft_dir, exist_ok=True)
        seg_path = chosen[1] if chosen is not None else self._wal_path(snap_index)
        self._segment = _Segment(seg_path, self.encrypter)
        return BootstrapResult(hard_state, entries, snapshot)

    # -- writes ------------------------------------------------------------
    def save(self, hard_state: Optional[HardState],
             entries: Sequence[Entry]) -> None:
        """Persist one Ready batch (reference: SaveEntries storage.go:320);
        single fsync per batch, like wal.Save."""
        if self._segment is None:
            raise RuntimeError("logger not bootstrapped")
        records: list[tuple[int, bytes]] = []
        if hard_state is not None:
            records.append((_REC_HARDSTATE, _pack_hardstate(hard_state)))
        records.extend((_REC_ENTRY, _pack_entry(e)) for e in entries)
        if records:
            self._segment.append_many(records)
            self._segment.sync()

    def save_snapshot(self, snapshot: Snapshot,
                      retained_entries: Sequence[Entry] = (),
                      hard_state: Optional[HardState] = None) -> None:
        """Write snapshot + start a fresh self-contained segment
        (reference: SaveSnapshot storage.go:198)."""
        index = snapshot.meta.index
        tmp = self._snap_path(index) + ".tmp"
        os.makedirs(self.raft_dir, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(self.encrypter.encrypt(_pack_snapshot(snapshot)).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path(index))
        old = self._segment
        seg_path = self._wal_path(index)
        if old is not None and os.path.abspath(old.path) == os.path.abspath(seg_path):
            return  # re-snapshot at same index; keep segment
        self._segment = _Segment(seg_path, self.encrypter)
        if hard_state is not None:
            self._segment.append(_REC_HARDSTATE, _pack_hardstate(hard_state))
        for e in retained_entries:
            if e.index > index:
                self._segment.append(_REC_ENTRY, _pack_entry(e))
        self._segment.sync()
        if old is not None:
            old.close()

    def gc(self, snap_index: int) -> None:
        """Drop WALs/snapshots older than the given snapshot
        (reference: GC storage.go:221)."""
        for index, path in self._list("snap-"):
            if index < snap_index:
                os.unlink(path)
        keep = {os.path.abspath(self._segment.path)} if self._segment else set()
        for index, path in self._list("wal-"):
            if index < snap_index and os.path.abspath(path) not in keep:
                os.unlink(path)

    # -- key rotation ------------------------------------------------------
    def rotate_encryption_key(self, encrypter: Encrypter,
                              decrypter: Decrypter) -> None:
        """Switch the DEK for subsequent writes without closing the WAL
        (reference: RotateEncryptionKey storage.go:175).  Full re-encryption
        of history completes at the next snapshot, which starts a fresh
        segment under the new key."""
        self.encrypter = encrypter
        self.decrypter = MultiDecrypter(decrypter, self.decrypter)
        if self._segment is not None:
            self._segment.encrypter = encrypter

    def close(self) -> None:
        if self._segment is not None:
            self._segment.close()
            self._segment = None

"""Raft peer transport: async per-peer message fan-out behind the Transport
seam.

Behavioral reference: manager/state/raft/transport/ — ``Transport`` owns one
``peer`` per remote with a non-blocking bounded send queue (4096 deep,
transport/peer.go:61; messages DROPPED when full, peer.go:82-89), reports
unreachable peers and snapshot delivery status back to the raft node through
the ``Raft`` callback interface (transport.go:26), tracks per-peer activity
for ``LongestActive``, and supports live address updates.

This is the seam the TPU device-mesh backend slots behind (SURVEY.md §2.7):
impl #1 here is an in-process asyncio network with per-edge drop/partition
fault injection (replacing gRPC-over-mTLS); impl #3 (swarmkit_tpu.raft.sim)
exchanges messages as device-array collectives.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional, Protocol

from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.raft.faults import FaultSurface
from swarmkit_tpu.raft.messages import Message, MsgType

log = logging.getLogger("swarmkit_tpu.raft.transport")

MAX_PEER_QUEUE = 4096  # reference: transport/peer.go:61


class RaftHandlers(Protocol):
    """Callbacks from transport into the raft node
    (reference: transport.Raft transport.go:26)."""

    async def process_raft_message(self, m: Message) -> None: ...
    def report_unreachable(self, raft_id: int, failures: int = 1) -> None: ...
    def report_snapshot(self, raft_id: int, ok: bool) -> None: ...
    def is_id_removed(self, raft_id: int) -> bool: ...
    def update_node(self, raft_id: int, addr: str) -> None: ...
    def node_removed(self) -> None: ...


class Unreachable(Exception):
    pass


class PeerRemoved(Exception):
    """Raised by a server when the caller has been removed from the cluster
    (reference: ErrMemberRemoved grpc error)."""


class Network(FaultSurface):
    """In-process wire: addr -> server object, with fault injection.

    The fault vocabulary (down/drop/partition/delay + crash_restart + heal)
    lives on the shared FaultSurface so the gRPC and device-mesh wires
    expose the identical surface; see swarmkit_tpu/raft/faults.py.
    """

    wire_name = "inproc"  # transport metric label; subclasses override

    def __init__(self, seed: int = 0) -> None:
        super().__init__(seed=seed)
        self._servers: dict[str, Any] = {}

    # -- topology ----------------------------------------------------------
    def register(self, addr: str, server: Any) -> None:
        self._servers[addr] = server
        self._down.discard(addr)

    def unregister(self, addr: str) -> None:
        self._servers.pop(addr, None)

    # -- reachability ------------------------------------------------------
    def _blocked(self, frm: str, to: str) -> bool:
        return to not in self._servers or self._fault_blocked(frm, to)

    def reachable(self, frm: str, to: str) -> bool:
        return not self._blocked(frm, to)

    def healthy(self, addr: str) -> bool:
        return addr in self._servers and addr not in self._down

    def server(self, frm: str, to: str) -> Any:
        """Dial: returns the server at `to` or raises Unreachable."""
        if self._blocked(frm, to):
            raise Unreachable(f"{to} unreachable from {frm}")
        return self._servers[to]


class _Peer:
    """One remote: bounded queue + drain task
    (reference: transport/peer.go)."""

    def __init__(self, tr: "Transport", raft_id: int, addr: str) -> None:
        self.tr = tr
        self.raft_id = raft_id
        self.addr = addr
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=MAX_PEER_QUEUE)
        self.active_since: float = 0.0
        self.failures = 0   # consecutive delivery failures
        self._task = asyncio.get_running_loop().create_task(self._drain())

    def send(self, m: Message) -> bool:
        try:
            self.queue.put_nowait((self.tr.clock.now(), m))
            return True
        except asyncio.QueueFull:
            return False  # drop, reference peer.go:82-89

    async def _drain(self) -> None:
        while True:
            queued_at, m = await self.queue.get()
            if self.failures:
                self.tr.m_redials.inc()
                await self._redial_backoff()
            await self._deliver(m, queued_at)

    async def _redial_backoff(self) -> None:
        """Bounded exponential backoff + jitter between redials of a failing
        peer (reference: peer.go resolve/redial backoff). Only wires that
        opt in via a ``dial_backoff = (base, cap)`` attribute pay it — the
        in-process Network keeps immediate retry so fake-clock tests keep
        their exact tick schedules."""
        bk = getattr(self.tr.network, "dial_backoff", None)
        if bk is None:
            return
        base, cap = bk
        delay = min(cap, base * (2 ** min(self.failures - 1, 8)))
        rng = getattr(self.tr.network, "_rng", None)
        jitter = rng.random() if rng is not None else 0.5
        await self.tr.clock.sleep(delay * (0.5 + 0.5 * jitter))

    async def _deliver(self, m: Message, queued_at: float = 0.0) -> None:
        net, tr = self.tr.network, self.tr
        try:
            if net.lossy(tr.local_addr, self.addr):
                net.dropped += 1
                return  # silent loss: raft retries; not "unreachable"
            delay = net.delay_for(tr.local_addr, self.addr) \
                if hasattr(net, "delay_for") else 0.0
            if delay > 0:
                await tr.clock.sleep(delay)
            server = net.server(tr.local_addr, self.addr)
            await server.process_raft_message(m)
            net.delivered += 1
            tr.m_delivery.observe(max(0.0, tr.clock.now() - queued_at))
            if self.failures:
                self.failures = 0
                # recovery signal: clears the peer's failure count in status
                tr.handlers.report_unreachable(self.raft_id, 0)
            if self.active_since == 0.0:
                self.active_since = tr.clock.now() or 1e-9
            if m.type == MsgType.SNAP:
                tr.handlers.report_snapshot(self.raft_id, True)
        except PeerRemoved:
            tr.handlers.node_removed()
        except Exception as e:
            # Any delivery/processing failure counts as "peer unreachable"
            # (matching the reference's RPC-error handling, peer.go:261),
            # but log it — a receiver-side crash must not vanish silently.
            if not isinstance(e, Unreachable):
                log.warning("raft message delivery %s -> %s failed: %r",
                            tr.local_addr, self.addr, e)
            self.active_since = 0.0
            self.failures += 1
            tr.m_send_failures.inc()
            if m.type == MsgType.SNAP:
                tr.handlers.report_snapshot(self.raft_id, False)
            tr.handlers.report_unreachable(self.raft_id, self.failures)

    def stop(self) -> None:
        self._task.cancel()


class Transport:
    """reference: transport.Transport transport.go:47."""

    def __init__(self, network: Network, handlers: RaftHandlers,
                 local_addr: str, clock) -> None:
        self.network = network
        self.handlers = handlers
        self.local_addr = local_addr
        self.clock = clock
        self._peers: dict[int, _Peer] = {}
        self.stopped = False
        # share the node's typed registry when the handlers carry one
        self.obs = getattr(handlers, "obs", None) or obs_registry.DEFAULT
        wire = getattr(network, "wire_name", "inproc")
        self.m_delivery = obs_catalog.get(
            self.obs, "swarm_transport_delivery_latency_seconds"
        ).labels(wire=wire)
        self.m_redials = obs_catalog.get(
            self.obs, "swarm_transport_redials_total").labels(wire=wire)
        self.m_send_failures = obs_catalog.get(
            self.obs, "swarm_transport_send_failures_total").labels(wire=wire)

    def add_peer(self, raft_id: int, addr: str) -> None:
        if raft_id in self._peers:
            if self._peers[raft_id].addr == addr:
                return
            self._peers[raft_id].stop()
        self._peers[raft_id] = _Peer(self, raft_id, addr)

    def remove_peer(self, raft_id: int) -> None:
        p = self._peers.pop(raft_id, None)
        if p is not None:
            p.stop()

    def update_peer(self, raft_id: int, addr: str) -> None:
        self.add_peer(raft_id, addr)

    def peer_ids(self) -> list[int]:
        return list(self._peers)

    def send(self, m: Message) -> None:
        """Non-blocking send (reference: Send transport.go:125)."""
        if self.stopped:
            return
        if self.handlers.is_id_removed(m.to):
            return
        p = self._peers.get(m.to)
        if p is None:
            # unknown peer: the reference resolves via LongestActive; we just
            # report unreachable so raft backs off
            self.handlers.report_unreachable(m.to)
            if m.type == MsgType.SNAP:
                self.handlers.report_snapshot(m.to, False)
            return
        if not p.send(m):
            if m.type == MsgType.SNAP:
                self.handlers.report_snapshot(m.to, False)

    def longest_active(self) -> Optional[int]:
        """reference: LongestActive transport.go:299."""
        best = None
        for raft_id, p in self._peers.items():
            if p.active_since <= 0:
                continue
            if best is None or p.active_since < self._peers[best].active_since:
                best = raft_id
        return best

    def active_count(self) -> int:
        return sum(1 for p in self._peers.values() if p.active_since > 0)

    def stop(self) -> None:
        self.stopped = True
        for p in self._peers.values():
            p.stop()
        self._peers = {}

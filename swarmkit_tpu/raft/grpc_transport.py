"""Transport impl #2: the raft wire over real gRPC sockets.

Reference: manager/state/raft/transport/ + api/raft.proto — services
``Raft.ProcessRaftMessage`` (:12) and ``RaftMembership.Join/Leave`` (:37),
4 MiB message cap with snapshot chunking (transport/peer.go:24,:156),
NotLeader redirects carrying the leader address, and ErrMemberRemoved as a
typed RPC error.

``GrpcNetwork`` is a drop-in for the in-process ``Network`` seam
(raft/transport.py): ``register`` starts a grpc.aio server for the local
raft node, ``server(frm, to)`` returns a stub whose calls cross real
sockets.  Wire format is msgpack (the generic-handler path — no protoc
codegen, mirroring the hand-rolled Message dataclasses), with large
snapshots split into ≤4 MiB chunks over a client-streaming RPC exactly
like StreamRaftMessage.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional

import grpc
import msgpack

from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.raft.faults import FaultSurface
from swarmkit_tpu.raft.messages import (
    ConfChange, ConfChangeType, Entry, EntryType, Message, MsgType, Snapshot,
    SnapshotMeta,
)
from swarmkit_tpu.raft.transport import PeerRemoved, Unreachable

log = logging.getLogger("swarmkit_tpu.raft.grpc")

GRPC_MAX_MSG_SIZE = 4 * 1024 * 1024   # reference: peer.go:24
_CHUNK = GRPC_MAX_MSG_SIZE - (64 * 1024)   # headroom for framing

_SVC = "swarmkit.Raft"
_BOOT = "swarmkit.Bootstrap"
_MEM = "swarmkit.RaftMembership"


# --------------------------------------------------------------------------
# codec: the shared versioned raft wire format (one codec for every
# transport — device-mesh mailboxes and this gRPC bridge must interoperate)

from swarmkit_tpu.raft.wire import decode_message, encode_message  # noqa: E402,F401

_IDENT = lambda b: b


# --------------------------------------------------------------------------
# server side

class _RaftService:
    """Hosts one local raft node behind the gRPC services.

    With a SecurityConfig every raft RPC is manager-only, authorized from
    the mTLS peer certificate (reference: api/raft.proto tls_authorization
    roles=swarm-manager; ca/auth.go AuthorizeOrgAndRole)."""

    def __init__(self, node, security=None) -> None:
        self.node = node
        self.security = security

    async def _authorize(self, context) -> None:
        if self.security is None:
            return
        from swarmkit_tpu.ca.auth import PermissionDenied
        from swarmkit_tpu.ca.certificates import MANAGER_ROLE_OU
        from swarmkit_tpu.ca.tlsutil import authorize_peer

        try:
            authorize_peer(context, self.security, MANAGER_ROLE_OU)
        except PermissionDenied as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))

    async def process_raft_message(self, request: bytes, context) -> bytes:
        await self._authorize(context)
        try:
            await self.node.process_raft_message(decode_message(request))
        except PeerRemoved:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED,
                                "member removed")
        return b""

    # Reassembled stream cap: bounds a misbehaving peer's buffering before
    # the message is even parsed (the per-message gRPC cap is 4 MiB; a
    # snapshot stream may legitimately span many chunks).
    MAX_STREAM_BYTES = 512 * 1024 * 1024

    async def stream_raft_message(self, request_iterator, context) -> bytes:
        """Chunked delivery for big snapshots
        (reference: StreamRaftMessage raft.go:1330; reassembly then Step).
        Authorization runs BEFORE consuming the stream so an unauthorized
        peer cannot make us buffer unbounded data."""
        await self._authorize(context)
        chunks, total = [], 0
        async for chunk in request_iterator:
            total += len(chunk)
            if total > self.MAX_STREAM_BYTES:
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                    "stream exceeds reassembly cap")
            chunks.append(chunk)
        return await self.process_raft_message(b"".join(chunks), context)

    async def join(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.raft.node import NotLeaderError

        await self._authorize(context)
        node_id, addr = msgpack.unpackb(request)
        try:
            resp = await self.node.join(node_id, addr)
        except NotLeaderError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                f"not-leader:{e.leader_addr}")
        return msgpack.packb((
            resp.raft_id,
            [(m.raft_id, m.node_id, m.addr) for m in resp.members],
            list(resp.removed)))

    async def leave(self, request: bytes, context) -> bytes:
        await self._authorize(context)
        (raft_id,) = msgpack.unpackb(request)
        await self.node.leave(raft_id)
        return b""

    def handlers(self) -> list:
        raft = grpc.method_handlers_generic_handler(_SVC, {
            "ProcessRaftMessage": grpc.unary_unary_rpc_method_handler(
                self.process_raft_message,
                request_deserializer=_IDENT, response_serializer=_IDENT),
            "StreamRaftMessage": grpc.stream_unary_rpc_method_handler(
                self.stream_raft_message,
                request_deserializer=_IDENT, response_serializer=_IDENT),
        })
        membership = grpc.method_handlers_generic_handler(_MEM, {
            "Join": grpc.unary_unary_rpc_method_handler(
                self.join,
                request_deserializer=_IDENT, response_serializer=_IDENT),
            "Leave": grpc.unary_unary_rpc_method_handler(
                self.leave,
                request_deserializer=_IDENT, response_serializer=_IDENT),
        })
        return [raft, membership]


# --------------------------------------------------------------------------
# client side

class _RemoteStub:
    """What ``GrpcNetwork.server(frm, to)`` hands the raft node/transport:
    the same duck type as a local raft node, backed by RPCs."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._process = channel.unary_unary(
            f"/{_SVC}/ProcessRaftMessage",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._stream = channel.stream_unary(
            f"/{_SVC}/StreamRaftMessage",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._join = channel.unary_unary(
            f"/{_MEM}/Join",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._leave = channel.unary_unary(
            f"/{_MEM}/Leave",
            request_serializer=_IDENT, response_deserializer=_IDENT)

    async def process_raft_message(self, m: Message) -> None:
        raw = encode_message(m)
        try:
            if len(raw) > _CHUNK:
                async def chunks():
                    for off in range(0, len(raw), _CHUNK):
                        yield raw[off:off + _CHUNK]
                await self._stream(chunks())
            else:
                await self._process(raw)
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e)

    async def join(self, node_id: str, addr: str):
        from swarmkit_tpu.raft.node import JoinResponse, NotLeaderError
        from swarmkit_tpu.raft.membership import Member

        try:
            raw = await self._join(msgpack.packb((node_id, addr)))
        except grpc.aio.AioRpcError as e:
            details = e.details() or ""
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION \
                    and details.startswith("not-leader:"):
                raise NotLeaderError(details.split(":", 1)[1])
            raise _map_rpc_error(e)
        raft_id, members, removed = msgpack.unpackb(raw)
        return JoinResponse(
            raft_id=raft_id,
            members=[Member(raft_id=r, node_id=n, addr=a)
                     for r, n, a in members],
            removed=list(removed))

    async def leave(self, raft_id: int) -> None:
        try:
            await self._leave(msgpack.packb((raft_id,)))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e)


def _map_rpc_error(e: grpc.aio.AioRpcError) -> Exception:
    if e.code() == grpc.StatusCode.PERMISSION_DENIED \
            and "member removed" in (e.details() or ""):
        return PeerRemoved(e.details())
    return Unreachable(f"rpc failed: {e.code().name}: {e.details()}")


# --------------------------------------------------------------------------
# active peer health probing

class _PeerProber:
    """Active health probe for one peer address.

    Serves ``GrpcNetwork.healthy``/``reachable`` the way the reference's
    raft transport consumes manager/health (health.go:21, raft.go:1422):
    a loop Checks the peer's Health service; ``failure_threshold``
    consecutive failures flip the peer unhealthy, redials back off
    exponentially with jitter, and recovery requires sustained success
    spanning ``grace_period`` so a flapping peer does not oscillate the
    vote-health gate."""

    def __init__(self, net: "GrpcNetwork", addr: str) -> None:
        self.net = net
        self.addr = addr
        self.failures = 0          # consecutive probe failures
        self._healthy = True       # optimistic until proven otherwise
        self._first_ok: Optional[float] = None
        obs = net.obs
        self._m_probes = obs_catalog.get(
            obs, "swarm_transport_probes_total")
        self._m_transitions = obs_catalog.get(
            obs, "swarm_transport_probe_transitions_total")
        self._m_healthy = obs_catalog.get(
            obs, "swarm_transport_probe_healthy").labels(peer=addr)
        self._m_healthy.set(1.0)
        self._task = asyncio.get_running_loop().create_task(self._loop())

    @property
    def healthy(self) -> bool:
        return self._healthy

    def _set_healthy(self, healthy: bool) -> None:
        if healthy == self._healthy:
            return
        self._healthy = healthy
        state = "healthy" if healthy else "unhealthy"
        self._m_transitions.labels(peer=self.addr, state=state).inc()
        self._m_healthy.set(1.0 if healthy else 0.0)

    def reset(self) -> None:
        """Forget accumulated failure state (peer process bounced)."""
        self.failures = 0
        self._first_ok = None

    def stop(self) -> None:
        self._task.cancel()

    async def _probe_once(self) -> bool:
        if self.addr in self.net._down:
            return False
        # Injected partitions block at the dial seam, not the socket, so the
        # health RPC itself would still succeed — mirror the block here so
        # probe state flips the way a real severed link would make it.
        frms = [a for a in self.net._local if a != self.addr]
        if frms and all(self.net._fault_blocked(f, self.addr) for f in frms):
            return False
        try:
            raw = await asyncio.wait_for(
                self.net._health_call(self.addr)(msgpack.packb("Raft")),
                timeout=self.net.probe_timeout)
            return msgpack.unpackb(raw) == 1   # HealthStatus.SERVING
        except asyncio.CancelledError:
            raise
        except Exception:
            return False

    async def _loop(self) -> None:
        net = self.net
        while True:
            ok = await self._probe_once()
            self._m_probes.labels(
                peer=self.addr, result="ok" if ok else "fail").inc()
            now = asyncio.get_running_loop().time()
            if ok:
                self.failures = 0
                if not self._healthy:
                    if self._first_ok is None:
                        self._first_ok = now
                    if now - self._first_ok >= net.grace_period:
                        self._set_healthy(True)
                        self._first_ok = None
                await asyncio.sleep(
                    net.probe_interval * (0.75 + 0.5 * net._rng.random()))
            else:
                self.failures += 1
                self._first_ok = None
                if self.failures >= net.failure_threshold:
                    self._set_healthy(False)
                base, cap = net.dial_backoff
                delay = min(cap, base * (2 ** min(self.failures - 1, 8)))
                await asyncio.sleep(delay * (0.5 + 0.5 * net._rng.random()))


# --------------------------------------------------------------------------
# the Network-shaped seam

class GrpcNetwork(FaultSurface):
    """Drop-in for raft.transport.Network over real sockets.

    Addresses are host:port listen addresses.  ``register`` starts a
    grpc.aio server for the node (raft + a gRPC health service);
    ``server(frm, to)`` returns a cached remote stub, refusing the dial
    when fault injection blocks the edge — the same down/drop/partition/
    delay vocabulary as the in-process Network (FaultSurface).
    ``healthy``/``reachable`` are backed by active peer probing
    (_PeerProber) instead of the seed's hardcoded True, so vote-health
    gating and the CanRemoveMember quorum precheck operate for real
    across processes.
    """

    wire_name = "grpc"   # transport metric label (see metrics/catalog.py)

    def __init__(self, security=None, seed: int = 0,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 1.0,
                 failure_threshold: int = 3,
                 grace_period: float = 1.0,
                 redial_backoff: float = 0.05,
                 redial_backoff_max: float = 2.0,
                 obs: Optional[obs_registry.MetricsRegistry] = None) -> None:
        # security: a ca.SecurityConfig or a zero-arg callable returning one
        # (late-bound: swarmd loads its identity after the network object
        # exists). When set, the listener serves with TLS from the node
        # identity (client certs verified when presented) and every dialed
        # channel is mutual-TLS; raft RPCs then require the swarm-manager
        # role OU (reference: manager.go:252-270 + ca/auth.go). A companion
        # plaintext BOOTSTRAP port (port+1) serves only the public root CA
        # certificate so joiners can pin it against their token digest (the
        # python-grpc analog of the reference's InsecureSkipVerify +
        # digest-pin GetRemoteCA, ca/certificates.go).
        # None = plaintext, for in-process tests only.
        super().__init__(seed=seed)
        self.obs = obs or obs_registry.DEFAULT
        self._security_arg = security
        self._servers: dict[str, grpc.aio.Server] = {}
        self._channels: dict[str, grpc.aio.Channel] = {}
        self._stubs: dict[str, _RemoteStub] = {}
        self._local: dict[str, Any] = {}
        self._extra_handlers: dict[str, list] = {}
        self._join_handlers: dict[str, list] = {}
        self._bind_map: dict[str, str] = {}   # advertise -> bind address
        # health-probe knobs (see _PeerProber)
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.failure_threshold = failure_threshold
        self.grace_period = grace_period
        # redial backoff (base, cap): consumed both by _PeerProber and by
        # the shared transport's per-peer drain loop (_Peer._redial_backoff)
        self.dial_backoff = (redial_backoff, redial_backoff_max)
        self._probers: dict[str, _PeerProber] = {}
        self._health_rpcs: dict[str, Any] = {}
        # addr -> HealthServer (or zero-arg callable returning one); set by
        # the manager before its raft node registers (Manager.start)
        self._health_refs: dict[str, Any] = {}

    @property
    def security(self):
        s = self._security_arg
        return s() if callable(s) else s

    def add_service(self, addr: str, handlers: list) -> None:
        """Queue extra generic handlers (dispatcher/CA/control services) to
        serve alongside the raft services once ``register`` runs — gRPC
        servers only accept handlers before start."""
        self._extra_handlers.setdefault(addr, []).extend(handlers)

    def set_bind_addr(self, advertise: str, listen: str) -> None:
        """Bind `listen` for the server whose ADVERTISED address is
        `advertise` (reference --listen-remote-api vs
        --advertise-remote-api: wildcard/NAT-internal binds with a
        dialable advertised address). Call before register()."""
        self._bind_map[advertise] = listen

    def set_health(self, addr: str, health_ref) -> None:
        """Point the wire health service for `addr` at a HealthServer (or a
        zero-arg callable returning one). The manager calls this before its
        raft node registers, promoting manager/health.py onto the wire
        (reference: the HealthServer registration manager.go:526-548)."""
        self._health_refs[addr] = health_ref

    def _health_check_fn(self, addr: str, node: Any):
        """Per-service status for this listener: the manager's HealthServer
        when one is wired, else derived from the raft node's liveness (bare
        raft-node clusters in tests/tools have no manager)."""
        def check(service: str) -> int:
            ref = self._health_refs.get(addr)
            h = ref() if callable(ref) else ref
            if h is not None:
                status = int(h.check(service))
                if status != 0:       # not UNKNOWN
                    return status
            current = self._local.get(addr)
            target = current if current is not None else node
            return 1 if getattr(target, "running", True) else 2
        return check

    def register(self, addr: str, node: Any) -> None:
        # gRPC server startup is async; do it lazily-but-synchronously via
        # the running loop (register is called from async context in
        # node.start)
        from swarmkit_tpu.rpc import health_handlers

        self._local[addr] = node
        self._down.discard(addr)
        bind = self._bind_map.get(addr, addr)
        loop = asyncio.get_event_loop()
        server = grpc.aio.server(options=[
            ("grpc.max_send_message_length", GRPC_MAX_MSG_SIZE),
            ("grpc.max_receive_message_length", GRPC_MAX_MSG_SIZE),
        ])
        for h in _RaftService(node, security=self.security).handlers():
            server.add_generic_rpc_handlers((h,))
        for h in health_handlers(self._health_check_fn(addr, node)):
            server.add_generic_rpc_handlers((h,))
        for h in self._extra_handlers.get(addr, ()):
            server.add_generic_rpc_handlers((h,))
        if self.security is not None:
            from swarmkit_tpu.ca.tlsutil import server_credentials

            bound = server.add_secure_port(bind,
                                           server_credentials(self.security))
        else:
            bound = server.add_insecure_port(bind)
        if bound == 0:
            raise RuntimeError(f"cannot bind raft listener on {bind}")
        self._servers[addr] = server
        loop.create_task(server.start())
        if self.security is not None:
            self._start_bootstrap(addr, loop)

    def add_join_service(self, addr: str, handlers: list) -> None:
        """Handlers served to certificate-less joiners on the TLS join port
        (port+2): certificate issuance + leader info."""
        self._join_handlers.setdefault(addr, []).extend(handlers)

    def _start_bootstrap(self, addr: str, loop) -> None:
        """Two companion listeners for the join dance (see ca/tlsutil):
        plaintext port+1 serves ONLY the public root CA certificate (joiners
        digest-pin it against their SWMTKN — the reference's
        InsecureSkipVerify + pin, ca/certificates.go GetRemoteCA; python-grpc
        cannot skip verify); TLS port+2 (server-auth only) serves
        certificate issuance so the join token never travels plaintext."""
        from swarmkit_tpu.ca.tlsutil import join_server_credentials

        async def get_root(request: bytes, context) -> bytes:
            sec = self.security
            return sec.root_ca.cert_pem if sec is not None else b""

        host, port = self._bind_map.get(addr, addr).rsplit(":", 1)
        boot = grpc.aio.server()
        boot.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(_BOOT, {
                "GetRootCACertificate": grpc.unary_unary_rpc_method_handler(
                    get_root, request_deserializer=_IDENT,
                    response_serializer=_IDENT)}),))
        if boot.add_insecure_port(f"{host}:{int(port) + 1}") == 0:
            log.warning("cannot bind bootstrap listener on %s:%d — joins "
                        "from certificate-less nodes will fail",
                        host, int(port) + 1)
        else:
            self._servers[addr + "/bootstrap"] = boot
            loop.create_task(boot.start())

        join_handlers = self._join_handlers.get(addr, ())
        if join_handlers:
            join_srv = grpc.aio.server()
            for h in join_handlers:
                join_srv.add_generic_rpc_handlers((h,))
            if join_srv.add_secure_port(
                    f"{host}:{int(port) + 2}",
                    join_server_credentials(self.security)) == 0:
                log.warning("cannot bind join listener on %s:%d",
                            host, int(port) + 2)
            else:
                self._servers[addr + "/join"] = join_srv
                loop.create_task(join_srv.start())

    def unregister(self, addr: str) -> None:
        self._local.pop(addr, None)
        for key in (addr, addr + "/bootstrap", addr + "/join"):
            server = self._servers.pop(key, None)
            if server is not None:
                asyncio.get_event_loop().create_task(server.stop(grace=0.1))

    # -- dialing -----------------------------------------------------------
    def _channel(self, to: str) -> grpc.aio.Channel:
        channel = self._channels.get(to)
        if channel is None:
            options = [
                ("grpc.max_send_message_length", GRPC_MAX_MSG_SIZE),
                ("grpc.max_receive_message_length", GRPC_MAX_MSG_SIZE),
            ]
            if self.security is not None:
                from swarmkit_tpu.ca.tlsutil import (
                    channel_credentials, secure_channel_options,
                )

                channel = grpc.aio.secure_channel(
                    to, channel_credentials(self.security),
                    options=secure_channel_options(options))
            else:
                channel = grpc.aio.insecure_channel(to, options=options)
            self._channels[to] = channel
        return channel

    def server(self, frm: str, to: str) -> _RemoteStub:
        """Dial: connection-level fault interception happens HERE — this is
        called per delivery attempt (the per-peer drain loop and the join
        flow), so an injected down/partition refuses the edge immediately,
        without touching the socket."""
        if self._fault_blocked(frm, to):
            raise Unreachable(f"{to} blocked from {frm} by fault injection")
        self._ensure_prober(to)
        stub = self._stubs.get(to)
        if stub is None:
            stub = _RemoteStub(self._channel(to))
            self._stubs[to] = stub
        return stub

    # -- health probing ----------------------------------------------------
    def _health_call(self, addr: str):
        call = self._health_rpcs.get(addr)
        if call is None:
            from swarmkit_tpu.rpc import HEALTH_SVC

            call = self._channel(addr).unary_unary(
                f"/{HEALTH_SVC}/Check",
                request_serializer=_IDENT, response_deserializer=_IDENT)
            self._health_rpcs[addr] = call
        return call

    def _ensure_prober(self, addr: str) -> Optional[_PeerProber]:
        p = self._probers.get(addr)
        if p is None:
            try:
                p = _PeerProber(self, addr)
            except RuntimeError:
                return None   # no running loop (sync caller): stay optimistic
            self._probers[addr] = p
        return p

    # -- reachability (fault injection + live probe state) -----------------
    def reachable(self, frm: str, to: str) -> bool:
        if self._fault_blocked(frm, to):
            return False
        p = self._probers.get(to)
        return True if p is None else p.healthy

    def healthy(self, addr: str) -> bool:
        if addr in self._down:
            return False
        p = self._probers.get(addr) or self._ensure_prober(addr)
        return True if p is None else p.healthy

    def crash_restart(self, addr: str) -> None:
        """Sever cached wire state for a bounced process at `addr`: close
        its channel (in-flight RPCs fail, the next dial reconnects) and
        reset the prober's accumulated failure window."""
        self._stubs.pop(addr, None)
        self._health_rpcs.pop(addr, None)
        channel = self._channels.pop(addr, None)
        if channel is not None:
            try:
                asyncio.get_running_loop().create_task(channel.close())
            except RuntimeError:
                pass
        p = self._probers.get(addr)
        if p is not None:
            p.reset()

    async def close(self) -> None:
        for p in self._probers.values():
            p.stop()
        self._probers = {}
        for ch in self._channels.values():
            await ch.close()
        self._channels = {}
        self._stubs = {}
        self._health_rpcs = {}
        for server in self._servers.values():
            await server.stop(grace=0.1)
        self._servers = {}

"""Transport impl #2: the raft wire over real gRPC sockets.

Reference: manager/state/raft/transport/ + api/raft.proto — services
``Raft.ProcessRaftMessage`` (:12) and ``RaftMembership.Join/Leave`` (:37),
4 MiB message cap with snapshot chunking (transport/peer.go:24,:156),
NotLeader redirects carrying the leader address, and ErrMemberRemoved as a
typed RPC error.

``GrpcNetwork`` is a drop-in for the in-process ``Network`` seam
(raft/transport.py): ``register`` starts a grpc.aio server for the local
raft node, ``server(frm, to)`` returns a stub whose calls cross real
sockets.  Wire format is msgpack (the generic-handler path — no protoc
codegen, mirroring the hand-rolled Message dataclasses), with large
snapshots split into ≤4 MiB chunks over a client-streaming RPC exactly
like StreamRaftMessage.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional

import grpc
import msgpack

from swarmkit_tpu.raft.messages import (
    ConfChange, ConfChangeType, Entry, EntryType, Message, MsgType, Snapshot,
    SnapshotMeta,
)
from swarmkit_tpu.raft.transport import PeerRemoved, Unreachable

log = logging.getLogger("swarmkit_tpu.raft.grpc")

GRPC_MAX_MSG_SIZE = 4 * 1024 * 1024   # reference: peer.go:24
_CHUNK = GRPC_MAX_MSG_SIZE - (64 * 1024)   # headroom for framing

_SVC = "swarmkit.Raft"
_MEM = "swarmkit.RaftMembership"


# --------------------------------------------------------------------------
# codec: the shared versioned raft wire format (one codec for every
# transport — device-mesh mailboxes and this gRPC bridge must interoperate)

from swarmkit_tpu.raft.wire import decode_message, encode_message  # noqa: E402,F401

_IDENT = lambda b: b


# --------------------------------------------------------------------------
# server side

class _RaftService:
    """Hosts one local raft node behind the gRPC services."""

    def __init__(self, node) -> None:
        self.node = node

    async def process_raft_message(self, request: bytes, context) -> bytes:
        try:
            await self.node.process_raft_message(decode_message(request))
        except PeerRemoved:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED,
                                "member removed")
        return b""

    async def stream_raft_message(self, request_iterator, context) -> bytes:
        """Chunked delivery for big snapshots
        (reference: StreamRaftMessage raft.go:1330; reassembly then Step)."""
        chunks = []
        async for chunk in request_iterator:
            chunks.append(chunk)
        return await self.process_raft_message(b"".join(chunks), context)

    async def join(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.raft.node import NotLeaderError

        node_id, addr = msgpack.unpackb(request)
        try:
            resp = await self.node.join(node_id, addr)
        except NotLeaderError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                f"not-leader:{e.leader_addr}")
        return msgpack.packb((
            resp.raft_id,
            [(m.raft_id, m.node_id, m.addr) for m in resp.members],
            list(resp.removed)))

    async def leave(self, request: bytes, context) -> bytes:
        (raft_id,) = msgpack.unpackb(request)
        await self.node.leave(raft_id)
        return b""

    def handlers(self) -> list:
        raft = grpc.method_handlers_generic_handler(_SVC, {
            "ProcessRaftMessage": grpc.unary_unary_rpc_method_handler(
                self.process_raft_message,
                request_deserializer=_IDENT, response_serializer=_IDENT),
            "StreamRaftMessage": grpc.stream_unary_rpc_method_handler(
                self.stream_raft_message,
                request_deserializer=_IDENT, response_serializer=_IDENT),
        })
        membership = grpc.method_handlers_generic_handler(_MEM, {
            "Join": grpc.unary_unary_rpc_method_handler(
                self.join,
                request_deserializer=_IDENT, response_serializer=_IDENT),
            "Leave": grpc.unary_unary_rpc_method_handler(
                self.leave,
                request_deserializer=_IDENT, response_serializer=_IDENT),
        })
        return [raft, membership]


# --------------------------------------------------------------------------
# client side

class _RemoteStub:
    """What ``GrpcNetwork.server(frm, to)`` hands the raft node/transport:
    the same duck type as a local raft node, backed by RPCs."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._process = channel.unary_unary(
            f"/{_SVC}/ProcessRaftMessage",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._stream = channel.stream_unary(
            f"/{_SVC}/StreamRaftMessage",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._join = channel.unary_unary(
            f"/{_MEM}/Join",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._leave = channel.unary_unary(
            f"/{_MEM}/Leave",
            request_serializer=_IDENT, response_deserializer=_IDENT)

    async def process_raft_message(self, m: Message) -> None:
        raw = encode_message(m)
        try:
            if len(raw) > _CHUNK:
                async def chunks():
                    for off in range(0, len(raw), _CHUNK):
                        yield raw[off:off + _CHUNK]
                await self._stream(chunks())
            else:
                await self._process(raw)
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e)

    async def join(self, node_id: str, addr: str):
        from swarmkit_tpu.raft.node import JoinResponse, NotLeaderError
        from swarmkit_tpu.raft.membership import Member

        try:
            raw = await self._join(msgpack.packb((node_id, addr)))
        except grpc.aio.AioRpcError as e:
            details = e.details() or ""
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION \
                    and details.startswith("not-leader:"):
                raise NotLeaderError(details.split(":", 1)[1])
            raise _map_rpc_error(e)
        raft_id, members, removed = msgpack.unpackb(raw)
        return JoinResponse(
            raft_id=raft_id,
            members=[Member(raft_id=r, node_id=n, addr=a)
                     for r, n, a in members],
            removed=list(removed))

    async def leave(self, raft_id: int) -> None:
        try:
            await self._leave(msgpack.packb((raft_id,)))
        except grpc.aio.AioRpcError as e:
            raise _map_rpc_error(e)


def _map_rpc_error(e: grpc.aio.AioRpcError) -> Exception:
    if e.code() == grpc.StatusCode.PERMISSION_DENIED \
            and "member removed" in (e.details() or ""):
        return PeerRemoved(e.details())
    return Unreachable(f"rpc failed: {e.code().name}: {e.details()}")


# --------------------------------------------------------------------------
# the Network-shaped seam

class GrpcNetwork:
    """Drop-in for raft.transport.Network over real sockets.

    Addresses are host:port listen addresses.  ``register`` starts a
    grpc.aio server for the node; ``server(frm, to)`` returns a cached
    remote stub.  Reachability is what the sockets say (no fault-injection
    knobs — use the in-process Network for partition tests).
    """

    def __init__(self) -> None:
        self._servers: dict[str, grpc.aio.Server] = {}
        self._channels: dict[str, grpc.aio.Channel] = {}
        self._stubs: dict[str, _RemoteStub] = {}
        self._local: dict[str, Any] = {}
        self._extra_handlers: dict[str, list] = {}
        self.delivered = 0   # counters kept for interface parity
        self.dropped = 0

    def add_service(self, addr: str, handlers: list) -> None:
        """Queue extra generic handlers (dispatcher/CA/control services) to
        serve alongside the raft services once ``register`` runs — gRPC
        servers only accept handlers before start."""
        self._extra_handlers.setdefault(addr, []).extend(handlers)

    def register(self, addr: str, node: Any) -> None:
        # gRPC server startup is async; do it lazily-but-synchronously via
        # the running loop (register is called from async context in
        # node.start)
        self._local[addr] = node
        loop = asyncio.get_event_loop()
        server = grpc.aio.server(options=[
            ("grpc.max_send_message_length", GRPC_MAX_MSG_SIZE),
            ("grpc.max_receive_message_length", GRPC_MAX_MSG_SIZE),
        ])
        for h in _RaftService(node).handlers():
            server.add_generic_rpc_handlers((h,))
        for h in self._extra_handlers.get(addr, ()):
            server.add_generic_rpc_handlers((h,))
        if server.add_insecure_port(addr) == 0:
            raise RuntimeError(f"cannot bind raft listener on {addr}")
        self._servers[addr] = server
        loop.create_task(server.start())

    def unregister(self, addr: str) -> None:
        self._local.pop(addr, None)
        server = self._servers.pop(addr, None)
        if server is not None:
            asyncio.get_event_loop().create_task(server.stop(grace=0.1))

    # -- dialing -----------------------------------------------------------
    def server(self, frm: str, to: str) -> _RemoteStub:
        stub = self._stubs.get(to)
        if stub is None:
            channel = grpc.aio.insecure_channel(to, options=[
                ("grpc.max_send_message_length", GRPC_MAX_MSG_SIZE),
                ("grpc.max_receive_message_length", GRPC_MAX_MSG_SIZE),
            ])
            self._channels[to] = channel
            stub = _RemoteStub(channel)
            self._stubs[to] = stub
        return stub

    # -- reachability (best effort over real sockets) ----------------------
    def reachable(self, frm: str, to: str) -> bool:
        return True   # the RPC itself reports unreachable peers

    def healthy(self, addr: str) -> bool:
        return True

    def lossy(self, frm: str, to: str) -> bool:
        return False

    async def close(self) -> None:
        for ch in self._channels.values():
            await ch.close()
        self._channels = {}
        self._stubs = {}
        for server in self._servers.values():
            await server.stop(grace=0.1)
        self._servers = {}

"""Raft cluster membership registry.

Reference: manager/state/raft/membership/cluster.go — active members, the
permanent blacklist of removed ids (never reused), conf-change validation,
and a broadcast queue that fires whenever the peer list changes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from swarmkit_tpu.raft.messages import ConfChange, ConfChangeType
from swarmkit_tpu.watch.queue import Queue


class MembershipError(Exception):
    pass


ERR_ID_EXISTS = "member with this id already exists"
ERR_ID_REMOVED = "member with this id was removed and can never rejoin"
ERR_ID_NOT_FOUND = "member with this id does not exist"
ERR_CONFIG_CHANGE_INVALID = "configuration change is invalid"


@dataclass
class Member:
    raft_id: int = 0
    node_id: str = ""     # swarm node id (cert CN)
    addr: str = ""


class Cluster:
    """reference: membership.Cluster cluster.go:30."""

    def __init__(self) -> None:
        self.members: dict[int, Member] = {}
        self.removed: set[int] = set()
        self.broadcast = Queue()   # PeersBroadcast (cluster.go:38)

    def is_id_removed(self, raft_id: int) -> bool:
        return raft_id in self.removed

    def get_member(self, raft_id: int) -> Optional[Member]:
        return self.members.get(raft_id)

    def add_member(self, m: Member) -> None:
        if m.raft_id in self.removed:
            raise MembershipError(ERR_ID_REMOVED)
        self.members[m.raft_id] = m
        self.broadcast.publish(tuple(self.members))

    def remove_member(self, raft_id: int) -> None:
        """Remove AND blacklist (cluster.go:114)."""
        self.removed.add(raft_id)
        if raft_id in self.members:
            del self.members[raft_id]
        self.broadcast.publish(tuple(self.members))

    def update_member(self, raft_id: int, addr: str) -> None:
        m = self.members.get(raft_id)
        if m is None:
            raise MembershipError(ERR_ID_NOT_FOUND)
        if m.addr != addr:
            m.addr = addr
            self.broadcast.publish(tuple(self.members))

    def clear(self) -> None:
        self.members = {}
        self.removed = set()

    def validate_configuration_change(self, cc: ConfChange) -> None:
        """reference: ValidateConfigurationChange cluster.go:185."""
        if cc.node_id in self.removed:
            raise MembershipError(ERR_ID_REMOVED)
        if cc.type == ConfChangeType.ADD_NODE:
            if cc.node_id in self.members:
                raise MembershipError(ERR_ID_EXISTS)
        elif cc.type in (ConfChangeType.REMOVE_NODE,
                         ConfChangeType.UPDATE_NODE):
            if cc.node_id not in self.members:
                raise MembershipError(ERR_ID_NOT_FOUND)
        else:
            raise MembershipError(ERR_CONFIG_CHANGE_INVALID)

"""The raft consensus state machine — host-side golden implementation.

Behavioral reference: vendor/github.com/coreos/etcd/raft/raft.go (Step,
stepLeader/stepCandidate/stepFollower, campaign/poll, maybeCommit quorum rule
at raft.go:478-486, becomeFollower/Candidate/Leader, handleAppendEntries,
checkQuorum lease, PreVote, leader transfer) and progress.go (probe/replicate/
snapshot flow control with inflight windows).

This is a from-scratch re-expression in Python: single-threaded, explicitly
clocked (tick() is a pure event — no goroutines, no timers), message-passing
via an outbox list. It is both the consensus core used by the host Node shell
(swarmkit_tpu.raft.node) and the oracle the batched JAX kernel
(swarmkit_tpu.raft.sim) is differential-tested against.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from swarmkit_tpu.raft.log import CompactedError, RaftLog, UnavailableError
from swarmkit_tpu.raft.messages import (
    CAMPAIGN_TRANSFER, NONE, ConfChange, ConfChangeType, Entry, EntryType,
    HardState, Message, MsgType, Snapshot, SnapshotMeta, SoftState,
    vote_resp_type,
)

FOLLOWER = "follower"
CANDIDATE = "candidate"
PRE_CANDIDATE = "pre-candidate"
LEADER = "leader"

# Progress.State (progress.go:12-20)
PROBE = "probe"
REPLICATE = "replicate"
SNAPSHOT = "snapshot"


class Progress:
    """Leader's view of one follower (progress.go)."""

    def __init__(self, next_idx: int, max_inflight: int, match: int = 0):
        self.match = match
        self.next = next_idx
        self.state = PROBE
        self.paused = False
        self.pending_snapshot = 0
        self.recent_active = False
        self.max_inflight = max_inflight
        self.inflights: list[int] = []  # last indexes of inflight appends

    def become_probe(self) -> None:
        if self.state == SNAPSHOT:
            pending = self.pending_snapshot
            self._reset(PROBE)
            self.next = max(self.match + 1, pending + 1)
        else:
            self._reset(PROBE)
            self.next = self.match + 1

    def become_replicate(self) -> None:
        self._reset(REPLICATE)
        self.next = self.match + 1

    def become_snapshot(self, snapshot_index: int) -> None:
        self._reset(SNAPSHOT)
        self.pending_snapshot = snapshot_index

    def _reset(self, state: str) -> None:
        self.paused = False
        self.pending_snapshot = 0
        self.state = state
        self.inflights = []

    def maybe_update(self, n: int) -> bool:
        updated = False
        if self.match < n:
            self.match = n
            updated = True
            self.paused = False
        if self.next < n + 1:
            self.next = n + 1
        return updated

    def optimistic_update(self, n: int) -> None:
        self.next = n + 1

    def maybe_decr_to(self, rejected: int, last: int) -> bool:
        if self.state == REPLICATE:
            if rejected <= self.match:
                return False  # stale rejection
            self.next = self.match + 1
            return True
        if self.next - 1 != rejected:
            return False  # stale
        self.next = max(min(rejected, last + 1), 1)
        self.paused = False
        return True

    def is_paused(self) -> bool:
        if self.state == PROBE:
            return self.paused
        if self.state == REPLICATE:
            return len(self.inflights) >= self.max_inflight
        return True  # SNAPSHOT

    def snapshot_failure(self) -> None:
        self.pending_snapshot = 0

    def need_snapshot_abort(self) -> bool:
        return self.state == SNAPSHOT and self.match >= self.pending_snapshot

    def inflight_add(self, last: int) -> None:
        self.inflights.append(last)

    def inflight_free_to(self, to: int) -> None:
        self.inflights = [i for i in self.inflights if i > to]

    def inflight_free_first(self) -> None:
        if self.inflights:
            self.inflights.pop(0)


@dataclass
class Config:
    id: int = 0
    peers: tuple = ()
    election_tick: int = 10
    heartbeat_tick: int = 1
    max_size_per_msg: int = 64       # entries per append (size proxy)
    max_inflight_msgs: int = 256
    check_quorum: bool = False
    pre_vote: bool = False
    # Deterministic PRNG for randomized election timeouts.
    seed: int = 0


class Raft:
    def __init__(self, cfg: Config, log: Optional[RaftLog] = None,
                 hard_state: Optional[HardState] = None,
                 voters: Optional[Sequence[int]] = None):
        assert cfg.id != NONE
        self.id = cfg.id
        self.cfg = cfg
        self.log = log or RaftLog()
        self.term = 0
        self.vote = NONE
        self.lead = NONE
        self.state = FOLLOWER
        self.prs: dict[int, Progress] = {}
        self.votes: dict[int, bool] = {}
        self.msgs: list[Message] = []
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        # Ticks since last CURRENT-TERM leader contact (append/heartbeat/
        # snapshot) — the CheckQuorum lease measures THIS, not
        # election_elapsed.  etcd-3.1 conflates the two (electionElapsed
        # resets on every campaign attempt, perpetually re-arming the lease
        # after total leader loss and livelocking PreVote elections when
        # randomized timeouts land on election_tick); the raft dissertation
        # (§4.2.3) defines the lease from leader contact.  The reference
        # never enables PreVote so it cannot hit this; we expose PreVote as
        # first-class and fix the lease.
        self.contact_elapsed = 0
        self.randomized_election_timeout = 0
        self.lead_transferee = NONE
        self.pending_conf = False
        # Materialized snapshot (set by the Node shell after each snapshot
        # save) used to catch up followers behind the compaction watermark.
        self.stored_snapshot: Optional[Snapshot] = None
        self._rng = random.Random((cfg.seed << 16) ^ cfg.id)
        self._step_fn: Callable[[Message], None] = self._step_follower

        for pid in (voters if voters is not None else cfg.peers):
            self.prs[pid] = Progress(1, cfg.max_inflight_msgs)
        if hard_state is not None and not hard_state.is_empty():
            self.term = hard_state.term
            self.vote = hard_state.vote
            self.log.commit_to(hard_state.commit)
        self.become_follower(self.term, NONE)

    # -- basic views -------------------------------------------------------
    def quorum(self) -> int:
        return len(self.prs) // 2 + 1

    def hard_state(self) -> HardState:
        return HardState(term=self.term, vote=self.vote, commit=self.log.committed)

    def soft_state(self) -> SoftState:
        return SoftState(lead=self.lead, state=self.state)

    def promotable(self) -> bool:
        return self.id in self.prs

    def voter_ids(self) -> tuple:
        return tuple(sorted(self.prs))

    # -- outbox ------------------------------------------------------------
    def _send(self, m: Message) -> None:
        m.frm = self.id
        if m.type in (MsgType.VOTE, MsgType.VOTE_RESP,
                      MsgType.PRE_VOTE, MsgType.PRE_VOTE_RESP):
            assert m.term != 0, f"{m.type} needs explicit term"
        else:
            assert m.term == 0, f"{m.type} must not set term"
            if m.type != MsgType.PROP:
                m.term = self.term
        self.msgs.append(m)

    # -- ticks -------------------------------------------------------------
    def tick(self) -> None:
        if self.state == LEADER:
            self._tick_heartbeat()
        else:
            self._tick_election()

    def _tick_election(self) -> None:
        self.election_elapsed += 1
        self.contact_elapsed += 1
        if self.promotable() and self.election_elapsed >= self.randomized_election_timeout:
            self.election_elapsed = 0
            self.step(Message(type=MsgType.HUP, frm=self.id))

    def _tick_heartbeat(self) -> None:
        self.heartbeat_elapsed += 1
        self.election_elapsed += 1
        self.contact_elapsed += 1
        if self.election_elapsed >= self.cfg.election_tick:
            self.election_elapsed = 0
            if self.cfg.check_quorum:
                self.step(Message(type=MsgType.CHECK_QUORUM, frm=self.id))
            if self.state == LEADER and self.lead_transferee != NONE:
                self._abort_leader_transfer()
        if self.state != LEADER:
            return
        if self.heartbeat_elapsed >= self.cfg.heartbeat_tick:
            self.heartbeat_elapsed = 0
            self.step(Message(type=MsgType.BEAT, frm=self.id))

    def _reset_randomized_election_timeout(self) -> None:
        self.randomized_election_timeout = (
            self.cfg.election_tick + self._rng.randrange(self.cfg.election_tick))

    # -- role transitions --------------------------------------------------
    def _reset(self, term: int) -> None:
        if self.term != term:
            self.term = term
            self.vote = NONE
        self.lead = NONE
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self._reset_randomized_election_timeout()
        self._abort_leader_transfer()
        self.votes = {}
        for pid in self.prs:
            pr = Progress(self.log.last_index() + 1, self.cfg.max_inflight_msgs)
            if pid == self.id:
                pr.match = self.log.last_index()
            self.prs[pid] = pr
        self.pending_conf = False

    def become_follower(self, term: int, lead: int) -> None:
        self._step_fn = self._step_follower
        self._reset(term)
        self.lead = lead
        self.state = FOLLOWER

    def become_candidate(self) -> None:
        assert self.state != LEADER, "leader -> candidate"
        self._step_fn = self._step_candidate
        self._reset(self.term + 1)
        self.vote = self.id
        self.state = CANDIDATE

    def become_pre_candidate(self) -> None:
        assert self.state != LEADER, "leader -> pre-candidate"
        # Does NOT bump term or change vote.
        self._step_fn = self._step_candidate
        self.votes = {}
        self.state = PRE_CANDIDATE

    def become_leader(self) -> None:
        assert self.state != FOLLOWER, "follower -> leader"
        self._step_fn = self._step_leader
        self._reset(self.term)
        self.lead = self.id
        self.state = LEADER
        self.contact_elapsed = 0
        ents = self.log.entries_from(self.log.committed + 1)
        if sum(1 for e in ents if e.type == EntryType.CONF_CHANGE) == 1:
            self.pending_conf = True
        self._append_entries([Entry(type=EntryType.NORMAL, data=b"")])

    # -- campaign ----------------------------------------------------------
    def _campaign(self, transfer: bool = False, pre: bool = False) -> None:
        if pre:
            self.become_pre_candidate()
            vote_msg = MsgType.PRE_VOTE
            term = self.term + 1
        else:
            self.become_candidate()
            vote_msg = MsgType.VOTE
            term = self.term
        if self._poll(self.id, True) >= self.quorum():
            if pre:
                self._campaign(transfer=transfer)
            else:
                self.become_leader()
            return
        ctx = CAMPAIGN_TRANSFER if transfer else b""
        for pid in self.prs:
            if pid == self.id:
                continue
            self._send(Message(
                type=vote_msg, to=pid, term=term,
                index=self.log.last_index(), log_term=self.log.last_term(),
                context=ctx))

    def _poll(self, pid: int, granted: bool) -> int:
        """Record first response per voter; tally grants over the CURRENT
        configuration (modern etcd counts via the tracker config, so a vote
        from a peer an applied conf change removed is dead weight)."""
        if pid not in self.votes:
            self.votes[pid] = granted
        return sum(1 for p, v in self.votes.items() if v and p in self.prs)

    def _poll_rejections(self) -> int:
        return sum(1 for p, v in self.votes.items()
                   if not v and p in self.prs)

    # -- replication sends -------------------------------------------------
    def _append_entries(self, ents: Sequence[Entry]) -> None:
        li = self.log.last_index()
        stamped = [Entry(index=li + 1 + i, term=self.term, type=e.type,
                         data=e.data) for i, e in enumerate(ents)]
        self.log.append(stamped)
        self.prs[self.id].maybe_update(self.log.last_index())
        self._maybe_commit()

    def _send_append(self, to: int) -> None:
        pr = self.prs[to]
        if pr.is_paused():
            return
        prev = pr.next - 1
        try:
            prev_term = self.log.term(prev)
            ents = self.log.slice(pr.next, self.log.last_index() + 1,
                                  self.cfg.max_size_per_msg)
        except (CompactedError, UnavailableError):
            # Follower is behind the compaction watermark: ship a snapshot.
            if not pr.recent_active:
                return
            # Prefer the materialized snapshot installed by the Node shell
            # (store + membership data at its index); fall back to a bare
            # compaction-point snapshot (etcd MemoryStorage.Snapshot analog).
            snap = self.stored_snapshot
            if snap is None or snap.meta.index < self.log.offset:
                meta = SnapshotMeta(index=self.log.offset,
                                    term=self.log.offset_term,
                                    voters=self.voter_ids())
                snap = Snapshot(meta=meta, data=self._snapshot_data())
            self._send(Message(type=MsgType.SNAP, to=to, snapshot=snap))
            pr.become_snapshot(snap.meta.index)
            return
        m = Message(type=MsgType.APP, to=to, index=prev, log_term=prev_term,
                    entries=tuple(ents), commit=self.log.committed)
        if ents:
            if pr.state == REPLICATE:
                pr.optimistic_update(ents[-1].index)
                pr.inflight_add(ents[-1].index)
            elif pr.state == PROBE:
                pr.paused = True
            else:
                raise AssertionError(f"sending append in state {pr.state}")
        self._send(m)

    def _snapshot_data(self) -> bytes:
        """Hook: Node shell overrides to attach real store snapshot bytes."""
        return b""

    def _bcast_append(self) -> None:
        for pid in self.prs:
            if pid != self.id:
                self._send_append(pid)

    def _bcast_heartbeat(self) -> None:
        for pid in self.prs:
            if pid != self.id:
                commit = min(self.prs[pid].match, self.log.committed)
                self._send(Message(type=MsgType.HEARTBEAT, to=pid, commit=commit))

    def _maybe_commit(self) -> bool:
        matches = sorted((pr.match for pr in self.prs.values()), reverse=True)
        mci = matches[self.quorum() - 1]
        return self.log.maybe_commit(mci, self.term)

    # -- Step --------------------------------------------------------------
    def step(self, m: Message) -> None:
        if m.term == 0:
            pass  # local message
        elif m.term > self.term:
            lead = m.frm
            if m.type in (MsgType.VOTE, MsgType.PRE_VOTE):
                force = m.context == CAMPAIGN_TRANSFER
                in_lease = (self.cfg.check_quorum and self.lead != NONE and
                            self.contact_elapsed < self.cfg.election_tick)
                if not force and in_lease:
                    return  # leader lease not expired; ignore
                lead = NONE
            if m.type == MsgType.PRE_VOTE:
                pass  # never change term for a PreVote request
            elif m.type == MsgType.PRE_VOTE_RESP and not m.reject:
                pass  # term will bump when we win
            else:
                self.become_follower(m.term, lead)
        elif m.term < self.term:
            if self.cfg.check_quorum and m.type in (MsgType.HEARTBEAT, MsgType.APP):
                # Stale leader (or we partitioned and advanced): nudge it.
                self._send(Message(type=MsgType.APP_RESP, to=m.frm))
            return

        if m.type == MsgType.HUP:
            if self.state != LEADER:
                ents = self.log.unapplied_entries()
                if any(e.type == EntryType.CONF_CHANGE for e in ents):
                    return  # pending conf change; cannot campaign
                self._campaign(pre=self.cfg.pre_vote)
            return
        if m.type in (MsgType.VOTE, MsgType.PRE_VOTE):
            can_vote = (self.vote == NONE or m.term > self.term
                        or self.vote == m.frm)
            if can_vote and self.log.is_up_to_date(m.index, m.log_term):
                self._send(Message(type=vote_resp_type(m.type), to=m.frm,
                                   term=m.term))
                if m.type == MsgType.VOTE:
                    self.election_elapsed = 0
                    self.vote = m.frm
            else:
                self._send(Message(type=vote_resp_type(m.type), to=m.frm,
                                   term=self.term, reject=True))
            return
        self._step_fn(m)

    # -- per-role steps ----------------------------------------------------
    def _step_leader(self, m: Message) -> None:
        if m.type == MsgType.BEAT:
            self._bcast_heartbeat()
            return
        if m.type == MsgType.CHECK_QUORUM:
            if not self._check_quorum_active():
                self.become_follower(self.term, NONE)
            else:
                # quorum contact confirmed: the leader's own lease re-arms
                self.contact_elapsed = 0
            return
        if m.type == MsgType.PROP:
            assert m.entries, "empty proposal"
            if self.id not in self.prs:
                raise ProposalDropped("proposer removed from configuration")
            if self.lead_transferee != NONE:
                raise ProposalDropped("leadership transfer in progress")
            ents = list(m.entries)
            for i, e in enumerate(ents):
                if e.type == EntryType.CONF_CHANGE:
                    if self.pending_conf:
                        ents[i] = Entry(type=EntryType.NORMAL, data=b"")
                    else:
                        self.pending_conf = True
            self._append_entries(ents)
            self._bcast_append()
            return

        pr = self.prs.get(m.frm)
        if pr is None:
            return
        if m.type == MsgType.APP_RESP:
            pr.recent_active = True
            if m.reject:
                if pr.maybe_decr_to(m.index, m.reject_hint):
                    if pr.state == REPLICATE:
                        pr.become_probe()
                    self._send_append(m.frm)
            else:
                old_paused = pr.is_paused()
                if pr.maybe_update(m.index):
                    if pr.state == PROBE:
                        pr.become_replicate()
                    elif pr.state == SNAPSHOT and pr.need_snapshot_abort():
                        pr.become_probe()
                    elif pr.state == REPLICATE:
                        pr.inflight_free_to(m.index)
                    if self._maybe_commit():
                        self._bcast_append()
                    elif old_paused:
                        self._send_append(m.frm)
                    if (m.frm == self.lead_transferee
                            and pr.match == self.log.last_index()):
                        self._send(Message(type=MsgType.TIMEOUT_NOW, to=m.frm))
        elif m.type == MsgType.HEARTBEAT_RESP:
            pr.recent_active = True
            pr.paused = False
            if pr.state == REPLICATE and len(pr.inflights) >= pr.max_inflight:
                pr.inflight_free_first()
            if pr.match < self.log.last_index():
                self._send_append(m.frm)
        elif m.type == MsgType.SNAP_STATUS:
            if pr.state != SNAPSHOT:
                return
            if not m.reject:
                pr.become_probe()
            else:
                pr.snapshot_failure()
                pr.become_probe()
            pr.paused = True
        elif m.type == MsgType.UNREACHABLE:
            if pr.state == REPLICATE:
                pr.become_probe()
        elif m.type == MsgType.TRANSFER_LEADER:
            transferee = m.frm
            if self.lead_transferee != NONE:
                if self.lead_transferee == transferee:
                    return
                self._abort_leader_transfer()
            if transferee == self.id:
                return
            self.election_elapsed = 0
            self.lead_transferee = transferee
            if pr.match == self.log.last_index():
                self._send(Message(type=MsgType.TIMEOUT_NOW, to=transferee))
            else:
                self._send_append(transferee)

    def _step_candidate(self, m: Message) -> None:
        my_resp = (MsgType.PRE_VOTE_RESP if self.state == PRE_CANDIDATE
                   else MsgType.VOTE_RESP)
        if m.type == MsgType.PROP:
            raise ProposalDropped(f"no leader at term {self.term}")
        if m.type == MsgType.APP:
            self.become_follower(self.term, m.frm)
            self.contact_elapsed = 0
            self._handle_append(m)
        elif m.type == MsgType.HEARTBEAT:
            self.become_follower(self.term, m.frm)
            self.contact_elapsed = 0
            self._handle_heartbeat(m)
        elif m.type == MsgType.SNAP:
            self.become_follower(m.term, m.frm)
            self.contact_elapsed = 0
            self._handle_snapshot(m)
        elif m.type == my_resp:
            # >= (not etcd's ==): identical decisions in the static-config
            # sequential case (counts rise by 1 per response, checked each
            # time), and well-defined when an applied conf change shrinks
            # the quorum below an already-recorded tally.
            gr = self._poll(m.frm, not m.reject)
            if gr >= self.quorum():
                if self.state == PRE_CANDIDATE:
                    self._campaign()
                else:
                    self.become_leader()
                    self._bcast_append()
            elif self._poll_rejections() >= self.quorum():
                self.become_follower(self.term, NONE)

    def _step_follower(self, m: Message) -> None:
        if m.type == MsgType.PROP:
            if self.lead == NONE:
                raise ProposalDropped(f"no leader at term {self.term}")
            m.to = self.lead
            m.frm = NONE  # will be restamped
            self._send(m)
        elif m.type == MsgType.APP:
            self.election_elapsed = 0
            self.contact_elapsed = 0
            self.lead = m.frm
            self._handle_append(m)
        elif m.type == MsgType.HEARTBEAT:
            self.election_elapsed = 0
            self.contact_elapsed = 0
            self.lead = m.frm
            self._handle_heartbeat(m)
        elif m.type == MsgType.SNAP:
            self.election_elapsed = 0
            self.contact_elapsed = 0
            self.lead = m.frm
            self._handle_snapshot(m)
        elif m.type == MsgType.TRANSFER_LEADER:
            if self.lead == NONE:
                return
            m.to = self.lead
            m.frm = NONE
            self._send(m)
        elif m.type == MsgType.TIMEOUT_NOW:
            if self.promotable():
                # Transfer campaigns skip prevote by design.
                self._campaign(transfer=True)

    # -- message handlers --------------------------------------------------
    def _handle_append(self, m: Message) -> None:
        if m.index < self.log.committed:
            self._send(Message(type=MsgType.APP_RESP, to=m.frm,
                               index=self.log.committed))
            return
        last = self.log.maybe_append(m.index, m.log_term, m.commit, m.entries)
        if last is not None:
            self._send(Message(type=MsgType.APP_RESP, to=m.frm, index=last))
        else:
            self._send(Message(type=MsgType.APP_RESP, to=m.frm, index=m.index,
                               reject=True,
                               reject_hint=self.log.last_index()))

    def _handle_heartbeat(self, m: Message) -> None:
        # Leader sends commit=min(match, committed); clamping to our last
        # index keeps a node that lost state out-of-band (wiped disk) alive —
        # the reference panics here, but the sim prefers graceful re-sync.
        self.log.commit_to(min(m.commit, self.log.last_index()))
        self._send(Message(type=MsgType.HEARTBEAT_RESP, to=m.frm,
                           context=m.context))

    def _handle_snapshot(self, m: Message) -> None:
        meta = m.snapshot.meta
        if self._restore(m.snapshot):
            self._send(Message(type=MsgType.APP_RESP, to=m.frm,
                               index=self.log.last_index()))
        else:
            self._send(Message(type=MsgType.APP_RESP, to=m.frm,
                               index=self.log.committed))

    def _restore(self, snap: Snapshot) -> bool:
        if snap.meta.index <= self.log.committed:
            return False
        if self.log.match_term(snap.meta.index, snap.meta.term):
            # Log already contains the snapshot point: fast-forward commit.
            self.log.commit_to(snap.meta.index)
            return False
        self.log.restore(snap)
        self.prs = {}
        for pid in snap.meta.voters:
            match = self.log.last_index() if pid == self.id else 0
            pr = Progress(self.log.last_index() + 1,
                          self.cfg.max_inflight_msgs, match=match)
            self.prs[pid] = pr
        return True

    # -- checkQuorum -------------------------------------------------------
    def _check_quorum_active(self) -> bool:
        act = 0
        for pid, pr in self.prs.items():
            if pid == self.id:
                act += 1
                continue
            if pr.recent_active:
                act += 1
            pr.recent_active = False
        return act >= self.quorum()

    # -- membership --------------------------------------------------------
    def add_node(self, pid: int) -> None:
        self.pending_conf = False
        if pid in self.prs:
            return
        self.prs[pid] = Progress(self.log.last_index() + 1,
                                 self.cfg.max_inflight_msgs)
        # A new joiner is considered recently active (raft.go addNode).
        self.prs[pid].recent_active = True

    def remove_node(self, pid: int, recheck: bool = True) -> None:
        """`recheck=False` defers the quorum-lowering commit re-check to the
        next commit evaluation (the sim oracle's once-per-tick Phase D —
        same decision one tick later); the Node shell keeps the reference's
        immediate re-check."""
        self.prs.pop(pid, None)
        self.pending_conf = False
        if not self.prs:
            return
        # Removal can lower the quorum size: re-check commit.
        if recheck and self.state == LEADER and self._maybe_commit():
            self._bcast_append()
        if self.state == LEADER and self.lead_transferee == pid:
            self._abort_leader_transfer()

    def _abort_leader_transfer(self) -> None:
        self.lead_transferee = NONE

    def transfer_leadership(self, to: int) -> None:
        self.step(Message(type=MsgType.TRANSFER_LEADER, frm=to, to=self.id))


class ProposalDropped(Exception):
    """Raised when a proposal cannot be accepted right now (no leader, etc.)."""

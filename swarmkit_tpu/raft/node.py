"""The raft Node shell: consensus member with storage, transport, membership
and the store Proposer seam.

Behavioral reference: manager/state/raft/raft.go — Node (:104), NewNode
(:212), Run main loop (:540), ProposeValue (:1588) /
processInternalRaftRequest (:1784), processCommitted (:1889), Join/Leave RPCs
(:920/:1132), ProcessRaftMessage (:1397) with vote-health gating
(:1422-1433), saveToStorage (:1738), restoreFromSnapshot (:743), snapshot
triggering (:677-681), leadership broadcast (:683-689), CanRemoveMember
quorum precheck (:1164-1190), and defaults (DefaultNodeConfig :482,
DefaultRaftConfig :497).

Re-expression: goroutines/channels become one asyncio event loop — a tick
task advances the logical clock (injectable Clock seam, the analog of
NodeOptions.ClockSource raft.go:187), and a run task drains Ready batches:
persist (WAL fsync) → send (Transport) → apply (store / conf changes) →
advance.  All public awaitables run on the same loop, so proposal
registration and commit callbacks need no locking.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from swarmkit_tpu.api.raft_msgs import (
    ClusterMember, ClusterSnapshot, InternalRaftRequest, Snapshot as ApiSnapshot,
    StoreAction,
)
from swarmkit_tpu.raft.membership import Cluster, Member, MembershipError
from swarmkit_tpu.raft.messages import (
    NONE, ConfChange, ConfChangeType, Entry, EntryType, HardState, Message,
    MsgType, Snapshot, SnapshotMeta,
)
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.metrics import trace as obs_trace
from swarmkit_tpu.raft.core import (
    CANDIDATE, Config as RaftConfig, LEADER, PRE_CANDIDATE, ProposalDropped,
)
from swarmkit_tpu.raft.rawnode import RawNode, Ready
from swarmkit_tpu.raft.storage import EncryptedRaftLogger
from swarmkit_tpu.raft.transport import Network, PeerRemoved, Transport
from swarmkit_tpu.raft.wait import Wait
from swarmkit_tpu.raft.wire import decode_conf_change, encode_conf_change
from swarmkit_tpu.utils import metrics
from swarmkit_tpu.store.memory import MemoryStore, Proposer
from swarmkit_tpu.utils.clock import Clock, SystemClock, wait_for
from swarmkit_tpu.watch.queue import Queue

log = logging.getLogger("swarmkit_tpu.raft")

# reference: DefaultRaftConfig raft.go:497
DEFAULT_SNAPSHOT_INTERVAL = 10000
DEFAULT_LOG_ENTRIES_FOR_SLOW_FOLLOWERS = 500
# reference: DefaultNodeConfig raft.go:482
DEFAULT_ELECTION_TICK = 10
DEFAULT_HEARTBEAT_TICK = 1
DEFAULT_TICK_INTERVAL = 1.0  # seconds (raft.go:218)


class ErrNoRaftMember(Exception):
    pass


class ErrLostLeadership(Exception):
    pass


class ErrMemberRemoved(Exception):
    pass


class ErrProposalTooLarge(Exception):
    pass


class ErrCannotRemoveMember(Exception):
    pass


class NotLeaderError(Exception):
    def __init__(self, leader_addr: str = "") -> None:
        super().__init__(f"not the leader (leader at {leader_addr or '?'})")
        self.leader_addr = leader_addr


@dataclass
class JoinResponse:
    raft_id: int
    members: list[Member]
    removed: list[int] = field(default_factory=list)


@dataclass
class LeadershipState:
    is_leader: bool


@dataclass
class NodeOpts:
    """reference: NodeOptions raft.go:169."""

    node_id: str
    addr: str
    network: Network
    state_dir: str
    clock: Optional[Clock] = None
    join_addr: str = ""
    force_new_cluster: bool = False
    tick_interval: float = DEFAULT_TICK_INTERVAL
    election_tick: int = DEFAULT_ELECTION_TICK
    heartbeat_tick: int = DEFAULT_HEARTBEAT_TICK
    snapshot_interval: int = DEFAULT_SNAPSHOT_INTERVAL
    log_entries_for_slow_followers: int = DEFAULT_LOG_ENTRIES_FOR_SLOW_FOLLOWERS
    encrypter: object = None
    decrypter: object = None
    seed: int = 0
    # proposal size cap; reference MaxTransactionBytes enforced raft.go:1809
    max_proposal_bytes: int = int(1.5 * 1024 * 1024)
    # Transport impl selector (the seam from transport.go:26): receives
    # (network, handlers, local_addr, clock). None = in-process Transport;
    # pass swarmkit_tpu.transport.DeviceMeshTransport (with a DeviceMeshNet
    # network) to exchange raft messages through the device mailbox.
    transport_factory: object = None
    # Per-node metric registry; None = the process-global one. In-process
    # multi-node deployments pass one per node so latency percentiles do
    # not mix across members.
    metrics_registry: object = None
    # Typed observability registry (swarmkit_tpu.metrics.MetricsRegistry);
    # None = the process-global default. Same per-node sharing rule as
    # metrics_registry.
    obs_registry: object = None
    # Trace collector (swarmkit_tpu.metrics.Tracer); None = global default.
    tracer: object = None


class Node(Proposer):
    """A full consensus member (reference: raft.Node raft.go:104)."""

    _WEDGE_RETRY_S = 10.0  # cooldown between wedge-triggered transfers

    def __init__(self, opts: NodeOpts) -> None:
        self.opts = opts
        self.clock = opts.clock or SystemClock()
        self.node_id = opts.node_id
        self.addr = opts.addr
        self.raft_id: int = 0

        self.cluster = Cluster()
        self.storage = EncryptedRaftLogger(
            opts.state_dir, encrypter=opts.encrypter, decrypter=opts.decrypter)
        self.metrics = opts.metrics_registry or metrics.REGISTRY
        self.obs = opts.obs_registry or obs_registry.DEFAULT
        self.store = MemoryStore(proposer=None, clock=self.clock.now,
                                 metrics_registry=self.metrics,
                                 obs=self.obs)
        self.transport: Optional[Transport] = None
        self.leadership = Queue()   # publishes LeadershipState
        # awaited with (node_id, addr) before a NEW member's ADD_NODE is
        # proposed; the manager points this at node-record creation
        self.pre_join_hook = None
        # join budget scales with the tick: a slow wire (device mesh on a
        # real chip through the axon tunnel, production 1s ticks) makes
        # the seed's first election take many tick-times, and a joiner
        # must outlast it rather than give up at a wall-clock constant
        self._JOIN_TIMEOUT_S = max(30.0, 600 * opts.tick_interval)

        self._raw: Optional[RawNode] = None
        self._wait = Wait()
        self._wake = asyncio.Event()
        self._stopped = asyncio.Event()
        self._tasks: list[asyncio.Task] = []
        self._rng = random.Random(opts.seed or None)
        self._reqid = itertools.count(1)
        self._run_error: Optional[BaseException] = None
        self._applied = 0
        self._snapshot_index = 0
        self._was_leader = False
        self._removed = False
        self._ticks_until_campaign = 0
        self._wedge_transfer_at = float("-inf")
        # per-peer {"count": consecutive failures, "last_failure": clock ts}
        self._peer_failures: dict[int, dict] = {}
        self.running = False

        self.tracer = opts.tracer or obs_trace.DEFAULT
        self._last_role: Optional[str] = None
        nid = self.node_id
        self._m_elections_started = obs_catalog.get(
            self.obs, "swarm_raft_elections_started_total").labels(node=nid)
        self._m_elections_won = obs_catalog.get(
            self.obs, "swarm_raft_elections_won_total").labels(node=nid)
        self._m_leader_changes = obs_catalog.get(
            self.obs, "swarm_raft_leader_changes_total").labels(node=nid)
        self._m_proposal_latency = obs_catalog.get(
            self.obs, "swarm_raft_proposal_latency_seconds").labels(node=nid)
        self._m_proposals = obs_catalog.get(
            self.obs, "swarm_raft_proposals_total")
        self._m_peer_sends = obs_catalog.get(
            self.obs, "swarm_raft_peer_sends_total")
        self._m_peer_send_failures = obs_catalog.get(
            self.obs, "swarm_raft_peer_send_failures_total")
        obs_catalog.get(self.obs, "swarm_raft_term").labels(
            node=nid).set_function(
            lambda: self._raw.raft.term if self._raw is not None else 0)
        obs_catalog.get(self.obs, "swarm_raft_commit_index").labels(
            node=nid).set_function(
            lambda: self._raw.raft.log.committed
            if self._raw is not None else 0)
        obs_catalog.get(self.obs, "swarm_raft_applied_index").labels(
            node=nid).set_function(lambda: self._applied)
        obs_catalog.get(self.obs, "swarm_raft_is_leader").labels(
            node=nid).set_function(lambda: 1.0 if self.is_leader() else 0.0)

    # ------------------------------------------------------------------
    # lifecycle

    async def start(self) -> None:
        """JoinAndStart + Run (reference: raft.go:375, manager.go:568-588)."""
        opts = self.opts
        self.opts.network.register(self.addr, self)
        cfg_kwargs = dict(
            election_tick=opts.election_tick,
            heartbeat_tick=opts.heartbeat_tick,
            check_quorum=True,
            seed=opts.seed,
        )
        if self.storage.has_existing_state():
            self._load_from_disk(cfg_kwargs)
        elif opts.join_addr:
            await self._join_existing(cfg_kwargs)
        else:
            self._bootstrap_new_cluster(cfg_kwargs)

        factory = opts.transport_factory or Transport
        self.transport = factory(opts.network, self, self.addr, self.clock)
        for m in self.cluster.members.values():
            if m.raft_id != self.raft_id:
                self.transport.add_peer(m.raft_id, m.addr)
        for m in getattr(self, "_seed_peers", []):
            if m.raft_id != self.raft_id:
                self.transport.add_peer(m.raft_id, m.addr)

        self.store.set_proposer(self)
        self.running = True
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._tick_loop()),
                       loop.create_task(self._run())]
        # kick the run loop: replayed committed entries / bootstrap conf
        # change apply without waiting for the first tick
        self._wake.set()
        self._maybe_campaign_bootstrap()

    def _make_raw(self, cfg_kwargs, log=None, hard_state=None, voters=None
                  ) -> RawNode:
        cfg = RaftConfig(id=self.raft_id, **cfg_kwargs)
        return RawNode(cfg, log=log, hard_state=hard_state, voters=voters)

    def _bootstrap_new_cluster(self, cfg_kwargs) -> None:
        """etcd StartNode analog: seed the log with the initial add-self conf
        change at index 1, pre-committed, then campaign once applied."""
        self.raft_id = self._new_raft_id()
        self.storage.bootstrap_new()
        self._raw = self._make_raw(cfg_kwargs)
        cc = ConfChange(id=0, type=ConfChangeType.ADD_NODE,
                        node_id=self.raft_id,
                        context=self._member_context())
        ent = Entry(index=1, term=1, type=EntryType.CONF_CHANGE,
                    data=encode_conf_change(cc))
        r = self._raw.raft
        r.term = 1
        r.log.append([ent])
        r.log.commit_to(1)

    def _member_context(self, node_id: str = "", addr: str = "") -> bytes:
        import msgpack
        return msgpack.packb((node_id or self.node_id, addr or self.addr))

    async def _join_existing(self, cfg_kwargs) -> None:
        """Dial the join address and ask the leader for membership
        (reference: joinCluster raft.go:454)."""
        net = self.opts.network
        target = self.opts.join_addr
        resp: Optional[JoinResponse] = None
        # Keep dialing through transient failures — the seed manager may
        # still be electing itself or mid-restart (reference: joinCluster
        # retries via the connection broker until the context deadline).
        deadline = self.clock.now() + self._JOIN_TIMEOUT_S
        backoff = 0.2
        redirects = 0
        last_err: Optional[Exception] = None
        while resp is None and self.clock.now() < deadline:
            try:
                server = net.server(self.addr, target)
                resp = await server.join(self.node_id, self.addr)
            except NotLeaderError as e:
                last_err = e
                # Follow a few redirects eagerly, then assume an election
                # is bouncing leadership between peers and back off — an
                # unthrottled redirect ping-pong would spin the event loop
                # (and under a fake clock never advance the deadline).
                if e.leader_addr and redirects < 5:
                    redirects += 1
                    target = e.leader_addr
                    continue
                redirects = 0
                target = e.leader_addr or self.opts.join_addr
                await self.clock.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
            except Exception as e:
                # includes dial errors from net.server() itself: the seed
                # manager may be mid-restart with its listener unregistered
                last_err = e
                redirects = 0
                target = self.opts.join_addr
                await self.clock.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
        if resp is None:
            raise RuntimeError(
                f"could not reach the raft leader to join: {last_err}")
        self.raft_id = resp.raft_id
        self.storage.bootstrap_new()
        self._raw = self._make_raw(cfg_kwargs)
        # Transport peers only — membership state arrives via the replicated
        # log / snapshot (conf-change replay), not the join response.
        self._seed_peers = resp.members

    def _load_from_disk(self, cfg_kwargs) -> None:
        """reference: loadAndStart raft/storage.go:63 (+ ForceNewCluster
        storage.go:117-156)."""
        from swarmkit_tpu.raft.log import RaftLog

        boot = self.storage.bootstrap_from_disk()
        voters: tuple = ()
        if boot.snapshot is not None:
            self._apply_snapshot_payload(boot.snapshot, to_raft=False)
            log = RaftLog(snapshot=boot.snapshot)
            log.pending_snapshot = None  # already applied above
            voters = boot.snapshot.meta.voters
            self._snapshot_index = boot.snapshot.meta.index
            self._applied = boot.snapshot.meta.index
        else:
            log = RaftLog()
        if self.raft_id == 0:
            # recover own id: it's in the snapshot membership or the WAL conf
            # changes; scan both.
            for m in self.cluster.members.values():
                if m.node_id == self.node_id:
                    self.raft_id = m.raft_id
            if self.raft_id == 0:
                for e in boot.entries:
                    if e.type == EntryType.CONF_CHANGE:
                        cc = decode_conf_change(e.data)
                        nid, _ = self._decode_member_context(cc.context)
                        if cc.type == ConfChangeType.ADD_NODE \
                                and nid == self.node_id:
                            self.raft_id = cc.node_id
        if self.raft_id == 0:
            raise ErrNoRaftMember("cannot recover raft id from disk state")

        if self.opts.force_new_cluster:
            # Discard other members: keep the store/log data but rewrite
            # membership to exactly this node.
            self.cluster.clear()
            self.cluster.add_member(Member(
                raft_id=self.raft_id, node_id=self.node_id, addr=self.addr))
            voters = (self.raft_id,)
            # strip pending conf changes from the replayed tail
            boot.entries = [
                e if e.type != EntryType.CONF_CHANGE else
                Entry(index=e.index, term=e.term, type=EntryType.NORMAL,
                      data=b"")
                for e in boot.entries]

        if boot.entries:
            log.append(boot.entries)
            log.stabilized(boot.entries[-1].index)
        hs = boot.hard_state
        if hs is not None:
            # clamp against a torn WAL tail
            hs = HardState(term=hs.term, vote=hs.vote,
                           commit=min(hs.commit, log.last_index()))
        self._raw = self._make_raw(cfg_kwargs, log=log, hard_state=hs,
                                   voters=voters)
        if self.opts.force_new_cluster and boot.snapshot is None \
                and self.raft_id not in self._raw.raft.prs:
            self._raw.raft.add_node(self.raft_id)
        if boot.snapshot is not None:
            self._raw.raft.stored_snapshot = boot.snapshot

    @staticmethod
    def _decode_member_context(ctx: bytes) -> tuple[str, str]:
        import msgpack
        try:
            nid, addr = msgpack.unpackb(ctx)
            return nid, addr
        except Exception:
            return "", ""

    def _new_raft_id(self) -> int:
        while True:
            rid = self._rng.getrandbits(63) | 1
            if rid not in self.cluster.members \
                    and rid not in self.cluster.removed:
                return rid

    def _next_req_id(self) -> int:
        """Node-unique proposal/conf-change id: high bits from our raft id,
        low bits a local counter (reference: idutil generator seeded from the
        member id, raft.go:284)."""
        return ((self.raft_id & 0xFFFFFFFF) << 32) \
            | (next(self._reqid) & 0xFFFFFFFF)

    async def stop(self, unregister: bool = True) -> None:
        """reference: Stop/Shutdown raft.go:1239."""
        if self._stopped.is_set():
            return
        self.running = False
        self._wait.cancel_all()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []
        if self.transport is not None:
            self.transport.stop()
        self.storage.close()
        if unregister:
            self.opts.network.unregister(self.addr)
        self._stopped.set()

    # ------------------------------------------------------------------
    # main loops (reference: Run raft.go:540)

    async def _tick_loop(self) -> None:
        ticker = self.clock.ticker(self.opts.tick_interval)
        async for _ in ticker:
            if not self.running:
                return
            self._raw.tick()
            self._wake.set()

    async def _run(self) -> None:
        while self.running:
            await self._wake.wait()
            self._wake.clear()
            try:
                while self._raw.has_ready():
                    rd = self._raw.ready()
                    await self._process_ready(rd)
                    if not self.running:
                        return
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                # A Ready-processing failure (e.g. WAL write error) is fatal
                # for this member: surface it, fail pending proposals, and
                # step out of the cluster rather than wedging silently.
                log.exception("raft node %s: fatal error processing Ready",
                              self.node_id)
                self._run_error = e
                self.running = False
                self._wait.cancel_all()
                return

    async def _process_ready(self, rd: Ready) -> None:
        # 0. wedge watchdog (reference: raft.go:589-606 — a leader whose
        #    store is wedged hands leadership away rather than stalling the
        #    cluster behind a stuck writer). Retries with a cooldown: a
        #    transfer whose random target is down must not latch the
        #    watchdog off while the wedge persists.
        if self.is_leader() and self.store.wedged():
            now = self.clock.now()
            if now - self._wedge_transfer_at > self._WEDGE_RETRY_S:
                self._wedge_transfer_at = now
                if len(self.cluster.members) <= 1:
                    # nowhere to transfer to; surface the stall without a
                    # traceback storm
                    log.error("raft node %s: store wedged >%ss but this is "
                              "the only manager — no transfer possible",
                              self.node_id, self.store.WEDGE_TIMEOUT)
                else:
                    log.error("raft node %s: store wedged >%ss as leader; "
                              "transferring leadership", self.node_id,
                              self.store.WEDGE_TIMEOUT)
                    try:
                        await self.transfer_leadership()
                    except Exception:
                        log.exception(
                            "wedge-triggered leadership transfer failed")

        # 1. persist hard state + entries (WAL fsync) BEFORE sending
        #    (reference: saveToStorage raft.go:1738, called at raft.go:585)
        self.storage.save(rd.hard_state, rd.entries)

        # 2. apply + persist an incoming snapshot (raft.go:618-626)
        if rd.snapshot is not None:
            self._apply_snapshot_payload(rd.snapshot, to_raft=True)
            self.storage.save_snapshot(rd.snapshot, retained_entries=(),
                                       hard_state=rd.hard_state)
            self._snapshot_index = rd.snapshot.meta.index
            self._applied = max(self._applied, rd.snapshot.meta.index)
            self.storage.gc(self._snapshot_index)

        # 3. fan out messages (raft.go:608-613; async, never blocks)
        for m in rd.messages:
            self._m_peer_sends.labels(node=self.node_id,
                                      peer=str(m.to)).inc()
            self.transport.send(m)

        # 4. leadership flips (raft.go:638-664)
        if rd.soft_state is not None:
            role = rd.soft_state.state
            if role != self._last_role:
                campaigning = (CANDIDATE, PRE_CANDIDATE)
                # a pre-vote that graduates to a real vote is ONE campaign
                if role in campaigning \
                        and self._last_role not in campaigning:
                    self._m_elections_started.inc()
                elif role == LEADER:
                    self._m_elections_won.inc()
                self._last_role = role
            is_leader = role == LEADER
            if self._was_leader and not is_leader:
                self._wait.cancel_all()
            if is_leader != self._was_leader:
                self._was_leader = is_leader
                self._m_leader_changes.inc()
                self.leadership.publish(LeadershipState(is_leader=is_leader))

        # 5. apply committed entries (raft.go:667 → processCommitted :1889)
        for e in rd.committed_entries:
            await self._process_committed(e)

        # 6. snapshot trigger (raft.go:677-681)
        if self._applied - self._snapshot_index >= self.opts.snapshot_interval:
            self._do_snapshot()

        applied_conf_change = any(e.type == EntryType.CONF_CHANGE
                                  for e in rd.committed_entries)
        self._raw.advance(rd)
        # The bootstrap/join campaign must be re-attempted AFTER advance:
        # during entry processing the conf change still sits in
        # log.unapplied_entries(), and step(HUP) refuses to campaign over a
        # pending conf change — a check done mid-apply silently no-ops and
        # the node waits out a full election timeout instead.
        if applied_conf_change:
            self._maybe_campaign_bootstrap()
        if self._raw.has_ready():
            self._wake.set()

    async def _process_committed(self, e: Entry) -> None:
        if e.type == EntryType.CONF_CHANGE:
            self._process_conf_change(e)
        elif e.data:
            self._process_entry(e)
        self._applied = max(self._applied, e.index)

    def _process_entry(self, e: Entry) -> None:
        """reference: processEntry raft.go:1906-1913."""
        r = InternalRaftRequest.decode(e.data)
        if not self._wait.trigger(r.id, e.index):
            # not our proposal (or we lost the wait): follower/replay path
            self.store.apply_store_actions(r.actions, e.index)

    def _process_conf_change(self, e: Entry) -> None:
        """reference: processConfChange raft.go:1939 +
        applyAddNode/applyUpdateNode/applyRemoveNode :1953-2024."""
        cc: ConfChange = decode_conf_change(e.data)
        err: Optional[Exception] = None
        try:
            self.cluster.validate_configuration_change(cc)
        except MembershipError as exc:
            err = exc
        if err is None:
            self._raw.apply_conf_change(cc)
            node_id, addr = self._decode_member_context(cc.context)
            if cc.type == ConfChangeType.ADD_NODE:
                self.cluster.add_member(Member(
                    raft_id=cc.node_id, node_id=node_id, addr=addr))
                if cc.node_id != self.raft_id and self.transport is not None:
                    self.transport.add_peer(cc.node_id, addr)
            elif cc.type == ConfChangeType.UPDATE_NODE:
                self.cluster.update_member(cc.node_id, addr)
                if cc.node_id != self.raft_id and self.transport is not None:
                    self.transport.update_peer(cc.node_id, addr)
            elif cc.type == ConfChangeType.REMOVE_NODE:
                if cc.node_id == self.raft_id:
                    # we were removed (raft.go:2005): stop everything
                    self._removed = True
                    self.running = False
                    self.cluster.remove_member(cc.node_id)
                else:
                    self.cluster.remove_member(cc.node_id)
                    if self.transport is not None:
                        self.transport.remove_peer(cc.node_id)
        else:
            self._raw.raft.pending_conf = False
        self._wait.trigger(cc.id, err if err is not None else e.index)
        self._maybe_campaign_bootstrap()

    def _maybe_campaign_bootstrap(self) -> None:
        """Single-member cluster: no one to elect us, so self-elect
        immediately (reference: campaignWhenAble raft.go:383-401)."""
        r = self._raw.raft
        if (len(self.cluster.members) == 1
                and self.raft_id in self.cluster.members
                and r.state != LEADER and r.promotable()):
            self._raw.campaign()
            self._wake.set()

    # ------------------------------------------------------------------
    # snapshots

    def _snapshot_payload(self) -> bytes:
        snap = ApiSnapshot(
            version=self._applied,
            membership=ClusterSnapshot(
                members=[ClusterMember(raft_id=m.raft_id, node_id=m.node_id,
                                       addr=m.addr)
                         for m in self.cluster.members.values()],
                removed=sorted(self.cluster.removed)),
            store=self.store.save())
        return snap.encode()

    def snapshot_now(self) -> None:
        """Force an immediate snapshot (reference: the DEK-rotation path
        triggers one so the log history re-encrypts under the new key and
        old generations become garbage; manager/deks.go MaybeUpdateKEK ->
        TriggerSnapshot)."""
        if self.running and self._raw is not None:
            self._do_snapshot()

    def _do_snapshot(self) -> None:
        """reference: triggerSnapshot raft.go:677 → storage.go:186 (timed
        per storage.go:20-29 snapshot latency)."""
        with metrics.timed(metrics.RAFT_SNAPSHOT_LATENCY,
                           registry=self.metrics):
            self._do_snapshot_timed()

    def _do_snapshot_timed(self) -> None:
        r = self._raw.raft
        index = self._applied
        snap = Snapshot(
            meta=SnapshotMeta(index=index, term=r.log.zero_term(index),
                              voters=r.voter_ids()),
            data=self._snapshot_payload())
        retained = r.log.entries_from(index + 1) if index < r.log.last_index() \
            else []
        self.storage.save_snapshot(snap, retained_entries=retained,
                                   hard_state=r.hard_state())
        r.stored_snapshot = snap
        self._snapshot_index = index
        # keep a tail of entries for slow followers
        # (reference: raftConfig.LogEntriesForSlowFollowers raft.go:500)
        compact_to = index - self.opts.log_entries_for_slow_followers
        if compact_to > r.log.first_index() - 1:
            r.log.compact(compact_to)
        self.storage.gc(index)

    def _apply_snapshot_payload(self, snap: Snapshot, to_raft: bool) -> None:
        """reference: restoreFromSnapshot raft.go:743."""
        if not snap.data:
            return
        payload = ApiSnapshot.decode(snap.data)
        self.store.restore(payload.store, version=payload.version)
        old_members = set(self.cluster.members)
        self.cluster.clear()
        for rid in payload.membership.removed:
            self.cluster.removed.add(rid)
        for m in payload.membership.members:
            self.cluster.add_member(Member(raft_id=m.raft_id,
                                           node_id=m.node_id, addr=m.addr))
            if self.transport is not None and m.raft_id != self.raft_id:
                self.transport.add_peer(m.raft_id, m.addr)
        if self.transport is not None:
            for rid in old_members - set(self.cluster.members):
                if rid != self.raft_id:
                    self.transport.remove_peer(rid)
        if to_raft and self._raw is not None:
            self._raw.raft.stored_snapshot = snap
        self._applied = max(self._applied, snap.meta.index)

    # ------------------------------------------------------------------
    # Proposer seam (reference: ProposeValue raft.go:1588,
    # processInternalRaftRequest :1784)

    async def propose_value(self, actions: list[StoreAction],
                            apply_cb=None, timeout: float = 30.0) -> int:
        if not self.running or self._raw is None:
            raise ErrLostLeadership("node is not running")
        if not self.is_leader():
            raise ErrLostLeadership("this node is not the leader")
        if apply_cb is None:
            # a bare ProposeValue must still apply to OUR store when the
            # entry commits (the follower path won't run: wait.trigger
            # returns True for our own proposals)
            def apply_cb(index, _actions=actions):
                self.store.apply_store_actions(_actions, index)
        r = InternalRaftRequest(id=self._next_req_id(), actions=actions)
        data = r.encode()
        if len(data) > self.opts.max_proposal_bytes:
            raise ErrProposalTooLarge(
                f"proposal is {len(data)} bytes > "
                f"{self.opts.max_proposal_bytes}")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_commit(value):
            if fut.done():
                return
            if isinstance(value, Exception):
                fut.set_exception(value)
                return
            if apply_cb is not None:
                apply_cb(value)
            fut.set_result(value)

        def on_cancel():
            if not fut.done():
                fut.set_exception(ErrLostLeadership("leadership lost"))

        self._wait.register(r.id, on_commit, on_cancel)
        try:
            self._raw.propose(data)
        except ProposalDropped:
            self._wait.trigger(r.id, ErrLostLeadership("proposal dropped"))
        self._wake.set()
        # reference: proposeLatencyTimer wraps exactly this wait
        # (raft.go:69-71, observed at :1589)
        with self.tracer.span("raft.propose", node=self.node_id,
                              req_id=r.id, actions=len(actions)) as sp:
            t0 = time.perf_counter()
            try:
                with metrics.timed(metrics.RAFT_PROPOSE_LATENCY,
                                   registry=self.metrics):
                    index = await self._await_with_timeout(fut, timeout, r.id)
            except BaseException:
                self._m_proposals.labels(node=self.node_id,
                                         result="error").inc()
                raise
            finally:
                self._m_proposal_latency.observe(time.perf_counter() - t0)
            sp.set(index=index)
            self._m_proposals.labels(node=self.node_id, result="ok").inc()
            return index

    async def _await_with_timeout(self, fut: asyncio.Future, timeout: float,
                                  wait_id: Optional[int] = None):
        sleeper = asyncio.get_running_loop().create_task(
            self.clock.sleep(timeout))
        try:
            done, _ = await asyncio.wait(
                {fut, sleeper}, return_when=asyncio.FIRST_COMPLETED)
            if fut in done:
                return fut.result()
            if fut.done():  # resolved in the same loop step as the sleeper
                return fut.result()
            if wait_id is not None:
                self._wait.forget(wait_id)
            raise TimeoutError("proposal timed out")
        finally:
            sleeper.cancel()
            if not fut.done():
                fut.cancel()

    def get_version(self) -> int:
        return self._applied

    def changes_between(self, frm: int, to: int):
        """reference: ChangesBetween raft.go (store WatchFrom catch-up)."""
        out = []
        log = self._raw.raft.log
        for e in log.slice(frm + 1, to + 1):
            if e.type == EntryType.NORMAL and e.data:
                r = InternalRaftRequest.decode(e.data)
                out.append((e.index, r.actions))
        return out

    # ------------------------------------------------------------------
    # membership RPCs (server side; reference: Join raft.go:920,
    # Leave :1132)

    async def join(self, node_id: str, addr: str) -> JoinResponse:
        if not self.running:
            raise ErrNoRaftMember("node not running")
        if not self.is_leader():
            raise NotLeaderError(self.leader_addr())
        # re-join of a known node at a (possibly new) address
        for m in self.cluster.members.values():
            if m.node_id == node_id:
                if m.addr != addr:
                    await self._configure(ConfChange(
                        type=ConfChangeType.UPDATE_NODE, node_id=m.raft_id,
                        context=self._member_context(node_id, addr)))
                return JoinResponse(raft_id=m.raft_id,
                                    members=self._member_list(),
                                    removed=sorted(self.cluster.removed))
        if not self.opts.network.healthy(addr):
            raise RuntimeError(f"joiner at {addr} failed health check "
                               "(reference: raft.go:986)")
        # Create the joiner's node record BEFORE the member exists (set by
        # the manager; reference parity: ca/server.go IssueNodeCertificate
        # creates the record before the manager ever joins raft).  Without
        # this ordering the role manager can observe a member with no
        # record and reap it as an orphan mid-join.
        if self.pre_join_hook is not None:
            await self.pre_join_hook(node_id, addr)
        raft_id = self._new_raft_id()
        await self._configure(ConfChange(
            type=ConfChangeType.ADD_NODE, node_id=raft_id,
            context=self._member_context(node_id, addr)))
        return JoinResponse(raft_id=raft_id, members=self._member_list(),
                            removed=sorted(self.cluster.removed))

    async def leave(self, raft_id: int) -> None:
        if not self.is_leader():
            raise NotLeaderError(self.leader_addr())
        await self.remove_member(raft_id)

    async def remove_member(self, raft_id: int) -> None:
        """reference: RemoveMember raft.go:1206 + CanRemoveMember :1164."""
        if not self.can_remove_member(raft_id):
            raise ErrCannotRemoveMember(
                "removing this member would break quorum among reachable "
                "members")
        await self._configure(ConfChange(
            type=ConfChangeType.REMOVE_NODE, node_id=raft_id))

    def can_remove_member(self, raft_id: int) -> bool:
        """Quorum precheck among remaining reachable members
        (reference: raft.go:1164-1190)."""
        remaining = [m for rid, m in self.cluster.members.items()
                     if rid != raft_id]
        if not remaining:
            return False
        reachable = 0
        for m in remaining:
            if m.raft_id == self.raft_id \
                    or self.opts.network.reachable(self.addr, m.addr):
                reachable += 1
        return reachable >= len(remaining) // 2 + 1

    async def _configure(self, cc: ConfChange, timeout: float = 30.0) -> None:
        """Propose a conf change and wait for it to apply
        (reference: configure raft.go:1848)."""
        cc = ConfChange(id=self._next_req_id(), type=cc.type,
                        node_id=cc.node_id, context=cc.context)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_commit(value):
            if fut.done():
                return
            if isinstance(value, Exception):
                fut.set_exception(value)
            else:
                fut.set_result(value)

        def on_cancel():
            if not fut.done():
                fut.set_exception(ErrLostLeadership("leadership lost"))

        self._wait.register(cc.id, on_commit, on_cancel)
        try:
            self._raw.propose_conf_change(cc)
        except ProposalDropped:
            self._wait.trigger(
                cc.id, ErrLostLeadership("conf change proposal dropped"))
        self._wake.set()
        await self._await_with_timeout(fut, timeout, cc.id)

    def _member_list(self) -> list[Member]:
        return [Member(raft_id=m.raft_id, node_id=m.node_id, addr=m.addr)
                for m in self.cluster.members.values()]

    # ------------------------------------------------------------------
    # transport server side (registered on the Network at self.addr)

    async def process_raft_message(self, m: Message) -> None:
        """reference: ProcessRaftMessage raft.go:1397."""
        if not self.running or self._raw is None:
            raise ErrNoRaftMember("node not running")
        if m.frm != NONE and self.cluster.is_id_removed(m.frm):
            raise PeerRemoved("sender was removed from the cluster")
        # vote-health gating (swarmkit addition, raft.go:1422-1433): reject
        # votes from members we cannot reach, so flapping nodes don't
        # destabilize a healthy leader.
        if m.type in (MsgType.VOTE, MsgType.PRE_VOTE):
            sender = self.cluster.get_member(m.frm)
            if sender is not None and not self.opts.network.reachable(
                    self.addr, sender.addr):
                return
        self._raw.step(m)
        self._wake.set()

    # Raft callback interface for the Transport
    # (reference: transport.Raft transport.go:26)
    def report_unreachable(self, raft_id: int, failures: int = 1) -> None:
        """`failures` is the transport's consecutive-failure count for the
        peer (drives its redial backoff); tracked here so operators see
        which peers are flapping via status().  A count of 0 signals
        recovery — the first successful delivery after a failure streak."""
        if failures <= 0:
            self._peer_failures.pop(raft_id, None)
            return
        self._peer_failures[raft_id] = {"count": failures,
                                        "last_failure": self.clock.now()}
        self._m_peer_send_failures.labels(node=self.node_id,
                                          peer=str(raft_id)).inc()
        if self._raw is not None and self.running:
            self._raw.report_unreachable(raft_id)
            self._wake.set()

    def report_snapshot(self, raft_id: int, ok: bool) -> None:
        if self._raw is not None and self.running:
            self._raw.report_snapshot(raft_id, ok)
            self._wake.set()

    def is_id_removed(self, raft_id: int) -> bool:
        return self.cluster.is_id_removed(raft_id)

    def update_node(self, raft_id: int, addr: str) -> None:
        pass  # address updates flow through conf changes in this build

    def node_removed(self) -> None:
        """A peer told us we were removed (reference: raft.go:1454)."""
        self._removed = True
        self.running = False

    # ------------------------------------------------------------------
    # views / helpers

    def is_leader(self) -> bool:
        return (self._raw is not None
                and self._raw.raft.state == LEADER)

    def leader_id(self) -> int:
        return self._raw.raft.lead if self._raw is not None else NONE

    def leader_addr(self) -> str:
        m = self.cluster.get_member(self.leader_id())
        return m.addr if m is not None else ""

    def is_member(self) -> bool:
        return self._raw is not None and self._raw.raft.promotable()

    @property
    def removed(self) -> bool:
        return self._removed

    def status(self) -> dict:
        st = self._raw.status() if self._raw is not None else {}
        st["members"] = {rid: m.addr for rid, m in self.cluster.members.items()}
        st["removed"] = sorted(self.cluster.removed)
        st["applied_index"] = self._applied
        st["snapshot_index"] = self._snapshot_index
        st["peer_failures"] = {rid: dict(info) for rid, info in
                               self._peer_failures.items()
                               if info["count"] > 0}
        return st

    def subscribe_leadership(self):
        """reference: SubscribeLeadership raft.go:2035."""
        return self.leadership.watch()

    async def transfer_leadership(self, to: int = NONE) -> None:
        """reference: TransferLeadership raft.go:1222 — the target is the
        most caught-up reachable member (transferee.Match maximal), so the
        TIMEOUT_NOW shortcut fires and the transfer cannot stall behind a
        lagging or partitioned follower."""
        if to == NONE:
            candidates = [rid for rid in self.cluster.members
                          if rid != self.raft_id]
            if not candidates:
                raise ErrCannotRemoveMember("no transfer target")
            prs = self._raw.raft.prs if self._raw is not None else {}
            to = max(candidates, key=lambda rid: (
                (pr := prs.get(rid)) is not None and pr.recent_active,
                pr.match if pr is not None else -1,
                self._rng.random()))
        self._raw.transfer_leadership(to)
        self._wake.set()

    async def wait_for_leader(self, timeout: float = 10.0) -> int:
        await wait_for(lambda: self.leader_id() != NONE, clock=self.clock,
                       timeout=timeout)
        return self.leader_id()

    async def propose_and_wait_applied(self, actions, timeout: float = 30.0
                                       ) -> int:
        return await self.propose_value(actions, timeout=timeout)

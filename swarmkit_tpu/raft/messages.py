"""Raft protocol message and log-entry types.

Behavioral reference: /root/reference/vendor/github.com/coreos/etcd/raft/raftpb
(raft.pb.go message/entry enums) — re-expressed as Python dataclasses. These are
the host-side golden types; the device sim packs the same information into
fixed-width arrays (swarmkit_tpu.raft.sim).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

NONE = 0  # "no node" sentinel (etcd raft.None)


class EntryType(enum.IntEnum):
    NORMAL = 0
    CONF_CHANGE = 1


@dataclass(frozen=True)
class Entry:
    index: int = 0
    term: int = 0
    type: EntryType = EntryType.NORMAL
    data: bytes = b""


class ConfChangeType(enum.IntEnum):
    ADD_NODE = 0
    REMOVE_NODE = 1
    UPDATE_NODE = 2


@dataclass(frozen=True)
class ConfChange:
    id: int = 0
    type: ConfChangeType = ConfChangeType.ADD_NODE
    node_id: int = 0
    context: bytes = b""


class MsgType(enum.IntEnum):
    HUP = 0            # local: start election
    BEAT = 1           # local: leader heartbeat timer fired
    PROP = 2           # propose entries
    APP = 3            # append entries
    APP_RESP = 4
    VOTE = 5
    VOTE_RESP = 6
    SNAP = 7
    HEARTBEAT = 8
    HEARTBEAT_RESP = 9
    UNREACHABLE = 10   # local report: peer unreachable
    SNAP_STATUS = 11   # local report: snapshot send finished/failed
    CHECK_QUORUM = 12  # local: leader lease check
    TRANSFER_LEADER = 13
    TIMEOUT_NOW = 14
    PRE_VOTE = 15
    PRE_VOTE_RESP = 16


LOCAL_MSGS = {MsgType.HUP, MsgType.BEAT, MsgType.UNREACHABLE,
              MsgType.SNAP_STATUS, MsgType.CHECK_QUORUM}

# Context marker for leadership-transfer campaigns (etcd campaignTransfer).
CAMPAIGN_TRANSFER = b"CampaignTransfer"


@dataclass(frozen=True)
class SnapshotMeta:
    index: int = 0
    term: int = 0
    voters: tuple = ()  # member ids in the config at snapshot time


@dataclass(frozen=True)
class Snapshot:
    meta: SnapshotMeta = field(default_factory=SnapshotMeta)
    data: bytes = b""

    @property
    def empty(self) -> bool:
        return self.meta.index == 0


@dataclass
class Message:
    type: MsgType = MsgType.HUP
    to: int = NONE
    frm: int = NONE
    term: int = 0        # 0 => local message
    log_term: int = 0
    index: int = 0
    entries: tuple = ()
    commit: int = 0
    reject: bool = False
    reject_hint: int = 0
    snapshot: Optional[Snapshot] = None
    context: bytes = b""


@dataclass
class HardState:
    """Durable state that must hit the WAL before messages are sent."""

    term: int = 0
    vote: int = NONE
    commit: int = 0

    def is_empty(self) -> bool:
        return self.term == 0 and self.vote == NONE and self.commit == 0


@dataclass
class SoftState:
    lead: int = NONE
    state: str = "follower"  # follower | candidate | pre-candidate | leader


def vote_resp_type(t: MsgType) -> MsgType:
    return MsgType.VOTE_RESP if t == MsgType.VOTE else MsgType.PRE_VOTE_RESP

"""On-device telemetry plane: tick-resolution latency histograms and a
strided time-series ring, aggregated where the data lives.

Device side (series.py): the bucket ladder / series enum and the
jittable fold + ring ops the kernel's end-of-tick telemetry block uses
when ``SimConfig.collect_telemetry`` is on.  Host side (obs.py): the
TelemetryObs publisher, the ring decoder, and the JSON summary that DST
artifacts and bench lines attach.
"""

from .obs import TelemetryObs, decode_series, percentile_edge, summarize_state
from .series import (GAUGE_ROWS, LATENCY_BUCKET_EDGES, NUM_BUCKETS,
                     NUM_SERIES, SERIES_COMMIT_RATE, SERIES_LEADER_CHANGES,
                     SERIES_LOG_OCCUPANCY, SERIES_NAMES, SERIES_READS_BLOCKED,
                     bucket_of, hist_fold, percentile_edge_device, ring_write)

__all__ = [
    "TelemetryObs", "decode_series", "percentile_edge", "summarize_state",
    "GAUGE_ROWS", "LATENCY_BUCKET_EDGES", "NUM_BUCKETS", "NUM_SERIES",
    "SERIES_COMMIT_RATE", "SERIES_LEADER_CHANGES", "SERIES_LOG_OCCUPANCY",
    "SERIES_NAMES", "SERIES_READS_BLOCKED",
    "bucket_of", "hist_fold", "percentile_edge_device", "ring_write",
]

"""Device-side telemetry vocabulary and array ops.

This module is the single ground truth for the on-device telemetry
plane's layout: the fixed latency-bucket ladder, the time-series row
indices of ``SimState.tel_series``, and the jittable fold/ring ops the
kernel's end-of-tick telemetry block calls.  The host scrape schema
(metrics/catalog.py ``swarm_telemetry_*`` specs) mirrors the ladder and
the series names; tools/metrics_lint.py check #6 keeps the two in
lockstep the same way check #5 pins flightrec/codes.py to the events
counter.

Everything here is tick-unit integer math: latencies are measured in
simulated ticks (the only clock the kernel has), so the histograms are
exact counters — p50/p99 read off them are true percentiles up to bucket
resolution, with zero host traffic during the run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32

# Fixed histogram bucket UPPER edges, in ticks: a latency of t lands in
# the first bucket with t <= edge; the extra last counter is overflow
# (> 256 ticks).  Power-of-two ladder because the interesting spans are
# log-spread: steady-state propose->commit is 0-1 ticks on the instant
# wire and ~2*(latency+jitter) on the mailbox wire, elections take
# [election_tick, 2*election_tick) plus collision retries.
LATENCY_BUCKET_EDGES = (1, 2, 4, 8, 16, 32, 64, 128, 256)
NUM_BUCKETS = len(LATENCY_BUCKET_EDGES) + 1          # + overflow

# Row indices of SimState.tel_series [NUM_SERIES, telemetry_window].
SERIES_COMMIT_RATE = 0      # committed entries per stride bucket (sum)
SERIES_LEADER_CHANGES = 1   # election wins per stride bucket (sum)
SERIES_LOG_OCCUPANCY = 2    # sum over rows of last - snap_idx (gauge)
SERIES_READS_BLOCKED = 3    # read ops refused per stride bucket (sum)
NUM_SERIES = 4

# Scrape-side names, index -> name (the lint pins these to the catalog's
# swarm_telemetry_series_value label space and to the constants above).
SERIES_NAMES = {
    SERIES_COMMIT_RATE: "commit_rate",
    SERIES_LEADER_CHANGES: "leader_changes",
    SERIES_LOG_OCCUPANCY: "log_occupancy",
    SERIES_READS_BLOCKED: "reads_blocked",
}

# Gauge-mode rows OVERWRITE within a stride bucket (last tick wins);
# counter-mode rows accumulate ticks into the bucket.
GAUGE_ROWS = (SERIES_LOG_OCCUPANCY,)

# Propose-batch ring depth of SimState.tel_prop_* [N, PROP_RING]: slot
# t % PROP_RING holds the (first idx, count, tick) of the batch a leader
# appended at tick t.  Batches uncommitted after PROP_RING ticks age out
# of measurement — 2x the histogram's overflow edge, so every latency
# the bucket ladder can distinguish is covered.
PROP_RING = 512


def col_set(ring: jnp.ndarray, col: jnp.ndarray,
            vals: jnp.ndarray) -> jnp.ndarray:
    """Overwrite ring[:, col] with vals [N] via dynamic_update_slice.

    `.at[:, col].set` with a traced column index lowers to a scatter,
    which XLA:CPU executes element-at-a-time (the same serialization the
    log-axis scatter-add hit); an [N, 1] slice update is a plain strided
    store.
    """
    return jax.lax.dynamic_update_slice(
        ring, vals[:, None], (jnp.asarray(0, I32), col.astype(I32)))


def bucket_of(lat: jnp.ndarray) -> jnp.ndarray:
    """Bucket index (0..NUM_BUCKETS-1) of tick-latency `lat` (any shape)."""
    edges = jnp.asarray(LATENCY_BUCKET_EDGES, I32)
    return jnp.sum((lat[..., None] > edges).astype(I32), axis=-1)


def hist_fold(hist: jnp.ndarray, mask: jnp.ndarray, lat: jnp.ndarray,
              weight: jnp.ndarray | None = None) -> jnp.ndarray:
    """Fold masked latencies into a [NUM_BUCKETS] counter vector; each
    masked element contributes `weight` samples (1 when None — a batch of
    entries sharing one propose tick folds as one weighted element).

    SCATTER-FREE: one masked exceed-count reduction per bucket edge (9
    dense passes over the operand), then bucket counts by differencing —
    equivalent to scatter-adding bucket_of(lat) but lowered entirely to
    vector reductions (a large flattened scatter-add serializes per
    element; measured 19x slower on the n=256 bench shape on CPU).
    Latencies of masked-out elements never contribute (garbage from
    unstamped slots included).
    """
    m = mask.ravel()
    w = m.astype(I32) if weight is None else jnp.where(m, weight.ravel(), 0)
    lv = lat.ravel()
    total = jnp.sum(w)
    exceed = jnp.stack([jnp.sum(jnp.where(lv > e, w, 0))
                        for e in LATENCY_BUCKET_EDGES])
    zero = jnp.zeros((1,), I32)
    counts = (jnp.concatenate([total[None], exceed])
              - jnp.concatenate([exceed, zero]))
    return hist + counts


def ring_write(series: jnp.ndarray, stride: int, now: jnp.ndarray,
               vals: jnp.ndarray) -> jnp.ndarray:
    """Write this tick's [NUM_SERIES] sample into the strided ring.

    Column of tick t is (t // stride) % window; the first tick of a
    stride bucket resets the column (overwriting the sample from one
    window-lap ago), later ticks accumulate (counter rows) or overwrite
    (gauge rows).  The decoder (telemetry/obs.py decode_series)
    reconstructs each column's absolute bucket from the final tick.
    """
    col = (now // stride) % series.shape[-1]
    fresh = (now % stride) == 0
    base = jnp.where(fresh, 0, series[:, col])
    gauge = jnp.asarray([i in GAUGE_ROWS for i in range(NUM_SERIES)])
    return col_set(series, col, jnp.where(gauge, vals, base + vals))


def percentile_edge_device(hist: jnp.ndarray, q: int) -> jnp.ndarray:
    """Upper edge (ticks) of the q-th percentile bucket, on device.

    q is an integer percent.  The overflow bucket reads as int32 max so
    any finite SLO bound trips on it.  On an empty histogram the result
    is the first edge — callers gate on sum(hist) > 0 (the SLO oracle in
    dst/invariants.py does).
    """
    total = jnp.sum(hist)
    k = jnp.maximum((q * total + 99) // 100, 1)      # ceil(q% of total)
    b = jnp.argmax(jnp.cumsum(hist) >= k).astype(I32)
    edges_ext = jnp.asarray(
        LATENCY_BUCKET_EDGES + (jnp.iinfo(jnp.int32).max,), I32)
    return edges_ext[b]

"""Host-side telemetry scrape: device counters -> registry + JSON.

Mirrors KernelObs (raft/sim/run.py) for the telemetry plane: pull the
tiny aggregate arrays off device once, publish them into catalog-declared
families, and hand back a JSON-able summary for bench lines and DST
artifacts.  Histogram publishing goes through the shared per-registry
delta seam (metrics/scrape.py), so repeated scrapes of the same state —
or scrapes from several publisher instances into one registry — add each
device observation exactly once.
"""

from __future__ import annotations

import numpy as np

from swarmkit_tpu.metrics import catalog, scrape
from swarmkit_tpu.metrics.registry import MetricsRegistry, default_registry

from . import series as tseries

# registry family name -> SimState field carrying its device counters
_HIST_FIELDS = {
    "swarm_telemetry_commit_latency_ticks": "tel_commit_hist",
    "swarm_telemetry_election_ticks": "tel_elect_hist",
    "swarm_telemetry_read_latency_ticks": "tel_read_hist",
}
_SERIES_GAUGE = "swarm_telemetry_series_value"


def percentile_edge(counts, q: int):
    """Host-side bucket-edge percentile over a [NUM_BUCKETS] count list.

    Returns the upper edge (ticks) of the bucket containing the q-th
    percentile observation, None when the histogram is empty.  Overflow
    clamps to the largest finite edge (JSON has no Inf); report the
    overflow count separately when it matters.
    """
    counts = [int(c) for c in counts]
    total = sum(counts)
    if total == 0:
        return None
    k = max(1, -(-q * total // 100))        # ceil(q% of total)
    running = 0
    for i, c in enumerate(counts):
        running += c
        if running >= k:
            edges = tseries.LATENCY_BUCKET_EDGES
            return edges[min(i, len(edges) - 1)]
    return tseries.LATENCY_BUCKET_EDGES[-1]


def decode_series(state, cfg) -> dict:
    """Unroll the strided ring into {series_name: [(tick, value), ...]}.

    The ring holds one column per stride bucket; the bucket a column
    currently belongs to is recovered from the final tick: the newest
    bucket is b_now = (tick-1) // stride, and column s holds the most
    recent bucket congruent to s mod window.  Columns from before tick 0
    (first window lap still filling) are skipped.
    """
    ring = np.asarray(state.tel_series)
    stride, window = cfg.telemetry_stride, cfg.telemetry_window
    now = int(state.tick) - 1                 # last tick the kernel ran
    if now < 0:
        return {name: [] for name in tseries.SERIES_NAMES.values()}
    b_now = now // stride
    points = []                               # (tick, column)
    for s in range(window):
        b = b_now - ((b_now - s) % window)
        if b >= 0:
            points.append((b * stride, s))
    points.sort()
    return {name: [(t, int(ring[idx, s])) for t, s in points]
            for idx, name in tseries.SERIES_NAMES.items()}


def summarize_state(state, cfg) -> dict:
    """JSON-able snapshot of the telemetry plane in `state`."""
    if getattr(state, "tel_commit_hist", None) is None:
        return {"enabled": False}
    out = {"enabled": True,
           "buckets": list(tseries.LATENCY_BUCKET_EDGES)}
    for short, field in (("commit", "tel_commit_hist"),
                         ("election", "tel_elect_hist"),
                         ("read", "tel_read_hist")):
        counts = [int(c) for c in np.asarray(getattr(state, field))]
        out[short] = {
            "counts": counts,
            "total": sum(counts),
            "overflow": counts[-1],
            "p50": percentile_edge(counts, 50),
            "p99": percentile_edge(counts, 99),
        }
    ser = decode_series(state, cfg)
    out["series_last"] = {name: (pts[-1][1] if pts else None)
                          for name, pts in ser.items()}
    return out


def summarize_groups(gstate, cfg) -> list:
    """Per-group ``summarize_state`` over a [G, N, ...] grouped state.

    One device_get of the whole tree, then host-side slicing: group g's
    summary is exactly what a solo run of that group would report (the
    grouped kernel folds each lane independently — pinned by
    tests/test_multiraft.py::TestGroupedTelemetry).  Returns
    ``[{"enabled": False}] * G`` when telemetry is off.
    """
    import jax

    groups = int(gstate.tick.shape[0])
    if getattr(gstate, "tel_commit_hist", None) is None:
        return [{"enabled": False} for _ in range(groups)]
    host = jax.tree_util.tree_map(np.asarray, jax.device_get(gstate))
    return [summarize_state(
        jax.tree_util.tree_map(lambda a, g=g: a[g], host), cfg)
        for g in range(groups)]


class TelemetryObs:
    """Publishes a telemetry-enabled SimState into a metrics registry."""

    def __init__(self, registry: MetricsRegistry | None = None) -> None:
        self.obs = registry or default_registry()
        self._deltas = scrape.deltas_for(self.obs)

    def publish(self, state, cfg) -> dict:
        """Scrape `state` into the registry; returns summarize_state()."""
        summary = summarize_state(state, cfg)
        if not summary["enabled"]:
            return summary
        for name, field in _HIST_FIELDS.items():
            fam = catalog.get(self.obs, name)
            counts = [int(c) for c in np.asarray(getattr(state, field))]
            for i, c in enumerate(counts):
                d = self._deltas.advance((name, i), c)
                if d:
                    fam.observe_bucket(i, d)
        fam = catalog.get(self.obs, _SERIES_GAUGE)
        for sname, last in summary["series_last"].items():
            if last is not None:
                fam.labels(series=sname).set(last)
        return summary

"""Go-template-style expansion in container specs.

Reference: template/ (513 LoC) — expands ``{{.Service.Name}}``,
``{{.Task.Slot}}``, ``{{.Node.Hostname}}`` … in env vars, hostname and
mount sources of a task's container spec, with the per-task context built
from the task + node objects (template/context.go NewContext).
"""

from __future__ import annotations

import re
from typing import Optional

_VAR_RE = re.compile(r"\{\{\s*\.([A-Za-z.]+)\s*\}\}")


class TemplateError(Exception):
    pass


def task_context(task, node=None) -> dict[str, str]:
    """reference: template/context.go Context fields."""
    service_name = task.service_annotations.name
    slot = str(task.slot) if task.slot else task.node_id
    ctx = {
        "Service.ID": task.service_id,
        "Service.Name": service_name,
        "Task.ID": task.id,
        "Task.Name": f"{service_name}.{slot}.{task.id}" if service_name
                     else task.id,
        "Task.Slot": str(task.slot),
    }
    for k, v in task.service_annotations.labels.items():
        ctx[f"Service.Labels.{k}"] = v
    if node is not None:
        ctx["Node.ID"] = node.id
        hostname = node.description.hostname if node.description else ""
        ctx["Node.Hostname"] = hostname
        plat = node.description.platform if node.description else None
        ctx["Node.Platform.OS"] = plat.os if plat else ""
        ctx["Node.Platform.Architecture"] = plat.architecture if plat else ""
    return ctx


def expand(text: str, ctx: dict[str, str]) -> str:
    def sub(m: re.Match) -> str:
        key = m.group(1)
        if key not in ctx:
            raise TemplateError(f"unknown template variable .{key}")
        return ctx[key]

    return _VAR_RE.sub(sub, text)


def expand_secret_spec(secret, task, node=None):
    """Per-task expansion of a templated secret/config PAYLOAD
    (reference: template/expand.go:132 ExpandSecretSpec,
    template/getter.go templatedSecretGetter).  No templating driver ->
    returned unchanged; expansion errors raise TemplateError so the task
    is rejected rather than fed a half-expanded payload."""
    if getattr(secret.spec, "templating", None) is None:
        return secret
    ctx = task_context(task, node)
    out = secret.copy()
    try:
        text = secret.spec.data.decode("utf-8")
    except UnicodeDecodeError:
        # a binary payload with templating enabled is a spec error, not a
        # crash: surface the documented TemplateError so the task FSM
        # rejects the task cleanly
        name = getattr(secret.spec.annotations, "name", "") or secret.id
        raise TemplateError(
            f"templated payload of {name} is not valid UTF-8")
    out.spec.data = expand(text, ctx).encode("utf-8")
    return out


def expand_container_spec(task, node=None):
    """Return a task copy with its container spec expanded
    (reference: template/expand.go ExpandContainerSpec)."""
    if task.spec.container is None:
        return task
    ctx = task_context(task, node)
    t = task.copy()
    c = t.spec.container
    c.env = [expand(e, ctx) for e in c.env]
    if c.hostname:
        c.hostname = expand(c.hostname, ctx)
    for m in c.mounts:
        # reference template/expand.go:expandMounts — per-task volume
        # sources like "data-{{.Task.Slot}}" and label values expand here
        if m.source:
            m.source = expand(m.source, ctx)
        if m.target:
            m.target = expand(m.target, ctx)
        m.volume_labels = {k: expand(v, ctx)
                           for k, v in m.volume_labels.items()}
    return t

"""Root CA and node certificates: real x509 over ECDSA P-256.

Reference: ca/certificates.go (954 LoC) — RootCA (:170), CreateRootCA
(:771), IssueAndSaveNewCertificates (:202), CrossSignCACertificate (:410).
Identity encoding matches the reference exactly: CN = node id,
OU = role ("swarm-manager" / "swarm-worker"), O = cluster/org id
(ca/certificates.go ManagerRole/WorkerRole constants), so authorization can
be derived from any presented certificate.
"""

from __future__ import annotations

import datetime
import hashlib
from dataclasses import dataclass
from typing import Optional

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - depends on the environment
    # x509 certificates cannot be faked in pure python; gate the import so
    # the package (and everything that merely transits it) stays importable
    # and fail at the point of actual use instead.
    HAVE_CRYPTOGRAPHY = False

    class _MissingCryptography:
        def __init__(self, name: str) -> None:
            self._name = name

        def __getattr__(self, attr: str):
            raise ModuleNotFoundError(
                f"{self._name}.{attr} needs the 'cryptography' package, "
                "which is not installed; TLS identities are unavailable")

    x509 = _MissingCryptography("cryptography.x509")
    hashes = _MissingCryptography("cryptography...hashes")
    serialization = _MissingCryptography("cryptography...serialization")
    ec = _MissingCryptography("cryptography...ec")
    NameOID = _MissingCryptography("cryptography...NameOID")

# reference: ca/certificates.go role OU values
MANAGER_ROLE_OU = "swarm-manager"
WORKER_ROLE_OU = "swarm-worker"
CA_ROLE_OU = "swarm-ca"
# Every node cert carries this SAN; gRPC channels override the target name
# to it, so transport-level TLS checks the chain while identity/role checks
# happen against the subject OU/O (reference: swarmkit verifies roles, not
# hostnames — MutualTLS ServerName handling in ca/config.go NewClientTLSConfig).
TLS_SERVER_NAME = "swarmkit-node"

DEFAULT_NODE_CERT_EXPIRATION = 90 * 24 * 3600.0   # ca/certificates.go:60
MIN_NODE_CERT_EXPIRATION = 3600.0
ROOT_CA_EXPIRATION = 20 * 365 * 24 * 3600.0


class CertificateError(Exception):
    pass


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def generate_key() -> ec.EllipticCurvePrivateKey:
    return ec.generate_private_key(ec.SECP256R1())


def key_to_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def key_from_pem(pem: bytes):
    return serialization.load_pem_private_key(pem, password=None)


def cert_to_pem(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def cert_from_pem(pem: bytes) -> x509.Certificate:
    return x509.load_pem_x509_certificate(pem)


def create_csr(node_id: str = "") -> tuple[bytes, bytes]:
    """Generate a key + CSR; returns (csr_pem, key_pem)
    (reference: GenerateNewCSR ca/certificates.go)."""
    key = generate_key()
    return (_csr_for_key(key, node_id), key_to_pem(key))


def create_csr_from_key(key_pem: bytes, node_id: str = "") -> bytes:
    """CSR over an EXISTING key — used for renewals, where the CSR's
    signature proves possession of the node's current key."""
    return _csr_for_key(key_from_pem(key_pem), node_id)


def _csr_for_key(key, node_id: str) -> bytes:
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         node_id or "unknown")])
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(name)
           .sign(key, hashes.SHA256()))
    return csr.public_bytes(serialization.Encoding.PEM)


@dataclass
class IssuedCertificate:
    cert_pem: bytes
    key_pem: Optional[bytes]   # None when signed from an external CSR
    # current CA trust bundle (old+new during a root rotation) — renewal
    # responses carry it so nodes refresh their trust store in step
    root_bundle: bytes = b""


class RootCA:
    """reference: ca.RootCA ca/certificates.go:170."""

    def __init__(self, cert_pem: bytes, key_pem: Optional[bytes] = None,
                 intermediates_pem: bytes = b"") -> None:
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.intermediates_pem = intermediates_pem
        self.cert = cert_from_pem(cert_pem)
        self._key = key_from_pem(key_pem) if key_pem else None

    # ------------------------------------------------------------------
    @classmethod
    def create(cls, cn: str = "swarm-ca") -> "RootCA":
        """reference: CreateRootCA ca/certificates.go:771."""
        key = generate_key()
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn),
                          x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME,
                                             CA_ROLE_OU)])
        now = _now()
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(
                    seconds=ROOT_CA_EXPIRATION))
                .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                               critical=True)
                # SKI/AKI disambiguate chain building: a rotation's old and
                # new roots share the same subject CN, and without key ids
                # OpenSSL may try the wrong same-subject issuer
                .add_extension(x509.SubjectKeyIdentifier.from_public_key(
                    key.public_key()), critical=False)
                .add_extension(x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True,
                    crl_sign=True, content_commitment=False,
                    key_encipherment=False, data_encipherment=False,
                    key_agreement=False, encipher_only=False,
                    decipher_only=False), critical=True)
                .sign(key, hashes.SHA256()))
        return cls(cert_to_pem(cert), key_to_pem(key))

    @property
    def can_sign(self) -> bool:
        return self._key is not None

    def digest(self) -> str:
        """sha256 of the root cert DER — embedded in join tokens
        (reference: RootCA.Digest)."""
        der = self.cert.public_bytes(serialization.Encoding.DER)
        return hashlib.sha256(der).hexdigest()

    # ------------------------------------------------------------------
    def issue_node_certificate(self, node_id: str, role_ou: str, org: str,
                               csr_pem: Optional[bytes] = None,
                               expiry: float = DEFAULT_NODE_CERT_EXPIRATION
                               ) -> IssuedCertificate:
        """Sign a leaf for (node, role, org)
        (reference: IssueAndSaveNewCertificates :202 / signNodeCert)."""
        if not self.can_sign:
            raise CertificateError("this RootCA has no signing key")
        if role_ou not in (MANAGER_ROLE_OU, WORKER_ROLE_OU):
            raise CertificateError(f"invalid role OU {role_ou!r}")
        expiry = max(MIN_NODE_CERT_EXPIRATION, expiry)
        key_pem: Optional[bytes] = None
        if csr_pem is not None:
            csr = x509.load_pem_x509_csr(csr_pem)
            if not csr.is_signature_valid:
                raise CertificateError("CSR signature invalid")
            public_key = csr.public_key()
        else:
            key = generate_key()
            key_pem = key_to_pem(key)
            public_key = key.public_key()
        subject = x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, node_id),
            x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, role_ou),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org)])
        now = _now()
        cert = (x509.CertificateBuilder()
                .subject_name(subject)
                .issuer_name(self.cert.subject)
                .public_key(public_key)
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(seconds=expiry))
                .add_extension(x509.BasicConstraints(ca=False,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.SubjectKeyIdentifier.from_public_key(
                    public_key), critical=False)
                .add_extension(
                    x509.AuthorityKeyIdentifier.from_issuer_public_key(
                        self._key.public_key()), critical=False)
                .add_extension(x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                     x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                    critical=False)
                .add_extension(x509.SubjectAlternativeName(
                    [x509.DNSName(TLS_SERVER_NAME), x509.DNSName(node_id)]),
                    critical=False)
                .sign(self._key, hashes.SHA256()))
        return IssuedCertificate(cert_pem=cert_to_pem(cert), key_pem=key_pem)

    # ------------------------------------------------------------------
    def validate_cert_chain(self, cert_pem: bytes) -> x509.Certificate:
        """Verify a leaf was signed by this root and is in its validity
        window (reference: CheckValidCertificate ca/config.go).  This
        RootCA's cert_pem may be an old+new BUNDLE mid-rotation — the leaf
        is accepted when it chains to ANY member root."""
        leaf = cert_from_pem(cert_pem)
        now = _now()
        if not (leaf.not_valid_before_utc <= now
                <= leaf.not_valid_after_utc):
            raise CertificateError("certificate outside validity window")
        try:
            roots = x509.load_pem_x509_certificates(self.cert_pem)
        except Exception:
            roots = [self.cert]
        last_err: Optional[Exception] = None
        for root in roots:
            try:
                root.public_key().verify(
                    leaf.signature, leaf.tbs_certificate_bytes,
                    ec.ECDSA(leaf.signature_hash_algorithm))
                return leaf
            except Exception as e:
                last_err = e
        raise CertificateError(
            f"certificate not signed by this CA: {last_err}")

    def cross_sign_ca_certificate(self, other_cert_pem: bytes) -> bytes:
        """Sign another root's public key with ours, for root rotation
        (reference: CrossSignCACertificate ca/certificates.go:410)."""
        if not self.can_sign:
            raise CertificateError("this RootCA has no signing key")
        other = cert_from_pem(other_cert_pem)
        now = _now()
        cert = (x509.CertificateBuilder()
                .subject_name(other.subject)
                .issuer_name(self.cert.subject)
                .public_key(other.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(other.not_valid_after_utc)
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.SubjectKeyIdentifier.from_public_key(
                    other.public_key()), critical=False)
                .add_extension(
                    x509.AuthorityKeyIdentifier.from_issuer_public_key(
                        self._key.public_key()), critical=False)
                .sign(self._key, hashes.SHA256()))
        return cert_to_pem(cert)


def parse_identity(cert_pem: bytes) -> tuple[str, str, str]:
    """(node_id, role_ou, org) from a leaf certificate
    (reference: ca/auth.go RemoteNode identity extraction)."""
    cert = cert_from_pem(cert_pem)

    def attr(oid):
        vals = cert.subject.get_attributes_for_oid(oid)
        return vals[0].value if vals else ""

    return (attr(NameOID.COMMON_NAME),
            attr(NameOID.ORGANIZATIONAL_UNIT_NAME),
            attr(NameOID.ORGANIZATION_NAME))


def is_issued_by(leaf_pem: bytes, root_cert_pem: bytes) -> bool:
    """True when the FIRST certificate in ``leaf_pem`` was signed by the
    root in ``root_cert_pem`` (rotation progress check — reference:
    ca/reconciler.go hasIssuer)."""
    try:
        leaf = cert_from_pem(leaf_pem)
        root = cert_from_pem(root_cert_pem)
        root.public_key().verify(
            leaf.signature, leaf.tbs_certificate_bytes,
            ec.ECDSA(leaf.signature_hash_algorithm))
        return True
    except Exception:
        return False


def split_bundle(bundle_pem: bytes) -> list[tuple[bytes, str]]:
    """(cert_pem, sha256-of-DER) for every certificate in a PEM bundle."""
    out = []
    try:
        for cert in x509.load_pem_x509_certificates(bundle_pem):
            der = cert.public_bytes(serialization.Encoding.DER)
            out.append((cert_to_pem(cert),
                        hashlib.sha256(der).hexdigest()))
    except Exception:
        pass
    return out


def bundle_digests(bundle_pem: bytes) -> list[str]:
    """sha256 digests of every certificate in a PEM bundle."""
    return [d for _, d in split_bundle(bundle_pem)]

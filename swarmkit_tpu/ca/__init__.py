from swarmkit_tpu.ca.auth import (
    PermissionDenied, RemoteNodeInfo, authorize_org_and_role,
)
from swarmkit_tpu.ca.certificates import (
    CA_ROLE_OU, DEFAULT_NODE_CERT_EXPIRATION, MANAGER_ROLE_OU,
    WORKER_ROLE_OU, CertificateError, IssuedCertificate, RootCA, create_csr, create_csr_from_key,
    parse_identity,
)
from swarmkit_tpu.ca.config import (
    InvalidJoinToken, SecurityConfig, TLSRenewer, generate_join_token,
    parse_join_token,
)
from swarmkit_tpu.ca.keyreadwriter import KeyReadWriter
from swarmkit_tpu.ca.server import CAServer

__all__ = [
    "CA_ROLE_OU", "MANAGER_ROLE_OU", "WORKER_ROLE_OU",
    "DEFAULT_NODE_CERT_EXPIRATION", "CertificateError", "IssuedCertificate",
    "RootCA", "create_csr", "create_csr_from_key", "parse_identity", "InvalidJoinToken",
    "SecurityConfig", "TLSRenewer", "generate_join_token",
    "parse_join_token", "KeyReadWriter", "CAServer", "PermissionDenied",
    "RemoteNodeInfo", "authorize_org_and_role",
]

"""CA server: join-token-gated certificate issuance + pending-cert
reconciliation.

Reference: ca/server.go (917 LoC) — IssueNodeCertificate (:236): a valid
join token admits a new node (role = which token matched), creating its
node record with the CSR PENDING; the signing loop (Run :422 +
reconciler) signs PENDING certificates; renewals derive the role from
Node.spec.desired_role so promotion/demotion flows through certificate
renewal.  NodeCertificateStatus (:180) lets joiners poll for their signed
certificate.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import MembershipState, NodeRole, NodeSpec, Annotations
from swarmkit_tpu.api.objects import (
    Node as ApiNode, NodeStatus, RootRotation,
)
from swarmkit_tpu.api.types import Certificate, IssuanceState
from swarmkit_tpu.ca.certificates import (
    MANAGER_ROLE_OU, WORKER_ROLE_OU, CertificateError, IssuedCertificate,
    RootCA, is_issued_by, parse_identity,
)
from swarmkit_tpu.ca.config import InvalidJoinToken, parse_join_token
from swarmkit_tpu.store.memory import Event, MemoryStore, match
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.utils.identity import new_id

log = logging.getLogger("swarmkit_tpu.ca.server")

_ROLE_OU = {NodeRole.MANAGER: MANAGER_ROLE_OU, NodeRole.WORKER: WORKER_ROLE_OU}


class CAServer:
    def __init__(self, store: MemoryStore, root_ca: RootCA, org: str,
                 clock: Optional[Clock] = None) -> None:
        # signing goes through _sign(): local root key when present, else
        # the cluster's configured external CFSSL CAs (ca/external.go)
        self.store = store
        self.root_ca = root_ca
        self.org = org
        self.clock = clock or SystemClock()
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._rot_cache: Optional[RootCA] = None

    # ------------------------------------------------------------------
    # Root rotation (reference: ca/server.go rotation handling +
    # ca/reconciler.go rootRotationReconciler + integration
    # TestSuccessfulRootRotation).  Protocol: the NEW root's certificate
    # is cross-signed by the OLD root, issuance switches to the new key
    # with the cross-signed cert appended (old-trusting verifiers still
    # chain), the trust bundle carries old+new, nodes with old-root certs
    # are marked ROTATE so their renewers re-issue, and once every node
    # certificate chains to the new root the cluster flips to it and
    # regenerates join tokens.
    def _rotation(self) -> Optional[RootRotation]:
        cl = self._cluster()
        rot = cl.root_ca.root_rotation if cl is not None else None
        return rot or None

    def _rotation_root(self) -> Optional[RootCA]:
        rot = self._rotation()
        if not rot:
            self._rot_cache = None
            return None
        if self._rot_cache is None \
                or self._rot_cache.cert_pem != rot.ca_cert:
            self._rot_cache = RootCA(rot.ca_cert, rot.ca_key)
        return self._rot_cache

    async def start_root_rotation(self, new_cert_pem: bytes = b"",
                                  new_key_pem: bytes = b"") -> None:
        """Begin rotating the cluster root CA to ``new_cert``/``new_key``
        (generated when omitted)."""
        if not self.root_ca.can_sign:
            raise CertificateError(
                "root rotation requires the local signing key "
                "(external-CA rotation is driven by re-configuring the "
                "external CA set)")
        if self._rotation() is not None:
            raise CertificateError(
                "a root rotation is already in progress — wait for it to "
                "finalize (re-rotating would orphan certificates already "
                "issued under the incoming root)")
        if new_cert_pem:
            new_root = RootCA(new_cert_pem, new_key_pem or None)
            if not new_root.can_sign:
                raise CertificateError("new root needs a signing key")
        else:
            new_root = RootCA.create()
        cross = self.root_ca.cross_sign_ca_certificate(new_root.cert_pem)

        def txn(tx):
            cl = tx.find("cluster")[0]
            cl = cl.copy()
            cl.root_ca.root_rotation = RootRotation(
                ca_cert=new_root.cert_pem,
                ca_key=new_root.key_pem or b"",
                cross_signed_ca_cert=cross)
            tx.update(cl)
            # every node holding an old-root cert renews (ROTATE wakes the
            # node-side TLSRenewer through its session node-watch); nodes
            # with NO recorded cert (the bootstrap manager self-issued its
            # identity before any CA server existed) are marked too — their
            # renewal both rotates the identity and records it
            for n in tx.find("node"):
                if not n.certificate.certificate or not is_issued_by(
                        n.certificate.certificate, new_root.cert_pem):
                    n = n.copy()
                    n.certificate.status_state = int(IssuanceState.ROTATE)
                    tx.update(n)
        await self.store.update(txn)
        await self._maybe_finalize_rotation()

    async def _maybe_finalize_rotation(self) -> None:
        rot = self._rotation()
        if not rot:
            return
        new_cert = rot.ca_cert
        nodes = self.store.find("node")
        # cheap flag scan first: signature checks (ECDSA verify per node)
        # run only once the marked set has drained, keeping convergence
        # O(N) instead of O(N^2) verifies across the rotation
        if any(n.certificate.status_state == int(IssuanceState.ROTATE)
               for n in nodes):
            return  # a marked node has not renewed yet
        for n in nodes:
            if n.certificate.certificate \
                    and not is_issued_by(n.certificate.certificate,
                                         new_cert):
                return  # still converging
        from swarmkit_tpu.ca.config import generate_join_token

        new_root = RootCA(rot.ca_cert, rot.ca_key)

        def txn(tx):
            cl = tx.find("cluster")[0]
            cl = cl.copy()
            cl.root_ca.ca_cert = new_root.cert_pem
            cl.root_ca.ca_key = new_root.key_pem or b""
            cl.root_ca.ca_cert_hash = new_root.digest()
            cl.root_ca.join_token_worker = generate_join_token(new_root)
            cl.root_ca.join_token_manager = generate_join_token(new_root)
            cl.root_ca.root_rotation = None
            tx.update(cl)
        await self.store.update(txn)
        self.root_ca = new_root
        self._rot_cache = None
        log.info("root CA rotation complete; join tokens regenerated")

    # ------------------------------------------------------------------
    def _cluster(self):
        clusters = self.store.find("cluster")
        return clusters[0] if clusters else None

    def _role_for_token(self, token: str) -> NodeRole:
        """Which join token matched decides the role
        (reference: server.go checkNodeCertificate / token switch).
        Comparisons are constant-time: join tokens are bearer secrets."""
        import hmac

        parsed = parse_join_token(token)
        if not hmac.compare_digest(parsed.ca_digest, self.root_ca.digest()):
            raise InvalidJoinToken("join token CA digest mismatch")
        cluster = self._cluster()
        if cluster is None:
            raise InvalidJoinToken("no cluster object")
        if hmac.compare_digest(token,
                               cluster.root_ca.join_token_manager or ""):
            return NodeRole.MANAGER
        if hmac.compare_digest(token,
                               cluster.root_ca.join_token_worker or ""):
            return NodeRole.WORKER
        raise InvalidJoinToken("join token not recognized")

    # ------------------------------------------------------------------
    def _external_client(self):
        from swarmkit_tpu.ca.external import ExternalCAClient

        cluster = self._cluster()
        cas = (cluster.spec.ca_config.external_cas
               if cluster is not None and cluster.spec.ca_config else [])
        client = ExternalCAClient(cas, self.root_ca)
        return client if client.configured else None

    async def _sign(self, node_id: str, role_ou: str, csr_pem: bytes
                    ) -> IssuedCertificate:
        """Local root key when available, else the cluster's external CA
        (reference: server.go signNodeCert -> ca/external.go)."""
        rot_root = self._rotation_root()
        if rot_root is not None and rot_root.can_sign:
            issued = rot_root.issue_node_certificate(
                node_id, role_ou, self.org, csr_pem=csr_pem,
                expiry=self._cert_expiry())
            # append the cross-signed new-root cert: verifiers that still
            # trust only the OLD root chain through it
            cross = self._rotation().cross_signed_ca_cert
            return IssuedCertificate(
                cert_pem=issued.cert_pem + cross, key_pem=issued.key_pem,
                root_bundle=self.get_root_ca_certificate())
        if self.root_ca.can_sign:
            issued = self.root_ca.issue_node_certificate(
                node_id, role_ou, self.org, csr_pem=csr_pem,
                expiry=self._cert_expiry())
            return IssuedCertificate(
                cert_pem=issued.cert_pem, key_pem=issued.key_pem,
                root_bundle=self.get_root_ca_certificate())
        ext = self._external_client()
        if ext is None:
            raise CertificateError(
                "root CA has no signing key and no external CA is "
                "configured")
        return await ext.sign(csr_pem, node_id, role_ou, self.org)

    async def issue_node_certificate(self, csr_pem: bytes, token: str,
                                     addr: str = "",
                                     requested_node_id: str = ""
                                     ) -> tuple[str, IssuedCertificate]:
        """Admit a new node via join token (reference: server.go:236).
        ``requested_node_id`` is honored only when vacant (test harnesses
        want stable names; the reference always assigns a fresh id)."""
        role = self._role_for_token(token)
        node_id = new_id()
        if requested_node_id \
                and self.store.get("node", requested_node_id) is None:
            node_id = requested_node_id
        issued = await self._sign(node_id, _ROLE_OU[role], csr_pem)
        node = ApiNode(
            id=node_id,
            spec=NodeSpec(annotations=Annotations(name=node_id),
                          desired_role=role,
                          membership=MembershipState.ACCEPTED),
            role=role,
            certificate=Certificate(
                role=role, csr=csr_pem,
                status_state=int(IssuanceState.ISSUED),
                certificate=issued.cert_pem, cn=node_id),
            status=NodeStatus(addr=addr))
        await self.store.update(lambda tx: tx.create(node))
        return node_id, issued

    async def renew_node_certificate(self, node_id: str,
                                     old_cert_pem: bytes,
                                     csr_pem: bytes) -> IssuedCertificate:
        """Renewal: identity proven by the old cert AND a CSR signed with
        the certificate's own key (possession proof — the reference proves
        possession via the mutual-TLS channel); role comes from
        Node.spec.desired_role (reference: issueRenewCertificate)."""
        from cryptography import x509 as _x509
        from cryptography.hazmat.primitives import serialization as _ser

        cn, _, org = parse_identity(old_cert_pem)
        try:
            old_cert = self.root_ca.validate_cert_chain(old_cert_pem)
        except CertificateError:
            rot_root = self._rotation_root()
            if rot_root is None:
                raise
            # mid-rotation: the presenting cert may already chain to the
            # new root
            old_cert = rot_root.validate_cert_chain(old_cert_pem)
        if cn != node_id or org != self.org:
            raise CertificateError("certificate identity mismatch")
        csr = _x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise CertificateError("renewal CSR signature invalid")
        pub = lambda k: k.public_bytes(
            _ser.Encoding.PEM, _ser.PublicFormat.SubjectPublicKeyInfo)
        if pub(csr.public_key()) != pub(old_cert.public_key()):
            raise CertificateError(
                "renewal CSR key does not match the certificate key")
        node = self.store.get("node", node_id)
        if node is None:
            raise CertificateError(f"node {node_id} not registered")
        role = NodeRole(node.spec.desired_role)
        issued = await self._sign(node_id, _ROLE_OU[role], csr_pem)

        def txn(tx):
            cur = tx.get("node", node_id)
            if cur is None:
                return
            cur = cur.copy()
            cur.role = role
            cur.certificate = Certificate(
                role=role, status_state=int(IssuanceState.ISSUED),
                certificate=issued.cert_pem, cn=node_id)
            tx.update(cur)
        await self.store.update(txn)
        return issued

    def node_certificate_status(self, node_id: str
                                ) -> tuple[IssuanceState, bytes]:
        """reference: NodeCertificateStatus server.go:180."""
        node = self.store.get("node", node_id)
        if node is None:
            raise CertificateError(f"node {node_id} not found")
        return (IssuanceState(node.certificate.status_state),
                node.certificate.certificate)

    def get_root_ca_certificate(self) -> bytes:
        """The trust bundle to distribute: the current root, plus the
        incoming root while a rotation is converging (reference:
        GetRootCACertificate ca.proto)."""
        rot = self._rotation()
        if rot:
            return self.root_ca.cert_pem + rot.ca_cert
        return self.root_ca.cert_pem

    def _cert_expiry(self) -> float:
        cluster = self._cluster()
        if cluster is not None:
            return cluster.spec.ca_config.node_cert_expiry
        from swarmkit_tpu.ca.certificates import DEFAULT_NODE_CERT_EXPIRATION

        return DEFAULT_NODE_CERT_EXPIRATION

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Pending-cert reconciliation loop (reference: Run server.go:422
        + ca/reconciler.go)."""
        self._watcher = self.store.watch(match(kind="node"))
        await self._sign_pending()
        # a leader failover mid-rotation must not wedge it: the last
        # renewal may have landed just before the old leader died
        await self._maybe_finalize_rotation()
        self._running = True
        self._task = asyncio.get_running_loop().create_task(
            self._run(self._watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if getattr(self, "_watcher", None) is not None:
            self._watcher.close()
            self._watcher = None

    async def _run(self, watcher) -> None:
        try:
            async for ev in watcher:
                if not self._running:
                    return
                if isinstance(ev, Event) and ev.action != "remove" \
                        and ev.object.certificate.status_state \
                        == IssuanceState.PENDING:
                    await self._sign_pending()
                if isinstance(ev, Event) \
                        and (ev.action == "remove"
                             or ev.object.certificate.status_state
                             == IssuanceState.ISSUED):
                    # a renewal — or the REMOVAL of the last old-root
                    # node — may be what the rotation was waiting on
                    await self._maybe_finalize_rotation()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("CA server loop crashed")

    async def _sign_pending(self) -> None:
        pending = [n for n in self.store.find("node")
                   if n.certificate.status_state == IssuanceState.PENDING
                   and n.certificate.csr]
        for n in pending:
            try:
                issued = await self._sign(
                    n.id, _ROLE_OU[NodeRole(n.spec.desired_role)],
                    n.certificate.csr)
            except Exception as e:
                log.warning("cannot sign CSR for %s: %s", n.id, e)
                continue

            role = NodeRole(n.spec.desired_role)

            def txn(tx, nid=n.id, cert=issued.cert_pem, role=role):
                cur = tx.get("node", nid)
                if cur is None:
                    return
                cur = cur.copy()
                cur.role = role
                cur.certificate.certificate = cert
                cur.certificate.status_state = int(IssuanceState.ISSUED)
                cur.certificate.role = role
                cur.certificate.cn = nid
                tx.update(cur)
            await self.store.update(txn)

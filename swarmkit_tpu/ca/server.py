"""CA server: join-token-gated certificate issuance + pending-cert
reconciliation.

Reference: ca/server.go (917 LoC) — IssueNodeCertificate (:236): a valid
join token admits a new node (role = which token matched), creating its
node record with the CSR PENDING; the signing loop (Run :422 +
reconciler) signs PENDING certificates; renewals derive the role from
Node.spec.desired_role so promotion/demotion flows through certificate
renewal.  NodeCertificateStatus (:180) lets joiners poll for their signed
certificate.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import MembershipState, NodeRole, NodeSpec, Annotations
from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
from swarmkit_tpu.api.types import Certificate, IssuanceState
from swarmkit_tpu.ca.certificates import (
    MANAGER_ROLE_OU, WORKER_ROLE_OU, CertificateError, IssuedCertificate,
    RootCA, parse_identity,
)
from swarmkit_tpu.ca.config import InvalidJoinToken, parse_join_token
from swarmkit_tpu.store.memory import Event, MemoryStore, match
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.utils.identity import new_id

log = logging.getLogger("swarmkit_tpu.ca.server")

_ROLE_OU = {NodeRole.MANAGER: MANAGER_ROLE_OU, NodeRole.WORKER: WORKER_ROLE_OU}


class CAServer:
    def __init__(self, store: MemoryStore, root_ca: RootCA, org: str,
                 clock: Optional[Clock] = None) -> None:
        # signing goes through _sign(): local root key when present, else
        # the cluster's configured external CFSSL CAs (ca/external.go)
        self.store = store
        self.root_ca = root_ca
        self.org = org
        self.clock = clock or SystemClock()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    # ------------------------------------------------------------------
    def _cluster(self):
        clusters = self.store.find("cluster")
        return clusters[0] if clusters else None

    def _role_for_token(self, token: str) -> NodeRole:
        """Which join token matched decides the role
        (reference: server.go checkNodeCertificate / token switch).
        Comparisons are constant-time: join tokens are bearer secrets."""
        import hmac

        parsed = parse_join_token(token)
        if not hmac.compare_digest(parsed.ca_digest, self.root_ca.digest()):
            raise InvalidJoinToken("join token CA digest mismatch")
        cluster = self._cluster()
        if cluster is None:
            raise InvalidJoinToken("no cluster object")
        if hmac.compare_digest(token,
                               cluster.root_ca.join_token_manager or ""):
            return NodeRole.MANAGER
        if hmac.compare_digest(token,
                               cluster.root_ca.join_token_worker or ""):
            return NodeRole.WORKER
        raise InvalidJoinToken("join token not recognized")

    # ------------------------------------------------------------------
    def _external_client(self):
        from swarmkit_tpu.ca.external import ExternalCAClient

        cluster = self._cluster()
        cas = (cluster.spec.ca_config.external_cas
               if cluster is not None and cluster.spec.ca_config else [])
        client = ExternalCAClient(cas, self.root_ca)
        return client if client.configured else None

    async def _sign(self, node_id: str, role_ou: str, csr_pem: bytes
                    ) -> IssuedCertificate:
        """Local root key when available, else the cluster's external CA
        (reference: server.go signNodeCert -> ca/external.go)."""
        if self.root_ca.can_sign:
            return self.root_ca.issue_node_certificate(
                node_id, role_ou, self.org, csr_pem=csr_pem,
                expiry=self._cert_expiry())
        ext = self._external_client()
        if ext is None:
            raise CertificateError(
                "root CA has no signing key and no external CA is "
                "configured")
        return await ext.sign(csr_pem, node_id, role_ou, self.org)

    async def issue_node_certificate(self, csr_pem: bytes, token: str,
                                     addr: str = "",
                                     requested_node_id: str = ""
                                     ) -> tuple[str, IssuedCertificate]:
        """Admit a new node via join token (reference: server.go:236).
        ``requested_node_id`` is honored only when vacant (test harnesses
        want stable names; the reference always assigns a fresh id)."""
        role = self._role_for_token(token)
        node_id = new_id()
        if requested_node_id \
                and self.store.get("node", requested_node_id) is None:
            node_id = requested_node_id
        issued = await self._sign(node_id, _ROLE_OU[role], csr_pem)
        node = ApiNode(
            id=node_id,
            spec=NodeSpec(annotations=Annotations(name=node_id),
                          desired_role=role,
                          membership=MembershipState.ACCEPTED),
            role=role,
            certificate=Certificate(
                role=role, csr=csr_pem,
                status_state=int(IssuanceState.ISSUED),
                certificate=issued.cert_pem, cn=node_id),
            status=NodeStatus(addr=addr))
        await self.store.update(lambda tx: tx.create(node))
        return node_id, issued

    async def renew_node_certificate(self, node_id: str,
                                     old_cert_pem: bytes,
                                     csr_pem: bytes) -> IssuedCertificate:
        """Renewal: identity proven by the old cert AND a CSR signed with
        the certificate's own key (possession proof — the reference proves
        possession via the mutual-TLS channel); role comes from
        Node.spec.desired_role (reference: issueRenewCertificate)."""
        from cryptography import x509 as _x509
        from cryptography.hazmat.primitives import serialization as _ser

        cn, _, org = parse_identity(old_cert_pem)
        old_cert = self.root_ca.validate_cert_chain(old_cert_pem)
        if cn != node_id or org != self.org:
            raise CertificateError("certificate identity mismatch")
        csr = _x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise CertificateError("renewal CSR signature invalid")
        pub = lambda k: k.public_bytes(
            _ser.Encoding.PEM, _ser.PublicFormat.SubjectPublicKeyInfo)
        if pub(csr.public_key()) != pub(old_cert.public_key()):
            raise CertificateError(
                "renewal CSR key does not match the certificate key")
        node = self.store.get("node", node_id)
        if node is None:
            raise CertificateError(f"node {node_id} not registered")
        role = NodeRole(node.spec.desired_role)
        issued = await self._sign(node_id, _ROLE_OU[role], csr_pem)

        def txn(tx):
            cur = tx.get("node", node_id)
            if cur is None:
                return
            cur = cur.copy()
            cur.role = role
            cur.certificate = Certificate(
                role=role, status_state=int(IssuanceState.ISSUED),
                certificate=issued.cert_pem, cn=node_id)
            tx.update(cur)
        await self.store.update(txn)
        return issued

    def node_certificate_status(self, node_id: str
                                ) -> tuple[IssuanceState, bytes]:
        """reference: NodeCertificateStatus server.go:180."""
        node = self.store.get("node", node_id)
        if node is None:
            raise CertificateError(f"node {node_id} not found")
        return (IssuanceState(node.certificate.status_state),
                node.certificate.certificate)

    def get_root_ca_certificate(self) -> bytes:
        """reference: GetRootCACertificate ca.proto."""
        return self.root_ca.cert_pem

    def _cert_expiry(self) -> float:
        cluster = self._cluster()
        if cluster is not None:
            return cluster.spec.ca_config.node_cert_expiry
        from swarmkit_tpu.ca.certificates import DEFAULT_NODE_CERT_EXPIRATION

        return DEFAULT_NODE_CERT_EXPIRATION

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Pending-cert reconciliation loop (reference: Run server.go:422
        + ca/reconciler.go)."""
        self._watcher = self.store.watch(match(kind="node"))
        await self._sign_pending()
        self._running = True
        self._task = asyncio.get_running_loop().create_task(
            self._run(self._watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if getattr(self, "_watcher", None) is not None:
            self._watcher.close()
            self._watcher = None

    async def _run(self, watcher) -> None:
        try:
            async for ev in watcher:
                if not self._running:
                    return
                if isinstance(ev, Event) and ev.action != "remove" \
                        and ev.object.certificate.status_state \
                        == IssuanceState.PENDING:
                    await self._sign_pending()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("CA server loop crashed")

    async def _sign_pending(self) -> None:
        pending = [n for n in self.store.find("node")
                   if n.certificate.status_state == IssuanceState.PENDING
                   and n.certificate.csr]
        for n in pending:
            try:
                issued = await self._sign(
                    n.id, _ROLE_OU[NodeRole(n.spec.desired_role)],
                    n.certificate.csr)
            except Exception as e:
                log.warning("cannot sign CSR for %s: %s", n.id, e)
                continue

            role = NodeRole(n.spec.desired_role)

            def txn(tx, nid=n.id, cert=issued.cert_pem, role=role):
                cur = tx.get("node", nid)
                if cur is None:
                    return
                cur = cur.copy()
                cur.role = role
                cur.certificate.certificate = cert
                cur.certificate.status_state = int(IssuanceState.ISSUED)
                cur.certificate.role = role
                cur.certificate.cn = nid
                tx.update(cur)
            await self.store.update(txn)

"""SecurityConfig, join tokens, and certificate renewal.

Reference: ca/config.go (721 LoC) — SecurityConfig holds the live TLS state
(root + node certificate + derived identity) with an update queue;
GenerateJoinToken / ParseJoinToken encode the CA digest + a secret into
``SWMTKN-1-<digest>-<secret>``; RenewTLSConfig (via ca/renewer.go
TLSRenewer) renews the node certificate at ~half life with jitter and
backoff.
"""

from __future__ import annotations

import asyncio
import logging
import random
import secrets as pysecrets
from dataclasses import dataclass
from typing import Optional

from swarmkit_tpu.ca.certificates import (
    MANAGER_ROLE_OU, WORKER_ROLE_OU, RootCA, parse_identity,
)
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.watch.queue import Queue

log = logging.getLogger("swarmkit_tpu.ca")


class InvalidJoinToken(Exception):
    pass


def generate_join_token(root_ca: RootCA, secret: Optional[str] = None) -> str:
    """``SWMTKN-1-<ca digest>-<secret>``
    (reference: ca/config.go GenerateJoinToken)."""
    return "SWMTKN-1-%s-%s" % (root_ca.digest(),
                               secret or pysecrets.token_hex(16))


@dataclass
class ParsedToken:
    version: int
    ca_digest: str
    secret: str


def pinned_cert(root_pem: bytes, token: str) -> Optional[bytes]:
    """The ONE certificate in a served (possibly old+new rotation) bundle
    whose digest matches the join token's pin, or None.  Only the pinned
    member may be trusted from an UNAUTHENTICATED fetch — trusting the
    whole bundle would let a MITM smuggle a rogue root alongside the real
    one (reference: GetRemoteCA digest verification).  The full rotation
    bundle is installed later from the ISSUANCE response, which arrives
    over a channel verified against this pinned cert."""
    import hmac

    from swarmkit_tpu.ca.certificates import split_bundle

    want = parse_join_token(token).ca_digest
    for cert_pem, digest in split_bundle(root_pem):
        if hmac.compare_digest(digest, want):
            return cert_pem
    return None


def verify_root_digest(root_pem: bytes, token: str) -> bool:
    """True when the join token's pin matches a member of the served
    bundle (see pinned_cert — callers needing trust material should use
    that and trust ONLY the returned cert)."""
    return pinned_cert(root_pem, token) is not None


def parse_join_token(token: str) -> ParsedToken:
    """reference: ca/config.go ParseJoinToken."""
    parts = token.split("-")
    if len(parts) != 4 or parts[0] != "SWMTKN":
        raise InvalidJoinToken("invalid join token format")
    if parts[1] != "1":
        raise InvalidJoinToken(f"unsupported join token version {parts[1]}")
    return ParsedToken(version=1, ca_digest=parts[2], secret=parts[3])


@dataclass
class SecurityUpdate:
    role: str


class SecurityConfig:
    """Live TLS identity (reference: ca.SecurityConfig ca/config.go)."""

    def __init__(self, root_ca: RootCA, node_id: str, role_ou: str,
                 org: str, cert_pem: bytes, key_pem: bytes) -> None:
        self.root_ca = root_ca
        self.node_id = node_id
        self.role_ou = role_ou
        self.org = org
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.updates = Queue()

    @property
    def is_manager(self) -> bool:
        return self.role_ou == MANAGER_ROLE_OU

    def update_cert(self, cert_pem: bytes, key_pem: bytes) -> None:
        node_id, role_ou, org = parse_identity(cert_pem)
        role_changed = role_ou != self.role_ou
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.node_id = node_id
        self.role_ou = role_ou
        self.org = org
        if role_changed:
            self.updates.publish(SecurityUpdate(role=role_ou))

    def validity_remaining(self, now_utc=None) -> float:
        import datetime

        from swarmkit_tpu.ca.certificates import cert_from_pem

        cert = cert_from_pem(self.cert_pem)
        now = now_utc or datetime.datetime.now(datetime.timezone.utc)
        return (cert.not_valid_after_utc - now).total_seconds()


class TLSRenewer:
    """Renews the node certificate before expiry
    (reference: ca/renewer.go TLSRenewer)."""

    def __init__(self, security: SecurityConfig, ca_client,
                 clock: Optional[Clock] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.security = security
        # renewal client protocol: ``await renew_node_certificate(node_id,
        # cert_pem) -> IssuedCertificate`` — a wrapper owning CSR creation
        # and persistence (see node._RenewClient), NOT the raw CAServer
        # (whose renew takes an explicit CSR)
        self.ca_client = ca_client
        self.clock = clock or SystemClock()
        self._rng = rng or random.Random()
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._wake: Optional[asyncio.Event] = None

    def start(self) -> None:
        self._running = True
        self._wake = asyncio.Event()
        self._task = asyncio.get_running_loop().create_task(self._run())

    def renew_soon(self) -> None:
        """Skip the half-life wait and renew at the next loop step —
        triggered on expected-role changes (a promoted worker needs a
        manager-OU cert NOW, reference: renewer.go SetExpectedRole →
        renew channel) and on certificate-format migrations."""
        if self._wake is not None:
            self._wake.set()

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def _next_delay(self) -> float:
        # renew in [half-life, 80% of life] (reference: calculateRandomExpiry)
        remaining = max(60.0, self.security.validity_remaining())
        return remaining * self._rng.uniform(0.5, 0.8)

    async def _run(self) -> None:
        try:
            while self._running:
                sleeper = asyncio.ensure_future(
                    self.clock.sleep(self._next_delay()))
                waker = asyncio.ensure_future(self._wake.wait())
                try:
                    await asyncio.wait({sleeper, waker},
                                       return_when=asyncio.FIRST_COMPLETED)
                finally:
                    sleeper.cancel()
                    waker.cancel()
                self._wake.clear()
                # retry on the short backoff until the renewal lands —
                # re-entering _next_delay() here would push each retry
                # 50-80% of the remaining validity into the future
                backoff = 1.0
                while self._running:
                    try:
                        await self.renew()
                        break
                    except Exception as e:
                        log.info("certificate renewal failed: %s", e)
                        await self.clock.sleep(backoff)
                        backoff = min(30.0, backoff * 2)
        except asyncio.CancelledError:
            pass

    async def renew(self) -> None:
        """One renewal round trip (reference: RenewTLSConfigNow)."""
        issued = await self.ca_client.renew_node_certificate(
            self.security.node_id, self.security.cert_pem)
        self.security.update_cert(issued.cert_pem,
                                  issued.key_pem or self.security.key_pem)

"""gRPC TLS credential plumbing from a SecurityConfig.

Reference: the reference serves every manager RPC behind one mutual-TLS
listener with VerifyClientCertIfGiven (manager/manager.go:252-270) and
per-RPC authorization from the peer certificate (ca/auth.go:50-120).
python-grpc has no verify-if-given mode (require_client_auth=False never
requests the client certificate), so the same surface splits across three
listeners:

- main port: strict mutual TLS — raft, dispatcher, control, renewal; the
  peer certificate carries identity for per-RPC role checks.
- port+1 (plaintext): ONLY the public root CA certificate, which joiners
  digest-pin against their SWMTKN (the reference fetches this over
  InsecureSkipVerify TLS with the same pin, ca/certificates.go GetRemoteCA).
- port+2 (server-auth TLS): certificate issuance + leader info for
  certificate-less joiners; the join token travels only over TLS.
"""

from __future__ import annotations

from typing import Optional

import grpc

from swarmkit_tpu.ca.certificates import TLS_SERVER_NAME


def _cert_config_fetcher(security):
    """Serve the CURRENT identity on every new handshake — a renewed
    certificate (role flip, root rotation) takes effect without restarting
    the listener (the reference gets this from Go's dynamic GetCertificate
    in its tls.Config; python-grpc's equivalent is the certificate
    configuration fetcher)."""
    def fetch():
        return grpc.ssl_server_certificate_configuration(
            [(security.key_pem, security.cert_pem)],
            root_certificates=security.root_ca.cert_pem)
    return fetch


def server_credentials(security) -> grpc.ServerCredentials:
    """Strict-mTLS server credentials for the main cluster port: the client
    must present a certificate chaining to the cluster root; per-RPC role
    authorization then reads it (authorize_peer).  DYNAMIC: each handshake
    reads the live SecurityConfig, so renewals and root rotations take
    effect immediately."""
    fetch = _cert_config_fetcher(security)
    return grpc.dynamic_ssl_server_credentials(
        fetch(), lambda: fetch(), require_client_authentication=True)


def join_server_credentials(security) -> grpc.ServerCredentials:
    """Server-auth-only TLS for the join port: certificate-less nodes
    verify US (via the digest-pinned root) and send their join token
    encrypted; they cannot present a client certificate yet.  Dynamic for
    the same rotation reasons as server_credentials."""
    fetch = _cert_config_fetcher(security)
    return grpc.dynamic_ssl_server_credentials(
        fetch(), lambda: fetch(), require_client_authentication=False)


def channel_credentials(security=None,
                        pinned_root_pem: Optional[bytes] = None
                        ) -> grpc.ChannelCredentials:
    """Client-side TLS: mutual when we have an identity; server-auth-only
    against a pinned root during the join dance."""
    if security is not None:
        return grpc.ssl_channel_credentials(
            root_certificates=security.root_ca.cert_pem,
            private_key=security.key_pem,
            certificate_chain=security.cert_pem)
    if pinned_root_pem is not None:
        return grpc.ssl_channel_credentials(root_certificates=pinned_root_pem)
    raise ValueError("need a SecurityConfig or a pinned root certificate")


def secure_channel_options(extra: Optional[list] = None) -> list:
    """Node certs carry the constant swarmkit-node SAN; gRPC must check the
    chain against it regardless of the host:port dialed."""
    return [("grpc.ssl_target_name_override", TLS_SERVER_NAME),
            *(extra or ())]


def peer_cert_pem(context) -> Optional[bytes]:
    """The verified peer certificate PEM from a grpc.aio handler context,
    or None when the client connected without one."""
    try:
        auth = context.auth_context()
    except Exception:
        return None
    certs = auth.get("x509_pem_cert") if auth else None
    if not certs:
        return None
    pem = certs[0]
    return pem if isinstance(pem, bytes) else pem.encode()


def authorize_peer(context, security, *allowed_roles: str):
    """Per-RPC authorization from the TLS peer certificate
    (reference: AuthorizeOrgAndRole ca/auth.go). Returns RemoteNodeInfo;
    raises PermissionDenied when no/invalid/wrong-role certificate."""
    from swarmkit_tpu.ca.auth import PermissionDenied, authorize_org_and_role

    pem = peer_cert_pem(context)
    if pem is None:
        raise PermissionDenied("no client certificate presented")
    return authorize_org_and_role(pem, security.root_ca, security.org,
                                  *allowed_roles)

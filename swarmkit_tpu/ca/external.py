"""External CA signer: delegate node-certificate signing to an HTTPS
CFSSL-protocol endpoint.

Reference: ca/external.go:1-230 — ExternalCA posts a CFSSL sign request
(JSON ``{"certificate_request": "<csr pem>"}``) to the configured URL over
TLS and expects ``{"success": true, "result": {"certificate": "<pem>"}}``;
the returned leaf must chain to the cluster root. Used when the cluster
spec configures ExternalCAs and the local RootCA has no signing key
(certificate authority held outside the cluster).

The HTTP round trip runs in a worker thread (stdlib urllib, no extra
dependencies); the external endpoint is authenticated by pinning its CA
certificate from the ExternalCA spec entry.
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import urllib.request
from typing import Optional, Sequence

from swarmkit_tpu.ca.certificates import (
    CertificateError, IssuedCertificate, RootCA,
)

log = logging.getLogger("swarmkit_tpu.ca.external")

PROTOCOL_CFSSL = "cfssl"


class ExternalCAError(Exception):
    pass


class ExternalCAClient:
    """Round-robin CFSSL signer over the cluster's configured external CAs
    (reference: ExternalCA external.go; request shape signNodeCertificate).
    """

    def __init__(self, cas: Sequence, cluster_root: RootCA,
                 timeout: float = 10.0) -> None:
        self.cas = [ca for ca in cas
                    if ca.protocol == PROTOCOL_CFSSL and ca.url]
        self.cluster_root = cluster_root
        self.timeout = timeout

    @property
    def configured(self) -> bool:
        return bool(self.cas)

    def _post(self, ca, payload: bytes) -> dict:
        ctx: Optional[ssl.SSLContext] = None
        if ca.url.startswith("https"):
            ctx = ssl.create_default_context()
            if ca.ca_cert:
                ctx.load_verify_locations(cadata=ca.ca_cert.decode())
        req = urllib.request.Request(
            ca.url, data=payload,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=ctx) as resp:
            return json.loads(resp.read())

    async def sign(self, csr_pem: bytes, node_id: str, role_ou: str,
                   org: str) -> IssuedCertificate:
        """Sign a CSR via the first healthy external CA; the result MUST
        chain to the cluster root (external.go CrossSign validation). The
        request carries the swarm identity subject the signer must emboss
        (reference: external.go signNodeCertificate request shape)."""
        if not self.configured:
            raise ExternalCAError("no external CA configured")
        from swarmkit_tpu.ca.certificates import TLS_SERVER_NAME

        payload = json.dumps({
            "certificate_request": csr_pem.decode(),
            "subject": {"CN": node_id,
                        "names": [{"OU": role_ou, "O": org}]},
            "hosts": [TLS_SERVER_NAME, node_id],
        }).encode()
        loop = asyncio.get_running_loop()
        last: Optional[Exception] = None
        for ca in self.cas:
            try:
                body = await loop.run_in_executor(
                    None, self._post, ca, payload)
                if not body.get("success"):
                    raise ExternalCAError(
                        f"external CA refused: {body.get('errors')}")
                cert_pem = body["result"]["certificate"].encode()
                self.cluster_root.validate_cert_chain(cert_pem)
                return IssuedCertificate(cert_pem=cert_pem, key_pem=None)
            except (ExternalCAError, CertificateError):
                raise
            except Exception as e:
                last = e
                log.warning("external CA %s failed: %s", ca.url, e)
        raise ExternalCAError(f"all external CAs failed: {last}")

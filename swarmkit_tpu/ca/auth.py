"""Per-RPC role authorization from the presented certificate.

Reference: ca/auth.go (247 LoC) — AuthorizeOrgAndRole checks the TLS peer
certificate's OU against the roles an RPC admits; RemoteNode extracts the
caller identity (with ForwardedBy for raft-proxied requests).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from swarmkit_tpu.ca.certificates import (
    CertificateError, RootCA, parse_identity,
)


class PermissionDenied(Exception):
    pass


@dataclass
class RemoteNodeInfo:
    """reference: ca/auth.go RemoteNodeInfo."""

    node_id: str
    role_ou: str
    org: str
    forwarded_by: Optional[str] = None


def authorize_org_and_role(cert_pem: bytes, root_ca: RootCA, org: str,
                           *allowed_roles: str) -> RemoteNodeInfo:
    """Validate the chain, the org, and the role OU
    (reference: AuthorizeOrgAndRole ca/auth.go)."""
    try:
        root_ca.validate_cert_chain(cert_pem)
    except CertificateError as e:
        raise PermissionDenied(f"invalid certificate: {e}")
    node_id, role_ou, cert_org = parse_identity(cert_pem)
    if org and cert_org != org:
        raise PermissionDenied(
            f"certificate from organization {cert_org!r} rejected")
    if allowed_roles and role_ou not in allowed_roles:
        raise PermissionDenied(
            f"role {role_ou!r} not allowed (need one of {allowed_roles})")
    return RemoteNodeInfo(node_id=node_id, role_ou=role_ou, org=cert_org)

"""At-rest storage for the node's TLS material, with optional KEK
encryption of the private key (cluster autolock).

Reference: ca/keyreadwriter.go (493 LoC) — cert.pem / key.pem under
<state>/certificates/, the key optionally PEM-encrypted with the kek;
headers on the key carry rotation state (here: a small JSON sidecar).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from typing import Optional

from cryptography.fernet import Fernet, InvalidToken


class KeyReadWriter:
    def __init__(self, cert_dir: str, kek: Optional[bytes] = None) -> None:
        self.cert_dir = cert_dir
        self._kek = kek
        os.makedirs(cert_dir, exist_ok=True)

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-node.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-node.key")

    @property
    def root_ca_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-root-ca.crt")

    # ------------------------------------------------------------------
    def _fernet(self, kek: bytes) -> Fernet:
        return Fernet(base64.urlsafe_b64encode(
            hashlib.sha256(kek).digest()))

    def set_kek(self, kek: Optional[bytes]) -> bool:
        """Re-encrypt the stored key under a new kek; no-op (returns False)
        when it is already in effect (reference: RotateKEK
        keyreadwriter.go)."""
        if kek == self._kek:
            return False
        cert, key = self.read()
        self._kek = kek
        if key is not None:
            self.write(cert or b"", key)
        return True

    # ------------------------------------------------------------------
    def write(self, cert_pem: bytes, key_pem: bytes) -> None:
        payload = key_pem
        meta = {"encrypted": False}
        if self._kek:
            payload = self._fernet(self._kek).encrypt(key_pem)
            meta["encrypted"] = True
        self._atomic(self.cert_path, cert_pem)
        self._atomic(self.key_path, payload, mode=0o600)
        self._atomic(self.key_path + ".meta",
                     json.dumps(meta).encode())

    def read(self) -> tuple[Optional[bytes], Optional[bytes]]:
        if not os.path.exists(self.cert_path) \
                or not os.path.exists(self.key_path):
            return None, None
        cert = open(self.cert_path, "rb").read()
        payload = open(self.key_path, "rb").read()
        meta = {"encrypted": False}
        if os.path.exists(self.key_path + ".meta"):
            meta = json.loads(open(self.key_path + ".meta").read())
        if meta.get("encrypted"):
            if not self._kek:
                raise PermissionError(
                    "node key is locked; unlock key required")
            try:
                payload = self._fernet(self._kek).decrypt(payload)
            except InvalidToken:
                raise PermissionError("invalid unlock key")
        return cert, payload

    def write_root_ca(self, cert_pem: bytes) -> None:
        self._atomic(self.root_ca_path, cert_pem)

    def read_root_ca(self) -> Optional[bytes]:
        if not os.path.exists(self.root_ca_path):
            return None
        return open(self.root_ca_path, "rb").read()

    @staticmethod
    def _atomic(path: str, data: bytes, mode: int = 0o644) -> None:
        """reference: ioutils.AtomicWriteFile.  ``mode`` applies from the
        first byte (keys must never exist world-readable, even as .tmp)."""
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        os.fchmod(fd, mode)  # O_CREAT mode is skipped if tmp pre-exists
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

"""At-rest storage for the node's TLS material, with optional KEK
encryption of the private key (cluster autolock).

Reference: ca/keyreadwriter.go (493 LoC) — cert.pem / key.pem under
<state>/certificates/, the key optionally PEM-encrypted with the kek, and
PEM headers on the key carrying rotation state (the raft DEK).  Here the
key, its encryption flag, and the headers live in ONE json envelope
written atomically — a KEK rotation flips all of them in a single rename,
so no crash can leave the key and its headers sealed under different
KEKs (the reference gets the same atomicity from headers living inside
the key PEM).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from typing import Optional

try:
    from cryptography.fernet import Fernet, InvalidToken
except ImportError:  # pragma: no cover - depends on the environment
    # kek-sealed key storage falls back to the hashlib-backed Fernet
    # stand-in (swarmkit_tpu.encryption); plaintext storage is unaffected.
    from swarmkit_tpu.encryption.encryption import Fernet, InvalidToken


class KeyReadWriter:
    def __init__(self, cert_dir: str, kek: Optional[bytes] = None) -> None:
        self.cert_dir = cert_dir
        self._kek = kek
        os.makedirs(cert_dir, exist_ok=True)

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-node.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-node.key")

    @property
    def root_ca_path(self) -> str:
        return os.path.join(self.cert_dir, "swarm-root-ca.crt")

    # ------------------------------------------------------------------
    def _fernet(self, kek: bytes) -> Fernet:
        return Fernet(base64.urlsafe_b64encode(
            hashlib.sha256(kek).digest()))

    def set_kek(self, kek: Optional[bytes]) -> bool:
        """Re-encrypt the stored key AND headers under a new kek in ONE
        atomic envelope write; no-op (returns False) when it is already in
        effect (reference: RotateKEK keyreadwriter.go)."""
        if kek == self._kek:
            return False
        env = self._load()
        key = self._open_key(env) if env else None
        headers = self._open_headers(env) if env else {}
        self._kek = kek
        if env is not None:
            self._store(key, headers)
        return True

    # -- the key envelope ------------------------------------------------
    # swarm-node.key holds {"v": 1, "key": b64, "encrypted": bool,
    # "headers": {name: {"v": b64, "encrypted": bool}}} — key and headers
    # always flip KEKs together.
    def _load(self) -> Optional[dict]:
        if not os.path.exists(self.key_path):
            return None
        raw = open(self.key_path, "rb").read()
        if raw[:1] == b"{":
            return json.loads(raw)
        # legacy layout: raw payload + .meta / .headers sidecars
        meta = {"encrypted": False}
        if os.path.exists(self.key_path + ".meta"):
            meta = json.loads(open(self.key_path + ".meta").read())
        headers = {}
        legacy_headers = os.path.join(self.cert_dir, "swarm-node.headers")
        if os.path.exists(legacy_headers):
            headers = json.loads(open(legacy_headers).read())
        return {"v": 1, "key": base64.b64encode(raw).decode(),
                "encrypted": bool(meta.get("encrypted")),
                "headers": headers}

    def _open_key(self, env: dict) -> bytes:
        payload = base64.b64decode(env["key"])
        if env.get("encrypted"):
            if not self._kek:
                raise PermissionError(
                    "node key is locked; unlock key required")
            try:
                payload = self._fernet(self._kek).decrypt(payload)
            except InvalidToken:
                raise PermissionError("invalid unlock key")
        return payload

    def _open_headers(self, env: dict) -> dict[str, bytes]:
        out: dict[str, bytes] = {}
        for name, entry in env.get("headers", {}).items():
            raw = base64.b64decode(entry["v"])
            if entry.get("encrypted"):
                if not self._kek:
                    raise PermissionError(
                        f"header {name} is locked; unlock key required")
                try:
                    raw = self._fernet(self._kek).decrypt(raw)
                except InvalidToken:
                    raise PermissionError("invalid unlock key (headers)")
            out[name] = raw
        return out

    def _store(self, key_pem: bytes, headers: dict[str, bytes]) -> None:
        enc = bool(self._kek)
        payload = self._fernet(self._kek).encrypt(key_pem) if enc \
            else key_pem
        blob = {}
        for name, value in headers.items():
            sealed = self._fernet(self._kek).encrypt(value) if enc else value
            blob[name] = {"v": base64.b64encode(sealed).decode(),
                          "encrypted": enc}
        env = {"v": 1, "key": base64.b64encode(payload).decode(),
               "encrypted": enc, "headers": blob}
        self._atomic(self.key_path, json.dumps(env).encode(), mode=0o600)
        for legacy in (self.key_path + ".meta",
                       os.path.join(self.cert_dir, "swarm-node.headers")):
            if os.path.exists(legacy):
                os.unlink(legacy)

    # -- raft DEK accessors (reference: manager/deks.go RaftDEKData — the
    # DEK generations ride the key headers so the KEK protects them) -----
    def get_raft_deks(self) -> tuple[Optional[bytes], list[bytes]]:
        """(current DEK, older generations still present in the log)."""
        h = self.get_headers()
        cur = h.get("raft_dek")
        hist = [base64.b64decode(x)
                for x in json.loads(h["raft_dek_history"].decode())] \
            if h.get("raft_dek_history") else []
        return cur, hist

    def set_raft_deks(self, current: bytes, history: list[bytes]) -> None:
        h = self.get_headers()
        h["raft_dek"] = current
        h["raft_dek_history"] = json.dumps(
            [base64.b64encode(x).decode() for x in history]).encode()
        self.set_headers(h)

    def is_encrypted(self) -> bool:
        env = self._load()
        return bool(env and env.get("encrypted"))

    def get_headers(self) -> dict[str, bytes]:
        env = self._load()
        return self._open_headers(env) if env else {}

    def set_headers(self, headers: dict[str, bytes]) -> None:
        env = self._load()
        key = self._open_key(env) if env else b""
        self._store(key, headers)

    # ------------------------------------------------------------------
    def write(self, cert_pem: bytes, key_pem: bytes) -> None:
        env = self._load()
        headers = self._open_headers(env) if env else {}
        self._atomic(self.cert_path, cert_pem)
        self._store(key_pem, headers)

    def read(self) -> tuple[Optional[bytes], Optional[bytes]]:
        env = self._load()
        if not os.path.exists(self.cert_path) or env is None:
            return None, None
        cert = open(self.cert_path, "rb").read()
        return cert, self._open_key(env)

    def write_root_ca(self, cert_pem: bytes) -> None:
        self._atomic(self.root_ca_path, cert_pem)

    def read_root_ca(self) -> Optional[bytes]:
        if not os.path.exists(self.root_ca_path):
            return None
        return open(self.root_ca_path, "rb").read()

    @staticmethod
    def _atomic(path: str, data: bytes, mode: int = 0o644) -> None:
        """reference: ioutils.AtomicWriteFile.  ``mode`` applies from the
        first byte (keys must never exist world-readable, even as .tmp)."""
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        os.fchmod(fd, mode)  # O_CREAT mode is skipped if tmp pre-exists
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

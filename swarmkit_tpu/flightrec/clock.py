"""Tick <-> wall-clock correlation for the causal trace export.

The device planes (flight ring, telemetry series) timestamp in ticks —
unitless scan iterations — while host tracer spans carry wall-clock
seconds.  :class:`ClockSync` collects ``(tick, host_ns)`` sync points at
host<->device exchange boundaries (each blocking ``device_get`` of
``state.tick`` is an observation of "the device was at tick T when my
clock read t_ns") and fits a robust line through them, so the export
layer (flightrec/export.py) can place device instants on the same
wall-clock axis as the host spans and draw flow arrows between them.

The fit is Theil–Sen (median of pairwise slopes): a stalled host thread,
an NTP step, or one garbage sample shifts the median far less than a
least-squares fit, and the estimator degrades gracefully — one sync
point anchors an offset with the caller's nominal tick rate, zero sync
points leaves the export on its synthetic tick-as-µs axis.  Residuals
and sample counts publish as ``swarm_trace_clock_*`` metrics so drift
between the two clock domains is visible on the scrape page.
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass
from typing import Optional

MAX_SYNC_POINTS = 256


@dataclass(frozen=True)
class ClockFit:
    """host_ns(tick) = intercept_ns + slope_ns_per_tick * tick."""
    slope_ns_per_tick: float
    intercept_ns: float
    n_samples: int
    residual_ns: float      # max |fit - sample| over the sync points
    degenerate: bool        # True when < 2 usable points pinned the slope

    def host_ns_at(self, tick) -> float:
        return self.intercept_ns + self.slope_ns_per_tick * float(tick)

    def to_dict(self) -> dict:
        return {"slope_ns_per_tick": self.slope_ns_per_tick,
                "intercept_ns": self.intercept_ns,
                "n_samples": self.n_samples,
                "residual_ns": self.residual_ns,
                "degenerate": self.degenerate}


class ClockSync:
    """Bounded sync-point collector + robust linear fit.

    ``fallback_tick_us`` is the nominal tick duration used when the
    samples cannot pin a slope themselves (0 or 1 point, or all points
    at one tick).  The default clock is ``time.time_ns()`` — the same
    wall-clock domain as metrics/trace.py spans — so a fit maps ticks
    straight onto the span timeline; pass explicit ``host_ns`` values
    to correlate against a different clock.
    """

    def __init__(self, fallback_tick_us: float = 1.0) -> None:
        if fallback_tick_us <= 0:
            raise ValueError(f"fallback_tick_us must be > 0, "
                             f"got {fallback_tick_us}")
        self.fallback_tick_us = float(fallback_tick_us)
        self.samples: list[tuple[int, int]] = []
        self.discarded = 0   # over-capacity evictions (oldest-first)

    def add(self, tick, host_ns: Optional[int] = None) -> None:
        """Record one sync point.  `tick` may be a device scalar (it is
        read back here — callers already paid the sync that makes the
        observation meaningful).  Non-monotonic and duplicate samples
        are kept: the robust fit, not the collector, decides what an
        outlier is."""
        t = int(tick)
        ns = time.time_ns() if host_ns is None else int(host_ns)
        self.samples.append((t, ns))
        if len(self.samples) > MAX_SYNC_POINTS:
            del self.samples[0]
            self.discarded += 1

    def fit(self) -> Optional[ClockFit]:
        """Theil–Sen fit over the sync points; None when empty."""
        if not self.samples:
            return None
        fallback_slope = self.fallback_tick_us * 1e3  # ns per tick
        pts = sorted(self.samples)
        slopes = [(ns_b - ns_a) / (t_b - t_a)
                  for i, (t_a, ns_a) in enumerate(pts)
                  for (t_b, ns_b) in pts[i + 1:]
                  if t_b != t_a]
        # A wall clock stepped backwards (or a tick observed out of
        # order) yields non-positive pairwise slopes; ticks never run
        # backwards, so those pairs are clock artifacts, not evidence.
        slopes = [s for s in slopes if s > 0]
        degenerate = not slopes
        slope = statistics.median(slopes) if slopes else fallback_slope
        intercept = statistics.median(ns - slope * t for t, ns in pts)
        residual = max(abs(intercept + slope * t - ns) for t, ns in pts)
        return ClockFit(slope_ns_per_tick=float(slope),
                        intercept_ns=float(intercept),
                        n_samples=len(pts), residual_ns=float(residual),
                        degenerate=degenerate)

    def to_dict(self) -> dict:
        d = {"fallback_tick_us": self.fallback_tick_us,
             "discarded": self.discarded,
             "samples": [[t, ns] for t, ns in self.samples]}
        f = self.fit()
        if f is not None:
            d["fit"] = f.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ClockSync":
        cs = cls(fallback_tick_us=d.get("fallback_tick_us", 1.0))
        cs.samples = [(int(t), int(ns)) for t, ns in d.get("samples", ())]
        cs.discarded = int(d.get("discarded", 0))
        return cs

    def publish(self, obs=None) -> None:
        """Fold the collector into the swarm_trace_clock_* metrics."""
        from swarmkit_tpu.metrics import catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        obs = obs or obs_registry.DEFAULT
        catalog.get(obs, "swarm_trace_clock_sync_points_total").inc(
            len(self.samples))
        f = self.fit()
        if f is not None:
            catalog.get(obs, "swarm_trace_clock_tick_us").set(
                f.slope_ns_per_tick / 1e3)
            catalog.get(obs, "swarm_trace_clock_residual_us").set(
                f.residual_ns / 1e3)


def fit_from(obj) -> Optional[ClockFit]:
    """Coerce a ClockSync, ClockFit, or to_dict() payload into a fit.
    None in, None out — callers treat None as "stay on the tick axis"."""
    if obj is None:
        return None
    if isinstance(obj, ClockFit):
        return obj
    if isinstance(obj, ClockSync):
        return obj.fit()
    if isinstance(obj, dict):
        if "samples" in obj:                      # ClockSync.to_dict form
            return ClockSync.from_dict(obj).fit()
        if "slope_ns_per_tick" in obj:            # ClockFit.to_dict form
            return ClockFit(
                slope_ns_per_tick=float(obj["slope_ns_per_tick"]),
                intercept_ns=float(obj["intercept_ns"]),
                n_samples=int(obj.get("n_samples", 0)),
                residual_ns=float(obj.get("residual_ns", 0.0)),
                degenerate=bool(obj.get("degenerate", False)))
    raise TypeError(f"cannot derive a clock fit from {type(obj).__name__}")

"""Event vocabulary + the on-device ring append.

Events are fixed-width i32 rows ``(tick, code, arg0, arg1)`` written into
``SimState.ev_buf`` ([N, event_ring, 4]) with a per-row cumulative cursor
``ev_pos`` — slot of event k is ``k % event_ring``, so old events
overwrite silently and the host derives the dropped count from the
cursor.  This module owns the code <-> meaning contract; the kernel
imports :func:`ring_append` (flightrec never imports the kernel, keeping
the layering acyclic) and the decoder mirrors the arg semantics below.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EVENT_WIDTH = 4         # (tick, code, arg0, arg1)
EVENT_WIDTH_TAGGED = 5  # (tick, code, arg0, arg1, tag) — cfg.trace_tags

# Codes (ISSUE 5 vocabulary).  args per code:
#   ELECTION_WON     arg0=new term            arg1=last log index
#   TERM_BUMP        arg0=new term            arg1=old term
#   COMMIT_ADVANCE   arg0=new commit index    arg1=advance delta
#   SNAPSHOT_RESTORE arg0=sending leader row  arg1=new snap_idx
#   FALLBACK_TICK    arg0=chunks needed       arg1=band cap (row 0 only:
#                    the tiled full-pass fallback is a cluster-wide event)
#   FAULT_EDGE       arg0=EDGE_* transition   arg1=drop degree (EDGE_DROP)
#   APPEND_REJECT    arg0=rejected leader row arg1=rejector's last index
#   READ_SERVED      arg0=applied idx served  arg1=batch size (reads)
#   READ_BLOCKED     arg0=reads refused       arg1=BLOCK_* reason
#   LEASE_EXPIRED    arg0=lease expiry tick   arg1=reads bounced with it
# Attack signatures (ISSUE 15: emitted by the dst/schedule.py adversary
# verbs on the row the attack targets, when the state carries a ring):
#   ATTACK_REJOIN    arg0=row's term          arg1=row's timeout
#   ATTACK_EQUIVOCATE arg0=wiped vote         arg1=row's term
#   ATTACK_FLOOD     arg0=extra proposals     arg1=leader uncommitted tail
#   ATTACK_TRANSFER  arg0=requested target    arg1=cooldown remaining
# Storage signatures (ISSUE 16 durability boundary: FSYNC_ADVANCE and
# RECOVER_REJECT_SNAP come from the kernel, the RECOVER_*/FSYNC_STALL/
# SNAP_CORRUPT verbs from dst/schedule.py storage-fault leaves):
#   FSYNC_ADVANCE    arg0=new sync_mark       arg1=entries synced
#   RECOVER_TRUNCATE arg0=new last (lost_tail) arg1=entries truncated
#   RECOVER_REJECT_SNAP arg0=sending row      arg1=kept snap_idx
#   RECOVER_TORN     arg0=new last (torn)     arg1=old sync_mark
#   FSYNC_STALL      arg0=unsynced suffix     arg1=row's sync_mark
#   SNAP_CORRUPT     arg0=row's snap_idx      arg1=row's commit
ELECTION_WON = 1
TERM_BUMP = 2
COMMIT_ADVANCE = 3
SNAPSHOT_RESTORE = 4
FALLBACK_TICK = 5
FAULT_EDGE = 6
APPEND_REJECT = 7
READ_SERVED = 8
READ_BLOCKED = 9
LEASE_EXPIRED = 10
ATTACK_REJOIN = 11
ATTACK_EQUIVOCATE = 12
ATTACK_FLOOD = 13
ATTACK_TRANSFER = 14
FSYNC_ADVANCE = 15
RECOVER_TRUNCATE = 16
RECOVER_REJECT_SNAP = 17
RECOVER_TORN = 18
FSYNC_STALL = 19
SNAP_CORRUPT = 20

CODE_NAMES = {
    ELECTION_WON: "ELECTION_WON",
    TERM_BUMP: "TERM_BUMP",
    COMMIT_ADVANCE: "COMMIT_ADVANCE",
    SNAPSHOT_RESTORE: "SNAPSHOT_RESTORE",
    FALLBACK_TICK: "FALLBACK_TICK",
    FAULT_EDGE: "FAULT_EDGE",
    APPEND_REJECT: "APPEND_REJECT",
    READ_SERVED: "READ_SERVED",
    READ_BLOCKED: "READ_BLOCKED",
    LEASE_EXPIRED: "LEASE_EXPIRED",
    ATTACK_REJOIN: "ATTACK_REJOIN",
    ATTACK_EQUIVOCATE: "ATTACK_EQUIVOCATE",
    ATTACK_FLOOD: "ATTACK_FLOOD",
    ATTACK_TRANSFER: "ATTACK_TRANSFER",
    FSYNC_ADVANCE: "FSYNC_ADVANCE",
    RECOVER_TRUNCATE: "RECOVER_TRUNCATE",
    RECOVER_REJECT_SNAP: "RECOVER_REJECT_SNAP",
    RECOVER_TORN: "RECOVER_TORN",
    FSYNC_STALL: "FSYNC_STALL",
    SNAP_CORRUPT: "SNAP_CORRUPT",
}

# Codes whose 5th lane carries a host trace tag when the ring is tagged
# (cfg.trace_tags, ISSUE 17 causal tracing): the commit/serve instants a
# host propose or read span is waiting on.  Every other code writes 0.
TAGGED_CODES = frozenset({COMMIT_ADVANCE, READ_SERVED})

# FAULT_EDGE arg0 values: row went down / came back / its drop degree
# (in+out partitioned edges) changed.
EDGE_DOWN = 0
EDGE_UP = 1
EDGE_DROP = 2

# READ_BLOCKED arg1 values: the row lost leadership with unstamped reads
# pending, or its lease expired without renewal.
BLOCK_DEPOSED = 0
BLOCK_LEASE = 1

I32 = jnp.int32


def ring_append(ev_buf: jax.Array, ev_pos: jax.Array, mask: jax.Array,
                tick: jax.Array, code: int, arg0: jax.Array,
                arg1: jax.Array, tag: jax.Array | None = None):
    """Append one event per row where `mask` is True.

    ev_buf [N, cap, W] (W = EVENT_WIDTH, or EVENT_WIDTH_TAGGED when the
    ring carries the trace-tag lane), ev_pos [N] cumulative cursor, mask
    [N] bool, tick scalar i32, arg0/arg1 [N] i32, tag optional [N] i32
    written into the 5th lane (0 when None; ignored on untagged rings).
    Rows where mask is False keep their slot contents and cursor.  The
    write is a plain per-row scatter — the ring is tiny and only traced
    when cfg.record_events is on, so the kernel's one-write-cond
    discipline (which protects the [N, L] log carries) does not apply
    here.  Shapes are row-local, so the same code composes with vmap
    over a leading schedule axis.
    """
    n, cap, width = ev_buf.shape
    node = jnp.arange(n, dtype=I32)
    slot = (ev_pos % cap).astype(I32)
    lanes = [jnp.broadcast_to(tick.astype(I32), (n,)),
             jnp.full((n,), code, I32),
             arg0.astype(I32), arg1.astype(I32)]
    if width == EVENT_WIDTH_TAGGED:
        lanes.append(jnp.zeros((n,), I32) if tag is None
                     else jnp.broadcast_to(tag.astype(I32), (n,)))
    row = jnp.stack(lanes, axis=-1)
    cur = ev_buf[node, slot]
    ev_buf = ev_buf.at[node, slot].set(
        jnp.where(mask[:, None], row, cur))
    return ev_buf, ev_pos + mask.astype(I32)

"""On-device flight recorder for the batched raft simulation.

`SimConfig.record_events` threads a fixed-width event ring through the
jitted tick (`codes.py` holds the coded event vocabulary and the masked
ring-append the kernel calls); the host side decodes rings into typed
events (`decoder.py`), wraps them with provenance into savable records
(`record.py`), and exports merged device + tracer-span timelines as
Chrome-trace / Perfetto JSON (`export.py`).
"""

from swarmkit_tpu.flightrec.clock import ClockFit, ClockSync, fit_from
from swarmkit_tpu.flightrec.codes import (
    APPEND_REJECT, CODE_NAMES, COMMIT_ADVANCE, EDGE_DOWN, EDGE_DROP,
    EDGE_UP, ELECTION_WON, EVENT_WIDTH, EVENT_WIDTH_TAGGED, FALLBACK_TICK,
    FAULT_EDGE, READ_SERVED, SNAPSHOT_RESTORE, TAGGED_CODES, TERM_BUMP,
    ring_append,
)
from swarmkit_tpu.flightrec.decoder import (
    FlightEvent, decode_rings, decode_state,
)
from swarmkit_tpu.flightrec.export import (
    export_record, to_chrome_trace, validate_chrome_trace,
)
from swarmkit_tpu.flightrec.record import (
    FlightRecord, capture, diff_records, load_record, save_record,
    summarize,
)

__all__ = [
    "APPEND_REJECT", "CODE_NAMES", "COMMIT_ADVANCE", "ClockFit",
    "ClockSync", "EDGE_DOWN", "EDGE_DROP", "EDGE_UP", "ELECTION_WON",
    "EVENT_WIDTH", "EVENT_WIDTH_TAGGED", "FALLBACK_TICK", "FAULT_EDGE",
    "READ_SERVED", "SNAPSHOT_RESTORE", "TAGGED_CODES", "TERM_BUMP",
    "FlightEvent",
    "FlightRecord", "capture", "decode_rings", "decode_state",
    "diff_records", "export_record", "fit_from", "load_record",
    "ring_append", "save_record", "summarize", "to_chrome_trace",
    "validate_chrome_trace",
]

"""Chrome-trace / Perfetto JSON export.

One timeline merges two clocks: device events are instants on a
tick-as-microsecond axis (pid "sim", one tid track per simulated
manager), host tracer spans are complete ("X") events on a wall-clock
axis normalized to start at 0 (pid "host", one tid track per subsystem —
the first dotted segment of the span name).  Telemetry time-series rows
(FlightRecord.counters, from the on-device ring) render as Perfetto
counter tracks ("C" phase, one track per series) on the sim tick axis,
so a post-mortem shows commit rate / leader churn / occupancy curves
next to the event instants.

Two optional layers fuse the clock domains into one causal picture
(ISSUE 17):

- ``clock`` (a flightrec/clock.py ClockSync / ClockFit / their dict
  forms) remaps the device tracks from the synthetic tick axis onto the
  host wall-clock axis, so a COMMIT_ADVANCE instant lands *inside* the
  host span that was waiting on it.
- trace tags (``cfg.trace_tags``): host spans carrying a ``trace_tag``
  attr (metrics/trace.py ``span_trace_tag``) and device events carrying
  the matching tag lane are joined by Chrome flow events (``ph`` s/t/f,
  shared ``id``), drawing propose -> commit -> settle arrows across the
  process boundary.  A tag seen on only one side (ring wrap ate the
  instant, span deque evicted the span) degrades to an orphan
  annotation + counter — never a crash.

Both load in chrome://tracing and ui.perfetto.dev;
:func:`validate_chrome_trace` is the dependency-free schema check the
tests (and `flight_view.py export --check`) run on the output.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

SIM_PID = 1
HOST_PID = 2

# Chrome trace "ph" phases used here: i = instant, X = complete span,
# M = metadata (process/thread names), s/t/f = flow start/step/finish.
_REQUIRED_EVENT_KEYS = {"ph", "pid", "tid", "name"}
_FLOW_PHASES = ("s", "t", "f")


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> list[dict]:
    out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name}}]
    if tid is not None:
        out = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tname or str(tid)}}]
    return out


def _publish_flow_metrics(n_flow: int, n_orphan_host: int,
                          n_orphan_device: int) -> None:
    # Best-effort, mirroring record.capture(): metrics must never cost
    # the export (tests call to_chrome_trace with no registry set up).
    try:
        from swarmkit_tpu.metrics import catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        obs = obs_registry.DEFAULT
        if n_flow:
            catalog.get(obs, "swarm_trace_flow_events_total").inc(n_flow)
        m = catalog.get(obs, "swarm_trace_flow_orphans_total")
        if n_orphan_host:
            m.labels(side="host_only").inc(n_orphan_host)
        if n_orphan_device:
            m.labels(side="device_only").inc(n_orphan_device)
    except Exception:
        pass


def to_chrome_trace(events: Iterable = (), spans: Iterable[dict] = (),
                    tick_us: float = 1.0,
                    counters: Iterable[dict] = (),
                    clock=None) -> dict:
    """Build the trace dict.  `events` are FlightEvents (or dicts from a
    saved record); `spans` are Span.to_dict() rows; `counters` are
    FlightRecord.counters rows ({"name", "tick", "value"}).  `tick_us`
    maps one sim tick onto the µs timeline when no usable `clock` is
    given (ticks are unitless; 1 µs/tick keeps the two clock domains
    visually comparable, not aligned).  With a `clock` carrying at least
    one sync point, device ticks are remapped onto the host wall-clock
    axis instead, and host spans + device tracks share one normalized
    t0."""
    from swarmkit_tpu.flightrec.clock import fit_from
    from swarmkit_tpu.flightrec.codes import CODE_NAMES

    fit = fit_from(clock)
    span_rows = [s for s in spans if s.get("duration") is not None]
    event_rows = [e if isinstance(e, dict) else e.to_dict() for e in events]

    # One normalized origin for both clock domains.  Without a fit the
    # domains stay independent (device on the synthetic tick axis), so
    # t0 only ranges over span starts, as before.
    counters = list(counters)
    origins = [s["start"] for s in span_rows]
    if fit is not None:
        ticks = [int(d["tick"]) for d in event_rows] \
            + [int(c["tick"]) for c in counters]
        if ticks:
            origins.append(fit.host_ns_at(min(ticks)) / 1e9)
    t0_s = min(origins, default=0.0)

    def tick_ts(tick) -> float:
        """Device tick -> trace µs (wall-clock when fitted)."""
        if fit is None:
            return float(tick) * tick_us
        return fit.host_ns_at(tick) / 1e3 - t0_s * 1e6

    # Effective tick width in trace µs, for thin tagged slices below.
    eff_tick_us = tick_us if fit is None else fit.slope_ns_per_tick / 1e3

    trace_events: list[dict] = _meta(SIM_PID, "sim (device flight ring)")
    sim_tids = set()
    host_tags: dict[int, list[dict]] = {}
    device_tags: dict[int, list[dict]] = {}
    for d in event_rows:
        node = int(d["node"])
        sim_tids.add(node)
        ev = {
            "ph": "i", "s": "t",  # thread-scoped instant
            "pid": SIM_PID, "tid": node,
            "ts": tick_ts(d["tick"]),
            "name": d.get("name", f"CODE_{d['code']}"),
            "args": {"arg0": int(d["arg0"]), "arg1": int(d["arg1"]),
                     "seq": int(d.get("seq", 0))},
        }
        tag = int(d.get("tag", 0) or 0)
        if tag:
            ev["args"]["trace_tag"] = tag
            device_tags.setdefault(tag, []).append(ev)
        trace_events.append(ev)
    for node in sorted(sim_tids):
        trace_events += _meta(SIM_PID, "", tid=node, tname=f"manager {node}")

    host_tids: dict[str, int] = {}
    for s in span_rows:
        subsystem = s["name"].split(".", 1)[0]
        tid = host_tids.setdefault(subsystem, len(host_tids))
        args = {k: v for k, v in (s.get("attrs") or {}).items()}
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        ev = {
            "ph": "X", "pid": HOST_PID, "tid": tid,
            "ts": (s["start"] - t0_s) * 1e6,
            "dur": max(s["duration"] * 1e6, 0.001),
            "name": s["name"], "args": args,
        }
        try:
            tag = int(args.get("trace_tag", 0) or 0)
        except (TypeError, ValueError):
            tag = 0
        if tag:
            host_tags.setdefault(tag, []).append(ev)
        trace_events.append(ev)
    if span_rows:
        trace_events = _meta(HOST_PID, "host (tracer spans)") + trace_events
        for subsystem, tid in sorted(host_tids.items(), key=lambda kv: kv[1]):
            trace_events += _meta(HOST_PID, "", tid=tid, tname=subsystem)

    # Flow arrows: for every tag seen on BOTH sides, start at the first
    # host span, step through the device instants (each also gets a thin
    # X slice so the arrow has a slice to bind to — flows attach to
    # enclosing slices, and "i" instants are not slices), finish at the
    # last host span (or the last device instant when the settle span is
    # missing from the ring).  One-sided tags degrade to annotations.
    n_flow = n_orphan_host = n_orphan_device = 0
    for tag in sorted(set(host_tags) | set(device_tags)):
        hs = sorted(host_tags.get(tag, ()), key=lambda e: e["ts"])
        ds = sorted(device_tags.get(tag, ()), key=lambda e: e["ts"])
        if not ds:
            n_orphan_host += 1
            for ev in hs:
                ev["args"]["flow_orphan"] = "no_device_event"
            continue
        for ev in ds:  # thin slice under each tagged instant (bind point)
            trace_events.append({
                "ph": "X", "pid": SIM_PID, "tid": ev["tid"],
                "ts": ev["ts"], "dur": max(eff_tick_us * 0.5, 0.001),
                "name": ev["name"], "args": dict(ev["args"]),
            })
        if not hs:
            n_orphan_device += 1
            for ev in ds:
                ev["args"]["flow_orphan"] = "no_host_span"
            continue
        flow = {"cat": "trace_tag", "name": "causal", "id": tag}
        chain = []
        first = hs[0]
        chain.append({"ph": "s", "pid": first["pid"], "tid": first["tid"],
                      "ts": first["ts"] + first["dur"] * 0.5, **flow})
        for ev in ds:
            chain.append({"ph": "t", "pid": ev["pid"], "tid": ev["tid"],
                          "ts": ev["ts"], "bp": "e", **flow})
        for ev in hs[1:]:
            chain.append({"ph": "t", "pid": ev["pid"], "tid": ev["tid"],
                          "ts": ev["ts"] + ev["dur"] * 0.5, "bp": "e",
                          **flow})
        chain[-1]["ph"] = "f"
        trace_events += chain
        n_flow += len(chain)
    _publish_flow_metrics(n_flow, n_orphan_host, n_orphan_device)

    # Counter tracks: Perfetto draws one area chart per (pid, name) "C"
    # series; tid 0 keeps them pinned under the sim process header.  Rows
    # are emitted in (name, tick) order so each track's timestamps are
    # monotonic (the validator enforces this).
    for c in sorted(counters, key=lambda c: (str(c["name"]), c["tick"])):
        trace_events.append({
            "ph": "C", "pid": SIM_PID, "tid": 0,
            "ts": tick_ts(c["tick"]),
            "name": f"telemetry.{c['name']}",
            "args": {"value": float(c["value"])},
        })

    out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if fit is not None:
        out["metadata"] = {"clock_fit": fit.to_dict()}
    return out


def validate_chrome_trace(trace: dict) -> list[str]:
    """Schema problems (empty = valid).  Checks the JSON-object format:
    a traceEvents array whose members carry ph/pid/tid/name, numeric
    ts (+dur for X phases), and JSON-serializable args.  Counter ("C")
    events additionally need numeric ts, an args object of numeric
    values, non-decreasing timestamps per (pid, name) track, and one
    track (pid, tid) per counter name — a name split across tids renders
    as two half-empty charts in Perfetto.  Flow events (s/t/f) need a
    numeric ts and an id, and every flow id must both start ("s") and
    terminate ("f") — a dangling flow renders as an arrow into nowhere."""
    problems: list[str] = []
    if not isinstance(trace, dict):
        return ["trace must be a JSON object"]
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents must be an array"]
    counter_last_ts: dict[tuple, float] = {}
    counter_tid: dict[tuple, object] = {}
    flow_phases: dict[object, set] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"event #{i} is not an object")
            continue
        missing = _REQUIRED_EVENT_KEYS - e.keys()
        if missing:
            problems.append(f"event #{i} missing keys {sorted(missing)}")
            continue
        if e["ph"] not in ("i", "X", "M", "B", "E", "C") + _FLOW_PHASES:
            problems.append(f"event #{i} has unknown phase {e['ph']!r}")
        if e["ph"] in ("i", "X", "C") + _FLOW_PHASES and not isinstance(
                e.get("ts"), (int, float)):
            problems.append(f"event #{i} ({e['ph']}) lacks numeric ts")
        if e["ph"] == "X" and not isinstance(e.get("dur"), (int, float)):
            problems.append(f"event #{i} (X) lacks numeric dur")
        if "args" in e and not isinstance(e["args"], dict):
            problems.append(f"event #{i} args is not an object")
        if e["ph"] in _FLOW_PHASES:
            if "id" not in e:
                problems.append(f"event #{i} ({e['ph']}) flow lacks an id")
            else:
                flow_phases.setdefault(e["id"], set()).add(e["ph"])
        if e["ph"] == "C" and isinstance(e.get("args"), dict) \
                and isinstance(e.get("ts"), (int, float)):
            bad = [k for k, v in e["args"].items()
                   if not isinstance(v, (int, float)) or isinstance(v, bool)]
            if bad:
                problems.append(f"event #{i} (C) has non-numeric counter "
                                f"values {sorted(bad)}")
            track = (e["pid"], e["name"])
            prev = counter_last_ts.get(track)
            if prev is not None and e["ts"] < prev:
                problems.append(
                    f"event #{i} (C) timestamp {e['ts']} goes backwards on "
                    f"counter track {e['name']!r} (prev {prev})")
            counter_last_ts[track] = e["ts"]
            seen_tid = counter_tid.setdefault(track, e["tid"])
            if seen_tid != e["tid"]:
                problems.append(
                    f"event #{i} (C) counter {e['name']!r} spans tids "
                    f"{seen_tid!r} and {e['tid']!r}; one track per series")
    for fid, phases in sorted(flow_phases.items(), key=lambda kv: str(kv[0])):
        for need in ("s", "f"):
            if need not in phases:
                problems.append(f"flow id {fid!r} never emits "
                                f"{'start' if need == 's' else 'finish'} "
                                f"({need!r}); arrows would dangle")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as exc:
        problems.append(f"trace is not JSON-serializable: {exc}")
    return problems


def export_record(rec, path: str, tick_us: float = 1.0) -> dict:
    """FlightRecord -> chrome trace JSON file; returns the trace dict.
    A record carrying clock sync points (FlightRecord.clock) exports on
    the fused wall-clock axis automatically."""
    trace = to_chrome_trace(rec.events, rec.spans, tick_us=tick_us,
                            counters=getattr(rec, "counters", ()),
                            clock=getattr(rec, "clock", None))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, indent=1)
    return trace

"""Chrome-trace / Perfetto JSON export.

One timeline merges two clocks: device events are instants on a
tick-as-microsecond axis (pid "sim", one tid track per simulated
manager), host tracer spans are complete ("X") events on a wall-clock
axis normalized to start at 0 (pid "host", one tid track per subsystem —
the first dotted segment of the span name).  Telemetry time-series rows
(FlightRecord.counters, from the on-device ring) render as Perfetto
counter tracks ("C" phase, one track per series) on the sim tick axis,
so a post-mortem shows commit rate / leader churn / occupancy curves
next to the event instants.  Both load in chrome://tracing and
ui.perfetto.dev; :func:`validate_chrome_trace` is the dependency-free
schema check the tests (and `flight_view.py export --check`) run on the
output.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

SIM_PID = 1
HOST_PID = 2

# Chrome trace "ph" phases used here: i = instant, X = complete span,
# M = metadata (process/thread names).
_REQUIRED_EVENT_KEYS = {"ph", "pid", "tid", "name"}


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> list[dict]:
    out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name}}]
    if tid is not None:
        out = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tname or str(tid)}}]
    return out


def to_chrome_trace(events: Iterable = (), spans: Iterable[dict] = (),
                    tick_us: float = 1.0,
                    counters: Iterable[dict] = ()) -> dict:
    """Build the trace dict.  `events` are FlightEvents (or dicts from a
    saved record); `spans` are Span.to_dict() rows; `counters` are
    FlightRecord.counters rows ({"name", "tick", "value"}).  `tick_us`
    maps one sim tick onto the µs timeline (ticks are unitless; 1 µs/tick
    keeps the two clock domains visually comparable, not aligned)."""
    trace_events: list[dict] = _meta(SIM_PID, "sim (device flight ring)")
    sim_tids = set()
    for e in events:
        d = e if isinstance(e, dict) else e.to_dict()
        node = int(d["node"])
        sim_tids.add(node)
        trace_events.append({
            "ph": "i", "s": "t",  # thread-scoped instant
            "pid": SIM_PID, "tid": node,
            "ts": float(d["tick"]) * tick_us,
            "name": d.get("name", f"CODE_{d['code']}"),
            "args": {"arg0": int(d["arg0"]), "arg1": int(d["arg1"]),
                     "seq": int(d.get("seq", 0))},
        })
    for node in sorted(sim_tids):
        trace_events += _meta(SIM_PID, "", tid=node, tname=f"manager {node}")

    span_rows = [s for s in spans if s.get("duration") is not None]
    t0 = min((s["start"] for s in span_rows), default=0.0)
    host_tids: dict[str, int] = {}
    for s in span_rows:
        subsystem = s["name"].split(".", 1)[0]
        tid = host_tids.setdefault(subsystem, len(host_tids))
        args = {k: v for k, v in (s.get("attrs") or {}).items()}
        args["span_id"] = s.get("span_id")
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        trace_events.append({
            "ph": "X", "pid": HOST_PID, "tid": tid,
            "ts": (s["start"] - t0) * 1e6,
            "dur": max(s["duration"] * 1e6, 0.001),
            "name": s["name"], "args": args,
        })
    if span_rows:
        trace_events = _meta(HOST_PID, "host (tracer spans)") + trace_events
        for subsystem, tid in sorted(host_tids.items(), key=lambda kv: kv[1]):
            trace_events += _meta(HOST_PID, "", tid=tid, tname=subsystem)

    # Counter tracks: Perfetto draws one area chart per (pid, name) "C"
    # series; tid 0 keeps them pinned under the sim process header.  Rows
    # are emitted in (name, tick) order so each track's timestamps are
    # monotonic (the validator enforces this).
    for c in sorted(counters, key=lambda c: (str(c["name"]), c["tick"])):
        trace_events.append({
            "ph": "C", "pid": SIM_PID, "tid": 0,
            "ts": float(c["tick"]) * tick_us,
            "name": f"telemetry.{c['name']}",
            "args": {"value": float(c["value"])},
        })

    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: dict) -> list[str]:
    """Schema problems (empty = valid).  Checks the JSON-object format:
    a traceEvents array whose members carry ph/pid/tid/name, numeric
    ts (+dur for X phases), and JSON-serializable args.  Counter ("C")
    events additionally need numeric ts, an args object of numeric
    values, non-decreasing timestamps per (pid, name) track, and one
    track (pid, tid) per counter name — a name split across tids renders
    as two half-empty charts in Perfetto."""
    problems: list[str] = []
    if not isinstance(trace, dict):
        return ["trace must be a JSON object"]
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents must be an array"]
    counter_last_ts: dict[tuple, float] = {}
    counter_tid: dict[tuple, object] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"event #{i} is not an object")
            continue
        missing = _REQUIRED_EVENT_KEYS - e.keys()
        if missing:
            problems.append(f"event #{i} missing keys {sorted(missing)}")
            continue
        if e["ph"] not in ("i", "X", "M", "B", "E", "C"):
            problems.append(f"event #{i} has unknown phase {e['ph']!r}")
        if e["ph"] in ("i", "X", "C") and not isinstance(
                e.get("ts"), (int, float)):
            problems.append(f"event #{i} ({e['ph']}) lacks numeric ts")
        if e["ph"] == "X" and not isinstance(e.get("dur"), (int, float)):
            problems.append(f"event #{i} (X) lacks numeric dur")
        if "args" in e and not isinstance(e["args"], dict):
            problems.append(f"event #{i} args is not an object")
        if e["ph"] == "C" and isinstance(e.get("args"), dict) \
                and isinstance(e.get("ts"), (int, float)):
            bad = [k for k, v in e["args"].items()
                   if not isinstance(v, (int, float)) or isinstance(v, bool)]
            if bad:
                problems.append(f"event #{i} (C) has non-numeric counter "
                                f"values {sorted(bad)}")
            track = (e["pid"], e["name"])
            prev = counter_last_ts.get(track)
            if prev is not None and e["ts"] < prev:
                problems.append(
                    f"event #{i} (C) timestamp {e['ts']} goes backwards on "
                    f"counter track {e['name']!r} (prev {prev})")
            counter_last_ts[track] = e["ts"]
            seen_tid = counter_tid.setdefault(track, e["tid"])
            if seen_tid != e["tid"]:
                problems.append(
                    f"event #{i} (C) counter {e['name']!r} spans tids "
                    f"{seen_tid!r} and {e['tid']!r}; one track per series")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as exc:
        problems.append(f"trace is not JSON-serializable: {exc}")
    return problems


def export_record(rec, path: str, tick_us: float = 1.0) -> dict:
    """FlightRecord -> chrome trace JSON file; returns the trace dict."""
    trace = to_chrome_trace(rec.events, rec.spans, tick_us=tick_us,
                            counters=getattr(rec, "counters", ()))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, indent=1)
    return trace

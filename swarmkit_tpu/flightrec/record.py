"""Flight records: decoded events + provenance, savable and diffable.

`capture()` is the one funnel from a recorded SimState (and optionally
the host tracer) to a :class:`FlightRecord`; it also publishes the
``swarm_flightrec_*`` counters so every capture shows up on the scrape
page.  Records serialize as plain JSON (version-tagged, like the DST
repro artifacts) so `tools/flight_view.py` can summarize / export /
diff them offline.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from swarmkit_tpu.flightrec.decoder import FlightEvent, decode_rings

RECORD_VERSION = 1

# Newest captures, kept process-global so the Manager scrape page's
# recent-events section (metrics/exposition.py) can show what the last
# post-mortems saw without threading a registry through every tool.
_RECENT: deque = deque(maxlen=4)


def recent_capture_events(limit: int = 16) -> list[dict]:
    """JSON-able rows for the scrape page: the tail events of the newest
    captures, each tagged with its trigger.  A capture with no device
    events (e.g. a host-span-only scenario-failure dump) still shows as
    one summary row — a post-mortem must never be invisible."""
    out: list[dict] = []
    for rec in list(_RECENT):
        if not rec.events:
            meta = json.dumps(rec.meta, sort_keys=True) if rec.meta else ""
            out.append({"source": "flightrec", "trigger": rec.trigger,
                        "describe": f"flightrec[{rec.trigger}] "
                                    f"{len(rec.spans)} host span(s) {meta}"})
            continue
        for e in rec.window(limit):
            d = e.to_dict()
            d["source"] = "flightrec"
            d["trigger"] = rec.trigger
            d["describe"] = f"flightrec[{rec.trigger}] {e.describe()}"
            out.append(d)
    return out[-limit:] if limit else out


@dataclass
class FlightRecord:
    events: list[FlightEvent]
    dropped: list[int]                  # per-row overwritten-event counts
    n: int
    trigger: str = "manual"             # manual / dst_violation / scenario
    meta: dict = field(default_factory=dict)
    spans: list[dict] = field(default_factory=list)  # host tracer spans
    # telemetry counter samples: {"name", "tick", "value"} rows decoded
    # from the time-series ring (telemetry.decode_series), rendered as
    # Perfetto counter tracks by flightrec/export.py
    counters: list = field(default_factory=list)
    # tick<->wall-clock sync points (flightrec/clock.py ClockSync.to_dict
    # payload); lets the export remap device tracks onto the host span
    # timeline instead of the synthetic tick-as-µs axis
    clock: Optional[dict] = None

    def window(self, last: int = 40) -> list[FlightEvent]:
        """The most recent `last` events — the post-mortem view."""
        return self.events[-last:]

    def to_dict(self) -> dict:
        d = {"version": RECORD_VERSION, "n": self.n,
             "trigger": self.trigger, "meta": self.meta,
             "dropped": list(self.dropped),
             "events": [e.to_dict() for e in self.events],
             "spans": self.spans,
             "counters": self.counters}
        if self.clock is not None:
            d["clock"] = self.clock
        return d


def capture(state, *, trigger: str = "manual", meta: Optional[dict] = None,
            tracer=None, obs=None, cfg=None, clock=None) -> FlightRecord:
    """Decode `state`'s rings into a FlightRecord and publish metrics.

    Pass `cfg` (the SimConfig the state was built with) to also decode a
    telemetry-enabled state's time-series ring into counter rows, so the
    Perfetto export shows latency/throughput series next to the event
    instants.  Pass `clock` (a flightrec/clock.py ClockSync fed at the
    driver's host<->device boundaries) to bake the tick<->wall-clock sync
    points into the record; its metrics publish alongside the capture
    counters."""
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.metrics import registry as obs_registry

    if state.ev_buf is None or state.ev_pos is None:
        raise ValueError("state carries no event ring "
                         "(SimConfig.record_events was off)")
    events, dropped = decode_rings(state.ev_buf, state.ev_pos)
    dropped = [int(d) for d in np.asarray(dropped)]
    spans = ([s.to_dict() for s in tracer.finished()]
             if tracer is not None else [])
    counters: list = []
    if cfg is not None and getattr(state, "tel_series", None) is not None:
        from swarmkit_tpu.telemetry import decode_series
        for name, points in sorted(decode_series(state, cfg).items()):
            counters += [{"name": name, "tick": t, "value": v}
                         for t, v in points]
    clock_dict = None
    if clock is not None:
        clock_dict = clock if isinstance(clock, dict) else clock.to_dict()
    rec = FlightRecord(events=events, dropped=dropped, n=len(dropped),
                       trigger=trigger, meta=dict(meta or {}), spans=spans,
                       counters=counters, clock=clock_dict)
    _RECENT.append(rec)

    obs = obs or obs_registry.DEFAULT
    if clock is not None and not isinstance(clock, dict):
        try:
            clock.publish(obs)
        except Exception:
            pass  # metrics must never cost the capture
    try:
        m_ev = catalog.get(obs, "swarm_flightrec_events_total")
        by_code: dict[str, int] = {}
        for e in events:
            by_code[e.name] = by_code.get(e.name, 0) + 1
        for name, count in sorted(by_code.items()):
            m_ev.labels(code=name).inc(count)
        total_drop = int(sum(rec.dropped))
        if total_drop:
            catalog.get(obs, "swarm_flightrec_dropped_total").inc(total_drop)
        catalog.get(obs, "swarm_flightrec_captures_total").labels(
            trigger=trigger).inc()
    except Exception:
        pass  # metrics must never cost the capture
    return rec


def save_record(rec: FlightRecord, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rec.to_dict(), f, indent=1, sort_keys=True)


def load_record(path: str) -> FlightRecord:
    with open(path, encoding="utf-8") as f:
        d = json.load(f)
    if d.get("version") != RECORD_VERSION:
        raise ValueError(f"unsupported flight-record version "
                         f"{d.get('version')!r} in {path}")
    events = [FlightEvent(tick=e["tick"], node=e["node"], code=e["code"],
                          arg0=e["arg0"], arg1=e["arg1"], seq=e["seq"],
                          tag=e.get("tag", 0))
              for e in d["events"]]
    return FlightRecord(events=events, dropped=list(d["dropped"]),
                        n=int(d["n"]), trigger=d.get("trigger", "manual"),
                        meta=d.get("meta", {}), spans=d.get("spans", []),
                        counters=d.get("counters", []),
                        clock=d.get("clock"))


def summarize(rec: FlightRecord, last: int = 20) -> str:
    """Human summary: per-code counts, drops, and the tail window."""
    by_code: dict[str, int] = {}
    for e in rec.events:
        by_code[e.name] = by_code.get(e.name, 0) + 1
    ticks = [e.tick for e in rec.events]
    lines = [f"flight record: {len(rec.events)} events across "
             f"{rec.n} nodes, trigger={rec.trigger}"]
    if ticks:
        lines.append(f"tick range: {min(ticks)}..{max(ticks)}")
    for name, count in sorted(by_code.items()):
        lines.append(f"  {name:<16} {count}")
    total_drop = sum(rec.dropped)
    if total_drop:
        worst = max(range(len(rec.dropped)), key=lambda i: rec.dropped[i])
        lines.append(f"  dropped (ring overwrote) {total_drop} events; "
                     f"worst row n{worst} lost {rec.dropped[worst]}")
    if rec.meta:
        lines.append("meta: " + json.dumps(rec.meta, sort_keys=True))
    if rec.events:
        lines.append(f"last {min(last, len(rec.events))} events:")
        lines += ["  " + e.describe() for e in rec.window(last)]
    if rec.spans:
        lines.append(f"host spans: {len(rec.spans)}")
    if rec.counters:
        series = sorted({c["name"] for c in rec.counters})
        lines.append(f"telemetry counters: {len(rec.counters)} samples "
                     f"across {len(series)} series ({', '.join(series)})")
    return "\n".join(lines)


def diff_records(a: FlightRecord, b: FlightRecord) -> str:
    """Where do two records diverge?  Compares the (tick, node, name,
    args) event streams and reports the first difference plus per-code
    count deltas — the tool for 'this seed passed, that seed failed'."""
    ka = [(e.tick, e.node, e.name, e.arg0, e.arg1) for e in a.events]
    kb = [(e.tick, e.node, e.name, e.arg0, e.arg1) for e in b.events]
    lines = [f"A: {len(ka)} events   B: {len(kb)} events"]
    counts: dict[str, list[int]] = {}
    for e in a.events:
        counts.setdefault(e.name, [0, 0])[0] += 1
    for e in b.events:
        counts.setdefault(e.name, [0, 0])[1] += 1
    for name in sorted(counts):
        ca, cb = counts[name]
        if ca != cb:
            lines.append(f"  {name:<16} A={ca} B={cb} (delta {cb - ca:+d})")
    first = next((i for i, (x, y) in enumerate(zip(ka, kb)) if x != y),
                 None)
    if first is None and len(ka) == len(kb):
        lines.append("streams are identical")
    else:
        i = first if first is not None else min(len(ka), len(kb))
        lines.append(f"first divergence at event #{i}:")
        lines.append("  A: " + (a.events[i].describe() if i < len(ka)
                                else "<end of record>"))
        lines.append("  B: " + (b.events[i].describe() if i < len(kb)
                                else "<end of record>"))
    return "\n".join(lines)

"""Host-side ring decoding: device arrays -> typed Python events."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from swarmkit_tpu.flightrec.codes import (
    BLOCK_DEPOSED, BLOCK_LEASE, CODE_NAMES, EDGE_DOWN, EDGE_DROP, EDGE_UP,
    EVENT_WIDTH, EVENT_WIDTH_TAGGED, FAULT_EDGE,
)

_EDGE_NAMES = {EDGE_DOWN: "down", EDGE_UP: "up", EDGE_DROP: "drop"}
_BLOCK_NAMES = {BLOCK_DEPOSED: "deposed", BLOCK_LEASE: "lease_expired"}


@dataclass(frozen=True)
class FlightEvent:
    tick: int
    node: int
    code: int
    arg0: int
    arg1: int
    seq: int        # per-row cumulative event number (cursor position)
    tag: int = 0    # host trace tag (cfg.trace_tags rings; 0 = untagged)

    @property
    def name(self) -> str:
        return CODE_NAMES.get(self.code, f"CODE_{self.code}")

    def describe(self) -> str:
        """One human line; arg semantics per flightrec/codes.py."""
        a0, a1 = self.arg0, self.arg1
        body = {
            "ELECTION_WON": f"term={a0} last={a1}",
            "TERM_BUMP": f"term={a0} (was {a1})",
            "COMMIT_ADVANCE": f"commit={a0} (+{a1})",
            "SNAPSHOT_RESTORE": f"from=n{a0} snap_idx={a1}",
            "FALLBACK_TICK": f"chunks={a0} band_cap={a1}",
            "APPEND_REJECT": f"leader=n{a0} last={a1}",
            "READ_SERVED": f"applied={a0} batch={a1}",
            "READ_BLOCKED": f"reads={a0} "
                            f"reason={_BLOCK_NAMES.get(a1, a1)}",
            "LEASE_EXPIRED": f"expired_at={a0} bounced={a1}",
            "ATTACK_REJOIN": f"term={a0} timeout={a1}",
            "ATTACK_EQUIVOCATE": f"wiped_vote=n{a0} term={a1}",
            "ATTACK_FLOOD": f"extra={a0} tail={a1}",
            "ATTACK_TRANSFER": f"target=n{a0} cooldown={a1}",
        }.get(self.name)
        if self.code == FAULT_EDGE:
            edge = _EDGE_NAMES.get(a0, f"edge_{a0}")
            body = f"{edge}" + (f" degree={a1}" if a0 == EDGE_DROP else "")
        if body is None:
            body = f"arg0={a0} arg1={a1}"
        if self.tag:
            body = f"{body} tag={self.tag:#x}"
        return f"t={self.tick:>5} n{self.node:<4} {self.name:<16} {body}"

    def to_dict(self) -> dict:
        d = {"tick": self.tick, "node": self.node, "code": self.code,
             "name": self.name, "arg0": self.arg0, "arg1": self.arg1,
             "seq": self.seq}
        if self.tag:
            d["tag"] = self.tag
        return d


def decode_rings(ev_buf, ev_pos) -> tuple[list[FlightEvent], np.ndarray]:
    """Drain rings into a (tick, node, seq)-ordered event list.

    ev_buf [N, cap, 4] (or [N, cap, 5] when the ring carries the
    trace-tag lane, cfg.trace_tags), ev_pos [N] cumulative cursors
    (device or numpy).  Returns (events, dropped[N]) where dropped
    counts per-row events overwritten before decoding (cursor -
    capacity, floored at 0).
    """
    buf = np.asarray(ev_buf)
    pos = np.asarray(ev_pos)
    if buf.ndim != 3 or buf.shape[-1] not in (EVENT_WIDTH,
                                              EVENT_WIDTH_TAGGED):
        raise ValueError(f"ev_buf must be [N, cap, {EVENT_WIDTH}] or "
                         f"[N, cap, {EVENT_WIDTH_TAGGED}], got {buf.shape}")
    tagged = buf.shape[-1] == EVENT_WIDTH_TAGGED
    n, cap, _ = buf.shape
    dropped = np.maximum(pos - cap, 0)
    events: list[FlightEvent] = []
    for node in range(n):
        for k in range(int(dropped[node]), int(pos[node])):
            vals = [int(v) for v in buf[node, k % cap]]
            t, code, a0, a1 = vals[:4]
            tag = vals[4] if tagged else 0
            events.append(FlightEvent(tick=t, node=node, code=code,
                                      arg0=a0, arg1=a1, seq=k, tag=tag))
    events.sort(key=lambda e: (e.tick, e.node, e.seq))
    return events, dropped


def decode_state(state) -> tuple[list[FlightEvent], np.ndarray]:
    """decode_rings over a SimState recorded with cfg.record_events."""
    if state.ev_buf is None or state.ev_pos is None:
        raise ValueError("state carries no event ring "
                         "(SimConfig.record_events was off)")
    return decode_rings(state.ev_buf, state.ev_pos)

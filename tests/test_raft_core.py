"""Raft golden-core behavior suite.

Scenario coverage mirrors manager/state/raft/raft_test.go:63-1025 (bootstrap,
elections, replication, quorum loss/recovery, restarts, conf changes,
snapshots, leader transfer) plus etcd raft edge cases (prevote, checkquorum
lease, stale-term nudge).
"""


import pytest

from swarmkit_tpu.raft import (
    Config, ConfChange, ConfChangeType, Entry, EntryType, Message, MsgType,
    ProposalDropped, RawNode,
)
from tests.raft_harness import InMemCluster


def all_applied_equal(c: InMemCluster, expect=None):
    ups = c.up_ids()
    logs = [c.applied[p] for p in ups]
    assert all(l == logs[0] for l in logs), c.status()
    if expect is not None:
        assert logs[0] == expect, (logs[0], expect)


class TestElection:
    def test_single_node_self_elects(self):
        c = InMemCluster([1])
        c.wait_leader()
        assert c.leader() == 1

    def test_three_node_bootstrap(self):
        c = InMemCluster([1, 2, 3])
        lead = c.wait_leader()
        assert lead in (1, 2, 3)
        # all agree on the leader and term
        terms = {c.nodes[p].raft.term for p in c.ids}
        assert len(terms) == 1

    def test_explicit_campaign(self):
        c = InMemCluster([1, 2, 3])
        c.elect(2)
        assert c.nodes[1].raft.lead == 2
        assert c.nodes[3].raft.lead == 2

    def test_reelection_after_leader_down(self):
        c = InMemCluster([1, 2, 3])
        lead = c.wait_leader()
        old_term = c.nodes[lead].raft.term
        c.stop(lead)
        new = c.wait_leader()
        assert new != lead
        assert c.nodes[new].raft.term > old_term

    def test_no_election_without_quorum(self):
        c = InMemCluster([1, 2, 3])
        lead = c.wait_leader()
        others = [p for p in c.ids if p != lead]
        c.stop(others[0])
        c.stop(lead)
        survivor = others[1]
        c.ticks(50)
        assert c.nodes[survivor].raft.state != "leader"

    def test_quorum_recovery(self):
        c = InMemCluster([1, 2, 3])
        lead = c.wait_leader()
        c.propose(b"a")
        others = [p for p in c.ids if p != lead]
        c.stop(others[0])
        c.stop(lead)
        c.ticks(30)
        c.start(others[0])
        new = c.wait_leader()
        c.propose(b"b")
        all_applied_equal(c, [b"a", b"b"])

    def test_up_to_date_log_wins(self):
        # A node with a stale log must not become leader.
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.partition([1, 2], [3])
        c.propose(b"x")
        c.heal()
        # 3 campaigns with a stale log: 1 and 2 reject.
        c.nodes[3].campaign()
        c.flush()
        assert c.nodes[3].raft.state != "leader"


class TestReplication:
    def test_basic_replication(self):
        c = InMemCluster([1, 2, 3])
        c.wait_leader()
        for i in range(5):
            c.propose(f"e{i}".encode())
        all_applied_equal(c, [f"e{i}".encode() for i in range(5)])

    def test_follower_catchup_after_downtime(self):
        c = InMemCluster([1, 2, 3])
        lead = c.wait_leader()
        follower = [p for p in c.ids if p != lead][0]
        c.stop(follower)
        for i in range(10):
            c.propose(f"v{i}".encode())
        c.start(follower)
        c.ticks(5)
        all_applied_equal(c)
        assert len(c.applied[follower]) == 10

    def test_proposal_without_leader_drops(self):
        c = InMemCluster([1, 2, 3])
        with pytest.raises(ProposalDropped):
            c.nodes[1].propose(b"nope")

    def test_follower_forwards_proposal(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.propose(b"fwd", pid=2)  # proposed at a follower
        all_applied_equal(c, [b"fwd"])

    def test_old_leader_rejoins_and_discards_uncommitted(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.propose(b"committed")
        # Partition leader alone; it accepts a proposal it can't commit.
        c.partition([1], [2, 3])
        c.nodes[1].propose(b"lost")
        c.flush()
        new = None
        for _ in range(100):
            c.tick()
            st = {p: c.nodes[p].raft.state for p in (2, 3)}
            if "leader" in st.values():
                new = [p for p, s in st.items() if s == "leader"][0]
                break
        assert new is not None
        c.propose(b"won", pid=new)
        c.heal()
        c.ticks(5)
        all_applied_equal(c, [b"committed", b"won"])

    def test_commit_requires_quorum(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.partition([1], [2, 3])
        c.nodes[1].propose(b"stuck")
        c.flush()
        committed_before = c.committed(1)
        c.ticks(3)
        assert c.committed(1) == committed_before


class TestRestart:
    def test_restart_preserves_log(self):
        c = InMemCluster([1, 2, 3])
        c.wait_leader()
        for i in range(3):
            c.propose(f"p{i}".encode())
        for p in list(c.ids):
            c.restart(p)
        c.wait_leader()
        c.propose(b"after")
        all_applied_equal(c, [b"p0", b"p1", b"p2", b"after"])

    def test_staggered_restart(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.propose(b"a")
        c.restart(2)
        c.wait_leader()
        c.propose(b"b")
        c.restart(3)
        c.wait_leader()
        c.propose(b"c")
        all_applied_equal(c, [b"a", b"b", b"c"])

    def test_wiped_node_does_not_panic(self):
        # Mirrors TestRaftWipedState (raft_test.go:674): a member that lost
        # its state out-of-band must not crash the cluster; it is NOT
        # expected to catch up (that is data loss by design).
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        for i in range(4):
            c.propose(f"w{i}".encode())
        c.restart(3, wipe=True)
        c.wait_leader()
        c.ticks(10)
        c.propose(b"after-wipe")
        assert c.applied[1][-1] == b"after-wipe"
        assert c.applied[2][-1] == b"after-wipe"


class TestConfChange:
    def test_add_node(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.propose(b"pre")
        c.nodes[1].propose_conf_change(
            ConfChange(id=1, type=ConfChangeType.ADD_NODE, node_id=4))
        c.flush()
        c.ticks(5)
        assert 4 in c.nodes[1].raft.voter_ids()
        c.propose(b"post")
        c.ticks(5)
        assert c.applied[4] == [b"pre", b"post"]

    def test_remove_node(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.nodes[1].propose_conf_change(
            ConfChange(id=1, type=ConfChangeType.REMOVE_NODE, node_id=3))
        c.flush()
        assert c.nodes[1].raft.voter_ids() == (1, 2)
        # Two-node quorum still works.
        c.stop(3)
        c.propose(b"two")
        assert c.applied[1] == [b"two"] and c.applied[2] == [b"two"]

    def test_remove_leader_then_reelect(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.nodes[1].propose_conf_change(
            ConfChange(id=1, type=ConfChangeType.REMOVE_NODE, node_id=1))
        c.flush()
        c.stop(1)
        new = c.wait_leader()
        assert new in (2, 3)
        c.propose(b"go")
        assert c.applied[2] == [b"go"]

    def test_quorum_grows_with_membership(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        for n in (4, 5):
            c.nodes[1].propose_conf_change(
                ConfChange(id=n, type=ConfChangeType.ADD_NODE, node_id=n))
            c.flush()
            c.ticks(5)
        assert c.nodes[1].raft.quorum() == 3
        # Lose two nodes: 3/5 still commits.
        c.stop(4)
        c.stop(5)
        c.propose(b"q")
        all_applied_equal(c)


class TestSnapshot:
    def test_slow_follower_gets_snapshot(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.stop(3)
        for i in range(10):
            c.propose(f"s{i}".encode())
        # Leader compacts its log (simulating SnapshotInterval trigger).
        lead_log = c.nodes[1].raft.log
        lead_log.compact(lead_log.applied)
        c.start(3)
        c.ticks(10)
        assert c.committed(3) == c.committed(1)
        # After a snapshot jump the follower's applied stream resumes from
        # the snapshot point (store contents come with the snapshot).
        assert c.nodes[3].raft.log.offset >= 10

    def test_snapshot_restore_membership(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.nodes[1].propose_conf_change(
            ConfChange(id=1, type=ConfChangeType.ADD_NODE, node_id=4))
        c.flush()
        c.ticks(3)
        c.stop(4)
        for i in range(6):
            c.propose(f"m{i}".encode())
        lead_log = c.nodes[1].raft.log
        lead_log.compact(lead_log.applied)
        c.start(4)
        c.ticks(10)
        assert c.nodes[4].raft.voter_ids() == (1, 2, 3, 4)


class TestLeaderTransfer:
    def test_transfer(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.propose(b"t")
        c.nodes[1].transfer_leadership(3)
        c.flush()
        c.ticks(3)
        assert c.nodes[3].raft.state == "leader"
        assert c.nodes[1].raft.state == "follower"

    def test_transfer_to_behind_follower_catches_up_first(self):
        c = InMemCluster([1, 2, 3])
        c.elect(1)
        c.stop(3)
        for i in range(5):
            c.propose(f"x{i}".encode())
        c.start(3)
        c.nodes[1].transfer_leadership(3)
        c.flush()
        c.ticks(5)
        assert c.nodes[3].raft.state == "leader"
        assert len(c.applied[3]) == 5


class TestCheckQuorum:
    def test_leader_steps_down_without_quorum(self):
        c = InMemCluster([1, 2, 3], check_quorum=True)
        c.elect(1)
        c.partition([1], [2, 3])
        # After an election timeout of no responses the leader abdicates.
        for _ in range(25):
            c.tick(1)
        assert c.nodes[1].raft.state == "follower"

    def test_lease_protects_leader_from_disruption(self):
        c = InMemCluster([1, 2, 3], check_quorum=True)
        c.elect(1)
        term = c.nodes[1].raft.term
        # A vote request arriving while the lease is fresh is ignored.
        c.nodes[2].step(Message(type=MsgType.VOTE, frm=3, to=2, term=term + 5,
                                index=0, log_term=0))
        c.flush()
        assert c.nodes[2].raft.term == term
        assert c.nodes[1].raft.state == "leader"


class TestPreVote:
    def test_prevote_elects(self):
        c = InMemCluster([1, 2, 3], pre_vote=True)
        lead = c.wait_leader()
        c.propose(b"pv")
        all_applied_equal(c, [b"pv"])

    def test_prevote_prevents_term_explosion(self):
        c = InMemCluster([1, 2, 3], pre_vote=True)
        c.elect(1)
        term = c.nodes[1].raft.term
        c.partition([3], [1, 2])
        c.ticks(100)
        # Partitioned node kept pre-campaigning but never bumped its term.
        assert c.nodes[3].raft.term == term
        c.heal()
        c.ticks(5)
        assert c.nodes[1].raft.state == "leader"
        assert c.nodes[1].raft.term == term


class TestChurn:
    def test_random_drops_still_converge(self):
        c = InMemCluster([1, 2, 3, 4, 5], seed=7)
        import random as _r
        rng = _r.Random(42)
        c.drop_fn = lambda m: rng.random() < 0.10
        lead = c.wait_leader(max_ticks=500)
        for i in range(20):
            lead = c.leader() or c.wait_leader(max_ticks=500)
            try:
                c.propose(f"c{i}".encode(), pid=lead)
            except ProposalDropped:
                pass
            c.ticks(3)
        c.drop_fn = None
        c.wait_leader(max_ticks=500)
        c.ticks(20)
        all_applied_equal(c)

    def test_repeated_leader_crashes(self):
        c = InMemCluster([1, 2, 3, 4, 5], seed=3)
        total = 0
        for round_i in range(5):
            lead = c.wait_leader(max_ticks=500)
            for i in range(3):
                c.propose(f"r{round_i}.{i}".encode())
                total += 1
            c.stop(lead)
            c.wait_leader(max_ticks=500)
            c.start(lead)
            c.ticks(10)
        c.ticks(10)
        all_applied_equal(c)
        assert len(c.applied[c.up_ids()[0]]) == total


class TestStaleTermNudge:
    def test_stale_leader_learns_new_term(self):
        c = InMemCluster([1, 2, 3], check_quorum=True)
        c.elect(1)
        c.partition([1], [2, 3])
        new = None
        for _ in range(100):
            c.tick()
            for p in (2, 3):
                if c.nodes[p].raft.state == "leader":
                    new = p
            if new:
                break
        assert new is not None
        c.heal()
        # Old leader (stale term) sends an append/heartbeat; receiver nudges
        # it with an APP_RESP carrying the new term → it steps down.
        c.ticks(5)
        states = {p: c.nodes[p].raft.state for p in c.ids}
        assert list(states.values()).count("leader") == 1

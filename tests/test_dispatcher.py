"""Dispatcher tests (reference: manager/dispatcher/dispatcher_test.go)."""

import asyncio
import random

import pytest

from swarmkit_tpu.api import (
    Annotations, Cluster, ClusterSpec, Config, ConfigSpec, Node, NodeSpec,
    NodeState, Secret, SecretSpec, Task, TaskSpec, TaskState, TaskStatus,
)
from swarmkit_tpu.api.dispatcher_msgs import (
    AssignmentAction, AssignmentsType,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.api.specs import ContainerSpec, SecretReference, ConfigReference
from swarmkit_tpu.manager.dispatcher import Dispatcher, ErrNodeNotFound
from swarmkit_tpu.manager.dispatcher.nodes import (
    ErrNodeNotRegistered, ErrSessionInvalid,
)
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


def make_node(i):
    return Node(id=f"node{i}",
                spec=NodeSpec(annotations=Annotations(name=f"node{i}")),
                status=NodeStatus(state=NodeState.UNKNOWN))


def make_task(i, node="node1", state=TaskState.ASSIGNED, secrets=(),
              configs=()):
    spec = TaskSpec(container=ContainerSpec(
        secrets=[SecretReference(secret_id=s) for s in secrets],
        configs=[ConfigReference(config_id=c) for c in configs]))
    return Task(id=f"task{i}", node_id=node, spec=spec,
                status=TaskStatus(state=state),
                desired_state=int(TaskState.RUNNING))


async def eventually(pred, clock=None, ticks=400):
    """Pump the event loop (and the fake clock a hair) until pred() holds."""
    for _ in range(ticks):
        if pred():
            return
        await asyncio.sleep(0)
        if clock is not None:
            await clock.advance(0.001)
    assert pred(), "condition not met"


async def pump(steps=8):
    for _ in range(steps):
        await asyncio.sleep(0)


async def setup(n_nodes=1):
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    d = Dispatcher(store, clock=clock, rng=random.Random(0))
    for i in range(1, n_nodes + 1):
        await store.update(lambda tx, i=i: tx.create(make_node(i)))
    await d.start(mark_unknown=False)
    return clock, store, d


@async_test
async def test_register_requires_node_record():
    clock, store, d = await setup(0)
    with pytest.raises(ErrNodeNotFound):
        await d.register("nodeX")
    await d.stop()


@async_test
async def test_register_marks_ready_and_heartbeat_keeps_alive():
    clock, store, d = await setup()
    sid = await d.register("node1", addr="10.0.0.1:999")
    node = store.get("node", "node1")
    assert node.status.state == NodeState.READY
    assert node.status.addr == "10.0.0.1:999"

    # heartbeats inside the TTL keep the node READY
    for _ in range(5):
        resp = await d.heartbeat("node1", sid)
        assert 4.5 <= resp.period <= 5.5
        await clock.advance(resp.period)
    assert store.get("node", "node1").status.state == NodeState.READY

    # missing ~3 periods marks it DOWN (grace multiplier 3)
    await clock.advance(20.0)
    await pump()
    assert store.get("node", "node1").status.state == NodeState.DOWN
    with pytest.raises(ErrNodeNotRegistered):
        await d.heartbeat("node1", sid)
    await d.stop()


@async_test
async def test_heartbeat_wrong_session_rejected():
    clock, store, d = await setup()
    await d.register("node1")
    with pytest.raises(ErrSessionInvalid):
        await d.heartbeat("node1", "bogus")
    await d.stop()


@async_test
async def test_reregistration_supersedes_session():
    clock, store, d = await setup()
    sid1 = await d.register("node1")
    sid2 = await d.register("node1")
    assert sid1 != sid2
    with pytest.raises(ErrSessionInvalid):
        await d.heartbeat("node1", sid1)
    await d.heartbeat("node1", sid2)
    await d.stop()


@async_test
async def test_update_task_status_batches_and_drops_regressions():
    clock, store, d = await setup()
    sid = await d.register("node1")
    await store.update(lambda tx: [tx.create(make_task(i)) for i in (1, 2)])

    await d.update_task_status("node1", sid, [
        ("task1", TaskStatus(state=TaskState.RUNNING)),
        ("task2", TaskStatus(state=TaskState.FAILED, message="boom")),
    ])
    await eventually(lambda: store.get("task", "task1").status.state
                   == TaskState.RUNNING, clock)
    assert store.get("task", "task2").status.state == TaskState.FAILED
    assert store.get("task", "task2").status.message == "boom"

    # regression RUNNING -> PREPARING is dropped
    await d.update_task_status("node1", sid, [
        ("task1", TaskStatus(state=TaskState.PREPARING))])
    await pump()
    assert store.get("task", "task1").status.state == TaskState.RUNNING
    await d.stop()


@async_test
async def test_update_task_status_foreign_node_rejected():
    clock, store, d = await setup(2)
    sid = await d.register("node2")
    await store.update(lambda tx: tx.create(make_task(1, node="node1")))
    with pytest.raises(PermissionError):
        await d.update_task_status("node2", sid, [
            ("task1", TaskStatus(state=TaskState.RUNNING))])
    await d.stop()


@async_test
async def test_assignments_complete_then_incremental():
    clock, store, d = await setup()
    await store.update(lambda tx: [
        tx.create(Secret(id="sec1", spec=SecretSpec(
            annotations=Annotations(name="sec1"), data=b"s3cret"))),
        tx.create(Config(id="cfg1", spec=ConfigSpec(
            annotations=Annotations(name="cfg1"), data=b"conf"))),
        tx.create(make_task(1, secrets=["sec1"], configs=["cfg1"])),
    ])
    sid = await d.register("node1")

    stream = d.assignments("node1", sid)
    msgs = []

    async def consume():
        async for m in stream:
            msgs.append(m)

    consumer = asyncio.get_running_loop().create_task(consume())
    await eventually(lambda: len(msgs) >= 1, clock)
    first = msgs[0]
    assert first.type == AssignmentsType.COMPLETE
    kinds = sorted(
        "task" if c.assignment.task is not None else
        ("secret" if c.assignment.secret is not None else "config")
        for c in first.changes)
    assert kinds == ["config", "secret", "task"]
    sec = next(c.assignment.secret for c in first.changes
               if c.assignment.secret is not None)
    assert sec.spec.data == b"s3cret"

    # new task assigned to this node -> INCREMENTAL update
    await store.update(lambda tx: tx.create(make_task(2)))
    await clock.advance(0.2)
    await eventually(lambda: len(msgs) >= 2, clock)
    inc = msgs[1]
    assert inc.type == AssignmentsType.INCREMENTAL
    assert [c.assignment.task.id for c in inc.changes] == ["task2"]
    assert inc.changes[0].action == AssignmentAction.UPDATE

    # task deleted -> REMOVE, and the secret/config are released with it
    await store.update(lambda tx: tx.delete("task", "task1"))
    await clock.advance(0.2)
    await eventually(lambda: len(msgs) >= 3, clock)
    rem = msgs[2]
    actions = {(("task" if c.assignment.task is not None else
                 ("secret" if c.assignment.secret is not None else "config")),
                c.action) for c in rem.changes}
    assert (("task", AssignmentAction.REMOVE)) in actions
    assert (("secret", AssignmentAction.REMOVE)) in actions
    assert (("config", AssignmentAction.REMOVE)) in actions

    consumer.cancel()
    await d.stop()


@async_test
async def test_assignments_ignores_foreign_and_preassigned_tasks():
    clock, store, d = await setup(2)
    sid = await d.register("node1")
    stream = d.assignments("node1", sid)
    msgs = []

    async def consume():
        async for m in stream:
            msgs.append(m)

    consumer = asyncio.get_running_loop().create_task(consume())
    await eventually(lambda: len(msgs) >= 1, clock)
    assert msgs[0].changes == []

    # a task on another node and a not-yet-assigned task produce nothing
    await store.update(lambda tx: [
        tx.create(make_task(1, node="node2")),
        tx.create(make_task(2, node="node1", state=TaskState.PENDING)),
    ])
    await clock.advance(0.5)
    await pump()
    assert len(msgs) == 1

    # scheduler moves task2 to ASSIGNED -> it flows out
    def assign(tx):
        t = tx.get("task", "task2").copy()
        t.status.state = TaskState.ASSIGNED
        tx.update(t)
    await store.update(assign)
    await clock.advance(0.2)
    await eventually(lambda: len(msgs) >= 2, clock)
    assert [c.assignment.task.id for c in msgs[1].changes] == ["task2"]
    consumer.cancel()
    await d.stop()


@async_test
async def test_session_stream_and_supersede():
    clock, store, d = await setup()
    await store.update(lambda tx: tx.create(
        Cluster(id="cl1", spec=ClusterSpec(
            annotations=Annotations(name="default")))))
    msgs = []

    async def consume():
        async for m in d.session("node1"):
            msgs.append(m)

    consumer = asyncio.get_running_loop().create_task(consume())
    await eventually(lambda: len(msgs) >= 1, clock)
    sid = msgs[0].session_id
    assert msgs[0].node.id == "node1"

    # re-registering closes the old session stream
    await d.register("node1")
    await eventually(lambda: consumer.done(), clock)
    await d.stop()


@async_test
async def test_mark_nodes_unknown_on_leader_start_then_down():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    n = make_node(1)
    n.status.state = NodeState.READY
    await store.update(lambda tx: tx.create(n))
    d = Dispatcher(store, clock=clock, rng=random.Random(0))
    await d.start(mark_unknown=True)
    assert store.get("node", "node1").status.state == NodeState.UNKNOWN

    # without re-registration within grace the node goes DOWN
    await clock.advance(30.0)
    await pump()
    assert store.get("node", "node1").status.state == NodeState.DOWN
    await d.stop()


@async_test
async def test_down_node_tasks_orphaned_after_24h():
    clock, store, d = await setup()
    sid = await d.register("node1")
    await store.update(lambda tx: tx.create(
        make_task(1, state=TaskState.RUNNING)))
    # node misses heartbeats -> DOWN
    await clock.advance(20.0)
    await pump()
    assert store.get("node", "node1").status.state == NodeState.DOWN
    # 24h later its tasks are ORPHANED
    await clock.advance(24 * 3600.0 + 1)
    await pump()
    assert (store.get("task", "task1").status.state == TaskState.ORPHANED)
    await d.stop()


@async_test
async def test_rate_limit_reregistrations():
    clock, store, d = await setup()
    for _ in range(3):
        await d.register("node1")
    with pytest.raises(RuntimeError):
        await d.register("node1")
    # after the rate-limit window, registration works again
    await clock.advance(10.0)
    await d.register("node1")
    await d.stop()


@async_test
async def test_update_task_status_partial_batch_not_stranded():
    """Regression: a foreign-node entry must not strand valid updates."""
    clock, store, d = await setup(2)
    sid = await d.register("node1")
    await store.update(lambda tx: [tx.create(make_task(1)),
                                   tx.create(make_task(2, node="node2"))])
    with pytest.raises(PermissionError):
        await d.update_task_status("node1", sid, [
            ("task1", TaskStatus(state=TaskState.RUNNING)),
            ("task2", TaskStatus(state=TaskState.RUNNING)),
        ])
    # nothing should have been enqueued from the rejected batch
    await pump()
    assert store.get("task", "task1").status.state == TaskState.ASSIGNED

    # a clean batch flows normally
    await d.update_task_status("node1", sid, [
        ("task1", TaskStatus(state=TaskState.RUNNING))])
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.RUNNING, clock)
    await d.stop()


@async_test
async def test_session_wakes_on_peer_broadcast():
    from swarmkit_tpu.api import Peer, WeightedPeer
    from swarmkit_tpu.watch.queue import Queue

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    peers_queue = Queue()
    managers = [WeightedPeer(peer=Peer(node_id="m1", addr="1.1.1.1:4242"))]
    d = Dispatcher(store, managers_fn=lambda: list(managers), clock=clock,
                   peers_queue=peers_queue, rng=random.Random(0))
    await store.update(lambda tx: tx.create(make_node(1)))
    await d.start(mark_unknown=False)

    msgs = []

    async def consume():
        async for m in d.session("node1"):
            msgs.append(m)

    consumer = asyncio.get_running_loop().create_task(consume())
    await eventually(lambda: len(msgs) >= 1, clock)
    assert [w.peer.node_id for w in msgs[0].managers] == ["m1"]

    # raft membership change (no store write) must reach the stream
    managers.append(WeightedPeer(peer=Peer(node_id="m2", addr="2.2.2.2:4242")))
    peers_queue.publish(object())
    await eventually(lambda: len(msgs) >= 2, clock)
    assert [w.peer.node_id for w in msgs[1].managers] == ["m1", "m2"]
    consumer.cancel()
    await d.stop()


@async_test
async def test_heartbeat_period_follows_cluster_spec():
    """cluster-update --heartbeat-period flows into the period the
    dispatcher hands agents on every heartbeat (reference:
    dispatcher.go:310-315 config reload on cluster events)."""
    from swarmkit_tpu.api import Cluster, ClusterSpec
    from swarmkit_tpu.api.specs import DispatcherConfig

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    d = Dispatcher(store, clock=clock)
    cl = Cluster(id="c1", spec=ClusterSpec(
        dispatcher=DispatcherConfig(heartbeat_period=5.0)))
    await store.update(lambda tx: tx.create(cl))
    await d.start()
    try:
        assert d.nodes.period == 5.0
        cur = store.get("cluster", "c1")
        cur.spec.dispatcher.heartbeat_period = 1.25
        await store.update(lambda tx: tx.update(cur))
        for _ in range(20):
            await asyncio.sleep(0)
        assert d.nodes.period == 1.25
    finally:
        await d.stop()

"""Slow wrapper around the DST sweep (tools/dst_sweep.py).

Runs the acceptance-sized sweep — 256 schedules x 100 ticks, seed 0 —
and the mutation self-test end to end (detect, shrink, artifact, exact
replay, oracle localization).  Excluded from tier-1 by the ``slow``
marker; run with::

    pytest tests/test_dst_sweep.py -m slow -q
"""

import pytest

from tools.dst_sweep import run_mutation_demo, run_sweep


@pytest.mark.slow
def test_dst_sweep_stock_kernel_clean():
    sweep = run_sweep(schedules=256, ticks=100, seed=0, verbose=False)
    assert sweep["violations"] == 0, sweep["violating_profiles"]
    assert sweep["schedules_per_sec"] > 0


@pytest.mark.slow
def test_dst_sweep_mutation_demo_end_to_end(tmp_path):
    demo = run_mutation_demo(schedules=24, ticks=100, seed=0,
                             out_path=str(tmp_path / "repro.json"),
                             verbose=False)
    assert demo["caught"], demo
    assert "leader_completeness" in demo["bits"]
    assert demo["fault_count_after"] < demo["fault_count_before"]
    assert demo["replay_matches"], demo
    # the field-level differential trace localizes the mutated commit path
    assert demo["oracle_diverged_at"] >= 0


@pytest.mark.slow
def test_dst_sweep_stale_read_mutation_demo(tmp_path):
    demo = run_mutation_demo(schedules=24, ticks=100, seed=0,
                             mutation="stale_lease_read",
                             out_path=str(tmp_path / "repro.json"),
                             verbose=False)
    assert demo["caught"], demo
    assert demo["bits"] == ["linearizable_read"]
    assert demo["profile"] == "stale_leader_reads"
    assert demo["fault_count_after"] <= demo["fault_count_before"]
    assert demo["replay_matches"], demo
    # the read registers sit OUTSIDE the differential oracle's field view
    # (dst/repro._VIEW_FIELDS), so no oracle divergence is expected here —
    # localization comes from the LINEARIZABLE_READ bit + flight window
    assert demo["oracle_diverged_at"] == -1
    assert demo["flight_events"] > 0


@pytest.mark.slow
def test_disruptive_rejoin_demo_neutralized():
    from tools.dst_sweep import run_disruptive_rejoin_demo
    demo = run_disruptive_rejoin_demo(verbose=False)
    assert demo["defense_off"]["churn_violations"] > 0, demo
    assert demo["defense_on"]["violations"] == 0, demo
    # PreVote + CheckQuorum hold churn at the SLO bound while the
    # undefended run deposes the leader on every barrage
    assert demo["defense_on"]["max_leader_changes"] \
        < demo["defense_off"]["max_leader_changes"], demo
    assert demo["neutralized"], demo


@pytest.mark.slow
def test_transfer_abuse_demo_neutralized():
    from tools.dst_sweep import run_transfer_abuse_demo
    demo = run_transfer_abuse_demo(verbose=False)
    assert demo["defense_off"]["churn_violations"] > 0, demo
    assert demo["defense_on"]["violations"] == 0, demo
    assert demo["defense_on"]["max_leader_changes"] \
        < demo["defense_off"]["max_leader_changes"], demo
    assert demo["neutralized"], demo


@pytest.mark.slow
def test_lost_tail_demo_neutralized(tmp_path):
    from tools.dst_sweep import run_lost_tail_demo
    demo = run_lost_tail_demo(out_path=str(tmp_path / "lost_tail.json"),
                              verbose=False)
    # gating-off commits entries a correlated crash then deletes from
    # every surviving log; the shrunk artifact replays bit-exact with
    # the differential oracle in lockstep over the clean prefix, and
    # ack-gating holds the SAME schedules violation-free
    assert demo["caught"] > 0, demo
    assert demo["gated_violations"] == 0, demo
    assert demo["replay_matches"], demo
    assert demo["oracle_diverged_at"] == -1, demo
    assert demo["neutralized"], demo

"""Causal cross-layer tracing (ISSUE 17): trace-tag propagation, clock
correlation, flow-linked export, and the console/profiler tools.

The load-bearing guarantees:

- ``trace_tags=False`` (and tags-on-but-untagged) leaves the kernel's
  consensus outputs bit-identical on the sync, mailbox, and sharded
  wires — the tag plane is Python-gated like both donor planes.
- A tagged propose batch surfaces as a tagged COMMIT_ADVANCE event; a
  tagged read batch as a tagged READ_SERVED event; the export joins
  those to host spans carrying the same tag with Chrome flow events
  (``ph`` s/t/f) that validate clean.
- Clock correlation degrades gracefully: zero sync points -> tick axis,
  one point -> degenerate anchored fit, a backwards host clock -> the
  robust fit ignores the non-positive pairwise slopes.
- A tag on only one side (ring wrap, evicted span) annotates an orphan
  instead of crashing or emitting a dangling flow.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swarmkit_tpu.flightrec import (
    COMMIT_ADVANCE, READ_SERVED, ClockFit, ClockSync, capture, decode_state,
    fit_from, load_record, save_record, to_chrome_trace,
    validate_chrome_trace,
)
from swarmkit_tpu.flightrec.codes import CODE_NAMES
from swarmkit_tpu.metrics.trace import Tracer, span_trace_tag
from swarmkit_tpu.raft.sim import (
    SimConfig, init_state, run_ticks, run_until_leader, step, submit_reads,
)
from swarmkit_tpu.raft.sim.kernel import propose_dense
from swarmkit_tpu.raft.sim.run import _payload_at

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

I32 = jnp.int32

PROP_TAG = 0x517A
READ_TAG = 0x9E3


def small_cfg(**kw):
    base = dict(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                keep=4, election_tick=10, seed=77)
    base.update(kw)
    return SimConfig(**base)


def tagged_cfg(**kw):
    return small_cfg(record_events=True, collect_telemetry=True,
                     trace_tags=True, read_batch=4, **kw)


def common_fields(a, b):
    """Leaf names present (non-None) on both states."""
    import dataclasses
    names = []
    for f in dataclasses.fields(a):
        if getattr(a, f.name) is not None and getattr(b, f.name) is not None:
            names.append(f.name)
    return names


def assert_common_bits_equal(a, b):
    for name in common_fields(a, b):
        va, vb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        if name == "ev_buf" and va.shape != vb.shape:
            # width-4 vs width-5 rings: the shared lanes must match and
            # the extra tag lane must be all-zero (nothing was tagged)
            w = min(va.shape[-1], vb.shape[-1])
            wide = va if va.shape[-1] > w else vb
            assert (va[..., :w] == vb[..., :w]).all(), "ev_buf diverged"
            assert (wide[..., w:] == 0).all(), "tag lane not zero"
            continue
        assert va.dtype == vb.dtype and (va == vb).all(), \
            f"field {name} diverged"


# ---------------------------------------------------------------------------
# knob-off / untagged bit-identity across the three wires


class TestTagsOffBitIdentity:
    def _run_pair(self, **wire_kw):
        cfg_off = small_cfg(record_events=True, collect_telemetry=True,
                            read_batch=4, **wire_kw)
        cfg_on = tagged_cfg(**wire_kw)
        off, tr_off = run_ticks(init_state(cfg_off), cfg_off, 40,
                                prop_count=4)
        on, tr_on = run_ticks(init_state(cfg_on), cfg_on, 40, prop_count=4)
        assert_common_bits_equal(off, on)
        assert (np.asarray(tr_off) == np.asarray(tr_on)).all()
        return on

    def test_sync_wire(self):
        on = self._run_pair()
        # the tag plane exists but stayed all-zero: nothing ever tagged
        assert int(jnp.sum(jnp.abs(on.tel_prop_tag))) == 0
        assert int(jnp.sum(jnp.abs(on.read_tag))) == 0

    @pytest.mark.slow  # tier-1 wall budget: sync wire is the tier-1 guard
    def test_mailbox_wire(self):
        self._run_pair(force_mailboxes=True)

    @pytest.mark.slow  # tier-1 wall budget: sync wire is the tier-1 guard
    def test_sharded_wire(self):
        from swarmkit_tpu.parallel import row_mesh, shard_rows

        cfg = tagged_cfg(n=8, seed=11)
        mesh = row_mesh(cfg.n)
        assert len(mesh.devices.ravel()) == 8
        plain, tr_p = run_ticks(init_state(cfg), cfg, 30, prop_count=4)
        sharded, tr_s = run_ticks(shard_rows(init_state(cfg), mesh), cfg,
                                  30, prop_count=4)
        assert_common_bits_equal(plain, sharded)
        assert (np.asarray(tr_p) == np.asarray(tr_s)).all()


# ---------------------------------------------------------------------------
# tag propagation: propose -> COMMIT_ADVANCE, reads -> READ_SERVED


@pytest.fixture(scope="module")
def tagged_run():
    cfg = tagged_cfg()
    st = init_state(cfg)
    st, _ = run_until_leader(st, cfg, max_ticks=200)
    st = propose_dense(st, cfg, _payload_at, jnp.asarray(4, I32),
                       tag=PROP_TAG)
    for _ in range(4):
        st = step(st, cfg)
    st = submit_reads(st, cfg, 2, tag=READ_TAG)
    for _ in range(6):
        st = step(st, cfg)
    events, _ = decode_state(st)
    return cfg, st, events


def test_event_ring_carries_tag_lane(tagged_run):
    cfg, st, _ = tagged_run
    assert cfg.event_width == 5
    assert st.ev_buf.shape[-1] == 5


def test_propose_tag_reaches_commit_advance(tagged_run):
    _, _, events = tagged_run
    tags = {e.tag for e in events if e.code == COMMIT_ADVANCE}
    assert PROP_TAG in tags
    # tags only appear on the taggable codes
    from swarmkit_tpu.flightrec import TAGGED_CODES
    for e in events:
        if e.tag:
            assert e.code in TAGGED_CODES


def test_read_tag_reaches_read_served(tagged_run):
    _, _, events = tagged_run
    tags = {e.tag for e in events if e.code == READ_SERVED}
    assert READ_TAG in tags


def test_record_roundtrips_tag_and_clock(tagged_run, tmp_path):
    cfg, st, events = tagged_run
    clock = ClockSync(fallback_tick_us=2.0)
    clock.add(1, host_ns=10_000)
    clock.add(5, host_ns=18_000)
    rec = capture(st, trigger="manual", cfg=cfg, clock=clock)
    assert rec.clock and rec.clock["samples"] == [[1, 10_000], [5, 18_000]]
    p = tmp_path / "rec.json"
    save_record(rec, str(p))
    back = load_record(str(p))
    assert back.clock == rec.clock
    assert [e.tag for e in back.events] == [e.tag for e in rec.events]
    assert any(e.tag == PROP_TAG for e in back.events)


# ---------------------------------------------------------------------------
# clock correlation edge cases


class TestClockSync:
    def test_zero_points_means_no_fit(self):
        cs = ClockSync()
        assert cs.fit() is None
        assert fit_from(None) is None
        assert fit_from(cs) is None

    def test_single_point_degenerate_anchor(self):
        cs = ClockSync(fallback_tick_us=3.0)
        cs.add(10, host_ns=1_000_000)
        f = cs.fit()
        assert f.degenerate and f.n_samples == 1
        assert f.slope_ns_per_tick == pytest.approx(3_000.0)
        assert f.host_ns_at(10) == pytest.approx(1_000_000.0)

    def test_non_monotonic_host_clock_is_robust(self):
        cs = ClockSync()
        # 100 ns/tick line, with one NTP step backwards in the middle
        for tick, ns in ((0, 0), (10, 1_000), (20, 500), (30, 3_000),
                         (40, 4_000)):
            cs.add(tick, host_ns=ns)
        f = cs.fit()
        assert not f.degenerate
        assert f.slope_ns_per_tick == pytest.approx(100.0, rel=0.35)
        assert f.slope_ns_per_tick > 0

    def test_fit_roundtrips_through_dicts(self):
        cs = ClockSync()
        cs.add(0, host_ns=100)
        cs.add(4, host_ns=500)
        f1 = fit_from(cs.to_dict())
        f2 = fit_from(cs.fit().to_dict())
        assert isinstance(f1, ClockFit) and isinstance(f2, ClockFit)
        assert f1.slope_ns_per_tick == pytest.approx(f2.slope_ns_per_tick)
        with pytest.raises(TypeError):
            fit_from(42)

    def test_bounded_collector_discards_oldest(self):
        from swarmkit_tpu.flightrec.clock import MAX_SYNC_POINTS
        cs = ClockSync()
        for t in range(MAX_SYNC_POINTS + 7):
            cs.add(t, host_ns=t * 10)
        assert len(cs.samples) == MAX_SYNC_POINTS and cs.discarded == 7


# ---------------------------------------------------------------------------
# flow-linked export (the acceptance journey)


def _span(name, start, dur, tag=None, sid="aa01"):
    attrs = {"trace_tag": tag} if tag else {}
    return {"name": name, "span_id": sid, "parent_id": None,
            "start": start, "duration": dur, "attrs": attrs}


def _dev_event(tick, code=COMMIT_ADVANCE, tag=0, node=0):
    return {"tick": tick, "node": node, "code": code,
            "name": CODE_NAMES[code], "arg0": 1, "arg1": 1, "seq": 0,
            "tag": tag}


def test_flow_links_propose_commit_settle():
    clock = ClockSync()
    clock.add(0, host_ns=int(10.0e9))       # tick 0 at t=10s
    clock.add(100, host_ns=int(10.1e9))     # 1 ms/tick
    spans = [_span("raft.propose", 10.00, 0.02, tag=PROP_TAG, sid="aa01"),
             _span("raft.settle", 10.06, 0.01, tag=PROP_TAG, sid="aa02")]
    events = [_dev_event(40, tag=PROP_TAG)]
    trace = to_chrome_trace(events, spans, clock=clock)
    assert validate_chrome_trace(trace) == []

    flows = [e for e in trace["traceEvents"] if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert {e["id"] for e in flows} == {PROP_TAG}
    s, t, f = flows
    # propose span -> device commit instant -> settle span, in time order
    assert s["ts"] < t["ts"] < f["ts"]
    assert s["pid"] == 2 and t["pid"] == 1 and f["pid"] == 2
    # the commit instant was remapped to wall clock: tick 40 at +40 ms
    inst = next(e for e in trace["traceEvents"] if e["ph"] == "i")
    assert inst["ts"] == pytest.approx(40_000.0, rel=1e-6)
    assert trace["metadata"]["clock_fit"]["slope_ns_per_tick"] == \
        pytest.approx(1e6)


def test_ring_wrap_orphan_annotates_instead_of_crashing():
    # host span whose device instant was overwritten by ring wrap...
    spans = [_span("raft.propose", 1.0, 0.1, tag=7)]
    # ...and a device instant whose span was evicted from the deque
    events = [_dev_event(3, tag=9)]
    trace = to_chrome_trace(events, spans)
    assert validate_chrome_trace(trace) == []
    assert not [e for e in trace["traceEvents"]
                if e["ph"] in ("s", "t", "f")]
    x = next(e for e in trace["traceEvents"]
             if e["ph"] == "X" and e["name"] == "raft.propose")
    assert x["args"]["flow_orphan"] == "no_device_event"
    inst = next(e for e in trace["traceEvents"] if e["ph"] == "i")
    assert inst["args"]["flow_orphan"] == "no_host_span"


def test_validator_rejects_dangling_flows():
    bad = {"traceEvents": [
        {"ph": "s", "pid": 1, "tid": 0, "name": "causal", "ts": 1.0,
         "id": 5}]}
    problems = validate_chrome_trace(bad)
    assert any("dangle" in p for p in problems)
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "t", "pid": 1, "tid": 0, "name": "causal",
                          "ts": 1.0}]})   # flow without an id


def test_captured_run_exports_validated_flow_trace(tagged_run, tmp_path):
    """The acceptance criterion end-to-end on a REAL kernel run: host
    propose span, device COMMIT_ADVANCE instant (wall-clock remapped),
    host settle span, one validated trace connecting them."""
    from swarmkit_tpu.flightrec.export import export_record

    cfg, st, _ = tagged_run
    tracer = Tracer()
    tag = PROP_TAG   # the tag the module fixture proposed with
    with tracer.span("raft.propose", trace_tag=tag):
        pass
    with tracer.span("raft.settle", trace_tag=tag):
        pass
    clock = ClockSync()
    clock.add(0, host_ns=int(1.0e9))
    clock.add(int(jax.device_get(st.tick)), host_ns=int(2.0e9))
    rec = capture(st, trigger="scenario", cfg=cfg, tracer=tracer,
                  clock=clock)
    path = tmp_path / "trace.json"
    trace = export_record(rec, str(path))
    assert validate_chrome_trace(trace) == []
    with open(path, encoding="utf-8") as f:
        assert validate_chrome_trace(json.load(f)) == []

    flows = [e for e in trace["traceEvents"] if e["ph"] in ("s", "t", "f")]
    ours = [e for e in flows if e["id"] == tag]
    assert {e["ph"] for e in ours} >= {"s", "t", "f"}
    # at least one flow step rides a device COMMIT_ADVANCE instant
    commit_inst = [e for e in trace["traceEvents"]
                   if e["ph"] == "i" and e["name"] == "COMMIT_ADVANCE"
                   and e["args"].get("trace_tag") == tag]
    assert commit_inst
    assert any(t["ph"] == "t" and t["pid"] == 1 for t in ours)


def test_span_trace_tag_folds_to_positive_i32():
    tracer = Tracer()
    with tracer.span("raft.propose"):
        pass
    span = tracer.finished()[0]
    tag = span_trace_tag(span)
    assert 1 <= tag <= 0x7FFFFFFF
    assert tag == span_trace_tag(span.span_id)
    assert span_trace_tag("000000000000") == 1   # floor at 1, never 0


# ---------------------------------------------------------------------------
# bench_gate: provenance + resource series


class TestBenchGateProvenance:
    def _round(self, tmp_path, name, **kw):
        d = {"n": 64, "cmd": "x", "rc": 0, "tail": "", "parsed": None}
        d.update(kw)
        p = tmp_path / name
        p.write_text(json.dumps(d))
        return str(p)

    def test_green_but_empty_is_flagged(self, tmp_path):
        from bench_gate import check_provenance
        paths = [
            self._round(tmp_path, "MULTICHIP_r01.json", ok=True, tail=""),
            self._round(tmp_path, "MULTICHIP_r02.json", ok=True,
                        tail='{"multichip_ok": true}'),
            self._round(tmp_path, "MULTICHIP_r03.json", rc=1, tail=""),
            self._round(tmp_path, "MULTICHIP_r04.json", skipped=True,
                        tail=""),
        ]
        findings = check_provenance(paths=paths)
        assert len(findings) == 1 and "MULTICHIP_r01" in findings[0]

    def test_strict_flag_fails_the_cli(self, tmp_path, capsys):
        from bench_gate import main as gate_main
        good = {"rc": 0, "parsed": {"value": 100.0}, "tail": "x"}
        bad = {"rc": 0, "ok": True, "tail": ""}
        p1 = tmp_path / "BENCH_r01.json"
        p2 = tmp_path / "BENCH_r02.json"
        p1.write_text(json.dumps(good))
        p2.write_text(json.dumps(dict(good, tail="")))
        assert gate_main([str(p1), str(p2)]) == 0       # flagged, not fatal
        assert "PROV" in capsys.readouterr().out
        p2.write_text(json.dumps(bad))
        assert gate_main([str(p1), str(p2),
                          "--strict-provenance"]) == 1

    def test_headline_recording_nothing_is_flagged(self, tmp_path):
        """A green round must carry numbers for what it claims to have
        measured: an only-config round needs a recorded rate among its
        matching configs_entries_per_s entries, a full round needs a
        rate headline value."""
        from bench_gate import check_provenance
        paths = [
            # only-config rounds: recorded / skipped-string-only / no dict
            self._round(tmp_path, "BENCH_r01.json", tail="x", parsed={
                "only_config": "multiraft-1024x3",
                "configs_entries_per_s": {"multiraft-1024x3": 812345.0}}),
            self._round(tmp_path, "BENCH_r02.json", tail="x", parsed={
                "only_config": "32768-sharded",
                "configs_entries_per_s": {
                    "32768-sharded": "skipped (cpu)"}}),
            self._round(tmp_path, "BENCH_r03.json", tail="x",
                        parsed={"only_config": "32768-sharded"}),
            # a cpu-reduced rename still counts for its parent config
            self._round(tmp_path, "BENCH_r04.json", tail="x", parsed={
                "only_config": "32768-sharded",
                "configs_entries_per_s": {
                    "32768-sharded-reduced-n4096": 5524.3}}),
            # an A/B tripwire dict counts as recorded
            self._round(tmp_path, "BENCH_r05.json", tail="x", parsed={
                "only_config": "densepeer",
                "configs_entries_per_s": {
                    "densepeer-ab": {"banded_over_dense": 0.97}}}),
            # full rounds: headline value present / absent
            self._round(tmp_path, "BENCH_r06.json", tail="x",
                        parsed={"value": 100.0}),
            self._round(tmp_path, "BENCH_r07.json", tail="x",
                        parsed={"value": None}),
        ]
        findings = check_provenance(paths=paths)
        flagged = sorted(f.split(":")[0] for f in findings)
        assert flagged == ["BENCH_r02.json", "BENCH_r03.json",
                           "BENCH_r07.json"]

    def test_resource_series_gates_growth_not_collapse(self, tmp_path):
        from bench_gate import run_gate

        def rnd(name, value, compile_s):
            p = tmp_path / name
            p.write_text(json.dumps({
                "rc": 0, "tail": "x",
                "parsed": {"value": value, "compile_seconds": compile_s}}))
            return str(p)

        paths = [rnd("BENCH_r01.json", 100.0, 10.0),
                 rnd("BENCH_r02.json", 120.0, 12.0)]
        assert run_gate(paths=paths)["ok"]
        # compile time tripling is a failure even while the rate improves
        paths.append(rnd("BENCH_r03.json", 150.0, 30.0))
        report = run_gate(paths=paths)
        assert not report["ok"]
        assert any("compile_seconds" in f for f in report["failures"])
        # a shrinking compile time is never a regression
        paths[-1] = rnd("BENCH_r03.json", 150.0, 1.0)
        assert run_gate(paths=paths)["ok"]


# ---------------------------------------------------------------------------
# swarm_top (pure renderer; the live demo loop is slow-marked below)


def _fake_snapshot(commits=100.0, leader=1.0):
    return {"metrics": {"swarm_raft_is_leader": leader,
                        "swarm_kernel_commit_advance_total": commits,
                        "swarm_flightrec_captures_total":
                            {"trigger=manual": 2.0}},
            "timers": {}, "objects": {"nodes": 3}, "spans": [],
            "recent_events": [{"describe": "flightrec[manual] 1 span"}]}


class TestSwarmTop:
    def test_render_frame_shows_series_and_rates(self):
        from swarm_top import TopState, render_frame
        state = TopState()
        state.observe({"m1": _fake_snapshot(100.0)}, now=0.0)
        state.observe({"m1": _fake_snapshot(250.0)}, now=10.0)
        frame = render_frame({"m1": _fake_snapshot(250.0)}, state)
        assert "m1" in frame and "[LEADER]" in frame
        assert "swarm_kernel_commit_advance_total" in frame
        assert "15.0/s" in frame           # (250-100)/10
        assert "trigger=manual" in frame   # labeled child flattened
        assert "flightrec[manual]" in frame

    def test_render_frame_shows_fleet_health_panels(self):
        from swarm_top import TopState, render_frame
        snap = _fake_snapshot()
        snap["hottest"] = [2, 0, 1]
        snap["slo_active"] = [{"slo": "leader_churn", "group": 2,
                               "state": "page"}]
        snap["alerts"] = [{"scrape": 4, "slo": "leader_churn", "group": 2,
                           "from": "ok", "to": "page",
                           "fast_burn": 10.0, "slow_burn": 7.5}]
        frame = render_frame({"fleet": snap}, TopState())
        assert "hottest groups: g2 g0 g1" in frame
        assert "SLO ALERTS (1 active):" in frame
        assert "!! PAGE  leader_churn group=2" in frame
        assert "ok->page" in frame and "burn fast 10.0x" in frame

    def test_render_frame_all_ok_banner(self):
        from swarm_top import TopState, render_frame
        snap = _fake_snapshot()
        snap["slo_active"] = []            # present-but-empty: fleet is ok
        frame = render_frame({"fleet": snap}, TopState())
        assert "SLO ALERTS: none — all objectives ok" in frame
        assert "hottest groups" not in frame

    def test_counter_reset_drops_sample(self):
        from swarm_top import TopState
        state = TopState()
        state.observe({"m1": _fake_snapshot(100.0)}, now=0.0)
        state.observe({"m1": _fake_snapshot(10.0)}, now=1.0)  # restart
        # negative delta is not a rate: no sample recorded
        assert not state.rates["m1"].get(
            "swarm_kernel_commit_advance_total")

    def test_sparkline_scales_to_max(self):
        from swarm_top import sparkline
        assert sparkline([]) == ""
        line = sparkline([0, 1, 2, 4])
        assert len(line) == 4 and line[0] == "▁" and line[-1] == "█"

    def test_once_from_snapshot_file(self, tmp_path, capsys):
        from swarm_top import main as top_main
        p = tmp_path / "snap.json"
        p.write_text(json.dumps({"mgr-a": _fake_snapshot(),
                                 "mgr-b": _fake_snapshot(leader=0.0)}))
        assert top_main(["--from", str(p), "--once"]) == 0
        out = capsys.readouterr().out
        assert "2 manager(s)" in out and "mgr-a" in out and "mgr-b" in out

    def test_unreadable_file_degrades_not_crashes(self, tmp_path, capsys):
        from swarm_top import main as top_main
        p = tmp_path / "broken.json"
        p.write_text("{nope")
        assert top_main(["--from", str(p), "--once"]) == 0
        assert "unreadable" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# slow wrappers: the tools end-to-end (tier-1 skips these)


@pytest.mark.slow
def test_profile_tick_end_to_end(tmp_path):
    from profile_tick import run_profile

    out = run_profile(32, quick=True)
    assert out["tick_ms"] > 0 and out["compile_seconds"] > 0
    assert out["missing_scopes"] == []     # named_scope seams reach HLO
    attributed = sum(p["attributed_ms"] for p in out["phases"].values())
    # the acceptance bar: per-phase timings sum to the whole tick
    assert attributed == pytest.approx(out["tick_ms"], rel=0.2)
    assert out["coverage"] > 0.2           # micro-kernels track the kernel


@pytest.mark.slow
def test_swarm_top_demo_live_frames(capsys):
    from swarm_top import main as top_main

    assert top_main(["--demo", "--once", "--n", "8"]) == 0
    out = capsys.readouterr().out
    assert "sim-quorum" in out
    assert "swarm_kernel_commit_advance_total" in out
    assert "/s" in out   # second poll produced rates
    # fleet-health panels (ISSUE 20): the demo's second manager runs a
    # deliberately overloaded multi-raft fleet through the SLO engine
    assert "sim-fleet" in out
    assert "swarm_multiraft_group_heat" in out
    assert "hottest groups:" in out
    assert "SLO ALERTS" in out

"""TPU executor: tasks are compiled + executed JAX programs.

Reference shape: the Docker executor suite (agent/exec/dockerapi) — here
Prepare compiles, Start dispatches to the device, Wait blocks on the
result; scheduling an end-to-end service runs real device computations on
the worker (reference: integration_test.go service flows with a real
executor instead of TestExecutor).
"""

import asyncio

import pytest

from swarmkit_tpu.agent.exec import TaskError, TaskRejected, do_task_state
from swarmkit_tpu.agent.tpu import TpuExecutor, parse_program
from swarmkit_tpu.api import (
    Annotations, ContainerSpec, ReplicatedService, ServiceSpec, Task,
    TaskSpec, TaskState, TaskStatus,
)
from tests.conftest import async_test


def tpu_task(image="tpu://matmul", args=(), desired=TaskState.RUNNING):
    return Task(id="t1", service_id="s1",
                spec=TaskSpec(container=ContainerSpec(image=image,
                                                      args=list(args))),
                status=TaskStatus(state=TaskState.ASSIGNED),
                desired_state=desired)


@async_test
async def test_controller_full_lifecycle():
    ex = TpuExecutor(hostname="w1")
    task = tpu_task(args=["n=32", "steps=2"])
    ctl = await ex.controller(task)
    await ctl.prepare()
    await ctl.start()
    await ctl.wait()
    assert ctl.result is not None
    import numpy as np

    assert np.isfinite(float(np.asarray(ctl.result)))
    await ctl.close()


@async_test
async def test_unknown_program_rejected():
    ex = TpuExecutor()
    ctl = await ex.controller(tpu_task(image="tpu://no-such-program"))
    with pytest.raises(TaskRejected):
        await ctl.prepare()


@async_test
async def test_non_tpu_image_rejected():
    ex = TpuExecutor()
    ctl = await ex.controller(tpu_task(image="nginx:latest"))
    with pytest.raises(TaskRejected):
        await ctl.prepare()


@async_test
async def test_bad_params_fail_at_prepare():
    ex = TpuExecutor()
    ctl = await ex.controller(tpu_task(args=["n=not-a-number"]))
    with pytest.raises(TaskError):
        await ctl.prepare()


@async_test
async def test_do_task_state_advances_to_complete():
    """The generic advancer drives the TPU controller ASSIGNED→COMPLETE."""
    ex = TpuExecutor()
    task = tpu_task(args=["n=16", "steps=1"])
    ctl = await ex.controller(task)
    seen = []
    for _ in range(10):
        st = await do_task_state(task, ctl, now=0.0)
        if st is None:
            break
        task.status = st
        seen.append(st.state)
    assert TaskState.RUNNING in seen
    assert task.status.state == TaskState.COMPLETE


@async_test
async def test_describe_advertises_devices():
    ex = TpuExecutor(hostname="w9")
    desc = await ex.describe()
    assert desc.engine.labels["executor"] == "tpu"
    chips = {k: v for k, v in desc.resources.generic.items()
             if k.endswith("-chip")}
    assert chips and all(v >= 1 for v in chips.values())
    # the key names the real platform (tests pin cpu)
    assert "cpu-chip" in chips


def test_parse_program():
    spec = ContainerSpec(image="tpu://matmul", args=["n=64"],
                         env=["STEPS=3"])
    name, params = parse_program(spec)
    assert name == "matmul"
    assert params == {"n": "64", "steps": "3"}


@async_test
async def test_service_of_tpu_tasks_runs_to_completion():
    """End-to-end: a replicated service whose tasks are device programs is
    scheduled onto a TPU-executor worker and the computations really run
    (VERDICT r02 missing #6 acceptance)."""
    from swarmkit_tpu.api import RestartCondition, RestartPolicy
    from tests.integration_harness import TestCluster

    c = TestCluster()
    try:
        # the manager runs a TPU executor too so every placement choice
        # really executes on a device
        await c.add_manager("m1", executor=TpuExecutor(hostname="m1"))
        w = await c.add_agent("w1", executor=TpuExecutor(hostname="w1"))
        spec = ServiceSpec(
            annotations=Annotations(name="burn"),
            task=TaskSpec(
                container=ContainerSpec(image="tpu://matmul",
                                        args=["n=32", "steps=2"]),
                restart=RestartPolicy(condition=RestartCondition.NONE)),
            replicated=ReplicatedService(replicas=2))
        lead = await c.wait_leader()
        svc = await lead.control_api.create_service(spec)

        def completed():
            tasks = lead.store.find("task")
            done = [t for t in tasks if t.service_id == svc.id
                    and t.status.state == TaskState.COMPLETE]
            return len(done) >= 2 and done or None

        done = await c.poll(completed, "2 tpu tasks complete", timeout=30)
        assert all(t.status.state == TaskState.COMPLETE for t in done)
    finally:
        await c.stop_all()


@async_test
async def test_pallas_matmul_program_full_lifecycle():
    """tpu://pallas_matmul (hand-tiled MXU kernels, interpreted off-TPU)
    compiles, runs, and finishes like any other task program."""
    ex = TpuExecutor(hostname="w1")
    ctl = await ex.controller(tpu_task(
        image="tpu://pallas_matmul", args=["n=128", "steps=2", "tile=64"]))
    await ctl.prepare()
    await ctl.start()
    await ctl.wait()
    import numpy as np

    assert np.isfinite(float(np.asarray(ctl.result)))
    await ctl.close()


@async_test
async def test_pallas_matmul_rejects_misaligned_tile():
    ex = TpuExecutor()
    ctl = await ex.controller(tpu_task(
        image="tpu://pallas_matmul", args=["n=100", "tile=64"]))
    with pytest.raises(TaskRejected):
        await ctl.prepare()
    # non-positive tile is a permanent rejection, not a retryable error
    ctl = await ex.controller(tpu_task(
        image="tpu://pallas_matmul", args=["tile=0"]))
    with pytest.raises(TaskRejected):
        await ctl.prepare()


@async_test
async def test_pallas_matmul_default_tile_divides_n():
    """No tile param: the builder picks an MXU-aligned divisor of n
    (n=384 -> 128), not a blind 256 clamp that would reject the task."""
    ex = TpuExecutor()
    ctl = await ex.controller(tpu_task(
        image="tpu://pallas_matmul", args=["n=384", "steps=1"]))
    await ctl.prepare()
    await ctl.start()
    await ctl.wait()
    import numpy as np

    assert np.isfinite(float(np.asarray(ctl.result)))
    await ctl.close()


@async_test
async def test_pmatmul_runs_sharded_over_the_device_mesh():
    """tpu://pmatmul shards its batch over ALL local devices (8 virtual CPU
    devices under the test conftest) and runs cross-device collectives
    inside the task program — the executor's multi-chip execution path."""
    import jax

    ex = TpuExecutor(hostname="h")
    ctl = await ex.controller(tpu_task(image="tpu://pmatmul",
                                       args=["n=32", "steps=2", "batch=8"]))
    await ctl.prepare()
    # the AOT-compiled program must actually span the device mesh
    hlo = ctl._compiled.as_text()
    n_dev = len(jax.devices())
    if n_dev > 1:
        assert any(tok in hlo for tok in
                   ("all-reduce", "collective-permute", "all-gather")), \
            "pmatmul must lower to cross-device collectives"
    await ctl.start()
    await ctl.wait()
    assert ctl.result is not None
    await ctl.close()


@async_test
async def test_tpu_program_params_from_templated_secret():
    """Secret payload k=v lines (template-expanded per task) feed tpu://
    program parameters — the runtime analog of mounted secret files."""
    from swarmkit_tpu.agent.dependency import Dependencies
    from swarmkit_tpu.api import Annotations, Secret, SecretSpec
    from swarmkit_tpu.api.specs import Driver, SecretReference

    ex = TpuExecutor()
    ex.dependencies = Dependencies()
    ex.dependencies.secrets.add(Secret(id="sec1", spec=SecretSpec(
        annotations=Annotations(name="tuning"),
        data=b"n=3{{.Task.Slot}}\nsteps=2",
        templating=Driver(name="golang"))))

    task = tpu_task("tpu://matmul")
    task.slot = 2
    task.service_annotations = Annotations(name="trainer")
    task.spec.container.secrets = [
        SecretReference(secret_id="sec1", secret_name="tuning")]
    ctl = await ex.controller(task)
    await ctl.prepare()
    # n expanded to 32 (= "3" + slot "2"); the compiled program ran with it
    await ctl.start()
    await ctl.wait()
    assert ctl.result is not None
    # the compile log records dependency param NAMES but never their
    # values (secret material must not reach `service logs`)
    lines = [m.data.decode() for m in ex.logs.tail(task.id)]
    assert any("n=<from-dependency>" in l and "steps=<from-dependency>" in l
               for l in lines), lines
    assert not any("n=32" in l for l in lines), lines

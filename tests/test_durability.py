"""The durability boundary (ISSUE 16): fsync-aware crash recovery.

Fast tier: storage-registry coherence (profiles <-> schedule leaves <->
flightrec signature codes), the SimConfig storage knobs with the
vote-guard fold, generator determinism, the unit semantics of each
storage-fault verb (truncation to the durable watermark, the snapshot
floor, the watermark rollback of a torn write, transient-flag hygiene),
the fsync round itself (cadence, batch clamp, stall/crash freeze, the
durable-commit fold), the write-through vote record under a stalled
disk, the DURABILITY / SLO_FSYNC_LAG / RECOVERY_MONOTONIC invariant
boundaries, the flight-recorder signatures, the crash-right-after-
snapshot-install recovery identity, the storage-off bit-identity of the
sync wire, and the host WAL's truncation parity (raft/storage.py drops
a torn tail on bootstrap, refuses mid-file corruption).

Slow tier: the DURABILITY off-trip / on-clean explore contrast, a crash
spliced INTO a gating-on snapshot-install window, torn_write at the
log_chunk band boundary (tiled parity, unit and explore), and 300-tick
storage-off bit-identity on the tiled / role-sparse / mailbox / sharded
wires.  The seed-pinned catch -> shrink -> artifact -> replay storage
sweeps live in tests/test_dst_sweep.py and tests/test_fault_sweep.py.
"""

import dataclasses
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu import dst
from swarmkit_tpu.dst.schedule import _OPTIONAL_LEAVES
from swarmkit_tpu.flightrec import codes as fcodes
from swarmkit_tpu.flightrec import decode_rings
from swarmkit_tpu.raft.sim.kernel import step
from swarmkit_tpu.raft.sim.run import run_ticks
from swarmkit_tpu.raft.sim.state import (
    LEADER, NONE, SimConfig, SimState, init_state,
)

CFG5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=0)

# the shared storage config: every fast kernel-step test runs on it so
# the tier-1 budget pays for ONE traced program (k=4: fsyncs complete on
# ticks 3, 7, 11, ...)
SCFG = dataclasses.replace(CFG5, fsync_lag_ticks=4, ack_gating=True)

# the validated sweep contrast (tools/fault_sweep.py STORAGE_SCENARIOS):
# a lazy watermark six ticks wide, with and without ack gating
STOR_OFF = dataclasses.replace(CFG5, fsync_lag_ticks=6)
STOR_ON = dataclasses.replace(STOR_OFF, ack_gating=True)

TRUE5 = jnp.ones((5,), bool)
step_j = jax.jit(step, static_argnames=("cfg",))

# the registers the durability boundary added: the ONLY permitted
# divergence between a storage-off run and a storage-on-but-never-
# gating run (vg_vote/vg_term ride along because cfg.storage_on
# subsumes the persisted-vote guard — satellite fold)
STORAGE_REG_FIELDS = frozenset({
    "sync_mark", "dur_commit", "ack_frontier", "fsync_stall", "snap_bad",
    "vg_vote", "vg_term",
})


def _arr(base, **updates):
    """dataclasses.replace with each update applied via .at[idx].set."""
    fields = {}
    for name, pairs in updates.items():
        a = getattr(base, name)
        for idx, val in pairs:
            a = a.at[idx].set(val)
        fields[name] = a
    return dataclasses.replace(base, **fields)


def _stor(cfg=SCFG, **kw):
    return _arr(init_state(cfg), **kw)


def _at_tick(st, t):
    return dataclasses.replace(st, tick=jnp.asarray(t, st.tick.dtype))


def _assert_identical_modulo_storage(a, b):
    for fld in dataclasses.fields(SimState):
        if fld.name in STORAGE_REG_FIELDS:
            continue
        x, y = getattr(a, fld.name), getattr(b, fld.name)
        if x is None and y is None:
            continue
        assert x is not None and y is not None, fld.name
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{fld.name} diverged"


# ---------------------------------------------------------------------------
# registry coherence: profiles <-> leaves <-> signature codes


def test_storage_profiles_are_extra_profiles():
    assert set(dst.STORAGE_PROFILES) <= set(dst.EXTRA_PROFILES)
    assert not set(dst.STORAGE_PROFILES) & set(dst.PROFILES)
    assert not set(dst.STORAGE_PROFILES) & set(dst.ATTACK_PROFILES)
    assert set(dst.STORAGE_LEAVES) == set(dst.STORAGE_PROFILES)
    assert set(dst.STORAGE_SIGNATURE_CODES) == set(dst.STORAGE_PROFILES)


def test_storage_leaves_are_optional_schedule_fields():
    fields = {f.name for f in dataclasses.fields(dst.FaultSchedule)}
    for leaf in dst.STORAGE_LEAVES.values():
        assert leaf in fields
        assert leaf in _OPTIONAL_LEAVES


def test_storage_signature_codes_resolve_in_flightrec():
    for code_name in dst.STORAGE_SIGNATURE_CODES.values():
        code = getattr(fcodes, code_name)
        assert fcodes.CODE_NAMES[code] == code_name


def test_new_invariant_bits_registered():
    assert dst.bits_to_names(dst.DURABILITY) == ["durability"]
    assert dst.bits_to_names(dst.RECOVERY_MONOTONIC) == \
        ["recovery_monotonic"]
    assert dst.bits_to_names(dst.SLO_FSYNC_LAG) == ["slo_fsync_lag"]
    # lost data and a regressed durable record are safety violations (the
    # oracle only trusts the clean prefix); the fsync-lag budget is an SLO
    assert dst.DURABILITY & dst.SAFETY_BITS
    assert dst.RECOVERY_MONOTONIC & dst.SAFETY_BITS
    assert not dst.SLO_FSYNC_LAG & dst.SAFETY_BITS


# ---------------------------------------------------------------------------
# config knobs: the storage model arms as a unit, vote guard folds in


def test_storage_registers_allocated_only_when_armed():
    regs = ("sync_mark", "dur_commit", "ack_frontier", "fsync_stall",
            "snap_bad")
    assert not CFG5.storage_on
    off = init_state(CFG5)
    for name in regs:
        assert getattr(off, name) is None, name
    assert SCFG.storage_on
    on = init_state(SCFG)
    for name in regs:
        assert getattr(on, name) is not None, name


def test_storage_knobs_require_fsync_lag():
    for kw in (dict(fsync_batch=4), dict(ack_gating=True),
               dict(slo_fsync_lag=4)):
        with pytest.raises(ValueError, match="fsync_lag_ticks"):
            dataclasses.replace(CFG5, **kw)
    with pytest.raises(ValueError):
        dataclasses.replace(CFG5, fsync_lag_ticks=-1)


def test_vote_guard_folds_into_storage_model():
    # cfg.vote_guard survives as the compat alias; an armed storage model
    # subsumes it (every vote record is a durable write)
    assert not CFG5.has_vote_guard
    assert dataclasses.replace(CFG5, vote_guard=True).has_vote_guard
    assert not SCFG.vote_guard and SCFG.has_vote_guard
    st = init_state(SCFG)
    assert st.vg_vote is not None and st.vg_term is not None


# ---------------------------------------------------------------------------
# generators: determinism, seed sensitivity, the leaf actually fires


@pytest.mark.parametrize("profile", dst.STORAGE_PROFILES)
def test_storage_generator_deterministic_per_seed(profile):
    # 140 ticks: enough for snap_corrupt's install window (start up to
    # 2T, outage 5T, corrupt window 2T) to land inside the schedule
    a = dst.make_schedule(STOR_ON, ticks=140, profile=profile, seed=5)
    b = dst.make_schedule(STOR_ON, ticks=140, profile=profile, seed=5)
    c = dst.make_schedule(STOR_ON, ticks=140, profile=profile, seed=6)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    assert all(np.array_equal(x, y) for x, y in zip(la, lb))
    lc = jax.tree_util.tree_leaves(c)
    assert any(not np.array_equal(x, y) for x, y in zip(la, lc))
    leaf = getattr(a, dst.STORAGE_LEAVES[profile])
    assert leaf is not None and bool(leaf.any())


# ---------------------------------------------------------------------------
# apply-verb unit semantics (pre-step transforms on hand-built states)


def test_lost_tail_truncates_to_watermark():
    st = _stor(last=[(1, 10)], commit=[(1, 8)], applied=[(1, 8)],
               sync_mark=[(1, 6)], dur_commit=[(1, 6)])
    out = dst.apply_lost_tail(st, jnp.arange(5) == 1, TRUE5)
    assert int(out.last[1]) == 6                       # unsynced tail gone
    assert int(out.sync_mark[1]) == 6                  # watermark honest
    assert int(out.commit[1]) == 6                     # re-clamped
    assert int(out.applied[1]) == 0                    # apply restarts
    assert int(out.apply_chk[1]) == int(st.snap_chk[1])
    assert int(out.dur_commit[1]) == 6                 # durable record kept
    assert int(out.last[0]) == int(st.last[0])         # unflagged untouched


def test_lost_tail_floors_at_snapshot_index():
    # a compacted row's disk image can never truncate below its snapshot
    st = _stor(last=[(2, 55)], commit=[(2, 52)], applied=[(2, 50)],
               sync_mark=[(2, 40)], snap_idx=[(2, 50)])
    out = dst.apply_lost_tail(st, jnp.arange(5) == 2, TRUE5)
    assert int(out.last[2]) == 50
    assert int(out.commit[2]) == 50
    assert int(out.applied[2]) == 50                   # snap_idx


def test_torn_write_rolls_back_watermark():
    st = _stor(last=[(1, 10)], commit=[(1, 8)], applied=[(1, 8)],
               sync_mark=[(1, 6)])
    out = dst.apply_torn_write(st, jnp.arange(5) == 1, TRUE5)
    # the LAST durable entry was torn: one below the watermark, and the
    # watermark itself rolls back with it (the disk lied about it)
    assert int(out.last[1]) == 5
    assert int(out.sync_mark[1]) == 5
    assert int(out.commit[1]) == 5
    # the snapshot floor holds even against a tear at the boundary
    st2 = _stor(last=[(2, 50)], commit=[(2, 50)], applied=[(2, 50)],
                sync_mark=[(2, 50)], snap_idx=[(2, 50)])
    out2 = dst.apply_torn_write(st2, jnp.arange(5) == 2, TRUE5)
    assert int(out2.last[2]) == 50
    assert int(out2.sync_mark[2]) == 50


def test_disk_stall_and_snap_corrupt_flag_live_rows_only():
    st = init_state(SCFG)
    alive = jnp.asarray([True, False, True, True, True])
    mask = jnp.asarray([True, True, False, False, False])
    out = dst.apply_disk_stall(st, mask, alive)
    assert np.asarray(out.fsync_stall).tolist() == \
        [True, False, False, False, False]
    out2 = dst.apply_snap_corrupt(st, mask, alive)
    assert np.asarray(out2.snap_bad).tolist() == \
        [True, False, False, False, False]


def test_storage_verbs_noop_without_storage():
    # Python-gated: a storage-off state traces the exact prior program
    st = init_state(CFG5)
    mask = jnp.ones((5,), bool)
    assert dst.apply_lost_tail(st, mask, TRUE5) is st
    assert dst.apply_torn_write(st, mask, TRUE5) is st
    assert dst.apply_disk_stall(st, mask, TRUE5) is st
    assert dst.apply_snap_corrupt(st, mask, TRUE5) is st


def test_storage_verbs_emit_signature_events():
    cfg = dataclasses.replace(SCFG, record_events=True)
    st = _stor(cfg, last=[(1, 10), (2, 10)], sync_mark=[(1, 6), (2, 6)])
    out = dst.apply_lost_tail(st, jnp.arange(5) == 1, TRUE5)
    out = dst.apply_torn_write(out, jnp.arange(5) == 2, TRUE5)
    out = dst.apply_disk_stall(out, jnp.arange(5) == 3, TRUE5)
    out = dst.apply_snap_corrupt(out, jnp.arange(5) == 4, TRUE5)
    events, dropped = decode_rings(out.ev_buf, out.ev_pos)
    assert int(dropped.sum()) == 0
    names = {e.name for e in events}
    for code_name in dst.STORAGE_SIGNATURE_CODES.values():
        assert code_name in names
    for e in events:
        text = e.describe()
        assert isinstance(text, str) and text


def test_storage_verbs_are_noops_on_recorder_off_states():
    st = _stor(last=[(1, 10)], sync_mark=[(1, 6)])
    out = dst.apply_lost_tail(st, jnp.arange(5) == 1, TRUE5)
    out = dst.apply_disk_stall(out, jnp.arange(5) == 3, TRUE5)
    assert out.ev_buf is None and out.ev_pos is None


# ---------------------------------------------------------------------------
# the fsync round: cadence, batch clamp, freeze, end-of-tick folds


def test_fsync_cadence_batch_and_durable_fold():
    st = _stor(last=[(0, 10)], commit=[(0, 8)])
    out = step_j(st, SCFG)
    assert int(out.sync_mark[0]) == 0                  # tick 0: not due
    out = step_j(_at_tick(st, 3), SCFG)
    assert int(out.sync_mark[0]) == 10                 # due, unlimited
    # the durable record folds min(commit, sync_mark); the oracle
    # frontier folds commit itself
    assert int(out.dur_commit[0]) >= 8
    assert int(out.ack_frontier[0]) >= 8
    bcfg = dataclasses.replace(SCFG, fsync_batch=4)
    out = step_j(_at_tick(_stor(bcfg, last=[(0, 10)]), 3), bcfg)
    assert int(out.sync_mark[0]) == 4                  # clamped per round


def test_fsync_freezes_on_stall_and_crash_and_flags_clear():
    st = _stor(last=[(0, 10), (1, 10), (2, 10)],
               fsync_stall=[(1, True)], snap_bad=[(3, True)])
    alive = TRUE5.at[2].set(False)
    out = step_j(_at_tick(st, 3), SCFG, alive=alive)
    assert int(out.sync_mark[0]) == 10                 # healthy row syncs
    assert int(out.sync_mark[1]) == 0                  # stalled disk frozen
    assert int(out.sync_mark[2]) == 0                  # crashed row frozen
    # the verb flags are one-tick inputs: consumed, then cleared
    assert not bool(np.asarray(out.fsync_stall).any())
    assert not bool(np.asarray(out.snap_bad).any())


def test_stalled_disk_refuses_vote_grants():
    # vote records are write-through (etcd MustSync), not on the fsync
    # cadence: under ack_gating a row whose disk is stalled cannot
    # persist the grant, so it refuses — and a candidate that cannot
    # assemble a quorum of durable grants stays a candidate
    others = jnp.arange(5) != 0

    def drive(stall):
        st = _arr(init_state(SCFG), elapsed=[(0, 100)])
        for _ in range(3):
            if stall:
                st = dst.apply_disk_stall(st, others, TRUE5)
            st = step_j(st, SCFG)
        return st

    clean = drive(False)
    assert int(clean.role[0]) == LEADER
    stalled = drive(True)
    # refused by a quorum, the candidate loses the poll and steps back
    # down (etcd VoteLost); no stalled row ever persisted a grant
    assert not bool((np.asarray(stalled.role) == LEADER).any())
    assert (np.asarray(stalled.vote)[1:] == NONE).all()
    assert (np.asarray(clean.vote) == 0).sum() >= 3    # clean quorum


def test_crash_right_after_snapshot_install_is_lossless():
    # a snapshot install jumps the watermark to the snapshot index
    # (the image hit disk before the restore applied), so a lost_tail
    # crash on the very next tick finds nothing to truncate
    st = _stor(last=[(2, 50)], commit=[(2, 50)], applied=[(2, 50)],
               snap_idx=[(2, 50)], sync_mark=[(2, 50)],
               dur_commit=[(2, 50)])
    out = dst.apply_lost_tail(st, jnp.arange(5) == 2, TRUE5)
    assert int(out.last[2]) == 50
    assert int(out.commit[2]) == 50
    assert int(out.applied[2]) == 50
    assert int(out.dur_commit[2]) == 50


# ---------------------------------------------------------------------------
# invariant boundaries


def test_durability_bit_boundary():
    st = init_state(SCFG)
    assert not int(dst.check_state(st, SCFG)) & dst.DURABILITY
    # the witness is cluster-wide: an acked frontier ABOVE every log's
    # last means some acked-as-committed entry exists on no disk
    bad = _arr(st, ack_frontier=[(0, 5)])
    assert int(dst.check_state(bad, SCFG)) & dst.DURABILITY
    # one surviving copy anywhere satisfies it (replication covers f<q)
    ok = _arr(st, ack_frontier=[(0, 5)], last=[(4, 5)])
    assert not int(dst.check_state(ok, SCFG)) & dst.DURABILITY


def test_slo_fsync_lag_boundary():
    cfg = dataclasses.replace(SCFG, slo_fsync_lag=4)
    at_bound = _arr(init_state(cfg), last=[(0, 4)])
    assert not int(dst.check_state(at_bound, cfg)) & dst.SLO_FSYNC_LAG
    over = _arr(init_state(cfg), last=[(0, 5)])
    assert int(dst.check_state(over, cfg)) & dst.SLO_FSYNC_LAG
    # bound unset = oracle off even over the line
    wide = _arr(init_state(SCFG), last=[(0, 50)])
    assert not int(dst.check_state(wide, SCFG)) & dst.SLO_FSYNC_LAG


def test_recovery_monotonic_and_recovering_mask():
    prev = _stor(last=[(0, 8)], commit=[(0, 8)], applied=[(0, 8)],
                 dur_commit=[(0, 6)])
    # a sanctioned recovery: commit/applied rebuilt from durable state
    new = _stor(last=[(0, 6)], commit=[(0, 6)], dur_commit=[(0, 6)])
    rec = jnp.arange(5) == 0
    assert int(dst.check_transition(prev, new)) & dst.COMMIT_MONOTONIC
    assert int(dst.check_transition(prev, new, recovering=rec)) == 0
    # the durable record is pinned even for recovering rows
    worse = _arr(new, dur_commit=[(0, 5)])
    assert int(dst.check_transition(prev, worse, recovering=rec)) \
        & dst.RECOVERY_MONOTONIC


# ---------------------------------------------------------------------------
# storage-off transparency: the sync wire, fast (heavier wires below)


def test_storage_nogate_bit_identity_sync():
    _assert_nogate_transparent(CFG5, ticks=120, prop_count=2)


def _assert_nogate_transparent(base, ticks, prop_count):
    """storage armed but ack_gating off must not change one decision:
    every pre-existing field stays bit-identical to the storage-off run,
    and only the new registers (plus the folded vote guard) differ."""
    nogate = dataclasses.replace(base, fsync_lag_ticks=4)
    off_st, off_tr = run_ticks(init_state(base), base, ticks,
                               prop_count=prop_count)
    on_st, on_tr = run_ticks(init_state(nogate), nogate, ticks,
                             prop_count=prop_count)
    _assert_identical_modulo_storage(off_st, on_st)
    assert np.array_equal(np.asarray(off_tr), np.asarray(on_tr))
    # the storage plane was actually live on the nogate side
    assert int(jnp.max(on_st.sync_mark)) > 0
    assert int(jnp.max(on_st.dur_commit)) > 0


@pytest.mark.slow  # tier-2: one kernel compile per wire, see ROADMAP
@pytest.mark.parametrize("wire", ["tiled", "sparse", "mailbox"])
def test_storage_nogate_bit_identity_wires(wire):
    base = {
        # log_chunk must be lane-aligned (multiples of 128), so the tiled
        # wire needs a ring big enough to band
        "tiled": lambda: dataclasses.replace(CFG5, log_len=512,
                                             log_chunk=128),
        "sparse": lambda: SimConfig(n=16, log_len=64, window=8,
                                    apply_batch=16, max_props=8, keep=4,
                                    election_tick=10, seed=3,
                                    active_rows=8),
        "mailbox": lambda: dataclasses.replace(CFG5, latency=2,
                                               latency_jitter=1,
                                               inflight=2),
    }[wire]()
    _assert_nogate_transparent(base, ticks=300, prop_count=2)


@pytest.mark.slow
def test_storage_nogate_bit_identity_sharded():
    from swarmkit_tpu.parallel import row_mesh, shard_rows
    base = SimConfig(n=64, log_len=128, window=16, apply_batch=32,
                     max_props=16, keep=8, seed=11)
    nogate = dataclasses.replace(base, fsync_lag_ticks=4)
    mesh = row_mesh(base.n)
    off_st, off_tr = run_ticks(shard_rows(init_state(base), mesh), base,
                               300, prop_count=8)
    on_st, on_tr = run_ticks(shard_rows(init_state(nogate), mesh), nogate,
                             300, prop_count=8)
    _assert_identical_modulo_storage(off_st, on_st)
    assert np.array_equal(np.asarray(off_tr), np.asarray(on_tr))
    assert int(jnp.max(on_st.sync_mark)) > 0


# ---------------------------------------------------------------------------
# the DURABILITY contrast: correlated loss trips it, ack gating closes it


@pytest.mark.slow
def test_lost_tail_trips_durability_and_gating_closes_it():
    batch, names = dst.make_batch(STOR_OFF, ticks=120, schedules=8, seed=7,
                                  profiles=("lost_tail",))
    r_off = dst.explore(init_state(STOR_OFF), STOR_OFF, batch,
                        profiles=names, prop_count=2)
    tripped = int(((r_off.viol & dst.DURABILITY) != 0).sum())
    assert tripped > 0, [hex(int(v)) for v in r_off.viol]
    # with gating a commit IMPLIES a durable quorum: the SAME schedules
    # come back violation-free
    r_on = dst.explore(init_state(STOR_ON), STOR_ON, batch,
                       profiles=names, prop_count=2)
    assert (r_on.viol == 0).all(), [hex(int(v)) for v in r_on.viol]


@pytest.mark.slow
def test_crash_spliced_into_snapshot_install_stays_clean():
    # the gating-on snap_corrupt schedule already forces a snapshot
    # install after the victim's outage; splice a cluster-wide lost_tail
    # crash INTO the corrupt-install window and another right after the
    # clean install — recovery must rebuild from durable registers with
    # no invariant trip either time
    cfg = STOR_ON
    T = cfg.election_tick
    ticks = 140
    sched = dst.make_schedule(cfg, ticks=ticks, profile="snap_corrupt",
                              seed=3)
    alive = np.asarray(sched.alive)
    down = ~alive.all(axis=1)
    assert down.any()                                  # sanity: an outage
    heal = int(np.where(down)[0].max()) + 1
    lost = np.zeros((ticks, cfg.n), bool)
    lost[min(heal + 1, ticks - 1), :] = True           # mid bad window
    lost[min(heal + 2 * T + 3, ticks - 1), :] = True   # post clean install
    spliced = dataclasses.replace(sched, lost_tail=jnp.asarray(lost))
    viol, _ = dst.replay(cfg, spliced, prop_count=2)
    assert viol == 0, hex(viol)


# ---------------------------------------------------------------------------
# torn_write at the log_chunk band boundary (tiled lowering parity)


@pytest.mark.slow
def test_torn_write_at_band_boundary_tiled_parity():
    tiled = dataclasses.replace(SCFG, log_len=512, log_chunk=128)
    flat = dataclasses.replace(tiled, log_chunk=0)
    st = _stor(flat, last=[(1, 140)], commit=[(1, 138)],
               applied=[(1, 138)], sync_mark=[(1, 129)])
    cut = dst.apply_torn_write(st, jnp.arange(5) == 1, TRUE5)
    assert int(cut.last[1]) == 128                     # exactly a band edge
    a = jax.jit(step, static_argnames=("cfg",))(cut, flat)
    b = jax.jit(step, static_argnames=("cfg",))(cut, tiled)
    for fld in dataclasses.fields(SimState):
        x, y = getattr(a, fld.name), getattr(b, fld.name)
        if x is None and y is None:
            continue
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            f"{fld.name} diverged across log_chunk"


@pytest.mark.slow
def test_torn_write_explore_agrees_across_log_chunk():
    tiled = dataclasses.replace(STOR_ON, log_len=512, log_chunk=128)
    flat = dataclasses.replace(tiled, log_chunk=0)
    batch, names = dst.make_batch(tiled, ticks=120, schedules=6, seed=11,
                                  profiles=("torn_write",))
    r_t = dst.explore(init_state(tiled), tiled, batch, profiles=names,
                      prop_count=2)
    r_f = dst.explore(init_state(flat), flat, batch, profiles=names,
                      prop_count=2)
    # a single torn disk is contained by replication on both lowerings
    assert (r_t.viol == 0).all(), [hex(int(v)) for v in r_t.viol]
    assert np.array_equal(np.asarray(r_t.viol), np.asarray(r_f.viol))
    assert np.array_equal(np.asarray(r_t.first_tick),
                          np.asarray(r_f.first_tick))


# ---------------------------------------------------------------------------
# host WAL truncation parity (raft/storage.py <-> the kernel verbs)


def _wal_entry(i):
    from swarmkit_tpu.raft.messages import Entry, EntryType
    return Entry(index=i, term=1, type=EntryType.NORMAL,
                 data=b"payload-%d" % i)


def test_host_wal_drops_torn_tail_on_bootstrap(tmp_path):
    from swarmkit_tpu.raft.messages import HardState
    from swarmkit_tpu.raft.storage import EncryptedRaftLogger
    log = EncryptedRaftLogger(str(tmp_path))
    log.bootstrap_new()
    log.save(HardState(term=1, vote=0, commit=0),
             [_wal_entry(i) for i in range(1, 6)])
    (wal,) = glob.glob(os.path.join(str(tmp_path), "raft", "wal-*.log"))
    blob = open(wal, "rb").read()
    # a torn final sector: recovery keeps the checksummed prefix — the
    # host analog of the kernel's lost_tail/torn_write truncation back
    # to the durable watermark
    with open(wal, "wb") as f:
        f.write(blob[:-7])
    boot = EncryptedRaftLogger(str(tmp_path)).bootstrap_from_disk()
    assert [e.index for e in boot.entries] == [1, 2, 3, 4]
    assert boot.hard_state is not None and boot.hard_state.term == 1


def test_host_wal_refuses_midfile_corruption(tmp_path):
    from swarmkit_tpu.raft.messages import HardState
    from swarmkit_tpu.raft.storage import DataCorrupt, EncryptedRaftLogger
    log = EncryptedRaftLogger(str(tmp_path))
    log.bootstrap_new()
    log.save(HardState(term=1, vote=0, commit=0),
             [_wal_entry(i) for i in range(1, 6)])
    (wal,) = glob.glob(os.path.join(str(tmp_path), "raft", "wal-*.log"))
    blob = bytearray(open(wal, "rb").read())
    # flip one byte INSIDE an early frame body: valid frames follow, so
    # this is a lying disk, not a torn tail — recovery must refuse
    # rather than serve a hole (the fleet defense is replication)
    blob[10] ^= 0xFF
    with open(wal, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(DataCorrupt):
        EncryptedRaftLogger(str(tmp_path)).bootstrap_from_disk()

"""MemoryStore tests (reference: manager/state/store/memory_test.go)."""

import pytest

from swarmkit_tpu.api import (
    Annotations, Node, NodeRole, NodeSpec, Service, ServiceSpec, Task,
    TaskState, TaskStatus,
)
from swarmkit_tpu.store import (
    All, ByID, ByIDPrefix, ByName, ByNamePrefix, ByNode, ByRole, ByService,
    BySlot, ByDesiredState, ByTaskState, Custom, Or,
    ErrExist, ErrNameConflict, ErrNotExist, ErrSequenceConflict,
    MemoryStore, NopProposer, MAX_CHANGES_PER_TRANSACTION,
)
from swarmkit_tpu.store.memory import Event, EventCommit, match, match_commit
from tests.conftest import async_test


def _node(i, role=NodeRole.WORKER):
    return Node(id=f"node{i}", role=role,
                spec=NodeSpec(annotations=Annotations(name=f"name{i}")))


def _task(i, service="svc1", node="node1", state=TaskState.RUNNING, slot=0):
    return Task(id=f"task{i}", service_id=service, node_id=node,
                slot=slot or i, desired_state=int(TaskState.RUNNING),
                status=TaskStatus(state=state))


@async_test
async def test_create_get_update_delete():
    s = MemoryStore()
    n = _node(1)
    await s.update(lambda tx: tx.create(n))
    got = s.get("node", "node1")
    assert got.id == "node1" and got.meta.version.index == 1

    got.spec.annotations.labels["x"] = "y"
    await s.update(lambda tx: tx.update(got))
    got2 = s.get("node", "node1")
    assert got2.meta.version.index == 2
    assert got2.spec.annotations.labels == {"x": "y"}

    await s.update(lambda tx: tx.delete("node", "node1"))
    assert s.get("node", "node1") is None


@async_test
async def test_create_duplicate_and_name_conflict():
    s = MemoryStore()
    await s.update(lambda tx: tx.create(_node(1)))
    with pytest.raises(ErrExist):
        await s.update(lambda tx: tx.create(_node(1)))
    dup = _node(2)
    dup.spec.annotations.name = "name1"
    with pytest.raises(ErrNameConflict):
        await s.update(lambda tx: tx.create(dup))


@async_test
async def test_update_nonexistent_and_sequence_conflict():
    s = MemoryStore()
    with pytest.raises(ErrNotExist):
        await s.update(lambda tx: tx.update(_node(9)))
    await s.update(lambda tx: tx.create(_node(1)))
    stale = s.get("node", "node1")
    fresh = s.get("node", "node1")
    await s.update(lambda tx: tx.update(fresh))
    with pytest.raises(ErrSequenceConflict):
        await s.update(lambda tx: tx.update(stale))


@async_test
async def test_tx_reads_see_writes():
    s = MemoryStore()

    def cb(tx):
        tx.create(_node(1))
        assert tx.get("node", "node1") is not None
        assert len(tx.find("node", All())) == 1
        tx.delete("node", "node1")
        assert tx.get("node", "node1") is None
        assert tx.find("node", All()) == []

    await s.update(cb)
    assert s.get("node", "node1") is None


@async_test
async def test_find_combinators():
    s = MemoryStore()

    def cb(tx):
        tx.create(_node(1, NodeRole.MANAGER))
        tx.create(_node(2))
        tx.create(Service(id="svc1", spec=ServiceSpec(
            annotations=Annotations(name="web"))))
        tx.create(_task(1, node="node1", state=TaskState.RUNNING))
        tx.create(_task(2, node="node2", state=TaskState.PENDING))
        tx.create(_task(3, service="svc2", node="node2",
                        state=TaskState.RUNNING))

    await s.update(cb)

    assert {t.id for t in s.find("task", ByService("svc1"))} == {"task1", "task2"}
    assert {t.id for t in s.find("task", ByNode("node2"))} == {"task2", "task3"}
    assert [t.id for t in s.find("task", BySlot("svc1", 2))] == ["task2"]
    assert {t.id for t in s.find("task", ByTaskState(TaskState.RUNNING))} == \
        {"task1", "task3"}
    assert len(s.find("task", ByDesiredState(TaskState.RUNNING))) == 3
    assert [n.id for n in s.find("node", ByRole(NodeRole.MANAGER))] == ["node1"]
    assert [n.id for n in s.find("node", ByName("name2"))] == ["node2"]
    assert len(s.find("node", ByNamePrefix("name"))) == 2
    assert len(s.find("task", ByIDPrefix("task"))) == 3
    assert [s_.id for s_ in s.find("service", ByName("web"))] == ["svc1"]
    assert {t.id for t in s.find(
        "task", Or(BySlot("svc1", 1), ByService("svc2")))} == {"task1", "task3"}
    assert [t.id for t in s.find(
        "task", Custom(lambda t: t.slot == 3))] == ["task3"]
    assert [n.id for n in s.find("node", ByID("node1"))] == ["node1"]


@async_test
async def test_index_maintenance_on_update():
    s = MemoryStore()
    await s.update(lambda tx: tx.create(_task(1, node="node1")))
    t = s.get("task", "task1")
    t.node_id = "node9"
    t.status.state = TaskState.FAILED
    await s.update(lambda tx: tx.update(t))
    assert s.find("task", ByNode("node1")) == []
    assert [x.id for x in s.find("task", ByNode("node9"))] == ["task1"]
    assert [x.id for x in s.find("task", ByTaskState(TaskState.FAILED))] == ["task1"]


@async_test
async def test_events_and_commit_event():
    s = MemoryStore()
    w = s.watch()
    commits = s.watch(match_commit)
    await s.update(lambda tx: tx.create(_node(1)))
    evs = w.poll()
    assert any(isinstance(e, Event) and e.action == "create" for e in evs)
    assert any(isinstance(e, EventCommit) for e in evs)
    assert len(commits.poll()) == 1

    task_events = s.watch(match(kind="task"))
    await s.update(lambda tx: tx.create(_task(1)))
    n = s.get("node", "node1")
    n.spec.availability = 1
    await s.update(lambda tx: tx.update(n))
    got = task_events.poll()
    assert len(got) == 1 and got[0].kind == "task"


@async_test
async def test_update_event_carries_old_object():
    s = MemoryStore()
    await s.update(lambda tx: tx.create(_node(1)))
    w = s.watch(match(kind="node", action="update"))
    n = s.get("node", "node1")
    n.spec.annotations.labels["k"] = "v"
    await s.update(lambda tx: tx.update(n))
    (ev,) = w.poll()
    assert ev.old_object.spec.annotations.labels == {}
    assert ev.object.spec.annotations.labels == {"k": "v"}


@async_test
async def test_rollback_on_error():
    s = MemoryStore()

    def cb(tx):
        tx.create(_node(1))
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        await s.update(cb)
    assert s.get("node", "node1") is None
    assert s.version == 0


@async_test
async def test_batch_splits_transactions():
    s = MemoryStore(proposer=NopProposer())
    batch = s.batch()
    n_objs = MAX_CHANGES_PER_TRANSACTION + 50
    for i in range(n_objs):
        await batch.update(lambda tx, i=i: tx.create(_task(i)))
    applied = await batch.commit()
    assert applied == n_objs
    assert len(s.find("task")) == n_objs
    # two proposals: one full chunk + remainder
    assert len(s._proposer.proposed) == 2
    assert len(s._proposer.proposed[0]) == MAX_CHANGES_PER_TRANSACTION


@async_test
async def test_proposer_receives_actions():
    p = NopProposer()
    s = MemoryStore(proposer=p)
    await s.update(lambda tx: tx.create(_node(1)))
    assert len(p.proposed) == 1
    assert p.proposed[0][0].kind == "node"
    assert s.get("node", "node1").meta.version.index == p.get_version()


@async_test
async def test_apply_store_actions_follower_path():
    leader = MemoryStore(proposer=NopProposer())
    follower = MemoryStore()
    w = follower.watch(match(kind="node"))
    await leader.update(lambda tx: tx.create(_node(1)))
    actions = leader._proposer.proposed[0]
    follower.apply_store_actions(actions, version=1)
    got = follower.get("node", "node1")
    assert got is not None and got.meta.version.index == 1
    assert len(w.poll()) == 1


@async_test
async def test_save_restore():
    s = MemoryStore()

    def cb(tx):
        tx.create(_node(1))
        tx.create(_task(1))

    await s.update(cb)
    snap = s.save()
    s2 = MemoryStore()
    s2.restore(snap, version=s.version)
    assert s2.get("node", "node1") is not None
    assert [t.id for t in s2.find("task", ByService("svc1"))] == ["task1"]


@async_test
async def test_view_and_watch_atomicity():
    s = MemoryStore()
    await s.update(lambda tx: tx.create(_node(1)))
    nodes, w = s.view_and_watch(lambda tx: tx.find("node"))
    assert len(nodes) == 1
    await s.update(lambda tx: tx.create(_node(2)))
    evs = [e for e in w.poll() if isinstance(e, Event)]
    assert len(evs) == 1 and evs[0].object.id == "node2"

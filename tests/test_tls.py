"""mTLS on the wire: raft peers authenticate with cluster certificates,
wrong-CA identities are rejected, and the join bootstrap pins the root CA
by the token digest.

Reference: every manager RPC runs behind mutual TLS built from the node's
SecurityConfig (manager/manager.go:252-270) with per-RPC role authorization
from the peer certificate (ca/auth.go:50-120); joiners verify the remote
root CA against the digest pinned in the SWMTKN (ca/certificates.go
GetRemoteCA).
"""

import asyncio
import os
import socket
import tempfile

import pytest

from swarmkit_tpu.api import Annotations, Node as ApiNode, NodeSpec
from swarmkit_tpu.ca.certificates import (
    MANAGER_ROLE_OU, WORKER_ROLE_OU, RootCA,
)
from swarmkit_tpu.ca.config import SecurityConfig, generate_join_token
from swarmkit_tpu.raft.grpc_transport import GrpcNetwork
from swarmkit_tpu.raft.node import Node, NodeOpts
from tests.conftest import async_test, requires_cryptography

pytestmark = requires_cryptography

ORG = "cluster-tls-test"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_security(root: RootCA, node_id: str,
                  role: str = MANAGER_ROLE_OU) -> SecurityConfig:
    issued = root.issue_node_certificate(node_id, role, ORG)
    return SecurityConfig(RootCA(root.cert_pem, root.key_pem), node_id, role,
                          ORG, issued.cert_pem, issued.key_pem)


class TlsCluster:
    """Raft nodes over real sockets, one GrpcNetwork per node (each node
    presents its own certificate)."""

    def __init__(self, root: RootCA) -> None:
        self.root = root
        self.tmp = tempfile.TemporaryDirectory(prefix="tls-raft-")
        self.nets: list[GrpcNetwork] = []
        self.nodes: list[Node] = []

    async def add_node(self, i: int, join_addr: str = "",
                       security=None) -> Node:
        sec = security or make_security(self.root, f"n{i}")
        net = GrpcNetwork(security=sec)
        addr = f"127.0.0.1:{free_port()}"
        node = Node(NodeOpts(
            node_id=f"n{i}", addr=addr, network=net,
            state_dir=os.path.join(self.tmp.name, f"n{i}"),
            join_addr=join_addr, tick_interval=0.05, election_tick=4,
            seed=90 + i))
        self.nets.append(net)
        self.nodes.append(node)
        await node.start()
        return node

    async def close(self) -> None:
        for n in self.nodes:
            try:
                if n.running:
                    await n.stop()
            except Exception:
                pass
        for net in self.nets:
            await net.close()
        self.tmp.cleanup()


async def wait_until(pred, timeout=10.0, interval=0.05):
    for _ in range(int(timeout / interval)):
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def _obj(i):
    return ApiNode(id=f"id{i}",
                   spec=NodeSpec(annotations=Annotations(name=f"obj{i}")))


@async_test
async def test_mtls_cluster_replicates():
    """3 managers with certs from one root form a cluster and replicate
    over TLS sockets."""
    root = RootCA.create()
    c = TlsCluster(root)
    try:
        n1 = await c.add_node(1)
        assert await wait_until(n1.is_leader)
        n2 = await c.add_node(2, join_addr=n1.addr)
        n3 = await c.add_node(3, join_addr=n1.addr)
        assert await wait_until(lambda: len(n1.cluster.members) == 3)

        await n1.store.update(lambda tx: tx.create(_obj(1)))
        assert await wait_until(
            lambda: n2.store.get("node", "id1") is not None
            and n3.store.get("node", "id1") is not None)
    finally:
        await c.close()


@async_test
async def test_wrong_ca_join_rejected():
    """A node whose certificate comes from a DIFFERENT root CA cannot join
    (TLS handshake and/or per-RPC authorization rejects it)."""
    root = RootCA.create()
    evil_root = RootCA.create()
    c = TlsCluster(root)
    try:
        n1 = await c.add_node(1)
        assert await wait_until(n1.is_leader)
        with pytest.raises(Exception):
            await asyncio.wait_for(
                c.add_node(2, join_addr=n1.addr,
                           security=make_security(evil_root, "evil")),
                timeout=8.0)
        assert len(n1.cluster.members) == 1
    finally:
        await c.close()


@async_test
async def test_worker_cert_cannot_drive_raft():
    """Per-RPC role authorization: a WORKER certificate from the correct
    root must still be refused on the manager-only raft surface
    (ca/auth.go role OU gating, not just chain validation)."""
    import grpc

    from swarmkit_tpu.ca.tlsutil import (
        channel_credentials, secure_channel_options,
    )
    from swarmkit_tpu.raft.wire import encode_message
    from swarmkit_tpu.raft.messages import Message, MsgType

    root = RootCA.create()
    c = TlsCluster(root)
    try:
        n1 = await c.add_node(1)
        assert await wait_until(n1.is_leader)
        worker_sec = make_security(root, "w1", role=WORKER_ROLE_OU)
        channel = grpc.aio.secure_channel(
            n1.addr, channel_credentials(worker_sec),
            options=secure_channel_options())
        call = channel.unary_unary("/swarmkit.Raft/ProcessRaftMessage",
                                   request_serializer=lambda b: b,
                                   response_deserializer=lambda b: b)
        msg = encode_message(Message(type=MsgType.APP, to=n1.raft_id,
                                     frm=12345, term=99))
        with pytest.raises(grpc.aio.AioRpcError) as exc:
            await call(msg)
        assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        await channel.close()
    finally:
        await c.close()


@async_test
async def test_bootstrap_root_ca_fetch_and_digest_pin():
    """The plaintext bootstrap port serves the root CA; the token digest
    accepts the genuine root and rejects a substituted one."""
    import hmac

    from swarmkit_tpu.rpc import fetch_root_ca

    root = RootCA.create()
    c = TlsCluster(root)
    try:
        n1 = await c.add_node(1)
        assert await wait_until(n1.is_leader)
        fetched = await fetch_root_ca(n1.addr)
        assert fetched, "bootstrap port returned nothing"
        token = generate_join_token(root)
        pin = token.split("-")[2]
        assert hmac.compare_digest(RootCA(fetched).digest(), pin)
        # a MITM substituting its own CA fails the pin
        evil = RootCA.create()
        assert not hmac.compare_digest(RootCA(evil.cert_pem).digest(), pin)
    finally:
        await c.close()


@async_test
async def test_swarmd_tls_worker_join_by_token():
    """End-to-end join dance over real sockets, everything TLS: manager
    bootstraps (self-signed root, mTLS listeners), worker fetches the root
    from the bootstrap port, pin-verifies it against the SWMTKN, gets its
    certificate over the TLS join port, then runs its agent session over
    mutual TLS (reference: integration_test.go join-by-token scenarios)."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-tls-")
    p1, p2 = free_port(), free_port()
    args1 = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", f"127.0.0.1:{p1}",
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    m1 = w1 = None
    try:
        m1 = await swarmd.run(args1)
        assert await wait_until(m1.is_leader, timeout=15)
        assert m1.security is not None, "manager must have a TLS identity"
        # raft leadership precedes the manager's leader startup (which
        # creates the cluster object) — wait for the record, not the flag
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        cluster = m1.manager.store.find("cluster")[0]
        token = cluster.root_ca.join_token_worker
        assert token.startswith("SWMTKN-1-")

        args2 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p2}",
            "--node-id", "w1",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", token, "--election-tick", "4",
            "--executor", "test",
        ])
        w1 = await swarmd.run(args2)
        assert w1.security is not None, "worker must be issued a cert"
        assert w1.security.role_ou == WORKER_ROLE_OU
        assert w1.security.org == m1.security.org

        # the worker's agent session (mTLS) registers it with the manager
        def worker_known():
            rec = m1.manager.store.get("node", w1.node_id)
            return rec is not None
        assert await wait_until(worker_known, timeout=20), \
            "worker never registered over the mTLS dispatcher session"
    finally:
        for n in (w1, m1):
            if n is not None:
                try:
                    await n.stop()
                except Exception:
                    pass
        tmp.cleanup()


@async_test
async def test_swarmd_advertise_addr_split_from_listen():
    """--advertise-remote-api: bind a wildcard address but advertise the
    dialable one (reference swarmd flag) — the join dance, the raft member
    context, and the manager address book all carry the ADVERTISED addr."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-adv-")
    p1, p2 = free_port(), free_port()
    args1 = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", f"0.0.0.0:{p1}",
        "--advertise-remote-api", f"127.0.0.1:{p1}",
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    m1 = w1 = None
    try:
        m1 = await swarmd.run(args1)
        assert m1.addr == f"127.0.0.1:{p1}"   # advertise, not the bind
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        token = m1.manager.store.find(
            "cluster")[0].root_ca.join_token_worker

        args2 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p2}",
            "--node-id", "w1",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", token, "--election-tick", "4",
            "--executor", "test",
        ])
        w1 = await swarmd.run(args2)

        def worker_known():
            return m1.manager.store.get("node", "w1") is not None
        assert await wait_until(worker_known, timeout=20)
        # the address book the worker's session receives must carry the
        # DIALABLE advertise address, never the 0.0.0.0 bind
        peers = list(m1.remotes.weights().keys())
        assert peers and all("0.0.0.0" not in a for a in peers), peers
        assert f"127.0.0.1:{p1}" in peers
    finally:
        for n in (w1, m1):
            if n is not None:
                try:
                    await n.stop()
                except Exception:
                    pass
        tmp.cleanup()


@async_test
async def test_root_ca_rotation_end_to_end():
    """Rotate the cluster root CA with a live manager + worker (reference:
    integration_test.go TestSuccessfulRootRotation + ca/reconciler.go):
    the new root is cross-signed by the old one, nodes are marked ROTATE
    and renew over their sessions, trust bundles carry old+new during the
    transition, and once every node cert chains to the new root the
    cluster flips to it and regenerates the join tokens — after which a
    NEW worker joins with the NEW token."""
    from swarmkit_tpu.ca.certificates import is_issued_by
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-rot-")
    p1, p2, p3 = free_port(), free_port(), free_port()
    args1 = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", f"127.0.0.1:{p1}",
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    m1 = w1 = w2 = None
    try:
        m1 = await swarmd.run(args1)
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        cluster = m1.manager.store.find("cluster")[0]
        old_root = cluster.root_ca.ca_cert
        old_token = cluster.root_ca.join_token_worker

        args2 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p2}",
            "--node-id", "w1",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", old_token, "--election-tick", "4",
            "--executor", "test",
        ])
        w1 = await swarmd.run(args2)
        assert await wait_until(
            lambda: m1.manager.store.get("node", w1.node_id) is not None,
            timeout=20)

        # --- rotate
        res = await m1.manager.control_api.rotate_root_ca()
        assert "new_ca_digest" in res and len(res["new_ca_digest"]) == 64

        def rotated():
            cl = m1.manager.store.find("cluster")[0]
            if cl.root_ca.root_rotation is not None:
                return False
            return cl.root_ca.ca_cert != old_root
        assert await wait_until(rotated, timeout=40), \
            "rotation never finalized"

        cl = m1.manager.store.find("cluster")[0]
        new_root = cl.root_ca.ca_cert
        # every node certificate now chains to the new root
        for n in m1.manager.store.find("node"):
            if n.certificate.certificate:
                assert is_issued_by(n.certificate.certificate, new_root), \
                    f"{n.id} still on the old root"
        # join tokens were regenerated against the new root
        assert cl.root_ca.join_token_worker != old_token

        # the rotated worker's on-disk identity chains to the new root too
        assert await wait_until(
            lambda: is_issued_by(w1.security.cert_pem, new_root),
            timeout=20), "worker identity never re-issued"

        # a NEW worker joins with the NEW token against the NEW root
        args3 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w2"),
            "--listen-control-api", os.path.join(tmp.name, "w2.sock"),
            "--listen-remote-api", f"127.0.0.1:{p3}",
            "--node-id", "w2",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", cl.root_ca.join_token_worker,
            "--election-tick", "4", "--executor", "test",
        ])
        w2 = await swarmd.run(args3)
        assert is_issued_by(w2.security.cert_pem, new_root)
        # ...and its mTLS agent session passes the per-RPC authorization
        # against the rotated trust (node status goes READY, not just the
        # issuance-time record existing)
        from swarmkit_tpu.api import NodeState

        def w2_ready():
            rec = m1.manager.store.get("node", w2.node_id)
            return rec is not None and rec.status.state == NodeState.READY
        assert await wait_until(w2_ready, timeout=20), (
            "post-rotation worker session never authorized")
    finally:
        for n in (w2, w1, m1):
            if n is not None:
                try:
                    await n.stop()
                except Exception:
                    pass
        tmp.cleanup()


@async_test
async def test_manager_autolock_locks_key_at_rest():
    """Autolock (reference: integration_test.go autolock scenarios +
    keyreadwriter RotateKEK): enabling it mints a manager unlock key,
    every manager re-encrypts its TLS key at rest, a restart WITHOUT
    --unlock-key refuses to load the identity, the right key unlocks it,
    and disabling autolock decrypts the key again."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-lock-")
    p1 = free_port()

    def m1_args(unlock_key=""):
        argv = [
            "--state-dir", os.path.join(tmp.name, "m1"),
            "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p1}",
            "--node-id", "m1", "--manager", "--election-tick", "4",
            "--executor", "test",
        ]
        if unlock_key:
            argv += ["--unlock-key", unlock_key]
        return swarmd.build_parser().parse_args(argv)

    m1 = None
    try:
        m1 = await swarmd.run(m1_args())
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        cl = m1.manager.store.find("cluster")[0]

        # enable autolock through the control API
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = True
        await m1.manager.control_api.update_cluster(
            cl.id, spec, version=cl.meta.version.index)
        info = m1.manager.control_api.get_unlock_key()
        assert info["autolock"] and info["unlock_key"].startswith("SWMKEY-1-")
        unlock = info["unlock_key"]

        # the node-side watch engages the KEK: key envelope encrypted
        key_path = os.path.join(tmp.name, "m1", "certificates",
                                "swarm-node.key")

        def locked():
            import base64 as _b64
            import json as _json
            if not os.path.exists(key_path):
                return False
            env = _json.loads(open(key_path, "rb").read())
            return env.get("encrypted") and b"PRIVATE KEY" not in \
                _b64.b64decode(env["key"])
        assert await wait_until(locked, timeout=15), \
            "manager key never encrypted after autolock"

        await m1.stop()
        m1 = None

        # restart without the unlock key: locked out
        with pytest.raises(PermissionError):
            await swarmd.run(m1_args())

        # restart WITH the key: unlocked and leading again
        m1 = await swarmd.run(m1_args(unlock_key=unlock))
        assert await wait_until(m1.is_leader, timeout=15)

        # disable autolock: key decrypts at rest again
        cl = m1.manager.store.find("cluster")[0]
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = False
        await m1.manager.control_api.update_cluster(
            cl.id, spec, version=cl.meta.version.index)
        assert await wait_until(lambda: not locked(), timeout=15), \
            "key not decrypted after autolock disabled"
        assert m1.manager.control_api.get_unlock_key()["unlock_key"] == ""
    finally:
        if m1 is not None:
            try:
                await m1.stop()
            except Exception:
                pass
        tmp.cleanup()


@async_test
async def test_autolock_kek_released_on_demotion():
    """A demoted manager must get its key DECRYPTED at rest (workers run
    no autolock watch and have no --unlock-key); reference: keyreadwriter
    RotateKEK(nil) on demotion."""
    from swarmkit_tpu.api import NodeRole
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-demolock-")
    p1, p2 = free_port(), free_port()
    args1 = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", f"127.0.0.1:{p1}",
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    m1 = m2 = None
    try:
        m1 = await swarmd.run(args1)
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        cl = m1.manager.store.find("cluster")[0]

        args2 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "m2"),
            "--listen-control-api", os.path.join(tmp.name, "m2.sock"),
            "--listen-remote-api", f"127.0.0.1:{p2}",
            "--node-id", "m2", "--manager",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", cl.root_ca.join_token_manager,
            "--election-tick", "4", "--executor", "test",
        ])
        m2 = await swarmd.run(args2)
        assert await wait_until(m2.is_manager, timeout=20)

        # autolock on: both managers encrypt their keys at rest
        cl = m1.manager.store.find("cluster")[0]
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = True
        await m1.manager.control_api.update_cluster(
            cl.id, spec, version=cl.meta.version.index)

        def key_encrypted(name):
            path = os.path.join(tmp.name, name, "certificates",
                                "swarm-node.key")
            import json as _json
            if not os.path.exists(path):
                return False
            return _json.loads(open(path, "rb").read()).get("encrypted")
        assert await wait_until(lambda: key_encrypted("m2"), timeout=20), \
            "joined manager never engaged the autolock KEK"

        # demote m2: its key must decrypt at rest
        node_rec = m1.manager.store.get("node", m2.node_id)
        spec2 = node_rec.spec.copy()
        spec2.desired_role = NodeRole.WORKER
        await m1.manager.control_api.update_node(
            m2.node_id, spec2, version=node_rec.meta.version.index)
        assert await wait_until(lambda: not m2.is_manager(), timeout=30)
        assert await wait_until(lambda: not key_encrypted("m2"), timeout=20), \
            "demoted node still locked out of its own key"
    finally:
        for nd in (m2, m1):
            if nd is not None:
                try:
                    await nd.stop()
                except Exception:
                    pass
        tmp.cleanup()


@async_test
async def test_unlock_key_rotation():
    """`swarmctl cluster-unlock-key --rotate` equivalent: the key changes,
    the manager re-encrypts under the NEW KEK, and the OLD key no longer
    unlocks a restart (reference: unlock-key rotation flows)."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-kekrot-")
    p1 = free_port()

    def m1_args(unlock_key=""):
        argv = [
            "--state-dir", os.path.join(tmp.name, "m1"),
            "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p1}",
            "--node-id", "m1", "--manager", "--election-tick", "4",
            "--executor", "test",
        ]
        if unlock_key:
            argv += ["--unlock-key", unlock_key]
        return swarmd.build_parser().parse_args(argv)

    m1 = None
    try:
        m1 = await swarmd.run(m1_args())
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        cl = m1.manager.store.find("cluster")[0]
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = True
        await m1.manager.control_api.update_cluster(
            cl.id, spec, version=cl.meta.version.index)
        key1 = m1.manager.control_api.get_unlock_key()["unlock_key"]
        assert await wait_until(
            lambda: m1.keyrw._kek == key1.encode(), timeout=15)

        rotated = await m1.manager.control_api.rotate_unlock_key()
        key2 = rotated["unlock_key"]
        assert key2 != key1 and key2.startswith("SWMKEY-1-")
        assert await wait_until(
            lambda: m1.keyrw._kek == key2.encode(), timeout=15), \
            "manager never re-encrypted under the rotated KEK"

        await m1.stop()
        m1 = None
        with pytest.raises(PermissionError):   # old key no longer works
            await swarmd.run(m1_args(unlock_key=key1))
        m1 = await swarmd.run(m1_args(unlock_key=key2))
        assert await wait_until(m1.is_leader, timeout=15)
    finally:
        if m1 is not None:
            try:
                await m1.stop()
            except Exception:
                pass
        tmp.cleanup()


@async_test
async def test_raft_wal_encrypted_at_rest_and_dek_rotates_with_kek():
    """The production manager path encrypts its raft WAL with a DEK kept
    in the KEK-protected key-store headers (reference: manager/deks.go):
    raw WAL bytes leak no store payloads, a restart decrypts via the
    persisted DEK, and rotating the unlock key rotates the DEK too."""
    import glob

    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-dek-")
    p1 = free_port()

    def m1_args(unlock_key=""):
        argv = [
            "--state-dir", os.path.join(tmp.name, "m1"),
            "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p1}",
            "--node-id", "m1", "--manager", "--election-tick", "4",
            "--executor", "test",
        ]
        if unlock_key:
            argv += ["--unlock-key", unlock_key]
        return swarmd.build_parser().parse_args(argv)

    m1 = None
    try:
        m1 = await swarmd.run(m1_args())
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        # write something recognizable through raft
        from swarmkit_tpu.api import Annotations, NetworkSpec

        await m1.manager.control_api.create_network(NetworkSpec(
            annotations=Annotations(name="dek-canary-network")))
        dek1 = m1.keyrw.get_headers()["raft_dek"]
        assert len(dek1) == 32

        # the WAL on disk must not contain the plaintext canary
        wals = glob.glob(os.path.join(tmp.name, "m1", "raft", "wal-*"))
        assert wals, "no WAL segments written"
        raw = b"".join(open(w, "rb").read() for w in wals)
        assert b"dek-canary-network" not in raw, \
            "raft WAL leaked plaintext store payloads"

        # restart: the persisted DEK decrypts the WAL and state survives
        await m1.stop()
        m1 = await swarmd.run(m1_args())
        assert await wait_until(m1.is_leader, timeout=15)
        nets = m1.manager.store.find("network")
        assert any(n.spec.annotations.name == "dek-canary-network"
                   for n in nets), "state lost across encrypted restart"

        # KEK rotation rotates the DEK (and the manager keeps serving)
        cl = m1.manager.store.find("cluster")[0]
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = True
        await m1.manager.control_api.update_cluster(
            cl.id, spec, version=cl.meta.version.index)

        def dek_rotated():
            try:
                h = m1.keyrw.get_headers()
            except PermissionError:
                return False
            # rotation completes with a snapshot under the new key, after
            # which the old-generation history is drained
            return h.get("raft_dek") not in (None, dek1)
        assert await wait_until(dek_rotated, timeout=20), \
            "DEK did not rotate with the KEK"
        await m1.manager.control_api.create_network(NetworkSpec(
            annotations=Annotations(name="post-rotation-net")))

        # restart WITH the unlock key: both DEK generations decrypt
        key = m1.manager.control_api.get_unlock_key()["unlock_key"]
        await m1.stop()
        m1 = await swarmd.run(m1_args(unlock_key=key))
        assert await wait_until(m1.is_leader, timeout=15)
        names = {n.spec.annotations.name
                 for n in m1.manager.store.find("network")}
        assert {"dek-canary-network", "post-rotation-net"} <= names
    finally:
        if m1 is not None:
            try:
                await m1.stop()
            except Exception:
                pass
        tmp.cleanup()


@async_test
async def test_foreign_cluster_certificate_rejected():
    """A node holding a VALID certificate from a DIFFERENT cluster must be
    rejected by mTLS/authorization (reference: integration_test.go
    wrong-cert join rejection — trust is per-cluster root, and identity
    carries the cluster org)."""
    from swarmkit_tpu.ca.certificates import WORKER_ROLE_OU
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-foreign-")
    p1, p2, p3 = free_port(), free_port(), free_port()

    def margs(name, port):
        return swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, name),
            "--listen-control-api", os.path.join(tmp.name, f"{name}.sock"),
            "--listen-remote-api", f"127.0.0.1:{port}",
            "--node-id", name, "--manager", "--election-tick", "4",
            "--executor", "test",
        ])

    a = b = w = None
    try:
        a = await swarmd.run(margs("ca-a", p1))
        b = await swarmd.run(margs("cb-b", p2))
        for m in (a, b):
            assert await wait_until(m.is_leader, timeout=15)
            assert await wait_until(
                lambda m=m: m.manager.store.find("cluster"), timeout=15)

        # join a worker to cluster A legitimately
        cl_a = a.manager.store.find("cluster")[0]
        wargs = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w"),
            "--listen-control-api", os.path.join(tmp.name, "w.sock"),
            "--listen-remote-api", f"127.0.0.1:{p3}",
            "--node-id", "w",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", cl_a.root_ca.join_token_worker,
            "--election-tick", "4", "--executor", "test",
        ])
        w = await swarmd.run(wargs)
        assert w.security.role_ou == WORKER_ROLE_OU
        assert await wait_until(
            lambda: a.manager.store.get("node", w.node_id) is not None,
            timeout=20)

        # the same identity dialing cluster B: TLS trust differs, so the
        # session/RPC must fail and B must never register the node
        from swarmkit_tpu.rpc import RemoteManager, RpcError

        rm = RemoteManager(f"127.0.0.1:{p2}",
                           security_ref=lambda: w.security)
        rm.start()
        try:
            with pytest.raises(Exception) as exc_info:
                await rm.control_call("node.ls", {})
            assert not isinstance(exc_info.value, AssertionError)
        finally:
            await rm.close()
        assert b.manager.store.get("node", w.node_id) is None, \
            "foreign-cluster node must not register"
    finally:
        for nd in (w, b, a):
            if nd is not None:
                try:
                    await nd.stop()
                except Exception:
                    pass
        tmp.cleanup()


@async_test
async def test_service_logs_over_mtls():
    """The full remote log pipeline: a worker joined over TLS runs a task,
    its agent hears the subscription via the LogBroker gRPC stream and
    publishes lines back over mutual TLS; the client tails them from the
    manager (reference: api/logbroker.proto services over the mTLS mesh)."""
    from swarmkit_tpu.api import (
        Annotations, ContainerSpec, Placement, ReplicatedService,
        ServiceSpec, TaskSpec, TaskState,
    )
    from swarmkit_tpu.cmd import swarmd
    from swarmkit_tpu.manager.logbroker import (
        LogSelector, SubscribeLogsOptions,
    )
    from swarmkit_tpu.store.by import ByService

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-tls-logs-")
    p1, p2 = free_port(), free_port()
    args1 = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", f"127.0.0.1:{p1}",
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    m1 = w1 = None
    try:
        m1 = await swarmd.run(args1)
        assert await wait_until(m1.is_leader, timeout=15)
        assert await wait_until(
            lambda: m1.manager.store.find("cluster"), timeout=15)
        token = m1.manager.store.find("cluster")[0].root_ca.join_token_worker

        args2 = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", f"127.0.0.1:{p2}",
            "--node-id", "w1",
            "--join-addr", f"127.0.0.1:{p1}",
            "--join-token", token, "--election-tick", "4",
            "--executor", "test",
        ])
        w1 = await swarmd.run(args2)

        # constrain the service onto the WORKER so the published lines
        # must cross the network
        svc = await m1.manager.control_api.create_service(ServiceSpec(
            annotations=Annotations(name="tls-logged"),
            task=TaskSpec(container=ContainerSpec(image="img"),
                          placement=Placement(
                              constraints=["node.id==w1"])),
            replicated=ReplicatedService(replicas=1)))

        def task_running():
            ts = m1.manager.store.find("task", ByService(svc.id))
            return any(t.status.state == TaskState.RUNNING and
                       t.node_id == "w1" for t in ts)
        assert await wait_until(task_running, timeout=30), \
            "task never ran on the TLS worker"

        ctl = next(c for c in w1.config.executor.controllers.values()
                   if c.task.service_id == svc.id)
        ctl.write_log("over-the-wire")

        got = []
        deadline = asyncio.get_running_loop().time() + 20

        async def consume():
            async for m in m1.manager.logbroker.subscribe_logs(
                    LogSelector(service_ids=[svc.id]),
                    SubscribeLogsOptions(follow=True)):
                got.append(m)

        t = asyncio.get_running_loop().create_task(consume())
        while asyncio.get_running_loop().time() < deadline:
            if any(m.data == b"over-the-wire" for m in got):
                break
            await asyncio.sleep(0.05)
        t.cancel()
        datas = {m.data for m in got}
        assert b"over-the-wire" in datas, f"got only {datas}"
        assert all(m.context.node_id == "w1" for m in got)
    finally:
        for n in (w1, m1):
            if n is not None:
                try:
                    await n.stop()
                except Exception:
                    pass
        tmp.cleanup()

"""Tier-2 wrapper for the gRPC control-plane load harness
(tools/soak_controlplane.py): a 500-agent, 1-minute run over the real
wire must sustain the fleet, place work through the kernel scheduler,
and keep heartbeat RTT sane.

Slow-marked: ~90s wall (manager quorum + 500 gRPC sessions).  The 5k/10k
acceptance runs live in bench.py (``controlplane-10k``); this pins the
harness itself against regressions at a size tier-2 can afford.
"""

import importlib.util
import pathlib

import pytest

from tests.conftest import async_test

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_harness():
    spec = importlib.util.spec_from_file_location(
        "soak_controlplane", _TOOLS / "soak_controlplane.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow  # tier-2: real gRPC wire, 500 sessions, ~90s
@async_test
async def test_load_harness_sustains_500_agents():
    harness = _load_harness()
    r = await harness.load(minutes=1.0, agents=500, active=64,
                           heartbeat=5.0, report_every=1e9,
                           sustain_floor=0.98)
    assert "error" not in r, r.get("error")
    assert r["agents_sustained"] >= int(0.98 * 500)
    # work actually flowed: assignments placed and acked over the wire
    assert r["assignments"] > 0
    assert r["status_writes"] > 0
    # scheduler kernel path engaged for the placement groups
    assert r["kernel_groups"] > 0
    # heartbeats went through the coalescing pipeline in packed proposals
    assert r["entries_per_proposal"] > 1.0
    assert r["rtt_p99_ms"] < 5_000.0

"""Pallas kernel correctness (interpret mode on the CPU backend).

The kernels in `parallel/pallas_ops.py` are the hand-tiled MXU path for
executor task programs; off-TPU they run under the Pallas interpreter, so
these tests pin numeric identity against the XLA reference implementation
the builtin programs use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu.parallel import pallas_ops


def _rand(shape, dtype, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matmul_matches_xla(dtype):
    a = _rand((256, 128), dtype, 0)
    b = _rand((128, 384), dtype, 1)
    got = pallas_ops.matmul(a, b, tile_m=128, tile_n=128, tile_k=64)
    want = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(dtype)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5, atol=1e-2)


def test_matmul_multi_k_accumulates():
    # K spans 4 grid steps: exercises the scratch carry across the K sweep
    a = _rand((128, 512), jnp.float32, 2)
    b = _rand((512, 128), jnp.float32, 3)
    got = pallas_ops.matmul(a, b, tile_m=128, tile_n=128, tile_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(a @ b),
                               rtol=1e-5, atol=1e-3)


def test_matmul_rejects_misaligned_shapes():
    a = jnp.zeros((100, 64), jnp.float32)
    b = jnp.zeros((64, 64), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        pallas_ops.matmul(a, b, tile_m=64, tile_n=64, tile_k=64)
    with pytest.raises(ValueError, match="contraction"):
        pallas_ops.matmul(jnp.zeros((64, 32), jnp.float32), b)


def test_compiled_path_requires_lane_alignment():
    """interpret=False (the real-TPU path) rejects non-128-multiple tiles
    up front instead of failing deep in Mosaic lowering."""
    a = jnp.zeros((64, 64), jnp.float32)
    with pytest.raises(ValueError, match="lane-aligned"):
        pallas_ops.matmul(a, a, tile_m=64, tile_n=64, tile_k=64,
                          interpret=False)
    with pytest.raises(ValueError, match="lane-aligned"):
        pallas_ops.sumsq(jnp.zeros((64, 96), jnp.float32), tile_m=64,
                         interpret=False)


def test_sumsq_matches_xla():
    x = _rand((256, 192), jnp.bfloat16, 4)
    got = pallas_ops.sumsq(x, tile_m=64)
    want = jnp.sum(jnp.square(x.astype(jnp.float32)))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-2)


def test_matmul_chain_matches_builtin_chain():
    """The pallas chain and the XLA chain implement the same recurrence."""
    n, steps = 128, 3
    a = _rand((n, n), jnp.bfloat16, 5)
    x = _rand((n, n), jnp.bfloat16, 6)

    got = pallas_ops.matmul_chain(x, a, steps, tile=64)

    def xla_chain(x):
        for _ in range(steps):
            y = jnp.dot(x, a, preferred_element_type=jnp.float32)
            denom = jnp.maximum(
                jnp.sqrt(jnp.mean(jnp.square(y))), 1e-6)
            x = (y / denom).astype(jnp.bfloat16)
        return x

    want = xla_chain(x)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=1e-1, atol=1e-1)
    assert np.isfinite(np.asarray(got, np.float32)).all()


@pytest.mark.parametrize("dtype", [jnp.int32, jnp.uint32])
def test_append_band_copy_matches_where(dtype):
    """The raft kernel's fused banded-append copy (SWARMKIT_PALLAS_BAND=1)
    must be value-identical to the jnp.where it replaces, for both log
    buffer dtypes, including uneven row-tile splits."""
    rng = np.random.default_rng(11)
    for m, c, tile_m in ((8, 128, 8), (5, 256, 8), (12, 128, 5)):
        dst = jnp.asarray(rng.integers(0, 2**31, (m, c)), dtype)
        src = jnp.asarray(rng.integers(0, 2**31, (m, c)), dtype)
        write = jnp.asarray(rng.random((m, c)) < 0.3)
        got = pallas_ops.append_band_copy(dst, src, write, tile_m=tile_m,
                                          interpret=True)
        want = jnp.where(write, src, dst)
        assert got.dtype == dtype
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_append_band_copy_rejects_shape_mismatch():
    dst = jnp.zeros((4, 128), jnp.int32)
    src = jnp.zeros((4, 256), jnp.int32)
    with pytest.raises(ValueError, match="shape mismatch"):
        pallas_ops.append_band_copy(dst, src, jnp.zeros((4, 128), bool))


def test_pallas_band_env_gate_selects_kernel(monkeypatch):
    """kernel._pallas_band_copy() resolves the env gate once: default off
    (pure jnp path), SWARMKIT_PALLAS_BAND=1 routes chunk write-backs
    through append_band_copy."""
    from swarmkit_tpu.raft.sim import kernel

    monkeypatch.setattr(kernel, "_PALLAS_BAND", None)
    monkeypatch.setenv("SWARMKIT_PALLAS_BAND", "0")
    assert kernel._pallas_band_copy() is False
    monkeypatch.setattr(kernel, "_PALLAS_BAND", None)
    monkeypatch.setenv("SWARMKIT_PALLAS_BAND", "1")
    assert kernel._pallas_band_copy() is pallas_ops.append_band_copy
    monkeypatch.setattr(kernel, "_PALLAS_BAND", None)

"""Exhaustive task-FSM transition check (the design/tla analog).

The reference model-checks the worker/task state machine
(design/tla/Tasks.tla `Transitions`, WorkerSpec.tla) — every legal
(source, target) pair per actor, plus monotonicity over the lamport rank.
This test enumerates the ENTIRE input space of the agent's one advancer,
`exec.do_task_state` (every observed state x every desired state x every
controller outcome), and asserts the produced transition relation equals
the legal set EXACTLY — nothing illegal reachable, nothing legal missing.

The legal set is Tasks.tla's agent table with the reference Go
implementation's two documented refinements (agent/exec/controller.go):
- fatal errors pick the terminal state by WHERE they occurred
  (fatal() switch :210-221): < STARTING -> REJECTED (Tasks.tla lists
  these as `rejected` too), >= STARTING -> FAILED (the Go switch sends
  starting-failures to FAILED where the TLA table only lists
  running->failed);
- desired_state >= SHUTDOWN short-circuits ANY non-terminal state to
  SHUTDOWN (Do's shutdown gate), where the TLA agent table lists only
  running->shutdown (pre-running shutdowns are modeled inside
  WorkerSpec.tla's reject/progress interleavings).

ORPHANED transitions (assigned..running -> orphaned) belong to the
DISPATCHER's down-node path, not the agent advancer — covered by
tests/test_dispatcher.py.  The reaper's x -> null removals are covered by
the task reaper tests.
"""

from __future__ import annotations

import asyncio
import itertools

from swarmkit_tpu.agent.exec import (
    Controller, TaskError, TaskRejected, do_task_state,
)
from swarmkit_tpu.api import Task, TaskState, TaskStatus
from swarmkit_tpu.api.specs import ContainerSpec
from swarmkit_tpu.api.types import TERMINAL_STATES
from swarmkit_tpu.manager.orchestrator import common

S = TaskState

ALL_STATES = list(TaskState)
DESIREDS = [S.READY, S.RUNNING, S.SHUTDOWN, S.REMOVE]
OUTCOMES = ["ok", "task_error", "task_rejected", "runtime_error"]

NON_TERMINAL = [s for s in ALL_STATES if s < S.COMPLETE]

# -- the legal transition relation (see module docstring for provenance) --
PROGRESS = {
    (S.NEW, S.ACCEPTED), (S.PENDING, S.ACCEPTED), (S.ASSIGNED, S.ACCEPTED),
    (S.ACCEPTED, S.PREPARING),
    (S.PREPARING, S.READY),
    (S.READY, S.STARTING),
    (S.STARTING, S.RUNNING),
    (S.RUNNING, S.COMPLETE),
}
FATAL = {
    (S.PREPARING, S.REJECTED),    # prepare() is the only pre-STARTING
                                  # controller call that can fail
    (S.STARTING, S.FAILED),
    (S.RUNNING, S.FAILED),
}
SHUTDOWNS = {(s, S.SHUTDOWN) for s in NON_TERMINAL}
LEGAL = PROGRESS | FATAL | SHUTDOWNS


class _Ctl(Controller):
    """Controller whose lifecycle calls share one scripted outcome."""

    def __init__(self, outcome: str):
        self.outcome = outcome

    def _maybe_raise(self):
        if self.outcome == "task_error":
            raise TaskError("boom")
        if self.outcome == "task_rejected":
            raise TaskRejected("cannot run here")
        if self.outcome == "runtime_error":
            raise RuntimeError("unexpected")

    async def prepare(self):
        self._maybe_raise()

    async def start(self):
        self._maybe_raise()

    async def wait(self):
        self._maybe_raise()

    async def shutdown(self):
        # shutdown errors are swallowed by the advancer (reference Do's
        # shutdown path ignores graceful-stop failures)
        self._maybe_raise()


def _task(state: TaskState, desired: TaskState) -> Task:
    t = Task(id="t1", service_id="s1", slot=1, node_id="n1")
    t.status = TaskStatus(state=state)
    t.desired_state = int(desired)
    return t


def test_agent_advancer_transition_relation_is_exactly_the_legal_set():
    seen: set[tuple[TaskState, TaskState]] = set()

    async def drive():
        for state, desired, outcome in itertools.product(
                ALL_STATES, DESIREDS, OUTCOMES):
            task = _task(state, desired)
            st = await do_task_state(task, _Ctl(outcome), 0.0)
            if st is None:
                # a no-op must only happen on terminal states or the
                # READY park (stop-first updates hold replacements there)
                assert state in TERMINAL_STATES or (
                    state == S.READY and desired <= S.READY), \
                    (state.name, desired.name, outcome)
                continue
            new = TaskState(st.state)
            if new == state:
                continue
            seen.add((state, new))
            # monotonicity: the lamport rank never decreases (reference
            # Do's transition() panics on current > state)
            assert new >= state, (state.name, new.name)

    asyncio.run(drive())
    missing = LEGAL - seen
    illegal = seen - LEGAL
    assert not illegal, {(a.name, b.name) for a, b in illegal}
    assert not missing, {(a.name, b.name) for a, b in missing}


def test_terminal_and_runnable_helpers_agree_with_the_rank():
    """orchestrator.common's predicates partition the state space the way
    Tasks.tla's rank order does: terminal states are exactly those at or
    past COMPLETE, and `runnable` is desired<=RUNNING on a non-terminal
    observed state."""
    for s in ALL_STATES:
        t = _task(s, S.RUNNING)
        assert common.in_terminal_state(t) == (s in TERMINAL_STATES)
        assert common.in_terminal_state(t) == (s >= S.COMPLETE)
        assert common.runnable(t) == (s < S.COMPLETE)
    # desired past RUNNING makes any task non-runnable
    t = _task(S.RUNNING, S.SHUTDOWN)
    assert not common.runnable(t)


def test_legal_set_matches_tasks_tla_modulo_documented_refinements():
    """Pin the relationship to design/tla/Tasks.tla's agent table so a
    future edit to either side surfaces here."""
    tla_agent = {
        (S.ASSIGNED, S.ACCEPTED), (S.ACCEPTED, S.PREPARING),
        (S.PREPARING, S.READY), (S.READY, S.STARTING),
        (S.STARTING, S.RUNNING),
        (S.ASSIGNED, S.REJECTED), (S.ACCEPTED, S.REJECTED),
        (S.PREPARING, S.REJECTED), (S.READY, S.REJECTED),
        (S.STARTING, S.REJECTED),
        (S.RUNNING, S.COMPLETE), (S.RUNNING, S.FAILED),
        (S.RUNNING, S.SHUTDOWN),
    }
    # refinements: Go's fatal() switch sends STARTING failures to FAILED;
    # pure status moves (no controller call) cannot fail, so several TLA
    # rejected-edges are unreachable in this implementation; pre-RUNNING
    # shutdown short-circuits exist (Do's gate); tasks arrive at the
    # agent before ASSIGNED only in tests.
    go_only = (LEGAL - tla_agent)
    assert go_only == (
        {(S.NEW, S.ACCEPTED), (S.PENDING, S.ACCEPTED),
         (S.STARTING, S.FAILED)}
        | {(s, S.SHUTDOWN) for s in NON_TERMINAL if s != S.RUNNING})
    tla_only = (tla_agent - LEGAL)
    assert tla_only == {(S.ASSIGNED, S.REJECTED), (S.ACCEPTED, S.REJECTED),
                        (S.READY, S.REJECTED), (S.STARTING, S.REJECTED)}

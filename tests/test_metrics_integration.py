"""End-to-end observability: three managers elect under a FaultPlan
partition and every layer's activity shows up in the typed registries and
the manager's /metrics-equivalent scrape surface.
"""

import asyncio
import os
import tempfile

from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.raft.faults import FaultPlan
from swarmkit_tpu.raft.transport import Network
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test

TICK = 1.0


class _Harness:
    def __init__(self):
        self.clock = FakeClock()
        self.network = Network(seed=11)
        self.tmp = tempfile.TemporaryDirectory(prefix="swarmkit-obs-")
        self.managers: list[Manager] = []

    def new_manager(self, i: int, join_addr: str = "") -> Manager:
        m = Manager(node_id=f"m{i}", addr=f"m{i}.test:4242",
                    network=self.network,
                    state_dir=os.path.join(self.tmp.name, f"m{i}"),
                    clock=self.clock, join_addr=join_addr,
                    election_tick=4, heartbeat_tick=1, seed=31 + i)
        self.managers.append(m)
        return m

    async def pump(self, seconds=TICK, steps=8):
        for _ in range(steps):
            await asyncio.sleep(0)
        await self.clock.advance(seconds)
        for _ in range(steps):
            await asyncio.sleep(0)

    def leader(self):
        for m in self.managers:
            if m.is_leader():
                return m
        return None

    async def wait_for(self, pred, what, ticks=60):
        for _ in range(ticks):
            if pred():
                return
            await self.pump()
        raise AssertionError(f"timed out waiting for {what}")

    async def stop_all(self):
        for m in self.managers:
            try:
                await m.stop()
            except Exception:
                pass


def counter_sum(m: Manager, name: str) -> float:
    fam = m.obs.get(name)
    if fam is None:
        return 0.0
    snap = fam.snapshot()
    return sum(snap.values()) if isinstance(snap, dict) else float(snap)


@async_test
async def test_three_manager_election_metrics_under_partition():
    h = _Harness()
    m1 = h.new_manager(1)
    await m1.start()
    await h.wait_for(lambda: h.leader() is not None, "first leader")
    for i in (2, 3):
        m = h.new_manager(i, join_addr=m1.addr)
        await m.start()
    await h.wait_for(
        lambda: all(len(m.raft.cluster.members) == 3 for m in h.managers),
        "3-way membership")

    lead = h.leader()
    # the election left its trace in the winner's per-manager registry
    assert counter_sum(lead, "swarm_raft_elections_won_total") >= 1
    assert counter_sum(lead, "swarm_raft_leader_changes_total") >= 1
    # raft traffic flowed through the instrumented store + transport
    assert counter_sum(lead, "swarm_store_commits_total") > 0
    assert counter_sum(lead, "swarm_raft_peer_sends_total") > 0

    # -- partition a follower; its OWN registry must record the campaign --
    victim = next(m for m in h.managers if m is not lead)
    before = counter_sum(victim, "swarm_raft_elections_started_total")
    others = [m.addr for m in h.managers if m is not victim]
    plan = FaultPlan.split([victim.addr], others)
    plan.inject(h.network)
    await h.wait_for(
        lambda: counter_sum(victim, "swarm_raft_elections_started_total")
        > before,
        "partitioned follower to campaign")
    # the majority side never lost its leader
    assert lead.is_leader()

    plan.heal(h.network)
    await h.wait_for(
        lambda: h.leader() is not None
        and all(not m.is_leader() or m is h.leader() for m in h.managers),
        "post-heal convergence")

    # -- scrape surface: one page covering every instrumented layer --------
    lead = h.leader()
    text = lead.metrics_text()
    for family, kind in (
        ("swarm_raft_elections_won_total", "counter"),
        ("swarm_raft_is_leader", "gauge"),
        ("swarm_transport_delivery_latency_seconds", "histogram"),
        ("swarm_scheduler_pending_tasks", "gauge"),
        ("swarm_dispatcher_heartbeats_total", "counter"),
        ("swarm_store_commits_total", "counter"),
    ):
        assert f"# TYPE {family} {kind}" in text, family
    # format sanity: every non-comment line is "<series> <value>"
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        assert name and not name.startswith(" ")
        float(value)  # must parse

    snap = lead.metrics_snapshot()
    assert snap["metrics"]["swarm_raft_elections_won_total"]
    assert "timers" in snap and "objects" in snap and "spans" in snap

    # per-manager registries stay isolated: the victim's campaign never
    # bleeds into the leader's counter
    assert counter_sum(lead, "swarm_raft_elections_started_total") \
        <= counter_sum(lead, "swarm_raft_elections_won_total") + 1

    await h.stop_all()
    h.tmp.cleanup()

"""Event bus tests (reference: watch/watch_test.go, watch/queue/queue_test.go)."""

import asyncio

import pytest

from swarmkit_tpu.watch import Queue, WatcherClosed
from tests.conftest import async_test


def test_publish_and_poll():
    q = Queue()
    w = q.watch()
    q.publish(1)
    q.publish(2)
    assert w.poll() == [1, 2]
    assert w.poll() == []


def test_filtering():
    q = Queue()
    evens = q.watch(lambda e: e % 2 == 0)
    q.publish_all([1, 2, 3, 4])
    assert evens.poll() == [2, 4]


def test_multiple_matchers_is_or():
    q = Queue()
    w = q.watch(lambda e: e == 1, lambda e: e == 3)
    q.publish_all([1, 2, 3])
    assert w.poll() == [1, 3]


def test_overflow_closes_watcher():
    # reference watch/queue/queue.go LimitQueue: exceeding the limit closes
    # the watcher instead of blocking the publisher.
    q = Queue()
    w = q.watch(limit=3)
    for i in range(3):
        q.publish(i)
    assert not w.closed
    q.publish(3)
    assert w.closed and w.overflowed
    assert len(q) == 0


@async_test
async def test_async_get_wakes():
    q = Queue()
    w = q.watch()

    async def producer():
        await asyncio.sleep(0)
        q.publish("ev")

    task = asyncio.ensure_future(producer())
    got = await w.get()
    assert got == "ev"
    await task


@async_test
async def test_get_after_close_raises():
    q = Queue()
    w = q.watch()
    q.publish("last")
    w.close()
    # buffered events still drain, then WatcherClosed
    assert await w.get() == "last"
    with pytest.raises(WatcherClosed):
        await w.get()


def test_close_queue_closes_watchers():
    q = Queue()
    w1, w2 = q.watch(), q.watch()
    q.close()
    assert w1.closed and w2.closed

"""gRPC raft transport: real-socket cluster formation, replication,
failover, snapshot streaming.

Reference scenarios: manager/state/raft/transport/transport_test.go +
raft_test.go bootstrap/join over the gRPC service.
"""

import asyncio
import random
import socket
import tempfile

import pytest

from swarmkit_tpu.api import (
    Annotations, ContainerSpec, ReplicatedService, ServiceSpec, TaskSpec,
)
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.raft.grpc_transport import (
    GrpcNetwork, decode_message, encode_message,
)
from swarmkit_tpu.raft.messages import (
    Entry, EntryType, Message, MsgType, Snapshot, SnapshotMeta,
)
from tests.conftest import async_test, requires_cryptography


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_message_codec_round_trip():
    m = Message(type=MsgType.APP, to=2, frm=1, term=7, log_term=6, index=41,
                entries=(Entry(index=42, term=7, type=EntryType.NORMAL,
                               data=b"payload"),),
                commit=40, reject=True, reject_hint=39,
                snapshot=Snapshot(meta=SnapshotMeta(index=10, term=3,
                                                    voters=(1, 2, 3)),
                                  data=b"snapdata"),
                context=b"ctx")
    out = decode_message(encode_message(m))
    assert out == m


def service_spec(name="web", replicas=1):
    return ServiceSpec(annotations=Annotations(name=name),
                       task=TaskSpec(container=ContainerSpec(image="img")),
                       replicated=ReplicatedService(replicas=replicas))


@async_test
async def test_three_managers_over_real_grpc():
    """Cluster formation, replication and failover across localhost
    sockets."""
    net = GrpcNetwork()
    tmp = tempfile.TemporaryDirectory(prefix="grpc-raft-")
    addrs = [f"127.0.0.1:{free_port()}" for _ in range(3)]
    managers = []
    try:
        for i, addr in enumerate(addrs):
            m = Manager(node_id=f"m{i}", addr=addr, network=net,
                        state_dir=f"{tmp.name}/m{i}",
                        join_addr=addrs[0] if i else "",
                        tick_interval=0.05, election_tick=4, seed=50 + i)
            await m.start()
            managers.append(m)
            if i == 0:
                for _ in range(200):
                    if m.is_leader():
                        break
                    await asyncio.sleep(0.05)
                assert m.is_leader()

        lead = managers[0]
        for _ in range(200):
            if len(lead.raft.cluster.members) == 3:
                break
            await asyncio.sleep(0.05)
        assert len(lead.raft.cluster.members) == 3

        # a write replicates to every member over the sockets
        svc = await lead.control_api.create_service(service_spec())
        for _ in range(200):
            if all(m.store.get("service", svc.id) is not None
                   for m in managers):
                break
            await asyncio.sleep(0.05)
        assert all(m.store.get("service", svc.id) is not None
                   for m in managers)

        # kill the leader; the others elect a new one and accept writes
        await lead.stop()
        new_lead = None
        for _ in range(400):
            new_lead = next((m for m in managers[1:] if m._is_leader), None)
            if new_lead is not None:
                break
            await asyncio.sleep(0.05)
        assert new_lead is not None
        svc2 = await new_lead.control_api.create_service(
            service_spec(name="after"))
        assert new_lead.store.get("service", svc2.id) is not None
    finally:
        for m in managers[1:]:
            try:
                await m.stop()
            except Exception:
                pass
        await net.close()


@async_test
async def test_snapshot_streams_in_chunks_over_grpc():
    """A >4MiB snapshot crosses via the client-streaming RPC."""
    from swarmkit_tpu.raft.grpc_transport import _CHUNK, _RaftService

    received = []

    class FakeNode:
        async def process_raft_message(self, m):
            received.append(m)

    net = GrpcNetwork()
    addr = f"127.0.0.1:{free_port()}"
    net.register(addr, FakeNode())
    await asyncio.sleep(0.2)  # let the server bind
    try:
        stub = net.server("x", addr)
        big = Message(type=MsgType.SNAP, to=2, frm=1, term=1,
                      snapshot=Snapshot(meta=SnapshotMeta(index=5, term=1),
                                        data=b"z" * (6 * 1024 * 1024)))
        await stub.process_raft_message(big)
        assert len(received) == 1
        assert received[0].snapshot.data == big.snapshot.data
    finally:
        await net.close()


@async_test
@requires_cryptography
async def test_worker_joins_manager_over_grpc_rpc_layer():
    """Full node-level join across the gRPC cluster services: a worker
    node with only an address + token reaches the manager's CA, dispatcher
    and control APIs through real sockets (reference: swarmd multi-host
    deployment)."""
    import os

    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="grpc-join-")
    m_addr = f"127.0.0.1:{free_port()}"
    m_args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", m_addr,
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    manager_node = await swarmd.run(m_args)
    try:
        for _ in range(200):
            if manager_node.is_leader():
                break
            await asyncio.sleep(0.05)
        assert manager_node.is_leader()
        lead = manager_node._running_manager()
        for _ in range(200):   # leader startup creates the cluster object
            if lead.store.find("cluster"):
                break
            await asyncio.sleep(0.05)
        token = lead.store.find("cluster")[0].root_ca.join_token_worker

        w_addr = f"127.0.0.1:{free_port()}"
        w_args = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", w_addr,
            "--node-id", "w1",
            "--join-addr", m_addr, "--join-token", token,
            "--executor", "test",
        ])
        worker_node = await swarmd.run(w_args)
        try:
            # CA assigned identity over gRPC; worker registers READY
            assert worker_node.security is not None
            from swarmkit_tpu.api import NodeState

            for _ in range(400):
                n = lead.store.get("node", worker_node.node_id)
                if n is not None and n.status.state == NodeState.READY:
                    break
                await asyncio.sleep(0.05)
            assert lead.store.get(
                "node", worker_node.node_id).status.state == NodeState.READY

            # tasks flow to the remote worker through the gRPC dispatcher
            svc = await lead.control_api.create_service(
                service_spec(replicas=4))
            from swarmkit_tpu.api import TaskState
            from swarmkit_tpu.store.by import ByService

            for _ in range(400):
                running = [t for t in lead.store.find(
                    "task", ByService(svc.id))
                    if t.status.state == TaskState.RUNNING]
                if len(running) == 4:
                    break
                await asyncio.sleep(0.05)
            assert len(running) == 4
            nodes_used = {t.node_id for t in running}
            assert worker_node.node_id in nodes_used
        finally:
            await worker_node._ctl_server.stop()
            await worker_node.stop()
            for rm in getattr(worker_node, "_remote_managers", {}).values():
                await rm.close()
    finally:
        await manager_node._ctl_server.stop()
        await manager_node.stop()
        net = manager_node.config.network
        if hasattr(net, "close"):
            await net.close()

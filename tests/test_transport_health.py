"""Transport health over the real gRPC wire.

These tests boot 3-node raft clusters on loopback sockets (GrpcNetwork,
system clock) and prove the active health-probing loop is genuinely
operative across processes: ``healthy()`` flips when a peer dies and
recovers after restart, the ``CanRemoveMember`` quorum precheck refuses
removals that would break quorum among *reachable* members, and a
partitioned minority cannot win elections.

The cluster harness is shared with the sweep tool (tools/fault_sweep.py);
the fake-clock equivalents of the fault semantics live in
tests/test_faults.py.

Reference bar: manager/state/raft/raft.go:986 (join health check),
:1164 (CanRemoveMember), :1422 (vote-health gating).
"""

import asyncio

from swarmkit_tpu.raft.faults import FaultPlan
from swarmkit_tpu.raft.node import ErrCannotRemoveMember
from tests.conftest import async_test
from tools.fault_sweep import _GrpcCluster, _commit_while_stepping, _has


async def _boot_three(h):
    n1 = await h.add_node()
    await h.wait_for(lambda: h.leader() is not None, "first leader")
    n2 = await h.add_node(join_from=n1)
    n3 = await h.add_node(join_from=n1)
    lead = await h.wait_for_cluster()
    return n1, n2, n3, lead


@async_test
async def test_grpc_healthy_flips_on_kill_and_recovers():
    """The acceptance bar for real transport health: kill a peer process
    and ``healthy(addr)`` goes False within the probe failure threshold;
    restart it and ``healthy(addr)`` returns True after the grace period.
    No fault injection involved — this is a genuine process death observed
    through the wire."""
    h = _GrpcCluster(seed=2009343)
    try:
        n1, n2, n3, lead = await _boot_three(h)
        victim = n2 if lead is not n2 else n3
        addr = victim.addr

        # steady state: the leader's prober sees the peer healthy
        await h.wait_for(lambda: h.network.healthy(addr),
                         "victim healthy before kill")

        await h.stop_node(victim)
        await h.wait_for(lambda: not h.network.healthy(addr),
                         "healthy() flips False after kill")

        victim = await h.restart_node(victim)
        await h.wait_for(lambda: h.network.healthy(addr),
                         "healthy() recovers after restart")
        await h.wait_for_cluster()
    finally:
        await h.close()


@async_test
async def test_can_remove_member_refused_then_allowed_over_grpc():
    """CanRemoveMember over real sockets: with one member dead, removing a
    *different* member would leave quorum unreachable and must be refused;
    once the dead member restarts and probes recover, the same removal
    succeeds (reference: raft.go:1164-1190)."""
    h = _GrpcCluster(seed=2009343)
    try:
        n1, n2, n3, lead = await _boot_three(h)
        followers = [n for n in (n1, n2, n3) if n is not lead]
        dead, target = followers[0], followers[1]

        await h.stop_node(dead)
        await h.wait_for(lambda: not h.network.healthy(dead.addr),
                         "dead peer detected unhealthy")

        # remaining after removing `target` would be {lead, dead}; only the
        # leader is reachable -> 1 < quorum(2) -> refused
        assert not lead.can_remove_member(target.raft_id)
        try:
            await lead.remove_member(target.raft_id)
        except ErrCannotRemoveMember:
            pass
        else:
            raise AssertionError("remove_member must refuse while quorum "
                                 "among reachable members would break")

        dead = await h.restart_node(dead)
        await h.wait_for(lambda: h.network.healthy(dead.addr)
                         and h.network.reachable(lead.addr, dead.addr),
                         "dead peer recovered")
        await h.wait_for_cluster()

        removal = asyncio.ensure_future(lead.remove_member(target.raft_id))
        await h.wait_for(lambda: removal.done(), "member removal")
        removal.result()
        await h.stop_node(target)

        lead = await h.wait_for_cluster()
        assert target.raft_id not in lead.cluster.members
    finally:
        await h.close()


@async_test
async def test_partitioned_minority_cannot_win_election_over_grpc():
    """Vote-health gating on the gRPC wire: an isolated node campaigns but
    never wins; the majority keeps committing, and healing restores the
    victim to a converged cluster."""
    h = _GrpcCluster(seed=2009343)
    try:
        n1, n2, n3, lead = await _boot_three(h)
        victim = n2 if lead is not n2 else n3
        majority = [n for n in (n1, n2, n3) if n is not victim]

        FaultPlan.split([victim.addr],
                        [n.addr for n in majority]).inject(h.network)

        # several election timeouts of real time; the minority must never
        # take leadership and the majority must keep one
        for _ in range(20):
            await h.settle()
            assert not victim.is_leader()
        lead = h.leader()
        assert lead is not None and lead in majority

        assert await _commit_while_stepping(h, lead, "during-partition")
        await h.wait_for(
            lambda: all(_has(n, "during-partition") for n in majority),
            "majority replication under partition")
        assert not _has(victim, "during-partition")

        h.network.heal()
        lead = await h.wait_for_cluster()
        await h.wait_for(lambda: _has(victim, "during-partition"),
                         "victim catches up after heal")
    finally:
        await h.close()

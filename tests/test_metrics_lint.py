"""Suite wrapper for tools/metrics_lint.py: the catalog stays the single
ground truth for every metric name in the tree (slow-marked; tier-1 skips
it, the full suite runs it)."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))


@pytest.mark.slow
def test_metrics_lint_is_clean():
    from metrics_lint import run_lint

    problems = run_lint(REPO_ROOT)
    assert not problems, "\n".join(problems)


@pytest.mark.slow
def test_check11_bites_in_both_directions(monkeypatch):
    """Check #11 (multi-raft lockstep) flags an obs.py constant with no
    catalog spec AND a swarm_multiraft_* catalog entry with no constant."""
    from metrics_lint import run_lint

    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.multiraft import obs as mr_obs

    monkeypatch.setitem(mr_obs.METRIC_NAMES,
                        "swarm_multiraft_bogus_total", ())
    orphan = "swarm_multiraft_orphan_total"
    monkeypatch.setitem(catalog.CATALOG, orphan,
                        catalog.MetricSpec("counter", "orphan for lint"))
    problems = run_lint(REPO_ROOT)
    assert any("swarm_multiraft_bogus_total" in p and "missing from the "
               "catalog" in p for p in problems), problems
    assert any(orphan in p and "no multiraft/obs.py constant" in p
               for p in problems), problems


@pytest.mark.slow
def test_check12_bites_in_both_directions(monkeypatch):
    """Check #12 (vectorized control plane) flags a pipeline/kernel
    constant with no catalog spec AND an orphaned swarm_cpl_* /
    swarm_sched_kernel_* catalog entry."""
    from metrics_lint import run_lint

    from swarmkit_tpu.manager.scheduler import kernel as sched_kernel
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.store import pipeline as cpl_pipeline

    monkeypatch.setitem(cpl_pipeline.METRIC_NAMES,
                        "swarm_cpl_bogus_total", ())
    monkeypatch.setitem(sched_kernel.METRIC_NAMES,
                        "swarm_sched_kernel_bogus_total", ())
    for orphan in ("swarm_cpl_orphan_total",
                   "swarm_sched_kernel_orphan_total"):
        monkeypatch.setitem(catalog.CATALOG, orphan,
                            catalog.MetricSpec("counter", "orphan for lint"))
    problems = run_lint(REPO_ROOT)
    assert any("swarm_cpl_bogus_total" in p and "missing from the catalog"
               in p for p in problems), problems
    assert any("swarm_sched_kernel_bogus_total" in p and "missing from "
               "the catalog" in p for p in problems), problems
    assert any("swarm_cpl_orphan_total" in p and "can't publish" in p
               for p in problems), problems
    assert any("swarm_sched_kernel_orphan_total" in p and "can't publish"
               in p for p in problems), problems


@pytest.mark.slow
def test_check13_bites_in_both_directions(monkeypatch):
    """Check #13 (fleet health plane) flags an engine constant with no
    catalog spec AND a swarm_slo_* catalog entry with no constant."""
    from metrics_lint import run_lint

    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.slo import engine as slo_engine

    monkeypatch.setitem(slo_engine.METRIC_NAMES,
                        "swarm_slo_bogus_total", ())
    orphan = "swarm_slo_orphan_total"
    monkeypatch.setitem(catalog.CATALOG, orphan,
                        catalog.MetricSpec("counter", "orphan for lint"))
    problems = run_lint(REPO_ROOT)
    assert any("swarm_slo_bogus_total" in p and "missing from the catalog"
               in p for p in problems), problems
    assert any(orphan in p and "has no slo/engine.py constant" in p
               for p in problems), problems

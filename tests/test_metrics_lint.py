"""Suite wrapper for tools/metrics_lint.py: the catalog stays the single
ground truth for every metric name in the tree (slow-marked; tier-1 skips
it, the full suite runs it)."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))


@pytest.mark.slow
def test_metrics_lint_is_clean():
    from metrics_lint import run_lint

    problems = run_lint(REPO_ROOT)
    assert not problems, "\n".join(problems)


@pytest.mark.slow
def test_check11_bites_in_both_directions(monkeypatch):
    """Check #11 (multi-raft lockstep) flags an obs.py constant with no
    catalog spec AND a swarm_multiraft_* catalog entry with no constant."""
    from metrics_lint import run_lint

    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.multiraft import obs as mr_obs

    monkeypatch.setitem(mr_obs.METRIC_NAMES,
                        "swarm_multiraft_bogus_total", ())
    orphan = "swarm_multiraft_orphan_total"
    monkeypatch.setitem(catalog.CATALOG, orphan,
                        catalog.MetricSpec("counter", "orphan for lint"))
    problems = run_lint(REPO_ROOT)
    assert any("swarm_multiraft_bogus_total" in p and "missing from the "
               "catalog" in p for p in problems), problems
    assert any(orphan in p and "no multiraft/obs.py constant" in p
               for p in problems), problems

"""Suite wrapper for tools/metrics_lint.py: the catalog stays the single
ground truth for every metric name in the tree (slow-marked; tier-1 skips
it, the full suite runs it)."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))


@pytest.mark.slow
def test_metrics_lint_is_clean():
    from metrics_lint import run_lint

    problems = run_lint(REPO_ROOT)
    assert not problems, "\n".join(problems)

"""Multi-raft serving plane (swarmkit_tpu/multiraft/): the [G, N, ...]
group-batched kernel, key->group router, placement rule, observability,
and DST drivability.

The two contracts this file pins are the subsystem's acceptance bar:

- G=1 BIT-IDENTITY: the grouped tick at G == 1 produces the same dtype
  and value on EVERY SimState field as today's single-group driver —
  the serving plane is a strict generalization, not a fork.
- GROUP ISOLATION: faults injected into group g leave every other
  group bit-identical to a fault-free run, on both the tick-synchronous
  wire and the latency>0 mailbox wire.
"""

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swarmkit_tpu.dst.schedule import FaultSchedule
from swarmkit_tpu.multiraft import (
    MultiRaftObs, Router, aggregate_committed, aggregate_reads_served,
    group_leaders, group_of_key, groups_with_leader, init_groups,
    run_group_ticks, run_groups_under_schedule, step_groups,
)
from swarmkit_tpu.parallel import (
    GROUP_AXIS, group_mesh, shard_rows, state_shardings,
)
from swarmkit_tpu.raft.sim import SimConfig, init_state, run_ticks
from swarmkit_tpu.raft.sim.run import KernelObs, sync_point

CFG = SimConfig(n=5, log_len=96, window=16, apply_batch=16, max_props=8,
                keep=8, seed=7, election_tick=10, collect_stats=True,
                read_batch=4, read_leases=True)


def _flat(state):
    return jax.tree_util.tree_flatten_with_path(state)[0]


def assert_states_identical(a, b, skip=()):
    for (pa, la), (_, lb) in zip(_flat(a), _flat(b)):
        name = jax.tree_util.keystr(pa)
        if any(s in name for s in skip):
            continue
        na, nb = np.asarray(la), np.asarray(lb)
        assert na.dtype == nb.dtype, f"leaf {name} dtype diverged"
        assert (na == nb).all(), f"leaf {name} diverged"


@pytest.fixture(scope="module")
def elected4():
    """G=4 fleet with every group led, shared by the router/obs tests —
    one 60-tick pc=1 program whose jit cache they all hit (tier-1 wall
    budget; states are immutable so sharing is safe)."""
    gstate = init_groups(CFG, 4)
    gstate, _ = run_group_ticks(gstate, CFG, 60, prop_count=1)
    assert int(groups_with_leader(gstate)) == 4
    return gstate


def _fault_free(groups, ticks, n):
    """All-quiet schedule batch [G, T, ...] (bool gates, no drops)."""
    return FaultSchedule(
        drop=jnp.zeros((groups, ticks, n, n), bool),
        alive=jnp.ones((groups, ticks, n), bool),
        target_leader=jnp.zeros((groups, ticks), bool),
        crash_campaign=jnp.zeros((groups, ticks), bool),
    )


# ---------------------------------------------------------------------------
# G=1 bit-identity (acceptance criterion)


class TestG1BitIdentity:
    def test_all_fields_identical_to_single_group_run(self):
        """120 ticks with fused proposes + the read path + stats on: every
        SimState leaf of the squeezed G=1 grouped run matches run_ticks
        bit for bit (dtype included)."""
        single, _ = run_ticks(init_state(CFG), CFG, 120, prop_count=2)
        grouped, trace = run_group_ticks(init_groups(CFG, 1), CFG, 120,
                                         prop_count=2)
        squeezed = jax.tree_util.tree_map(lambda a: a[0], grouped)
        assert_states_identical(single, squeezed)
        # the run did real work, so the identity is not vacuous
        assert int(aggregate_committed(grouped)) > 0
        assert int(aggregate_reads_served(grouped)) > 0
        assert int(np.asarray(trace)[-1, 0]) == 1   # led at the last tick

    @pytest.mark.slow
    def test_step_groups_g1_matches_step_per_tick(self):
        from swarmkit_tpu.raft.sim import step
        st1 = init_state(CFG)
        stg = init_groups(CFG, 1)
        for _ in range(25):
            st1 = step(st1, CFG)
            stg = step_groups(stg, CFG)
        assert_states_identical(
            st1, jax.tree_util.tree_map(lambda a: a[0], stg))


# ---------------------------------------------------------------------------
# init_groups


class TestInitGroups:
    def test_group0_is_init_state(self):
        g = init_groups(CFG, 4)
        assert_states_identical(
            init_state(CFG), jax.tree_util.tree_map(lambda a: a[0], g))

    def test_stagger_varies_timeouts_across_groups(self):
        g = init_groups(CFG, 8)
        tmo = np.asarray(g.timeout)
        assert len({tuple(r) for r in tmo}) > 1
        # still inside the kernel's [T, 2T) election window
        assert (tmo >= CFG.election_tick).all()
        assert (tmo < 2 * CFG.election_tick).all()

    def test_no_stagger_is_pure_broadcast(self):
        g = init_groups(CFG, 3, stagger=False)
        tmo = np.asarray(g.timeout)
        assert (tmo == tmo[0]).all()


# ---------------------------------------------------------------------------
# router


class TestRouter:
    def test_hash_is_stable_across_processes(self):
        """blake2b keyed routing must not depend on PYTHONHASHSEED —
        a restarted frontend must route every key to the same group."""
        keys = ["user/1", "user/2", b"\x00\xffraw", 1234567, -5]
        here = [group_of_key(k, 64, seed=3) for k in keys]
        code = ("from swarmkit_tpu.multiraft import group_of_key;"
                "ks=['user/1','user/2',b'\\x00\\xffraw',1234567,-5];"
                "print([group_of_key(k,64,seed=3) for k in ks])")
        env = dict(os.environ, PYTHONHASHSEED="12345",
                   JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, check=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        assert eval(out.stdout.strip()) == here

    def test_hash_spreads_and_respects_seed(self):
        groups = {group_of_key(f"k{i}", 16) for i in range(200)}
        assert len(groups) == 16            # 200 keys cover 16 groups
        moved = sum(group_of_key(f"k{i}", 16) != group_of_key(f"k{i}", 16,
                                                              seed=9)
                    for i in range(200))
        assert moved > 100                  # seed reshuffles placement

    def test_flush_applies_batches_spills_overflow_and_serves_reads(
            self, elected4):
        gstate = elected4
        base = int(aggregate_committed(gstate))
        reads0 = int(aggregate_reads_served(gstate))

        r = Router(CFG, 4, seed=1)
        offered = 0
        for i in range(10 * CFG.max_props):  # overfill at least one group
            r.offer(f"key/{i}", payload=i + 1)
            offered += 1
        r.offer_read("hot/key", count=6)
        writes0, pend_reads = r.pending()
        assert (writes0, pend_reads) == (offered, 6)
        for _ in range(12):                 # flushes drain spill over ticks
            gstate = r.flush(gstate)
        assert r.pending() == (0, 0)
        assert r.spilled > 0                # capacity really was exceeded
        assert r.routed == offered + 6
        gstate, _ = run_group_ticks(gstate, CFG, 60, prop_count=1)
        assert int(aggregate_committed(gstate)) >= base + offered
        assert int(aggregate_reads_served(gstate)) > reads0


# ---------------------------------------------------------------------------
# group isolation under the DST adversary (satellite contract)


def _isolation_schedule(groups, ticks, n, victim):
    """Crash rows, isolate leaders, and drop edges — in `victim` only."""
    drop = np.zeros((groups, ticks, n, n), bool)
    alive = np.ones((groups, ticks, n), bool)
    tl = np.zeros((groups, ticks), bool)
    cc = np.zeros((groups, ticks), bool)
    alive[victim, 50:120, 0] = False         # crash a row for 70 ticks
    tl[victim, 150:200] = True               # then partition the leader
    drop[victim, 220:260, 1, 2] = True       # then a lossy edge
    drop[victim, 220:260, 2, 1] = True
    cc[victim, 260:280] = True
    return FaultSchedule(drop=jnp.asarray(drop), alive=jnp.asarray(alive),
                         target_leader=jnp.asarray(tl),
                         crash_campaign=jnp.asarray(cc))


class TestGroupIsolation:
    def _run(self, cfg):
        groups, ticks, victim = 4, 300, 1
        g0 = init_groups(cfg, groups)
        quiet, v0, _ = run_groups_under_schedule(
            g0, cfg, _fault_free(groups, ticks, cfg.n), prop_count=2)
        faulty, v1, _ = run_groups_under_schedule(
            g0, cfg, _isolation_schedule(groups, ticks, cfg.n, victim),
            prop_count=2)
        assert not int(v0.sum()) and not int(v1.sum())  # invariants hold
        for g in range(groups):
            a = jax.tree_util.tree_map(lambda x, g=g: x[g], quiet)
            b = jax.tree_util.tree_map(lambda x, g=g: x[g], faulty)
            if g == victim:
                assert any((np.asarray(la) != np.asarray(lb)).any()
                           for (_, la), (_, lb) in zip(_flat(a), _flat(b)))
            else:
                assert_states_identical(a, b)
        assert int(aggregate_committed(faulty)) > 0

    def test_sync_wire(self):
        self._run(CFG)

    def test_mailbox_wire(self):
        self._run(dataclasses.replace(CFG, latency=1, latency_jitter=1,
                                      inflight=2))


# ---------------------------------------------------------------------------
# grouped telemetry (fleet health plane): the per-group histograms ride
# the existing Python gates, so telemetry-off programs and G=1 programs
# must stay bit-identical — telemetry observes the fleet, never steers it


def _leafmap(state):
    return {jax.tree_util.keystr(p): np.asarray(l) for p, l in _flat(state)}


class TestGroupedTelemetry:
    def _telemetry_is_inert(self, cfg):
        """collect_telemetry adds tel_* leaves and changes NOTHING else."""
        tel = dataclasses.replace(cfg, collect_telemetry=True,
                                  telemetry_prop_ring=64)
        base, _ = run_group_ticks(init_groups(cfg, 3), cfg, 120,
                                  prop_count=2)
        instr, _ = run_group_ticks(init_groups(tel, 3), tel, 120,
                                   prop_count=2)
        a, b = _leafmap(base), _leafmap(instr)
        extra = set(b) - set(a)
        assert extra and all("tel_" in name for name in extra)
        for name in a:
            assert a[name].dtype == b[name].dtype, f"{name} dtype diverged"
            assert (a[name] == b[name]).all(), f"{name} diverged"
        # the identity is not vacuous: telemetry really observed commits
        assert np.asarray(instr.tel_commit_hist).sum() > 0

    def test_telemetry_off_identity_sync_wire(self):
        self._telemetry_is_inert(CFG)

    @pytest.mark.slow
    def test_telemetry_off_identity_mailbox_wire(self):
        self._telemetry_is_inert(dataclasses.replace(
            CFG, latency=1, latency_jitter=1, inflight=2))

    @pytest.mark.slow
    def test_g1_bit_identity_with_telemetry_on(self):
        """G=1 identity holds with telemetry on AND a narrowed prop ring
        (the telemetry_prop_ring cost lever reshapes the stamp ring; the
        kernel derives every ring index from the array shape)."""
        tel = dataclasses.replace(CFG, collect_telemetry=True,
                                  telemetry_prop_ring=64)
        single, _ = run_ticks(init_state(tel), tel, 120, prop_count=2)
        grouped, _ = run_group_ticks(init_groups(tel, 1), tel, 120,
                                     prop_count=2)
        assert_states_identical(
            single, jax.tree_util.tree_map(lambda a: a[0], grouped))
        assert np.asarray(grouped.tel_commit_hist).sum() > 0

    @pytest.mark.slow
    def test_per_group_hists_match_single_group_run(self):
        """Without stagger every group runs the single-group program, so
        each group's commit-latency histogram equals the run_ticks one —
        the vmapped fold aggregates per group, not across groups."""
        tel = dataclasses.replace(CFG, collect_telemetry=True,
                                  telemetry_prop_ring=64)
        grouped, _ = run_group_ticks(init_groups(tel, 3, stagger=False),
                                     tel, 100, prop_count=2)
        single, _ = run_ticks(init_state(tel), tel, 100, prop_count=2)
        hist = np.asarray(grouped.tel_commit_hist)
        want = np.asarray(single.tel_commit_hist)
        assert want.sum() > 0
        for g in range(3):
            np.testing.assert_array_equal(hist[g], want)


# ---------------------------------------------------------------------------
# placement: group_mesh + the leading-[G] sharding rule (satellite)


class TestGroupPlacement:
    def test_state_shardings_leading_rule(self):
        mesh = group_mesh(64)
        ndev = len(mesh.devices.ravel())
        assert ndev == 8                    # conftest pins 8 virtual devices
        tree = {
            "grouped": jnp.zeros((64, 5, 7)),       # [G, ...] divisible
            "grouped_vec": jnp.zeros((64,)),
            "shared": jnp.zeros((8, 2)),            # dim0 != G: replicate
            "scalar": jnp.zeros(()),
        }
        sh = state_shardings(mesh, tree, axis=GROUP_AXIS, leading=64)

        def dim0(s):        # specs pad trailing dims with None
            return s.spec[0] if len(s.spec) else None
        assert dim0(sh["grouped"]) == GROUP_AXIS
        assert dim0(sh["grouped_vec"]) == GROUP_AXIS
        assert dim0(sh["shared"]) is None          # dim0 != G: replicate
        assert dim0(sh["scalar"]) is None
        # an indivisible G replicates rather than erroring
        sh2 = state_shardings(mesh, {"g": jnp.zeros((6, 3))},
                              axis=GROUP_AXIS, leading=6)
        assert dim0(sh2["g"]) is None

    @pytest.mark.slow
    def test_sharded_groups_tick_and_match_unsharded(self):
        groups = 16
        mesh = group_mesh(groups)
        g0 = init_groups(CFG, groups)
        gs = shard_rows(g0, mesh, axis=GROUP_AXIS, leading=groups)
        ref, _ = run_group_ticks(g0, CFG, 30, prop_count=1)
        out, _ = run_group_ticks(gs, CFG, 30, prop_count=1)
        assert_states_identical(ref, out)
        assert int(groups_with_leader(out)) > 0


# ---------------------------------------------------------------------------
# observability


class TestMultiRaftObs:
    def _registry(self):
        from swarmkit_tpu.metrics.registry import MetricsRegistry
        return MetricsRegistry()

    def test_publish_is_idempotent_and_counts_leader_changes(
            self, elected4):
        reg = self._registry()
        obs = MultiRaftObs(registry=reg)
        gstate = elected4
        out = obs.publish(gstate)
        assert out["groups"] == 4
        assert out["groups_with_leader"] == 4
        assert out["leader_changes"] == 0   # first publish is baseline
        assert out["committed_entries"] > 0

        committed = reg.counter(
            "swarm_multiraft_committed_entries_total", "x").snapshot()
        assert committed == out["committed_entries"]
        again = obs.publish(gstate)         # same state: deltas add nothing
        assert again["leader_changes"] == 0
        assert reg.counter("swarm_multiraft_committed_entries_total",
                           "x").snapshot() == committed

        # a group whose leader row moved counts exactly once
        moved = np.asarray(obs._last_leaders).copy()
        moved[2] = (moved[2] + 1) % CFG.n
        obs._last_leaders = moved
        assert obs.publish(gstate)["leader_changes"] == 1
        assert reg.counter("swarm_multiraft_leader_changes_total",
                           "x").snapshot() == 1.0

    def test_router_outcomes_reach_the_registry(self):
        reg = self._registry()
        obs = MultiRaftObs(registry=reg)
        r = Router(CFG, 8, obs=obs)
        for i in range(20):
            r.offer(i, payload=i)
        fam = reg.counter("swarm_multiraft_router_keys_total", "x",
                          labels=("outcome",))
        assert fam.labels(outcome="routed").value == 20.0

    def test_kernel_obs_folds_grouped_stats(self, elected4):
        """KernelObs.publish on a [G, ...] state sums the per-group stats
        tables into one fleet-wide delta (run.py grouped folding)."""
        reg = self._registry()
        out = KernelObs(obs=reg).publish(elected4)
        per_group = np.asarray(elected4.stats)
        assert per_group.shape == (4, 4)
        assert out["commit_advance"] == int(per_group[:, 2].sum())
        assert out["elections_won"] == int(per_group[:, 1].sum()) >= 4

    def test_sync_point_handles_group_tick_vector(self, elected4):
        class Clock:
            def add(self, tick):
                self.saw = tick
        c = Clock()
        assert sync_point(c, elected4) == 60 and c.saw == 60
